"""Embedding API: the C-API surface (WasmEdge_* families) for hosts.

Mirrors /root/reference/include/api/wasmedge/wasmedge.h (235 exported
functions, lib/api/wasmedge.cpp:1-2848) as a flat, C-style function
surface: opaque contexts, `we_Result` codes instead of exceptions, and
one function per operation, so an embedder (or a future real C binding
via ctypes) programs against the same shapes the reference's embedders
do.  Family coverage:

  Value/Result/String      value pack/unpack, error codes
  Configure*               proposals, host registrations, statistics,
                           engine selection (the TPU extension knob)
  Statistics*              instruction count / cost / rates
  Loader/Validator/Executor  staged pipeline (APIStepsCoreTest model)
  ASTModule*               import/export listings
  Store*                   module/function lookup, listings
  ModuleInstance/Function/Memory/Global/Table instance accessors
  ImportObject*            host modules incl. WASI + wasmedge_process
  VM*                      the façade incl. one-shot RunWasm and Async
  Batch* (TPU extension)   lane-batched execution over the same VM

The sibling test suite tests/test_capi.py drives the spec corpus through
the VM family exactly like the reference's APIVMCoreTest
(test/api/APIVMCoreTest.cpp:1-244).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from wasmedge_tpu.common.configure import (
    Configure,
    EngineKind,
    HostRegistration,
    Proposal,
)
from wasmedge_tpu.common.errors import (
    ErrCode,
    LoadError,
    TrapError,
    ValidationError,
    WasmError,
)
from wasmedge_tpu.common.statistics import Statistics
from wasmedge_tpu.common.types import (
    ValType,
    bits_to_typed,
    typed_to_bits,
    MASK32,
    MASK64,
    bits_to_f32,
    bits_to_f64,
    f32_to_bits,
    f64_to_bits,
    s32,
    s64,
)

# ---------------------------------------------------------------------------
# Result (reference: WasmEdge_Result / ResultGetCode / ResultOK)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class we_Result:
    code: int
    message: str = ""


we_Result_Success = we_Result(0, "success")


def we_ResultOK(res: we_Result) -> bool:
    return res.code == 0


def we_ResultGetCode(res: we_Result) -> int:
    return res.code


def we_ResultGetMessage(res: we_Result) -> str:
    return res.message


def _wrap(fn: Callable) -> Tuple[we_Result, object]:
    """Run fn; map engine exceptions onto Result codes (wasmedge.cpp's
    wrap() idiom)."""
    try:
        return we_Result_Success, fn()
    except (TrapError, LoadError, ValidationError, WasmError) as e:
        return we_Result(int(e.code), str(e)), None
    except KeyError as e:
        return we_Result(int(ErrCode.FuncNotFound), str(e)), None
    except OSError as e:
        return we_Result(int(ErrCode.IllegalPath), str(e)), None


# ---------------------------------------------------------------------------
# Value (reference: WasmEdge_Value + ValueGen*/ValueGet*)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class we_Value:
    type: str  # "i32" | "i64" | "f32" | "f64" | "v128" | "funcref" | "externref"
    raw: int   # raw cell bits


def we_ValueGenI32(v: int) -> we_Value:
    return we_Value("i32", v & 0xFFFFFFFF)


def we_ValueGenI64(v: int) -> we_Value:
    return we_Value("i64", v & 0xFFFFFFFFFFFFFFFF)


def we_ValueGenF32(v: float) -> we_Value:
    return we_Value("f32", f32_to_bits(v))


def we_ValueGenF64(v: float) -> we_Value:
    return we_Value("f64", f64_to_bits(v))


def we_ValueGenV128(v: int) -> we_Value:
    return we_Value("v128", v & ((1 << 128) - 1))


def we_ValueGetI32(v: we_Value) -> int:
    return s32(v.raw & MASK32)


def we_ValueGetI64(v: we_Value) -> int:
    return s64(v.raw & MASK64)


def we_ValueGetF32(v: we_Value) -> float:
    return bits_to_f32(v.raw & MASK32)


def we_ValueGetF64(v: we_Value) -> float:
    return bits_to_f64(v.raw & MASK64)


def _cells_to_values(types, cells) -> List[we_Value]:
    out = []
    for t, c in zip(types, cells):
        name = getattr(t, "name", str(t)).lower()
        out.append(we_Value(name if name in ("i32", "i64", "f32", "f64",
                                             "v128") else "i64", int(c)))
    return out


# ---------------------------------------------------------------------------
# Configure (reference: WasmEdge_Configure* family)
# ---------------------------------------------------------------------------


def we_ConfigureCreate() -> Configure:
    return Configure()


def we_ConfigureDelete(conf: Configure) -> None:
    pass  # Python GC


def we_ConfigureAddProposal(conf: Configure, prop: str) -> None:
    conf.proposals.add(Proposal(prop))


def we_ConfigureRemoveProposal(conf: Configure, prop: str) -> None:
    conf.proposals.discard(Proposal(prop))


def we_ConfigureHasProposal(conf: Configure, prop: str) -> bool:
    return Proposal(prop) in conf.proposals


def we_ConfigureAddHostRegistration(conf: Configure, host: str) -> None:
    conf.host_registrations.add(HostRegistration(host))


def we_ConfigureRemoveHostRegistration(conf: Configure, host: str) -> None:
    conf.host_registrations.discard(HostRegistration(host))


def we_ConfigureHasHostRegistration(conf: Configure, host: str) -> bool:
    return HostRegistration(host) in conf.host_registrations


def we_ConfigureSetMaxMemoryPage(conf: Configure, pages: int) -> None:
    conf.runtime.max_memory_pages = pages


def we_ConfigureGetMaxMemoryPage(conf: Configure) -> int:
    return conf.runtime.max_memory_pages


def we_ConfigureSetEngine(conf: Configure, engine: str) -> None:
    """TPU extension: scalar | native | tpu_batch | auto (the engine-switch
    seam, SURVEY.md §5.6)."""
    conf.engine = EngineKind(engine)


def we_ConfigureGetEngine(conf: Configure) -> str:
    return conf.engine.value


def we_ConfigureStatisticsSetInstructionCounting(conf, on: bool) -> None:
    conf.statistics.instr_counting = on


def we_ConfigureStatisticsIsInstructionCounting(conf) -> bool:
    return conf.statistics.instr_counting


def we_ConfigureStatisticsSetCostMeasuring(conf, on: bool) -> None:
    conf.statistics.cost_measuring = on


def we_ConfigureStatisticsIsCostMeasuring(conf) -> bool:
    return conf.statistics.cost_measuring


def we_ConfigureStatisticsSetTimeMeasuring(conf, on: bool) -> None:
    conf.statistics.time_measuring = on


def we_ConfigureStatisticsIsTimeMeasuring(conf) -> bool:
    return conf.statistics.time_measuring


# ---------------------------------------------------------------------------
# Statistics (reference: WasmEdge_Statistics* family)
# ---------------------------------------------------------------------------


def we_StatisticsCreate() -> Statistics:
    return Statistics()


def we_StatisticsDelete(stat) -> None:
    pass


def we_StatisticsGetInstrCount(stat: Statistics) -> int:
    return stat.instr_count


def we_StatisticsGetInstrPerSecond(stat: Statistics) -> float:
    return stat.instr_per_second()


def we_StatisticsGetTotalCost(stat: Statistics) -> int:
    return stat.total_cost


def we_StatisticsSetCostLimit(stat: Statistics, limit: int) -> None:
    stat.cost_limit = limit


# ---------------------------------------------------------------------------
# Loader / Validator / Executor (staged pipeline; APIStepsCoreTest model)
# ---------------------------------------------------------------------------


def we_LoaderCreate(conf: Optional[Configure] = None):
    from wasmedge_tpu.loader import Loader

    return Loader(conf or Configure())


def we_LoaderParseFromBuffer(loader, data: bytes):
    return _wrap(lambda: loader.parse_module(data))


def we_LoaderParseFromFile(loader, path: str):
    def go():
        with open(path, "rb") as f:
            return loader.parse_module(f.read())
    return _wrap(go)


def we_ValidatorCreate(conf: Optional[Configure] = None):
    from wasmedge_tpu.validator import Validator

    return Validator(conf or Configure())


def we_ValidatorValidate(validator, ast_mod):
    return _wrap(lambda: validator.validate(ast_mod))[0]


def we_ExecutorCreate(conf: Optional[Configure] = None, stat=None):
    from wasmedge_tpu.executor import Executor

    return Executor(conf or Configure(), stat=stat)


def we_ExecutorInstantiate(executor, store, ast_mod):
    return _wrap(lambda: executor.instantiate(store, ast_mod))


def we_ExecutorRegisterModule(executor, store, ast_mod, name: str):
    return _wrap(lambda: executor.register_module(store, ast_mod, name))[0]


def we_ExecutorRegisterImport(executor, store, import_object):
    return _wrap(
        lambda: executor.register_import_object(store, import_object))[0]


def we_ExecutorInvoke(executor, store, func_inst, params: Sequence[we_Value]):
    def go():
        if len(params) != len(func_inst.functype.params):
            raise TrapError(ErrCode.FuncSigMismatch,
                            f"expected {len(func_inst.functype.params)} "
                            f"args, got {len(params)}")
        return executor.invoke_raw(store, func_inst,
                                   [p.raw for p in params])

    res, out = _wrap(go)
    if not we_ResultOK(res):
        return res, []
    return res, _cells_to_values(func_inst.functype.results, out)


# ---------------------------------------------------------------------------
# ASTModule listings (reference: WasmEdge_ASTModuleListExports/Imports)
# ---------------------------------------------------------------------------


def we_ASTModuleListImports(ast_mod) -> List[Tuple[str, str, str]]:
    """[(module, name, kind)] — kind in func/table/memory/global."""
    kinds = {0: "func", 1: "table", 2: "memory", 3: "global"}
    return [(im.module, im.name, kinds.get(im.kind, "?"))
            for im in ast_mod.imports]


def we_ASTModuleListExports(ast_mod) -> List[Tuple[str, str]]:
    kinds = {0: "func", 1: "table", 2: "memory", 3: "global"}
    return [(ex.name, kinds.get(ex.kind, "?")) for ex in ast_mod.exports]


# ---------------------------------------------------------------------------
# Store (reference: WasmEdge_Store* family)
# ---------------------------------------------------------------------------


def we_StoreCreate():
    from wasmedge_tpu.runtime.store import StoreManager

    return StoreManager()


def we_StoreDelete(store) -> None:
    pass


def we_StoreFindModule(store, name: str):
    return store.find_module(name)


def we_StoreListModule(store) -> List[str]:
    return store.module_names()


def we_StoreFindFunctionRegistered(store, mod_name: str, func_name: str):
    mod = store.find_module(mod_name)
    return mod.find_func(func_name) if mod is not None else None


# ---------------------------------------------------------------------------
# Instance accessors (reference: WasmEdge_ModuleInstance*/...Instance*)
# ---------------------------------------------------------------------------


def we_ModuleInstanceGetModuleName(inst) -> str:
    return inst.name


def we_ModuleInstanceFindFunction(inst, name: str):
    return inst.find_func(name)


def we_ModuleInstanceListFunction(inst) -> List[str]:
    return [n for n, (kind, _) in inst.exports.items() if kind == 0]


def we_ModuleInstanceFindMemory(inst, name: str):
    ex = inst.exports.get(name)
    return inst.memories[ex[1]] if ex and ex[0] == 2 else None


def we_ModuleInstanceFindGlobal(inst, name: str):
    ex = inst.exports.get(name)
    return inst.globals[ex[1]] if ex and ex[0] == 3 else None


def we_ModuleInstanceFindTable(inst, name: str):
    ex = inst.exports.get(name)
    return inst.tables[ex[1]] if ex and ex[0] == 1 else None


def we_FunctionInstanceGetFunctionType(fi):
    return fi.functype


def we_MemoryInstanceGetPageSize(mem) -> int:
    return mem.pages


def we_MemoryInstanceGrowPage(mem, delta: int) -> we_Result:
    old = mem.grow(delta)
    return we_Result_Success if old >= 0 else \
        we_Result(int(ErrCode.MemoryOutOfBounds), "grow failed")


def we_MemoryInstanceGetData(mem, offset: int, length: int):
    return _wrap(lambda: bytes(mem.load_bytes(offset, length)))


def we_MemoryInstanceSetData(mem, offset: int, data: bytes) -> we_Result:
    return _wrap(lambda: mem.store_bytes(offset, data))[0]


def we_GlobalInstanceGetValue(g) -> we_Value:
    return we_Value(g.type.val_type.name.lower(), g.value)


def we_GlobalInstanceSetValue(g, v: we_Value) -> we_Result:
    if hasattr(g.type, "mutable") and not g.type.mutable:
        return we_Result(int(ErrCode.SetValueToConst),
                         "global is immutable")
    g.value = v.raw
    return we_Result_Success


def we_TableInstanceGetSize(t) -> int:
    return t.size


# ---------------------------------------------------------------------------
# ImportObject (reference: WasmEdge_ImportObject* family)
# ---------------------------------------------------------------------------


def we_ImportObjectCreate(name: str):
    from wasmedge_tpu.runtime.hostfunc import ImportObject

    return ImportObject(name)


def we_ImportObjectAddFunction(imp, name: str, params, results,
                               fn: Callable) -> None:
    """fn(mem, *typed_args) -> result(s); the HostFunc callback shape."""
    from wasmedge_tpu.runtime.hostfunc import PyHostFunction

    imp.add_func(name, PyHostFunction(fn, params, results))


def we_ImportObjectCreateWASI(dirs=None, args=None, envs=None):
    from wasmedge_tpu.host.wasi import WasiModule

    w = WasiModule()
    w.init_wasi(dirs=dirs, args=args, envs=envs)
    return w


def we_ImportObjectInitWASI(wasi, dirs=None, args=None, envs=None,
                            prog_name=None) -> None:
    if prog_name is None:
        wasi.init_wasi(dirs=dirs, args=args, envs=envs)
    else:
        wasi.init_wasi(dirs=dirs, prog_name=prog_name, args=args,
                       envs=envs)


def we_ImportObjectWASIGetExitCode(wasi) -> int:
    return wasi.exit_code


def we_ImportObjectWASIHasExited(wasi) -> bool:
    """True only after the guest called proc_exit (distinguishes
    proc_exit(0) from never-exited; the C shim's wasi command mode)."""
    return bool(getattr(wasi.env, "exited", False))


def we_ImportObjectCreateWasmEdgeProcess(allowed_cmds=None, allow_all=False):
    from wasmedge_tpu.host.process import WasmEdgeProcessModule

    return WasmEdgeProcessModule(allowed_cmds=allowed_cmds,
                                 allow_all=allow_all)


# ---------------------------------------------------------------------------
# VM (reference: WasmEdge_VM* family; include/vm/vm.h:42-268)
# ---------------------------------------------------------------------------


class _VMContext:
    def __init__(self, conf: Optional[Configure], store):
        from wasmedge_tpu.vm import VM

        self.vm = VM(conf or Configure(), store=store)


def we_VMCreate(conf: Optional[Configure] = None, store=None) -> _VMContext:
    return _VMContext(conf, store)


def we_VMDelete(ctx) -> None:
    pass


def we_VMGetStoreContext(ctx):
    return ctx.vm.store


def we_VMGetStatisticsContext(ctx):
    return ctx.vm.statistics()


def we_VMRegisterModuleFromBuffer(ctx, name: str, data: bytes) -> we_Result:
    return _wrap(lambda: ctx.vm.register_module(name, data))[0]


def we_VMRegisterModuleFromImport(ctx, import_object) -> we_Result:
    return _wrap(lambda: ctx.vm.register_import_object(import_object))[0]


def we_VMLoadWasmFromBuffer(ctx, data: bytes) -> we_Result:
    return _wrap(lambda: ctx.vm.load_wasm(data))[0]


def we_VMLoadWasmFromFile(ctx, path: str) -> we_Result:
    def go():
        with open(path, "rb") as f:
            ctx.vm.load_wasm(f.read())
    return _wrap(go)[0]


def we_VMValidate(ctx) -> we_Result:
    return _wrap(lambda: ctx.vm.validate())[0]


def we_VMInstantiate(ctx) -> we_Result:
    return _wrap(lambda: ctx.vm.instantiate())[0]


_VALTYPE_NAME = {ValType.I32: "i32", ValType.I64: "i64",
                 ValType.F32: "f32", ValType.F64: "f64",
                 ValType.V128: "v128", ValType.FuncRef: "funcref",
                 ValType.ExternRef: "externref"}


def _vm_exec_raw(ctx, func_name, params, module_name=None):
    vm = ctx.vm
    with vm._lock:
        fi = vm._find_function(func_name, module_name)
    if len(params) != len(fi.functype.params):
        raise TrapError(ErrCode.FuncSigMismatch,
                        f"expected {len(fi.functype.params)} args, "
                        f"got {len(params)}")
    # param TYPES are checked like the reference front door
    # (lib/executor/executor.cpp:87-97), not just arity.  type "raw"
    # (or None) marks an untyped 64-bit cell — the spec-harness /
    # cells-convenience channel — and skips the check.
    for i, (p, want) in enumerate(zip(params, fi.functype.params)):
        ty = getattr(p, "type", None)
        if ty not in (None, "raw") and ty != _VALTYPE_NAME.get(want, ty):
            raise TrapError(
                ErrCode.FuncSigMismatch,
                f"arg {i}: expected {_VALTYPE_NAME.get(want)}, got {ty}")
    cells = vm.executor.invoke_raw(vm.store, fi,
                                   [p.raw for p in params])
    return fi.functype.results, cells


def we_VMExecute(ctx, func_name: str, params: Sequence[we_Value] = ()):
    res, out = _wrap(
        lambda: _vm_exec_raw(ctx, func_name, list(params)))
    if not we_ResultOK(res):
        return res, []
    types, cells = out
    return res, _cells_to_values(types, cells)


def we_VMExecuteRegistered(ctx, mod_name: str, func_name: str,
                           params: Sequence[we_Value] = ()):
    res, out = _wrap(lambda: _vm_exec_raw(
        ctx, func_name, list(params), module_name=mod_name))
    if not we_ResultOK(res):
        return res, []
    types, cells = out
    return res, _cells_to_values(types, cells)


def we_VMRunWasmFromBuffer(ctx, data: bytes, func_name: str,
                           params: Sequence[we_Value] = ()):
    r = we_VMLoadWasmFromBuffer(ctx, data)
    if not we_ResultOK(r):
        return r, []
    r = we_VMValidate(ctx)
    if not we_ResultOK(r):
        return r, []
    r = we_VMInstantiate(ctx)
    if not we_ResultOK(r):
        return r, []
    return we_VMExecute(ctx, func_name, params)


def we_VMRunWasmFromFileCells(ctx, path: str, func_name: str,
                              cells: Sequence[int]):
    """FFI convenience (the C shim's run_i64): raw 64-bit cells coerced
    to the function's declared parameter types, then the strict typed
    execute.  we_VMExecute itself stays reference-strict
    (lib/executor/executor.cpp:87-97)."""
    for step in (lambda: we_VMLoadWasmFromFile(ctx, path),
                 lambda: we_VMValidate(ctx),
                 lambda: we_VMInstantiate(ctx)):
        r = step()
        if not we_ResultOK(r):
            return r, []

    def build():
        vm = ctx.vm
        with vm._lock:
            fi = vm._find_function(func_name)
        if len(cells) != len(fi.functype.params):
            raise TrapError(ErrCode.FuncSigMismatch,
                            f"expected {len(fi.functype.params)} args, "
                            f"got {len(cells)}")
        return [we_Value(_VALTYPE_NAME.get(want, "i64"),
                         int(c) & MASK64)
                for c, want in zip(cells, fi.functype.params)]

    res, params = _wrap(build)
    if not we_ResultOK(res):
        return res, []
    return we_VMExecute(ctx, func_name, params)


def we_VMRunWasmFromFile(ctx, path: str, func_name: str,
                         params: Sequence[we_Value] = ()):
    def read():
        with open(path, "rb") as f:
            return f.read()

    res, data = _wrap(read)
    if not we_ResultOK(res):
        return res, []
    return we_VMRunWasmFromBuffer(ctx, data, func_name, params)


def we_VMGetFunctionList(ctx) -> List[Tuple[str, object]]:
    return ctx.vm.get_function_list()


def we_VMGetFunctionType(ctx, func_name: str):
    inst = ctx.vm.active_module
    fi = inst.find_func(func_name) if inst else None
    return fi.functype if fi else None


def we_VMCleanup(ctx) -> None:
    ctx.vm.cleanup()


# -- async (reference: WasmEdge_VMAsync* + Async*; include/vm/async.h) ------


class _AsyncHandle:
    def __init__(self, inner, result_types):
        self.inner = inner
        self.result_types = result_types

    def __getattr__(self, name):
        return getattr(self.inner, name)


def we_VMAsyncExecute(ctx, func_name: str, params: Sequence[we_Value] = ()):
    """The async path runs the typed VM.execute (include/vm/async.h model);
    raw we_Value cells are decoded to typed values going in and re-encoded
    coming out of we_AsyncGet."""
    with ctx.vm._lock:
        fi = ctx.vm._find_function(func_name)
    typed = [bits_to_typed(t, p.raw)
             for t, p in zip(fi.functype.params, params)]
    return _AsyncHandle(ctx.vm.async_execute(func_name, typed),
                        fi.functype.results)


def we_AsyncWait(handle) -> None:
    handle.wait()


def we_AsyncWaitFor(handle, ms: int) -> bool:
    return handle.wait_for(ms / 1000.0)


def we_AsyncCancel(handle) -> None:
    handle.cancel()


def we_AsyncGet(handle):
    if not hasattr(handle, "inner"):
        # async-run family handles: the task already yields the
        # (we_Result, [we_Value]) pair
        res, out = _wrap(handle.get)
        if not we_ResultOK(res):
            return res, []
        return out
    res, out = _wrap(handle.inner.get)
    if not we_ResultOK(res):
        return res, []
    cells = [typed_to_bits(t, v)
             for t, v in zip(handle.result_types, out)]
    return res, _cells_to_values(handle.result_types, cells)


# ---------------------------------------------------------------------------
# Batch extension (TPU-native; no reference analog — the tpu_batch engine
# behind the same embedding surface)
# ---------------------------------------------------------------------------


def we_VMBatchExecute(ctx, func_name: str, per_lane_args, lanes: int,
                      max_steps: int = 10_000_000):
    """Run the active module's export over `lanes` SIMT lanes.

    per_lane_args: list of numpy int64 arrays (one per wasm param, one
    value per lane).  Returns (Result, BatchResult)."""
    def go():
        from wasmedge_tpu.batch.uniform import UniformBatchEngine

        from wasmedge_tpu.vm.vm import batch_conf_with_gas

        inst = ctx.vm.active_module
        if inst is None:
            raise WasmError(ErrCode.WrongVMWorkflow, "no instantiated module")
        conf = batch_conf_with_gas(ctx.vm.conf, ctx.vm.stat)
        eng = UniformBatchEngine(inst, store=ctx.vm.store, conf=conf,
                                 lanes=lanes)
        return eng.run(func_name, list(per_lane_args), max_steps=max_steps)
    return _wrap(go)


# ---------------------------------------------------------------------------
# Version (reference: WasmEdge_VersionGet*)
# ---------------------------------------------------------------------------
WE_VERSION = "0.9.1-tpu.3"  # tracks the reference release + our round


def we_VersionGet() -> str:
    return WE_VERSION


def we_VersionGetMajor() -> int:
    return int(WE_VERSION.split(".")[0])


def we_VersionGetMinor() -> int:
    return int(WE_VERSION.split(".")[1])


def we_VersionGetPatch() -> int:
    return int(WE_VERSION.split(".")[2].split("-")[0])


# ---------------------------------------------------------------------------
# Log (reference: WasmEdge_LogSetErrorLevel / LogSetDebugLevel)
# ---------------------------------------------------------------------------
def we_LogSetErrorLevel() -> None:
    import logging

    logging.getLogger("wasmedge_tpu").setLevel(logging.ERROR)


def we_LogSetDebugLevel() -> None:
    import logging

    logging.getLogger("wasmedge_tpu").setLevel(logging.DEBUG)


# ---------------------------------------------------------------------------
# FunctionType / TableType / MemoryType / GlobalType contexts
# (reference: WasmEdge_FunctionTypeCreate ... GlobalTypeGetMutability)
# ---------------------------------------------------------------------------
def _to_valtype(name):
    from wasmedge_tpu.common.types import to_valtype

    return to_valtype(name)


def we_FunctionTypeCreate(params: Sequence, results: Sequence):
    from wasmedge_tpu.loader import ast

    return ast.FunctionType(tuple(_to_valtype(p) for p in params),
                            tuple(_to_valtype(r) for r in results))


def we_FunctionTypeDelete(ft) -> None:
    pass


def we_FunctionTypeGetParametersLength(ft) -> int:
    return len(ft.params)


def we_FunctionTypeGetParameters(ft) -> list:
    return [t.name.lower() for t in ft.params]


def we_FunctionTypeGetReturnsLength(ft) -> int:
    return len(ft.results)


def we_FunctionTypeGetReturns(ft) -> list:
    return [t.name.lower() for t in ft.results]


def we_TableTypeCreate(ref_type: str, min_size: int,
                       max_size: Optional[int] = None):
    from wasmedge_tpu.loader import ast

    return ast.TableType(_to_valtype(ref_type),
                         ast.Limit(min_size, max_size))


def we_TableTypeDelete(tt) -> None:
    pass


def we_TableTypeGetRefType(tt) -> str:
    return tt.ref_type.name.lower()


def we_TableTypeGetLimit(tt) -> Tuple[int, Optional[int]]:
    return (tt.limit.min, tt.limit.max)


def we_MemoryTypeCreate(min_pages: int, max_pages: Optional[int] = None):
    from wasmedge_tpu.loader import ast

    return ast.MemoryType(ast.Limit(min_pages, max_pages))


def we_MemoryTypeDelete(mt) -> None:
    pass


def we_MemoryTypeGetLimit(mt) -> Tuple[int, Optional[int]]:
    return (mt.limit.min, mt.limit.max)


def we_GlobalTypeCreate(val_type: str, mutable: bool):
    from wasmedge_tpu.loader import ast

    return ast.GlobalType(_to_valtype(val_type), mutable)


def we_GlobalTypeDelete(gt) -> None:
    pass


def we_GlobalTypeGetValType(gt) -> str:
    return gt.val_type.name.lower()


def we_GlobalTypeGetMutability(gt) -> bool:
    return gt.mutable


# ---------------------------------------------------------------------------
# Instance creation (reference: WasmEdge_TableInstanceCreate etc.)
# ---------------------------------------------------------------------------
def we_TableInstanceCreate(tab_type):
    from wasmedge_tpu.runtime.instance import TableInstance

    return TableInstance(tab_type)


def we_TableInstanceDelete(tab) -> None:
    pass


def we_TableInstanceGetTableType(tab):
    from wasmedge_tpu.loader import ast

    # current size, not the declared min: grow updates the type's min
    # (reference TableInstance semantics)
    return ast.TableType(tab.ref_type, ast.Limit(len(tab.refs), tab.max))


def we_TableInstanceGetData(tab, idx: int):
    if not (0 <= idx < len(tab.refs)):
        return we_Result(int(ErrCode.TableOutOfBounds),
                         "out of bounds table access"), 0
    return we_Result_Success, tab.refs[idx]


def we_TableInstanceSetData(tab, idx: int, ref: int):
    if not (0 <= idx < len(tab.refs)):
        return we_Result(int(ErrCode.TableOutOfBounds),
                         "out of bounds table access")
    tab.refs[idx] = ref
    return we_Result_Success


def we_TableInstanceGrow(tab, delta: int):
    old = tab.grow(delta, 0)
    if old < 0:
        return we_Result(int(ErrCode.TableOutOfBounds),
                         "out of bounds table access")
    return we_Result_Success


def we_MemoryInstanceCreate(mem_type):
    from wasmedge_tpu.runtime.instance import MemoryInstance

    return MemoryInstance(mem_type)


def we_MemoryInstanceDelete(mem) -> None:
    pass


def we_MemoryInstanceGetMemoryType(mem):
    from wasmedge_tpu.loader import ast

    return ast.MemoryType(ast.Limit(mem.pages, mem.max))


def we_GlobalInstanceCreate(glob_type, value: we_Value):
    from wasmedge_tpu.runtime.instance import GlobalInstance

    g = GlobalInstance(glob_type, value.raw)
    return g


def we_GlobalInstanceDelete(glob) -> None:
    pass


def we_GlobalInstanceGetGlobalType(glob):
    return glob.type


# ---------------------------------------------------------------------------
# ImportObjectAdd{Table,Memory,Global}
# (reference: WasmEdge_ImportObjectAddTable/AddMemory/AddGlobal)
# ---------------------------------------------------------------------------
def we_ImportObjectAddTable(imp, name: str, tab) -> None:
    imp.add_table(name, tab)


def we_ImportObjectAddMemory(imp, name: str, mem) -> None:
    imp.add_memory(name, mem)


def we_ImportObjectAddGlobal(imp, name: str, glob) -> None:
    imp.add_global(name, glob)


# ---------------------------------------------------------------------------
# Compiler (reference: WasmEdge_CompilerCreate / CompilerCompile;
# our artifact is universal twasm — original bytes + tpu.aot section
# carrying the verified image and the fused Pallas encoding)
# ---------------------------------------------------------------------------
class _Compiler:
    def __init__(self, conf: Optional[Configure]):
        self.conf = conf or Configure()


def we_CompilerCreate(conf: Optional[Configure] = None):
    return _Compiler(conf)


def we_CompilerDelete(compiler) -> None:
    pass


def we_CompilerCompile(compiler, in_path: str, out_path: str):
    def go():
        from wasmedge_tpu.aot import compile_module

        with open(in_path, "rb") as f:
            data = f.read()
        out = compile_module(data, compiler.conf)
        with open(out_path, "wb") as f:
            f.write(out)
    return _wrap(go)[0]


def we_CompilerCompileFromBuffer(compiler, data: bytes):
    def go():
        from wasmedge_tpu.aot import compile_module

        return compile_module(bytes(data), compiler.conf)
    return _wrap(go)


# ---------------------------------------------------------------------------
# Extra instance/store/VM listings (reference: the List*/Get* remainder)
# ---------------------------------------------------------------------------
def we_ModuleInstanceListFunctionLength(inst) -> int:
    return len(we_ModuleInstanceListFunction(inst))


def we_ModuleInstanceListTable(inst) -> list:
    return [n for n, (k, _) in inst.exports.items() if k == 1]


def we_ModuleInstanceListTableLength(inst) -> int:
    return len(we_ModuleInstanceListTable(inst))


def we_ModuleInstanceListMemory(inst) -> list:
    return [n for n, (k, _) in inst.exports.items() if k == 2]


def we_ModuleInstanceListMemoryLength(inst) -> int:
    return len(we_ModuleInstanceListMemory(inst))


def we_ModuleInstanceListGlobal(inst) -> list:
    return [n for n, (k, _) in inst.exports.items() if k == 3]


def we_ModuleInstanceListGlobalLength(inst) -> int:
    return len(we_ModuleInstanceListGlobal(inst))


def we_StoreListModuleLength(store) -> int:
    return len(we_StoreListModule(store))


def we_FunctionInstanceGetName(fi) -> str:
    return getattr(fi, "name", "") or ""


def we_MemoryInstanceGetPageLimit(mem) -> int:
    return mem.page_limit


def we_StatisticsClear(stat: Statistics) -> None:
    stat.reset()


def we_StatisticsSetCostTable(stat: Statistics, table) -> None:
    # pad/truncate to the engine's slot count (wasm opcodes + the
    # lowered BR/BRZ/BRNZ pseudo-ops) so a reference-sized table can
    # never index out of bounds mid-run
    from wasmedge_tpu.common.statistics import _NUM_COST_SLOTS

    t = list(table)[:_NUM_COST_SLOTS]
    t += [1] * (_NUM_COST_SLOTS - len(t))
    stat.cost_table = t


def we_VMGetFunctionListLength(vm) -> int:
    return len(we_VMGetFunctionList(vm))



def we_VMGetActiveModule(ctx):
    """The anonymous (last-instantiated) module instance
    (reference: WasmEdge_VMGetActiveModule)."""
    return ctx.vm.active_module


# ---------------------------------------------------------------------------
# String (reference: WasmEdge_String family, wasmedge.h WasmEdge_String*)
# In C these manage ownership of char buffers; here we_String is a thin
# immutable wrapper so embedders port against the same call shapes.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class we_String:
    buf: str


def we_StringCreateByCString(s: str) -> we_String:
    return we_String(str(s))


def we_StringCreateByBuffer(data, length: int) -> we_String:
    if isinstance(data, (bytes, bytearray)):
        return we_String(bytes(data[:length]).decode("utf-8", "replace"))
    return we_String(str(data)[:length])


def we_StringWrap(s: str, length: Optional[int] = None) -> we_String:
    return we_String(s if length is None else s[:length])


def we_StringIsEqual(a, b) -> bool:
    sa = a.buf if isinstance(a, we_String) else str(a)
    sb = b.buf if isinstance(b, we_String) else str(b)
    return sa == sb


def we_StringCopy(dst_len: int, s) -> str:
    src = s.buf if isinstance(s, we_String) else str(s)
    return src[:dst_len]


def we_StringDelete(s) -> None:
    pass


# ---------------------------------------------------------------------------
# Result constructors (reference: WasmEdge_Result / _Terminate / _Fail)
# ---------------------------------------------------------------------------

we_Result_Terminate = we_Result(int(ErrCode.Terminated), "terminated")
we_Result_Fail = we_Result(int(ErrCode.ExecutionFailed), "generic runtime error")


# ---------------------------------------------------------------------------
# Reference values (reference: ValueGenFuncRef/ExternRef/NullRef family)
# ---------------------------------------------------------------------------


def we_ValueGenNullRef(ref_type: str = "funcref") -> we_Value:
    return we_Value("funcref" if ref_type in ("funcref", "func")
                    else "externref", 0)


def we_ValueGenFuncRef(index: int) -> we_Value:
    # handle encoding matches the engines' ref cells: 0 is null,
    # index+1 is a live funcref
    return we_Value("funcref", (int(index) + 1) & MASK64)


def we_ValueGenExternRef(store, obj) -> we_Value:
    """Extern refs intern the host object in the store (the reference
    boxes a void*; the TPU engines need a 64-bit cell, storemgr
    intern_ref provides it)."""
    return we_Value("externref", store.intern_ref(obj) & MASK64)


def we_ValueGetFuncRef(v: we_Value) -> Optional[int]:
    return None if v.raw == 0 else int(v.raw) - 1


def we_ValueGetExternRef(store, v: we_Value):
    return store.deref(int(v.raw))


def we_ValueIsNullRef(v: we_Value) -> bool:
    return v.type in ("funcref", "externref") and v.raw == 0


def we_ValueGetV128(v: we_Value) -> int:
    return v.raw & ((1 << 128) - 1)


# ---------------------------------------------------------------------------
# Compiler knobs on Configure (reference: ConfigureCompiler* family,
# include/common/configure.h:28-106); see CompilerConfigure for the
# TPU-mapping caveats.
# ---------------------------------------------------------------------------


def we_ConfigureCompilerSetOptimizationLevel(conf: Configure,
                                             level: str) -> None:
    conf.compiler.optimization_level = level


def we_ConfigureCompilerGetOptimizationLevel(conf: Configure) -> str:
    return conf.compiler.optimization_level


def we_ConfigureCompilerSetOutputFormat(conf: Configure, fmt: str) -> None:
    conf.compiler.output_format = fmt


def we_ConfigureCompilerGetOutputFormat(conf: Configure) -> str:
    return conf.compiler.output_format


def we_ConfigureCompilerSetDumpIR(conf: Configure, on: bool) -> None:
    conf.compiler.dump_ir = bool(on)


def we_ConfigureCompilerIsDumpIR(conf: Configure) -> bool:
    return conf.compiler.dump_ir


def we_ConfigureCompilerSetGenericBinary(conf: Configure, on: bool) -> None:
    conf.compiler.generic_binary = bool(on)


def we_ConfigureCompilerIsGenericBinary(conf: Configure) -> bool:
    return conf.compiler.generic_binary


def we_ConfigureCompilerSetInterruptible(conf: Configure, on: bool) -> None:
    conf.compiler.interruptible = bool(on)


def we_ConfigureCompilerIsInterruptible(conf: Configure) -> bool:
    return conf.compiler.interruptible


# ---------------------------------------------------------------------------
# Import/Export type contexts (reference: WasmEdge_ImportTypeGet* /
# ExportTypeGet* over contexts produced by ASTModuleListImports/Exports)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class we_ImportType:
    module: str
    name: str
    kind: str          # func | table | memory | global
    desc: object = dataclasses.field(repr=False, default=None)
    ast_mod: object = dataclasses.field(repr=False, default=None)

    # tuple-compat for embedders iterating the listing like the older
    # (module, name, kind) shape
    def __iter__(self):
        return iter((self.module, self.name, self.kind))

    def __getitem__(self, i):
        return (self.module, self.name, self.kind)[i]


@dataclasses.dataclass(frozen=True)
class we_ExportType:
    name: str
    kind: str
    index: int
    ast_mod: object = dataclasses.field(repr=False, default=None)

    def __iter__(self):
        return iter((self.name, self.kind))

    def __getitem__(self, i):
        return (self.name, self.kind)[i]


_KINDS = {0: "func", 1: "table", 2: "memory", 3: "global"}


def we_ASTModuleListImportsLength(ast_mod) -> int:
    return len(ast_mod.imports)


def we_ASTModuleListImportTypes(ast_mod) -> List[we_ImportType]:
    return [we_ImportType(im.module, im.name, _KINDS.get(im.kind, "?"),
                          im, ast_mod)
            for im in ast_mod.imports]


def we_ASTModuleListExportsLength(ast_mod) -> int:
    return len(ast_mod.exports)


def we_ASTModuleListExportTypes(ast_mod) -> List[we_ExportType]:
    return [we_ExportType(ex.name, _KINDS.get(ex.kind, "?"), ex.index,
                          ast_mod)
            for ex in ast_mod.exports]


def we_ASTModuleDelete(ast_mod) -> None:
    pass


def we_ImportTypeGetModuleName(it: we_ImportType) -> str:
    return it.module


def we_ImportTypeGetExternalName(it: we_ImportType) -> str:
    return it.name


def we_ImportTypeGetExternalType(it: we_ImportType) -> str:
    return it.kind


def we_ImportTypeGetFunctionType(it: we_ImportType):
    if it.kind != "func" or it.desc is None:
        return None
    return it.ast_mod.types[it.desc.type_idx]


def we_ImportTypeGetTableType(it: we_ImportType):
    return it.desc.table_type if it.kind == "table" and it.desc else None


def we_ImportTypeGetMemoryType(it: we_ImportType):
    return it.desc.memory_type if it.kind == "memory" and it.desc else None


def we_ImportTypeGetGlobalType(it: we_ImportType):
    return it.desc.global_type if it.kind == "global" and it.desc else None


def _export_desc_type(et: we_ExportType, kind, pool_getter):
    if et.kind != kind or et.ast_mod is None:
        return None
    return pool_getter(et.ast_mod)[et.index]


def we_ExportTypeGetExternalName(et: we_ExportType) -> str:
    return et.name


def we_ExportTypeGetExternalType(et: we_ExportType) -> str:
    return et.kind


def we_ExportTypeGetFunctionType(et: we_ExportType):
    if et.kind != "func" or et.ast_mod is None:
        return None
    m = et.ast_mod
    return m.func_type_of(et.index)


def we_ExportTypeGetTableType(et: we_ExportType):
    return _export_desc_type(et, "table", lambda m: m.all_table_types())


def we_ExportTypeGetMemoryType(et: we_ExportType):
    return _export_desc_type(et, "memory", lambda m: m.all_memory_types())


def we_ExportTypeGetGlobalType(et: we_ExportType):
    return _export_desc_type(et, "global", lambda m: m.all_global_types())


def we_LimitIsEqual(a, b) -> bool:
    return (a.min == b.min and a.max == b.max
            and getattr(a, "shared", False) == getattr(b, "shared", False))


# ---------------------------------------------------------------------------
# Store find/list remainder (reference: WasmEdge_StoreFind*/List* —
# wasmedge.h Store family; active-module forms search the anonymous
# module, Registered forms a named one, storemgr.h:199-218)
# ---------------------------------------------------------------------------


def _store_active(store):
    return store.get_active_module()


def we_StoreGetActiveModule(store):
    return _store_active(store)


def _find_in(inst, kind: str, name: str):
    if inst is None:
        return None
    ex = inst.exports.get(name)
    kinds = {"func": 0, "table": 1, "memory": 2, "global": 3}
    if ex is None or ex[0] != kinds[kind]:
        return None
    return inst.export_instance(name)


def we_StoreFindFunction(store, name: str):
    return _find_in(_store_active(store), "func", name)


def we_StoreFindTable(store, name: str):
    return _find_in(_store_active(store), "table", name)


def we_StoreFindMemory(store, name: str):
    return _find_in(_store_active(store), "memory", name)


def we_StoreFindGlobal(store, name: str):
    return _find_in(_store_active(store), "global", name)


def we_StoreFindTableRegistered(store, mod: str, name: str):
    return _find_in(store.find_module(mod), "table", name)


def we_StoreFindMemoryRegistered(store, mod: str, name: str):
    return _find_in(store.find_module(mod), "memory", name)


def we_StoreFindGlobalRegistered(store, mod: str, name: str):
    return _find_in(store.find_module(mod), "global", name)


def _list_exports(inst, kind: str) -> List[str]:
    if inst is None:
        return []
    kinds = {"func": 0, "table": 1, "memory": 2, "global": 3}
    return [n for n, (k, _i) in sorted(inst.exports.items())
            if k == kinds[kind]]


def we_StoreListFunction(store) -> List[str]:
    return _list_exports(_store_active(store), "func")


def we_StoreListFunctionLength(store) -> int:
    return len(we_StoreListFunction(store))


def we_StoreListFunctionRegistered(store, mod: str) -> List[str]:
    return _list_exports(store.find_module(mod), "func")


def we_StoreListFunctionRegisteredLength(store, mod: str) -> int:
    return len(we_StoreListFunctionRegistered(store, mod))


def we_StoreListTable(store) -> List[str]:
    return _list_exports(_store_active(store), "table")


def we_StoreListTableLength(store) -> int:
    return len(we_StoreListTable(store))


def we_StoreListTableRegistered(store, mod: str) -> List[str]:
    return _list_exports(store.find_module(mod), "table")


def we_StoreListTableRegisteredLength(store, mod: str) -> int:
    return len(we_StoreListTableRegistered(store, mod))


def we_StoreListMemory(store) -> List[str]:
    return _list_exports(_store_active(store), "memory")


def we_StoreListMemoryLength(store) -> int:
    return len(we_StoreListMemory(store))


def we_StoreListMemoryRegistered(store, mod: str) -> List[str]:
    return _list_exports(store.find_module(mod), "memory")


def we_StoreListMemoryRegisteredLength(store, mod: str) -> int:
    return len(we_StoreListMemoryRegistered(store, mod))


def we_StoreListGlobal(store) -> List[str]:
    return _list_exports(_store_active(store), "global")


def we_StoreListGlobalLength(store) -> int:
    return len(we_StoreListGlobal(store))


def we_StoreListGlobalRegistered(store, mod: str) -> List[str]:
    return _list_exports(store.find_module(mod), "global")


def we_StoreListGlobalRegisteredLength(store, mod: str) -> int:
    return len(we_StoreListGlobalRegistered(store, mod))


# ---------------------------------------------------------------------------
# Standalone host FunctionInstance creation (reference:
# WasmEdge_FunctionInstanceCreate / CreateBinding, wasmedge.h)
# ---------------------------------------------------------------------------


def we_FunctionInstanceCreate(func_type, host_fn, data=None, cost: int = 0):
    """host_fn(data, mem, params: [we_Value]) -> (we_Result, [we_Value]);
    the C callback ABI with the void* user-data slot made explicit."""
    from wasmedge_tpu.runtime.hostfunc import PyHostFunction

    params = list(func_type.params)
    results = list(func_type.results)

    def fn(mem, *typed_args):
        vals = [we_Value(getattr(t, "name", str(t)).lower(),
                         typed_to_bits(t, a))
                for t, a in zip(func_type.params, typed_args)]
        res, outs = host_fn(data, mem, vals)
        if not we_ResultOK(res):
            code = (ErrCode(res.code) if res.code in
                    set(int(e) for e in ErrCode) else ErrCode.HostFuncFailed)
            raise TrapError(code, res.message)
        outs = outs or []
        if len(outs) != len(func_type.results):
            # the reference treats a host function returning the wrong
            # arity as a host-func failure, never a silent truncation
            raise TrapError(ErrCode.HostFuncFailed,
                            "host function result arity mismatch")
        typed = tuple(bits_to_typed(t, o.raw & MASK64)
                      for t, o in zip(func_type.results, outs))
        return typed if len(typed) != 1 else typed[0]

    return PyHostFunction(fn, params, results, cost=cost)


def we_FunctionInstanceCreateBinding(func_type, wrap_fn, binding=None,
                                     data=None, cost: int = 0):
    """The reference's language-binding variant: wrap_fn receives the
    binding token verbatim (bindings marshal through it)."""
    def host_fn(d, mem, vals):
        return wrap_fn(binding, d, mem, vals)

    return we_FunctionInstanceCreate(func_type, host_fn, data, cost)


def we_FunctionInstanceDelete(fi) -> None:
    pass


def we_MemoryInstanceGetPointer(mem, offset: int, length: int):
    """Mutable view of guest memory (the reference hands out uint8_t*;
    Python's analog is a writable memoryview over the backing bytes)."""
    mem.check_bounds(offset, length)
    return memoryview(mem.data)[offset:offset + length]


def we_MemoryInstanceGetPointerConst(mem, offset: int, length: int):
    mem.check_bounds(offset, length)
    return bytes(mem.data[offset:offset + length])


# ---------------------------------------------------------------------------
# Pipeline deletes (contexts are GC'd; present for call-shape parity)
# ---------------------------------------------------------------------------


def we_LoaderDelete(loader) -> None:
    pass


def we_ValidatorDelete(validator) -> None:
    pass


def we_ExecutorDelete(executor) -> None:
    pass


def we_ExecutorInvokeRegistered(executor, store, mod_name: str,
                                func_name: str,
                                params: Sequence[we_Value]):
    def go():
        inst = store.find_module(mod_name)
        if inst is None:
            raise TrapError(ErrCode.WrongInstanceAddress, mod_name)
        fi = inst.find_func(func_name)
        if fi is None:
            raise TrapError(ErrCode.FuncNotFound, func_name)
        cells = executor.invoke_raw(store, fi, [p.raw for p in params])
        return _cells_to_values(fi.functype.results, cells)

    res, out = _wrap(go)
    return res, (out or [])


def we_ImportObjectDelete(imp) -> None:
    pass


def we_ImportObjectGetModuleName(imp) -> str:
    return imp.name


def we_ImportObjectInitWasmEdgeProcess(imp, allowed_cmds=None,
                                       allow_all: bool = False) -> None:
    imp.env.allowed_cmds = set(allowed_cmds or [])
    imp.env.allowed_all = bool(allow_all)


# ---------------------------------------------------------------------------
# VM remainder: ASTModule/file forms + async-run family (reference:
# WasmEdge_VMRunWasmFromASTModule, VMAsyncRunWasmFrom*, wasmedge.h;
# async: include/vm/async.h:25-105)
# ---------------------------------------------------------------------------


def we_VMLoadWasmFromASTModule(ctx, ast_mod) -> we_Result:
    return _wrap(lambda: ctx.vm.load_wasm(ast_mod))[0]


def we_VMRunWasmFromASTModule(ctx, ast_mod, func_name: str,
                              params: Sequence[we_Value] = ()):
    res = we_VMLoadWasmFromASTModule(ctx, ast_mod)
    if not we_ResultOK(res):
        return res, []
    res = we_VMValidate(ctx)
    if not we_ResultOK(res):
        return res, []
    res = we_VMInstantiate(ctx)
    if not we_ResultOK(res):
        return res, []
    return we_VMExecute(ctx, func_name, params)


def we_VMRegisterModuleFromFile(ctx, name: str, path: str) -> we_Result:
    def go():
        with open(path, "rb") as f:
            ctx.vm.register_module(name, f.read())
    return _wrap(go)[0]


def we_VMRegisterModuleFromASTModule(ctx, name: str, ast_mod) -> we_Result:
    return _wrap(lambda: ctx.vm.register_module(name, ast_mod))[0]


def we_VMGetFunctionTypeRegistered(ctx, mod_name: str, func_name: str):
    inst = ctx.vm.store.find_module(mod_name)
    fi = inst.find_func(func_name) if inst is not None else None
    return None if fi is None else fi.functype


def we_VMGetImportModuleContext(ctx, reg: str):
    from wasmedge_tpu.common.configure import HostRegistration

    key = {"wasi": HostRegistration.Wasi,
           "wasmedge_process": HostRegistration.WasmEdgeProcess}.get(
        str(reg).lower())
    return None if key is None else ctx.vm.get_import_module(key)


def _async_call(fn, ctx):
    from wasmedge_tpu.vm.async_ import Async

    return Async(fn, stop_fn=ctx.vm.stop)


def we_VMAsyncExecuteRegistered(ctx, mod_name: str, func_name: str,
                                params: Sequence[we_Value] = ()):
    return _async_call(
        lambda: we_VMExecuteRegistered(ctx, mod_name, func_name, params),
        ctx)


def we_VMAsyncRunWasmFromBuffer(ctx, data: bytes, func_name: str,
                                params: Sequence[we_Value] = ()):
    return _async_call(
        lambda: we_VMRunWasmFromBuffer(ctx, data, func_name, params), ctx)


def we_VMAsyncRunWasmFromFile(ctx, path: str, func_name: str,
                              params: Sequence[we_Value] = ()):
    return _async_call(
        lambda: we_VMRunWasmFromFile(ctx, path, func_name, params), ctx)


def we_VMAsyncRunWasmFromASTModule(ctx, ast_mod, func_name: str,
                                   params: Sequence[we_Value] = ()):
    return _async_call(
        lambda: we_VMRunWasmFromASTModule(ctx, ast_mod, func_name, params),
        ctx)


def we_AsyncGetReturnsLength(handle) -> int:
    if hasattr(handle, "result_types"):
        # legacy we_VMAsyncExecute handles know their arity statically
        return len(handle.result_types)
    try:
        out = handle.get()
    except Exception:
        return 0
    if isinstance(out, tuple) and len(out) == 2:
        return len(out[1])
    return 0


def we_AsyncDelete(handle) -> None:
    pass
