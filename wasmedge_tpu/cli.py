"""CLI: the `wasmedge` / `wasmedgec` tool analogs.

Mirrors /root/reference/tools/wasmedge/wasmedger.cpp:22-360 (runner:
command mode runs _start with WASI exit code; reactor mode calls an
exported function with typed argv) and wasmedgec.cpp:20-200 (compiler:
load -> validate -> emit universal artifact). TPU additions: `--batch N`
runs the export over N SIMT device lanes, `--engine` picks the execution
engine.

Usage:
  python -m wasmedge_tpu.cli run [options] app.wasm [args...]
  python -m wasmedge_tpu.cli compile [options] in.wasm out.twasm
  python -m wasmedge_tpu.cli app.wasm [args...]        # implicit run
"""

from __future__ import annotations

import sys
from typing import List, Optional

from wasmedge_tpu.common.configure import (
    Configure,
    EngineKind,
    HostRegistration,
    Proposal,
)
from wasmedge_tpu.common.errors import WasmError
from wasmedge_tpu.common.types import ValType
from wasmedge_tpu.host.wasi.environ import WasiExit
from wasmedge_tpu.utils.po import ArgumentParser, ListOpt, Option, Toggle


def _runner_parser() -> ArgumentParser:
    p = ArgumentParser("wasmedge-tpu run",
                       "run a WebAssembly file (command or reactor mode)")
    p.add_option("reactor", Toggle("enable reactor mode: call an exported fn "
                                   "with typed argv"))
    p.add_option("dir", ListOpt("bind guest:host directory (preopen)",
                                "guest_path:host_path"))
    p.add_option("env", ListOpt("environment variable NAME=VALUE", "env"))
    p.add_option(["enable-instruction-count"],
                 Toggle("enable instruction counting statistics"))
    p.add_option(["enable-gas-measuring"], Toggle("enable gas metering"))
    p.add_option(["enable-time-measuring"], Toggle("enable time measuring"))
    p.add_option(["enable-all-statistics"], Toggle("enable all statistics"))
    p.add_option(["gas-limit"], Option("gas limit (cost units)", "n", typ=int))
    p.add_option(["memory-page-limit"],
                 Option("page limit of linear memory", "n", typ=int))
    p.add_option(["time-limit"],
                 Option("time limit in milliseconds (async+cancel)", "ms",
                        typ=int))
    p.add_option(["allow-command"],
                 ListOpt("allow a command for wasmedge_process", "cmd"))
    p.add_option(["allow-command-all"],
                 Toggle("allow all commands for wasmedge_process"))
    p.add_option(["disable-bulk-memory"], Toggle("disable bulk-memory ops"))
    p.add_option(["disable-reference-types"], Toggle("disable ref types"))
    p.add_option(["disable-simd"], Toggle("disable 128-bit SIMD"))
    p.add_option(["disable-sign-extension"], Toggle("disable sign-ext ops"))
    p.add_option(["enable-tail-call"], Toggle("enable tail-call proposal"))
    p.add_option(["enable-multi-memory"], Toggle("enable multi memories"))
    p.add_option(["batch"],
                 Option("run over N SIMT device lanes (tpu_batch engine)",
                        "lanes", typ=int))
    p.add_option(["engine"],
                 Option("execution engine: scalar|native|tpu_batch|auto",
                        "kind", default="auto"))
    p.add_option(["devices"],
                 Option("shard --batch lanes across N devices (mesh "
                        "drive; with --supervised adds device "
                        "quarantine, lane migration, and coordinated "
                        "mesh checkpoints)", "n", typ=int))
    p.add_option(["mesh-drive"],
                 Option("mesh drive for --devices: shard (default; one "
                        "jitted program over the lane-sharded named "
                        "mesh) | threaded (per-device engines, the "
                        "degradation-ladder rung)", "kind"))
    p.add_option(["compact"],
                 Toggle("divergence-aware lane compaction for --batch "
                        "runs: PC-sorted lane regrouping + live-prefix "
                        "packing at launch boundaries "
                        "(batch/compact.py)"))
    p.add_option(["supervised"],
                 Toggle("supervise --batch runs: auto-checkpoint, "
                        "retry-with-backoff, engine-degradation ladder"))
    p.add_option(["checkpoint-dir"],
                 Option("checkpoint directory for supervised runs",
                        "dir"))
    p.add_option(["checkpoint-every"],
                 Option("checkpoint every N retired steps (supervised; "
                        "default 1000000)", "n", typ=int))
    p.add_option(["max-retries"],
                 Option("retry budget per engine tier (supervised)",
                        "n", typ=int))
    p.add_option(["resume"],
                 Toggle("adopt an existing --checkpoint-dir lineage at "
                        "startup (cross-process resume; implies "
                        "--supervised)"))
    p.add_option(["trace-out"],
                 Option("write a Chrome trace_event JSON of the batch "
                        "run (open in Perfetto / chrome://tracing)",
                        "path"))
    p.add_option(["metrics-out"],
                 Option("write a Prometheus text-format metrics "
                        "snapshot after the batch run", "path"))
    p.add_positional("wasm_file", "WebAssembly file to run")
    return p


def _build_conf(p: ArgumentParser) -> Configure:
    conf = Configure()
    conf.host_registrations.add(HostRegistration.Wasi)
    if p._opts["allow-command"].value or p._opts["allow-command-all"].value:
        conf.host_registrations.add(HostRegistration.WasmEdgeProcess)
    if p._opts["disable-bulk-memory"].value:
        conf.remove_proposal(Proposal.BulkMemoryOperations)
    if p._opts["disable-reference-types"].value:
        conf.remove_proposal(Proposal.ReferenceTypes)
    if p._opts["disable-simd"].value:
        conf.remove_proposal(Proposal.SIMD)
    if p._opts["disable-sign-extension"].value:
        conf.remove_proposal(Proposal.SignExtensionOperators)
    if p._opts["enable-tail-call"].value:
        conf.add_proposal(Proposal.TailCall)
    if p._opts["enable-multi-memory"].value:
        conf.add_proposal(Proposal.MultiMemories)
    st = conf.statistics
    if p._opts["enable-all-statistics"].value:
        st.instr_counting = st.cost_measuring = st.time_measuring = True
    if p._opts["enable-instruction-count"].value:
        st.instr_counting = True
    if p._opts["enable-gas-measuring"].value:
        st.cost_measuring = True
    if p._opts["enable-time-measuring"].value:
        st.time_measuring = True
    if p._opts["gas-limit"].seen:
        st.cost_measuring = True
        st.cost_limit = p._opts["gas-limit"].value
    if p._opts["memory-page-limit"].seen:
        conf.runtime.max_memory_pages = p._opts["memory-page-limit"].value
    if p._opts["compact"].value:
        conf.batch.compact = True
    if p._opts["checkpoint-dir"].seen:
        conf.supervisor.checkpoint_dir = p._opts["checkpoint-dir"].value
    if p._opts["checkpoint-every"].seen:
        conf.supervisor.checkpoint_every_steps = \
            p._opts["checkpoint-every"].value
    if p._opts["max-retries"].seen:
        conf.supervisor.max_retries = p._opts["max-retries"].value
    if p._opts["resume"].value:
        conf.supervisor.resume = True
    if p._opts["trace-out"].seen:
        conf.obs.enabled = True
        conf.obs.trace_out = p._opts["trace-out"].value
    if p._opts["metrics-out"].seen:
        conf.obs.enabled = True
        conf.obs.metrics_out = p._opts["metrics-out"].value
    if (p._opts["supervised"].value or p._opts["resume"].value) and not (
            conf.supervisor.checkpoint_every_steps
            or conf.supervisor.checkpoint_every_s):
        # --supervised promises auto-checkpointing: without an explicit
        # cadence every retry would silently restart from step 0
        conf.supervisor.checkpoint_every_steps = 1_000_000
    try:
        conf.engine = EngineKind(p._opts["engine"].value)
    except ValueError:
        raise ValueError(
            f"invalid --engine {p._opts['engine'].value!r} "
            f"(choose from {[e.value for e in EngineKind]})")
    return conf


def _parse_typed_args(functype, raw: List[str]) -> list:
    out = []
    for t, s in zip(functype.params, raw):
        if t in (ValType.I32, ValType.I64):
            out.append(int(s, 0))
        elif t in (ValType.F32, ValType.F64):
            out.append(float(s))
        else:
            out.append(int(s, 0))
    return out


def run_command(argv: List[str], out=None, err=None) -> int:
    out = out or sys.stdout
    err = err or sys.stderr
    p = _runner_parser()
    try:
        if not p.parse(argv, out):
            return 0
        conf = _build_conf(p)
    except ValueError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 2
    path = p.positional_values[0]
    prog_args = p.rest

    from wasmedge_tpu.vm import VM

    vm = VM(conf)
    if vm.wasi_module is not None:
        vm.wasi_module.init_wasi(dirs=p._opts["dir"].value, prog_name=path,
                                 args=prog_args, envs=p._opts["env"].value)
    proc = vm.get_import_module(HostRegistration.WasmEdgeProcess)
    if proc is not None:
        proc.env.allowed_cmds = set(p._opts["allow-command"].value)
        proc.env.allowed_all = p._opts["allow-command-all"].value

    reactor = p._opts["reactor"].value
    batch_lanes = p._opts["batch"].value
    time_limit_ms = p._opts["time-limit"].value

    try:
        vm.load_wasm(path)
        vm.validate()
        vm.instantiate()
    except WasmError as e:
        err.write(f"wasmedge-tpu: load failed: {e.formatted()}\n")
        return 1
    except OSError as e:
        err.write(f"wasmedge-tpu: cannot read {path}: {e}\n")
        return 1

    def invoke(fn_name: str, args: list) -> Optional[list]:
        if time_limit_ms is not None:
            h = vm.async_execute(fn_name, args)
            if not h.wait_for(time_limit_ms / 1000.0):
                h.cancel()
            return h.get()
        return vm.execute(fn_name, args)

    try:
        if reactor:
            # reactor mode (wasmedger.cpp:239-359): _initialize then func
            if not prog_args:
                err.write("wasmedge-tpu: reactor mode needs a function name\n")
                return 2
            fn_name, fn_args = prog_args[0], prog_args[1:]
            if vm.active_module.find_func("_initialize") is not None:
                vm.execute("_initialize")
            fi = vm.active_module.find_func(fn_name)
            if fi is None:
                err.write(f"wasmedge-tpu: function {fn_name!r} not found\n")
                return 1
            if batch_lanes:
                import numpy as np

                res = vm.execute_batch(
                    fn_name,
                    [np.full(batch_lanes, int(a, 0), np.int64)
                     for a in fn_args], lanes=batch_lanes,
                    devices=p._opts["devices"].value,
                    mesh_drive=p._opts["mesh-drive"].value,
                    supervised=p._opts["supervised"].value
                    or p._opts["resume"].value,
                    resume=p._opts["resume"].value)
                out.write(f"{[int(r[0]) for r in res.results]}"
                          f" ({int(res.completed.sum())}/{batch_lanes} lanes"
                          f" completed, {int(res.retired.sum())} instrs)\n")
            else:
                rets = invoke(fn_name, _parse_typed_args(fi.functype, fn_args))
                out.write(f"{rets}\n" if rets else "[]\n")
        else:
            # command mode: run _start, exit code from WASI
            invoke("_start", [])
        code = vm.wasi_module.exit_code if vm.wasi_module else 0
    except WasiExit as e:
        code = e.code
    except WasmError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 1
    finally:
        stat = vm.statistics()
        if stat.instr_counting or stat.cost_measuring or stat.time_measuring:
            err.write(f"statistics: {stat.dump()}\n")
    return code


def _serve_parser() -> ArgumentParser:
    p = ArgumentParser("wasmedge-tpu serve",
                       "continuous-batching serving over device lanes: "
                       "queue requests, recycle retired lanes, report "
                       "latency/occupancy")
    p.add_option(["lanes"], Option("device lanes to serve on", "n",
                                   typ=int, default=64))
    p.add_option(["requests"], Option("seeded request count", "n",
                                      typ=int, default=256))
    p.add_option(["arg-min"], Option("minimum argument value", "n",
                                     typ=int, default=8))
    p.add_option(["arg-max"], Option("maximum argument value", "n",
                                     typ=int, default=20))
    p.add_option(["seed"], Option("request schedule seed", "n",
                                  typ=int, default=0))
    p.add_option(["tenants"], Option("spread requests over N tenants",
                                     "n", typ=int, default=1))
    p.add_option(["deadline-ms"],
                 Option("per-request deadline in milliseconds", "ms",
                        typ=int))
    p.add_option(["queue-capacity"],
                 Option("bounded queue capacity (backpressure)", "n",
                        typ=int))
    p.add_option(["autotune"],
                 Toggle("auto-tune steps_per_launch from the hostcall "
                        "drain-latency histograms"))
    p.add_option(["max-virtual-lanes"],
                 Option("oversubscribe: admit up to N concurrent "
                        "requests (resident + host-swapped virtual "
                        "lanes; default = --lanes, no "
                        "oversubscription)", "n", typ=int))
    p.add_option(["resident-budget-bytes"],
                 Option("cap device-resident lane bytes: admission "
                        "installs floor(budget/lane-bytes) physical "
                        "lanes, the rest wait as virtual lanes", "b",
                        typ=int))
    p.add_option(["swap-dir"],
                 Option("spill swapped lane state to this directory "
                        "(default: host memory only)", "dir"))
    p.add_option(["compact"],
                 Toggle("divergence-aware lane compaction: PC-sorted "
                        "lane regrouping at launch boundaries "
                        "(bindings follow their lane)"))
    p.add_option(["checkpoint-dir"],
                 Option("serving-state checkpoint directory", "dir"))
    p.add_option(["checkpoint-every"],
                 Option("checkpoint every N serving rounds", "n",
                        typ=int))
    p.add_option(["resume"],
                 Toggle("adopt an existing --checkpoint-dir serving "
                        "lineage (in-flight requests come back)"))
    p.add_option(["trace-out"],
                 Option("write a Chrome trace_event JSON of the serving "
                        "run", "path"))
    p.add_option(["metrics-out"],
                 Option("write a Prometheus metrics snapshot after the "
                        "serving run", "path"))
    p.add_positional("wasm_file", "WebAssembly file to serve")
    p.add_positional("func", "exported function handling each request")
    return p


def serve_command(argv: List[str], out=None, err=None) -> int:
    """`wasmedge-tpu serve app.wasm func [options]`: drive a seeded
    request stream through the continuous-batching BatchServer and
    print one JSON summary line (req/s, latency percentiles, occupancy,
    recycled lanes)."""
    import json

    out = out or sys.stdout
    err = err or sys.stderr
    p = _serve_parser()
    try:
        if not p.parse(argv, out):
            return 0
        # the shared parser stops option processing at the last
        # positional (`run`'s trailing args are guest argv payload);
        # serve has no payload, so `serve app.wasm func --lanes 4`
        # must keep parsing options instead of dropping them
        if p.rest:
            trailing, p.rest = p.rest, []
            if not p.parse(trailing, out):
                return 0
            if p.rest:
                raise ValueError(
                    f"unexpected argument {p.rest[0]!r}")
    except ValueError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 2
    conf = Configure()
    conf.host_registrations.add(HostRegistration.Wasi)
    if p._opts["queue-capacity"].seen:
        conf.serve.queue_capacity = p._opts["queue-capacity"].value
    if p._opts["autotune"].value:
        conf.serve.autotune = True
        conf.obs.enabled = True   # the tuner reads the drain histograms
    if p._opts["checkpoint-every"].seen:
        conf.serve.checkpoint_every_rounds = p._opts["checkpoint-every"].value
    if p._opts["max-virtual-lanes"].seen:
        conf.hv.max_virtual_lanes = p._opts["max-virtual-lanes"].value
    if p._opts["resident-budget-bytes"].seen:
        conf.hv.resident_budget_bytes = \
            p._opts["resident-budget-bytes"].value
    if p._opts["swap-dir"].seen:
        conf.hv.swap_dir = p._opts["swap-dir"].value
    if p._opts["compact"].value:
        conf.batch.compact = True
    if p._opts["trace-out"].seen or p._opts["metrics-out"].seen:
        conf.obs.enabled = True

    from wasmedge_tpu.vm import VM

    path, func = p.positional_values[0], p.positional_values[1]
    vm = VM(conf)
    if vm.wasi_module is not None:
        vm.wasi_module.init_wasi(dirs=[], prog_name=path)
    try:
        vm.load_wasm(path)
        vm.validate()
        vm.instantiate()
    except WasmError as e:
        err.write(f"wasmedge-tpu: load failed: {e.formatted()}\n")
        return 1
    except OSError as e:
        err.write(f"wasmedge-tpu: cannot read {path}: {e}\n")
        return 1

    import time as _time

    import numpy as np

    server = vm.serve(lanes=p._opts["lanes"].value,
                      checkpoint_dir=p._opts["checkpoint-dir"].value,
                      resume=p._opts["resume"].value)
    # adopted in-flight requests complete alongside the fresh stream and
    # land in the same counters — the exit check must expect them too
    nadopted = len(server.adopted)
    try:
        # fail like run_command's "function not found", not a traceback
        server.recycler.func_idx(func)
    except (KeyError, ValueError) as e:
        err.write(f"wasmedge-tpu: {e.args[0] if e.args else e}\n")
        return 1
    rng = np.random.RandomState(p._opts["seed"].value)
    nreq = p._opts["requests"].value
    ntenants = max(p._opts["tenants"].value, 1)
    lo_a = p._opts["arg-min"].value
    hi_a = max(p._opts["arg-max"].value, lo_a)
    deadline_ms = p._opts["deadline-ms"].value

    futures = []
    t0 = _time.monotonic()
    try:
        for i in range(nreq):
            args = [int(rng.randint(lo_a, hi_a + 1))]
            while True:
                try:
                    futures.append(server.submit(
                        func, args, tenant=f"tenant{i % ntenants}",
                        deadline_s=deadline_ms / 1000.0
                        if deadline_ms is not None else None))
                    break
                except WasmError as e:
                    # the structured rejection contract: only a
                    # retryable rejection (backpressure) is worth a
                    # retry — permanent conditions re-raise unchanged
                    if not e.retryable:
                        raise
                    # backpressure: serve a round to free queue space
                    if not server.step():
                        if server.failed is not None:
                            # surface the terminal engine failure, not
                            # the stale backpressure signal it caused
                            raise server.failed from None
                        raise
        server.run_until_idle()
    except WasmError as e:
        err.write(f"wasmedge-tpu: serve failed: {e}\n")
        return 1
    wall = _time.monotonic() - t0
    from wasmedge_tpu.utils.bench_artifact import percentile

    lat = sorted(f.t_done - t0 for f in futures if f.t_done is not None)
    c = server.counters
    # true utilization, same definition bench.py --serve compares with:
    # retired instructions over device step-lanes
    occupancy = (c["retired_instructions"]
                 / max(server.total * server.lanes, 1))
    summary = {
        "metric": "serve_cli",
        "requests": nreq,
        "adopted": nadopted,
        "completed": c["completed"],
        "trapped": c["trapped"],
        "expired": c["expired"],
        "killed": c["killed"],
        "recycled_lanes": c["recycled_lanes"],
        "rounds": c["rounds"],
        "occupancy": round(occupancy, 4),
        "wall_s": round(wall, 3),
        "req_per_s": round(nreq / wall, 1) if wall > 0 else 0.0,
        "p50_latency_s": round(percentile(lat, 0.5), 4) if lat else None,
        "p99_latency_s": round(percentile(lat, 0.99), 4) if lat else None,
    }
    hv = server.hv_stats()
    if hv is not None:
        summary["swaps_in"] = hv["swaps_in"]
        summary["swaps_out"] = hv["swaps_out"]
        summary["peak_admitted"] = hv["peak_admitted"]
        summary["resident_cap"] = hv["resident_cap"]
    out.write(json.dumps(summary) + "\n")
    if conf.obs.enabled:
        rec = server.obs
        if p._opts["trace-out"].seen:
            from wasmedge_tpu.obs.trace import export_chrome_trace

            export_chrome_trace(rec, p._opts["trace-out"].value)
        if p._opts["metrics-out"].seen:
            from wasmedge_tpu.obs.metrics import export_prometheus

            export_prometheus(p._opts["metrics-out"].value, recorder=rec,
                              stats=vm.statistics(),
                              hostcall_stats=server.engine.hostcall_stats,
                              hv_stats=hv)
    return 0 if c["completed"] + c["trapped"] + c["expired"] \
        + c["killed"] == nreq + nadopted else 1


def _gateway_parser() -> ArgumentParser:
    p = ArgumentParser("wasmedge-tpu gateway",
                       "network-facing multi-tenant serving gateway: "
                       "HTTP invoke/poll, runtime module registration, "
                       "per-tenant auth/rate/quota")
    p.add_option(["host"], Option("bind address", "addr",
                                  default="127.0.0.1"))
    p.add_option(["port"], Option("bind port (0 = ephemeral; the bound "
                                  "port is printed)", "n", typ=int,
                                  default=8080))
    p.add_option(["lanes"], Option("device lanes per serving generation",
                                   "n", typ=int, default=64))
    p.add_option(["devices"],
                 Option("serve over N devices (single-program mesh "
                        "drive, lane-sharded serving pool; lanes round "
                        "up to a device multiple)", "n", typ=int))
    p.add_option(["module"],
                 ListOpt("preload a guest module as NAME=PATH "
                         "(repeatable; more can be registered at "
                         "runtime via POST /v1/modules)", "name=path"))
    p.add_option(["tenants"],
                 Option("tenant policy file (JSON or .toml): api keys, "
                        "weights, quotas, rate limits", "file"))
    p.add_option(["queue-capacity"],
                 Option("bounded request queue capacity "
                        "(backpressure -> 429)", "n", typ=int))
    p.add_option(["max-virtual-lanes"],
                 Option("oversubscribe each serving generation: admit "
                        "up to N concurrent requests (resident + "
                        "host-swapped virtual lanes; default = "
                        "--lanes)", "n", typ=int))
    p.add_option(["resident-budget-bytes"],
                 Option("cap device-resident lane bytes per "
                        "generation (admission counts the budget "
                        "instead of the raw free-lane count)", "b",
                        typ=int))
    p.add_option(["compact"],
                 Toggle("divergence-aware lane compaction on every "
                        "serving generation: PC-sorted lane regrouping "
                        "at launch boundaries"))
    p.add_option(["suspend"],
                 Toggle("guest suspend/resume via effect handlers: "
                        "blocking hostcalls (poll_oneoff sleeps, "
                        "wasmedge.await_event) park the session at "
                        "zero resident cost until POST "
                        "/v1/requests/<id>/wake or its timer"))
    p.add_option(["audit"],
                 Toggle("shadow-audit lanes: re-execute a seeded lane "
                        "sample at launch boundaries and compare "
                        "bit-exact; divergence rolls back, masks, and "
                        "feeds the device-quarantine ladder"))
    p.add_option(["scrub"],
                 Option("at-rest integrity scrubbing every N seconds: "
                        "re-verify swap blobs / checkpoint members / "
                        "compile-cache entries, repair from mirror or "
                        "fleet peer, else evict (0 = off)", "s",
                        typ=float))
    p.add_option(["obs"],
                 Toggle("enable the flight recorder (gateway/<tenant> "
                        "spans, drain histograms; served at /metrics)"))
    p.add_option(["state-dir"],
                 Option("durable gateway state directory: registered "
                        "module store + async-request journal + serve "
                        "checkpoints (crash/restart survivable)",
                        "dir"))
    p.add_option(["resume"],
                 Toggle("adopt an existing --state-dir at startup: "
                        "re-register the stored module set, restore "
                        "the serving checkpoint lineage, re-queue "
                        "journaled unresolved request ids"))
    p.add_option(["build-timeout"],
                 Option("generation build timeout in seconds; a build "
                        "exceeding it rolls back with a retryable 503 "
                        "(default 120)", "s", typ=float))
    p.add_option(["result-cache"],
                 Option("resolved async requests kept pollable (and "
                        "durably replayable) before pruning "
                        "(default 4096)", "n", typ=int))
    p.add_option(["duration"],
                 Option("serve for N seconds then drain and exit "
                        "(default: until SIGINT)", "s", typ=float))
    p.add_option(["peer"],
                 ListOpt("federate with the gateway at HOST:PORT "
                         "(repeatable; wasmedge_tpu/fleet/: peer-"
                         "replicated module store, rendezvous request "
                         "routing, journal-replicated failover, "
                         "cross-host lane migration)", "host:port"))
    p.add_option(["fleet-heartbeat"],
                 Option("peer heartbeat interval in seconds "
                        "(default 0.25; drives the suspect->dead "
                        "liveness state machine)", "s", typ=float))
    p.add_positional("wasm_file", "guest module registered as 'main'",
                     required=False)
    return p


def gateway_command(argv: List[str], out=None, err=None) -> int:
    """`wasmedge-tpu gateway [app.wasm] [options]`: serve the gateway
    until SIGINT (or --duration), printing one JSON line with the
    bound address at startup and one summary line at shutdown."""
    import json
    import time as _time

    out = out or sys.stdout
    err = err or sys.stderr
    p = _gateway_parser()
    try:
        if not p.parse(argv, out):
            return 0
        if p.rest:   # same trailing-options idiom as serve_command
            trailing, p.rest = p.rest, []
            if not p.parse(trailing, out):
                return 0
            if p.rest:
                raise ValueError(f"unexpected argument {p.rest[0]!r}")
    except ValueError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 2
    conf = Configure()
    conf.host_registrations.add(HostRegistration.Wasi)
    if p._opts["queue-capacity"].seen:
        conf.serve.queue_capacity = p._opts["queue-capacity"].value
    if p._opts["max-virtual-lanes"].seen:
        conf.hv.max_virtual_lanes = p._opts["max-virtual-lanes"].value
    if p._opts["resident-budget-bytes"].seen:
        conf.hv.resident_budget_bytes = \
            p._opts["resident-budget-bytes"].value
    if p._opts["compact"].value:
        conf.batch.compact = True
    if p._opts["suspend"].value:
        conf.effects.suspend = True
    if p._opts["audit"].value:
        conf.integrity.audit = True
    if p._opts["scrub"].seen and p._opts["scrub"].value > 0:
        conf.integrity.scrub = True
        conf.integrity.scrub_interval_s = p._opts["scrub"].value
    if p._opts["obs"].value:
        conf.obs.enabled = True

    from wasmedge_tpu.gateway import Gateway, GatewayService, \
        GatewayTenants

    tenants = None
    if p._opts["tenants"].seen:
        try:
            tenants = GatewayTenants.from_file(p._opts["tenants"].value)
        except (OSError, ValueError, KeyError) as e:
            err.write(f"wasmedge-tpu: bad tenants file: {e}\n")
            return 2
    if p._opts["resume"].value and not p._opts["state-dir"].seen:
        err.write("wasmedge-tpu: --resume requires --state-dir\n")
        return 2
    # the fleet controller is ALWAYS on for the CLI gateway (a no-peer
    # FleetConfig is inert and pinned bit-identical to a non-federated
    # gateway): the /v1/fleet/* routes must answer even on a gateway
    # started without --peer, or a peer that lists THIS address could
    # never introduce itself and one-directional configs would never
    # converge
    from wasmedge_tpu.fleet import FleetConfig

    fleet = FleetConfig(
        peers=p._opts["peer"].value,
        heartbeat_s=p._opts["fleet-heartbeat"].value
        if p._opts["fleet-heartbeat"].seen else 0.25)
    try:
        svc = GatewayService(
            conf=conf, lanes=p._opts["lanes"].value, tenants=tenants,
            devices=p._opts["devices"].value,
            state_dir=p._opts["state-dir"].value,
            resume=p._opts["resume"].value,
            build_timeout_s=p._opts["build-timeout"].value
            if p._opts["build-timeout"].seen else 120.0,
            result_cache=p._opts["result-cache"].value
            if p._opts["result-cache"].seen else 4096,
            fleet=fleet)
    except (WasmError, ValueError, OSError) as e:
        err.write(f"wasmedge-tpu: gateway resume failed: {e}\n")
        return 1
    boot = []
    if p.positional_values:
        boot.append(("main", p.positional_values[0]))
    for spec in p._opts["module"].value:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            err.write(f"wasmedge-tpu: bad --module {spec!r} "
                      f"(want NAME=PATH)\n")
            return 2
        boot.append((name, path))
    entries = []
    for name, path in boot:
        try:
            with open(path, "rb") as f:
                entries.append((name, f.read()))
        except OSError as e:
            err.write(f"wasmedge-tpu: cannot read {path}: {e}\n")
            return 1
    if p._opts["resume"].value:
        # a restart reuses the SAME command line (systemd et al.): boot
        # modules the manifest already restored must not re-register
        # and collide with themselves
        restored = set(svc.registry.names)
        entries = [(n, b) for n, b in entries if n not in restored]
    if entries:
        try:
            # ONE generation for the whole boot set — not a build-and-
            # drain per module
            svc.preload(entries)
        except (WasmError, ValueError) as e:
            err.write(f"wasmedge-tpu: boot module rejected: {e}\n")
            return 1
    # truthful-health boot gate: a dead driver thread or a terminally
    # failed boot generation must fail the command, not silently serve
    # 503s until someone notices (the /healthz fix's CLI half)
    health = svc.health()
    if health["status"] == "unhealthy":
        bad = "; ".join(c["detail"] for c in health["checks"].values()
                        if not c["ok"])
        err.write(f"wasmedge-tpu: gateway unhealthy after boot: "
                  f"{bad}\n")
        svc.shutdown(drain=False)
        return 1
    try:
        gw = Gateway(svc, host=p._opts["host"].value,
                     port=p._opts["port"].value).start()
    except OSError as e:
        err.write(f"wasmedge-tpu: cannot bind: {e}\n")
        svc.shutdown(drain=False)
        return 1
    out.write(json.dumps({
        "listening": f"http://{gw.host}:{gw.port}",
        "modules": svc.registry.names,
        "lanes": svc.lanes,
        "tenants": sorted(svc.tenants.policies),
        "health": health["status"],
        "durable": svc.durable is not None,
        "restarts": svc.counters["restarts"],
        "resumed_requests": svc.counters["resumed"],
        "fleet_peers": sorted(svc.fleet.peers)
        if svc.fleet is not None else None,
    }) + "\n")
    out.flush()
    duration = p._opts["duration"].value
    try:
        if duration is not None:
            _time.sleep(duration)
        else:
            while True:
                _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        gw.shutdown(drain=True)
    st = svc.status()
    out.write(json.dumps({"metric": "gateway_exit",
                          **st["gateway"], "http": st["http"]}) + "\n")
    return 0


def _analyze_parser() -> ArgumentParser:
    p = ArgumentParser("wasmedge-tpu analyze",
                       "static bytecode analysis over the lowered "
                       "image: per-function CFG, cost/gas bounds, "
                       "loop/recursion verdicts, hostcall inventory, "
                       "divergence scores, footprint bounds")
    p.add_option("disasm",
                 Toggle("include the block-annotated disassembly in "
                        "the report (\"disasm\" key)"))
    p.add_option(["out"],
                 Option("write the JSON report to a file instead of "
                        "stdout", "path"))
    p.add_option(["compact"],
                 Toggle("one-line JSON (default pretty-prints)"))
    p.add_positional("wasm_file", "WebAssembly file to analyze")
    return p


def analyze_command(argv: List[str], out=None, err=None) -> int:
    """`wasmedge-tpu analyze app.wasm [--disasm] [--out report.json]`:
    load + validate (no instantiation — unlinkable imports still
    analyze), run the static analyzer over the lowered image, and emit
    the JSON report (wasmedge-tpu/analysis/v1 schema)."""
    import json

    out = out or sys.stdout
    err = err or sys.stderr
    p = _analyze_parser()
    try:
        if not p.parse(argv, out):
            return 0
        if p.rest:   # same trailing-options idiom as serve_command
            trailing, p.rest = p.rest, []
            if not p.parse(trailing, out):
                return 0
            if p.rest:
                raise ValueError(f"unexpected argument {p.rest[0]!r}")
    except ValueError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 2
    path = p.positional_values[0]
    conf = Configure()
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        err.write(f"wasmedge-tpu: cannot read {path}: {e}\n")
        return 1
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.validator import Validator

    try:
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
    except WasmError as e:
        err.write(f"wasmedge-tpu: load failed: {e.formatted()}\n")
        return 1
    from wasmedge_tpu.analysis import analyze_validated

    analysis = analyze_validated(mod)
    report = analysis.to_dict()
    report["file"] = path
    # superinstruction translation view (batch/fuse.py): plan the fused
    # dispatch cells the batch engine would realize, so the report
    # shows planned-vs-realized per candidate.  numpy-only (no jax);
    # a planner failure degrades to a report without the section.
    fusion = None
    try:
        from wasmedge_tpu.batch.fuse import plan_fusion
        from wasmedge_tpu.batch.image import build_device_image

        img = build_device_image(mod.lowered, mod=mod)
        fusion = plan_fusion(img, conf.batch, analysis=analysis)
        report["fusion"] = fusion
    except Exception as e:  # advisory section, never a CLI failure
        err.write(f"wasmedge-tpu: fusion planning skipped: {e!r}\n")
    if p._opts["disasm"].value:
        report["disasm"] = analysis.annotated_disasm(mod.lowered,
                                                     fusion=fusion)
    text = json.dumps(report,
                      indent=None if p._opts["compact"].value else 2)
    if p._opts["out"].seen:
        from wasmedge_tpu.utils.fsio import atomic_write_bytes

        atomic_write_bytes(p._opts["out"].value, (text + "\n").encode())
        out.write(f"written: {p._opts['out'].value}\n")
    else:
        out.write(text + "\n")
    return 0


def compile_command(argv: List[str], out=None, err=None) -> int:
    out = out or sys.stdout
    err = err or sys.stderr
    p = ArgumentParser("wasmedge-tpu compile",
                       "precompile wasm to a universal twasm artifact")
    p.add_option("dump", Toggle("dump the lowered image disassembly"))
    p.add_option(["no-cache"], Toggle("bypass the content-addressed cache"))
    p.add_positional("in_wasm", "input wasm file")
    p.add_positional("out_wasm", "output artifact", required=False)
    try:
        if not p.parse(argv, out):
            return 0
    except ValueError as e:
        err.write(f"wasmedge-tpu: {e}\n")
        return 2

    from wasmedge_tpu import aot

    with open(p.positional_values[0], "rb") as f:
        data = f.read()
    try:
        artifact = (aot.compile_module(data) if p._opts["no-cache"].value
                    else aot.compile_cached(data))
    except WasmError as e:
        err.write(f"wasmedge-tpu: compile failed: {e}\n")
        return 1
    if p._opts["dump"].value:
        from wasmedge_tpu.loader.loader import Loader
        from wasmedge_tpu.validator.validator import Validator

        mod = Validator().validate(Loader().parse_module(artifact))
        out.write(mod.lowered.disasm() + "\n")
    if len(p.positional_values) > 1:
        with open(p.positional_values[1], "wb") as f:
            f.write(artifact)
        out.write(f"written: {p.positional_values[1]} "
                  f"({len(artifact)} bytes)\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        sys.stdout.write(
            "usage: wasmedge-tpu [run|serve|gateway|analyze|compile|"
            "version] ...\n"
            "  run      run a wasm file (default when first arg is a file)\n"
            "  serve    continuous-batching serving over device lanes\n"
            "  gateway  HTTP multi-tenant serving gateway (runtime module\n"
            "           registration, per-tenant auth/rate/quota)\n"
            "  analyze  static bytecode analysis: CFG/cost/divergence\n"
            "           JSON report over the lowered image\n"
            "  compile  precompile to a universal twasm artifact\n"
            "  version  print version\n")
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "run":
        return run_command(rest)
    if cmd == "serve":
        return serve_command(rest)
    if cmd == "gateway":
        return gateway_command(rest)
    if cmd == "analyze":
        return analyze_command(rest)
    if cmd == "compile":
        return compile_command(rest)
    if cmd == "version":
        import wasmedge_tpu

        sys.stdout.write(f"wasmedge-tpu {wasmedge_tpu.__version__}\n")
        return 0
    return run_command(argv)  # implicit run: wasmedge-tpu app.wasm ...


if __name__ == "__main__":
    sys.exit(main())
