"""Configure: proposals, host registrations, engine selection, runtime knobs.

Mirrors the reference Configure (/root/reference/include/common/configure.h:
173-260): a proposal bitset with the same defaults, host-registration set,
and sub-configs. The TPU-native addition is `EngineKind` — the engine-switch
seam the north star requires (interpreter / batch TPU / native scalar),
playing the role of the reference's interpreter/AOT selection.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class Proposal(enum.Enum):
    ImportExportMutGlobals = "mutable-globals"
    NonTrapFloatToIntConversions = "nontrap-f2i"
    SignExtensionOperators = "sign-extension"
    MultiValue = "multi-value"
    BulkMemoryOperations = "bulk-memory"
    ReferenceTypes = "reference-types"
    SIMD = "simd"
    TailCall = "tail-call"
    MultiMemories = "multi-memories"
    Annotations = "annotations"
    Memory64 = "memory64"
    ExceptionHandling = "exception-handling"
    Threads = "threads"
    FunctionReferences = "function-references"

    @property
    def gate_name(self) -> str:
        return self.value


# Defaults match the reference (configure.h:175-183).
DEFAULT_PROPOSALS = frozenset(
    {
        Proposal.ImportExportMutGlobals,
        Proposal.NonTrapFloatToIntConversions,
        Proposal.SignExtensionOperators,
        Proposal.MultiValue,
        Proposal.BulkMemoryOperations,
        Proposal.ReferenceTypes,
        Proposal.SIMD,
    }
)


class HostRegistration(enum.Enum):
    Wasi = "wasi"
    WasmEdgeProcess = "wasmedge_process"


class EngineKind(enum.Enum):
    SCALAR = "scalar"  # Python reference interpreter (oracle)
    NATIVE = "native"  # C++ scalar engine over the lowered image
    TPU_BATCH = "tpu_batch"  # SIMT lockstep JAX/Pallas engine
    AUTO = "auto"  # batch when module is batchable, else native/scalar


@dataclasses.dataclass
class RuntimeConfigure:
    max_memory_pages: int = 65536
    max_call_depth: int = 2048
    max_value_stack: int = 65536


@dataclasses.dataclass
class StatisticsConfigure:
    instr_counting: bool = False
    cost_measuring: bool = False
    time_measuring: bool = False
    cost_limit: int = (1 << 64) - 1


@dataclasses.dataclass
class BatchConfigure:
    """Knobs for the tpu_batch engine (no analog in the reference)."""

    lanes: int = 4096  # instances per chip
    value_stack_depth: int = 1024  # 64-bit slots per lane
    call_stack_depth: int = 512  # frames per lane
    memory_pages_per_lane: int = 1  # 64 KiB pages of linear memory per lane
    # table.grow capacity cap per lane (like memory_pages_per_lane: a
    # static HBM ceiling; grow beyond it returns -1, which the spec
    # allows at any size)
    table_elems_per_lane: int = 4096
    steps_per_launch: int = 1024  # device steps per host-loop iteration
    fuel_per_launch: Optional[int] = None  # per-lane fuel budget (gas analog)
    # per-opcode gas weights (Statistics cost-table bridge, set by the
    # VM/C-API batch entries when cost measuring is on; None = flat 1)
    cost_table: Optional[tuple] = None
    uniform: bool = True  # converged-lane fast path (scalar PC dispatch)
    interpret: bool = False  # run Pallas kernels in interpreter mode
    # Pallas warp-interpreter selection: None = auto (on whenever the
    # backend is TPU and the module fits the kernel's geometry), True =
    # force (interpret-mode on CPU), False = always per-step XLA.
    use_pallas: Optional[bool] = None
    # Pallas linear-memory placement: None = auto (HBM-resident plane +
    # VMEM window cache whenever that enlarges the lane block), True/False
    # force.  Only meaningful for modules with a memory.
    mem_hbm: Optional[bool] = None
    # Optimistic convergence (lane-0 decisions + canary validation at
    # commit points instead of per-instruction cross-lane reductions).
    # None = on; False forces the per-step-checked ("careful") kernel.
    optimistic: Optional[bool] = None
    # Basic-block fusion in the Pallas kernel: straight-line runs of
    # pure stack ops compile into single handlers that keep
    # intermediates in vector registers (one dispatch per block instead
    # of one per instruction).  None = on; False falls back to the
    # legacy peephole superinstruction fuser.
    block_fusion: Optional[bool] = None
    # --- SIMT-tier superinstruction fusion (batch/fuse.py) ---
    # Rewrite the analyzer's top straight-line candidates into fused
    # dispatch cells at image-build time: ONE _make_step dispatch
    # retires the whole run's stack effects (each constituent op keeps
    # its op_id for gas/opcode-histogram attribution).  Off compiles
    # the bit-identical seed per-op step; results are bit-identical
    # either way (pinned against the scalar engine and the unfused
    # SIMT build, tests/test_fuse.py).
    fuse_superinstructions: bool = True
    # How many ranked analyzer candidates the translation pass consumes
    # (ModuleAnalysis.superinstructions order: saved_dispatches).
    fuse_top_k: int = 12
    # Distinct fused (class, sub) cell patterns compiled into one step
    # function (each pattern is a specialized straight-line handler;
    # more patterns = bigger traced step).
    fuse_max_patterns: int = 8
    # Down-weight fusion candidates whose occurrences sit in
    # high-divergence blocks (the analyzer's r12 per-block scores):
    # ranking key becomes saved_dispatches / (1 + bias * block_score).
    # 0.0 (the default) is bit-identical to unbiased planning.
    fuse_divergence_bias: float = 0.0
    # --- memory-run fusion (r19, batch/fuse.py + analysis/absint.py) ---
    # Fuse straight-line runs CONTAINING loads/stores whose every
    # access the abstract interpreter licensed (proven in-bounds
    # against the module's minimum memory and word-aligned — the run
    # can never trap): the fused cell does one gather/scatter per
    # access instead of the per-op three-word RMW window, and one
    # dispatch retires the whole run.  Unlicensed sites always stay on
    # the per-op path; results are bit-identical either way
    # (tests/test_memfuse.py).
    fuse_memory_runs: bool = True
    # Distinct fused memory-run patterns per image (on top of
    # fuse_max_patterns for the pure tier), and the per-run cell cap.
    memfuse_max_patterns: int = 8
    memfuse_max_run: int = 24
    # --- whole-function tier-up compilation (r20, batch/tierup.py) ---
    # Promote the hottest COMPILABLE whole functions out of the any-lane
    # dispatch switch: each promoted function becomes a lane-masked
    # jitted CFG body (block dispatch inside a bounded lax.while_loop,
    # trip bounds licensed by the r19 abstract interpreter) so a call
    # costs ONE dispatch instead of one per retired op.  Promotion is
    # conservative — leaf functions whose every op is pure-eligible or
    # an absint-licensed load, with a finite analyzer cost bound — and
    # unpromoted code keeps the per-op/fused path.  Off compiles the
    # bit-identical seed step by construction; results are bit-identical
    # either way (tests/test_tierup.py).
    tierup: bool = True
    # How many verdict-passing functions the planner promotes, ranked
    # hottest-first (realized fusion-run weight, then cost bound).
    tierup_top_k: int = 4
    # Compiled-body size caps: candidates whose CFG exceeds either cap
    # keep the interpreted path (bigger bodies = bigger traced step).
    tierup_max_blocks: int = 16
    tierup_max_ops: int = 128
    # --- divergence-aware lane compaction (batch/compact.py) ---
    # Sort/permute live lanes by (divergence-score bias, pc) at launch
    # boundaries via one jitted gather-permutation, packing live lanes
    # to a contiguous prefix (retired lanes stop occupying dispatch
    # width on fixed-cohort runs — the step loop narrows to the live
    # prefix).  Off (the default) compiles and executes the exact seed
    # path; results are bit-identical either way for lane-placement-
    # independent guests (tier-0 random_get keys on the physical lane
    # index — the recycling/hv scoping caveat).
    compact: bool = False
    # Anti-thrash quantum: at least this many launch boundaries between
    # compactions (the hv min_resident_rounds shape).
    compact_min_interval: int = 2
    # Sorting trigger: adjacent-key breaks removable by a sort must
    # exceed this fraction of the live lanes.
    compact_trigger: float = 0.05
    # Cost model: the estimated win (removable breaks x steps per
    # launch) must exceed factor x lane-width copy cost; 0 fires on
    # every eligible boundary (tests).
    compact_cost_factor: float = 4.0
    # Live-prefix dispatch-width narrowing (fixed-cohort runs, single
    # device): retraces the step per power-of-two width, so the floor
    # bounds compile count and the smallest useful slice.
    compact_narrow: bool = True
    compact_width_floor: int = 64
    # --- three-tier hostcall pipeline knobs (batch/hostcall.py) ---
    # Tier 0: service pure WASI calls (clock_time_get / random_get /
    # sched_yield / proc_exit / fd_write-to-buffered-stdout) directly in
    # the SIMT kernel — they cost a dispatch slot, not a device<->host
    # round trip.  False parks every hostcall on the outcall channel.
    tier0_hostcalls: bool = True
    # Seed for the in-kernel counter-PRNG behind tier-0 random_get
    # (deterministic per (seed, lane, call, word)).  None (the default)
    # draws fresh entropy once per Configure, so guests get
    # unpredictable bytes run-to-run like the os.urandom-backed scalar
    # and tier-1 paths; set an explicit seed for reproducible runs.
    rng_seed: Optional[int] = None
    # Per-lane in-device stdout record buffer, in 4-byte words (tier-0
    # fd_write appends records here; the host drains them at flush
    # points).  Writes that would overflow the buffer park on the
    # tier-1 channel instead (after a flush they fit again).
    stdout_buffer_words: int = 2048
    # Max bytes of one tier-0 fd_write iovec / random_get request the
    # kernel services inline; longer requests park on tier 1.
    tier0_write_max: int = 256
    tier0_random_max: int = 64
    # Tier-1 vectorized drain: group parked lanes by hostcall and serve
    # each group with SoA-vectorized NumPy WASI implementations
    # (host/wasi/vectorized.py) instead of the per-lane Python loop.
    vectorized_hostcalls: bool = True
    # v128 SIMT-residue quarantine (batch/scheduler.py): the XLA
    # per-step v128 fallback is known to fault TPU workers on very long
    # runs, so a divergent v128 tenant's residue is capped at this many
    # further steps; lanes still running at the cap re-run on the
    # scalar engine when side-effect-free, else trap CostLimitExceeded.
    # None disables the cap.
    v128_residue_step_cap: Optional[int] = 1_000_000


@dataclasses.dataclass
class ObsConfigure:
    """Knobs for the batch observability subsystem (wasmedge_tpu/obs/).

    When `enabled` is False every instrumentation seam holds the no-op
    NULL_RECORDER guard object — hot loops pay no per-step Python
    branching and no allocation (the bit-identical-output contract with
    the seed engines is pinned by tests/test_obs.py)."""

    # Master switch: create a FlightRecorder and report launch/serve/
    # split/checkpoint/failure events + hostcall latency histograms.
    enabled: bool = False
    # Bounded event ring capacity (oldest events dropped beyond it;
    # the drop count is exported).
    ring_capacity: int = 65536
    # Device-side per-opcode histogram plane (SIMT engine): one extra
    # [code_len] int32 plane scatter-incremented per step, folded into
    # per-opcode retired counts (Statistics cost_table domain) on sync.
    # Costs one scatter-add per step — leave off unless attributing
    # hot opcodes.
    opcode_histogram: bool = False
    # Export paths applied by VM.execute_batch / the CLI after a run
    # (None = no file export; the recorder stays queryable in-process).
    trace_out: Optional[str] = None
    metrics_out: Optional[str] = None
    # Lazily-created shared FlightRecorder (obs/recorder.py
    # recorder_of); identity is preserved across Configure deepcopies.
    _recorder: object = dataclasses.field(
        default=None, init=False, repr=False, compare=False)


@dataclasses.dataclass
class SupervisorConfigure:
    """Knobs for supervised batch execution (batch/supervisor.py).

    The supervisor wraps long-lived batch runs with automatic
    checkpointing, retry-with-backoff, and an engine-degradation ladder
    (Pallas -> jit SIMT -> gas-metered scalar); structured
    FailureRecords land in common/statistics.py."""

    # --- checkpoint cadence (batch/checkpoint.py snapshots) ---
    # Take a checkpoint every N retired-step slice boundary (rounded up
    # to whole steps_per_launch chunks).  None = no step cadence.
    checkpoint_every_steps: Optional[int] = None
    # ... or every S seconds of wall clock, whichever fires first.
    checkpoint_every_s: Optional[float] = None
    # Where snapshots land ("ckpt-<steps>.npz", written atomically via a
    # temp file + os.replace).  None with a cadence set auto-creates a
    # temp directory (recorded on the supervisor as .checkpoint_dir).
    checkpoint_dir: Optional[str] = None
    # Lineage depth: older snapshots beyond this count are pruned.  A
    # corrupted newest snapshot falls back to the next in the lineage.
    keep_checkpoints: int = 2
    # --- retry / backoff ---
    # Consecutive failed attempts (no forward progress) before the
    # current engine tier is abandoned and the run demotes a tier.
    max_retries: int = 3
    # Exponential backoff between retries: min(backoff_max_s,
    # backoff_base_s * backoff_factor**(attempt-1)).
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 2.0
    # --- per-lane quarantine ---
    # A failure attributed to a concrete lane set (exceptions carrying a
    # .lanes attribute, e.g. from the fault-injection harness) repeating
    # this many times quarantines those lanes — demoted to the scalar
    # engine when the module is side-effect-free, else terminated with
    # ErrCode.Terminated — instead of sinking the whole batch.
    poison_lane_retries: int = 2
    # A lane still running after retiring this many instructions is
    # terminated (ErrCode.Terminated) and recorded as a "runaway" —
    # the generalization of the r6 v128_residue_step_cap quarantine.
    # None disables the cap.
    lane_step_cap: Optional[int] = None
    # --- ladder gates ---
    # Attempt the Pallas/BlockScheduler kernel tier first when eligible
    # (single-module, pallas enabled).  Checkpoint cadence only applies
    # on the SIMT tier, whose BatchState the checkpoint layer snapshots.
    use_kernel_tier: bool = True
    # Allow the bottom rung: whole-batch gas-metered scalar re-execution
    # (side-effect-free single-module batches only).
    allow_scalar_tier: bool = True
    # --- cross-process resume ---
    # Adopt an existing checkpoint_dir lineage at startup: scan for
    # ckpt-*.npz members, pick the newest that loads cleanly, and
    # record skipped/corrupt members as FailureRecord("checkpoint").
    # The run then continues from that snapshot on the SIMT tier (the
    # kernel tier cannot resume mid-state).  CLI: --resume.
    resume: bool = False
    # Attempt the single-program shard drive first on supervised mesh
    # runs (parallel/shard_drive.py: ONE jitted program over the named
    # mesh, lane planes sharded on the `lanes` axis).  Any shard-drive
    # failure demotes to the threaded per-device rungs below it;
    # cadence-configured (checkpointing) and resumed runs skip straight
    # to the per-device SIMT tier, whose states the coordinated
    # checkpoints snapshot.
    use_shard_drive: bool = True
    # --- mesh-level fault tolerance (parallel/supervisor.py) ---
    # Consecutive failed slices on ONE device of a supervised sharded
    # drive before that device is ejected from the mesh (its lanes
    # migrate to surviving devices).  Retries between failures back off
    # with the shared backoff_* formula above.
    max_device_retries: int = 2
    # Elastic shrink: eject a repeatedly-failing device and migrate its
    # lanes onto survivors.  False = fail fast instead — the whole mesh
    # run cancels cooperatively (sibling devices stop at their next
    # launch boundary) and raises with per-device attribution; some
    # operators prefer visible capacity loss over silent shrink.
    eject_devices: bool = True


@dataclasses.dataclass
class ServeConfigure:
    """Knobs for the continuous-batching serving layer (wasmedge_tpu/serve/).

    A BatchServer owns a bounded request queue, packs queued requests
    into device lanes, and recycles lanes the moment they retire
    instead of waiting for batch drain; per-tenant weighted-fair
    admission, deadlines, and backpressure live here."""

    # Bounded request queue: submit() beyond this many QUEUED (not yet
    # admitted) requests is rejected with QueueSaturated (ErrCode
    # backpressure, never silent drops).
    queue_capacity: int = 65536
    # Per-request retired-instruction budget: a request still running
    # past it is terminated with CostLimitExceeded (runaway guard; the
    # serving loop has no natural max_steps to drain to).
    max_steps_per_request: int = 10_000_000
    # Checkpoint the serving state every N serving rounds (the server's
    # analog of SupervisorConfigure cadence; each round is one
    # steps_per_launch slice).  None = only on demand.
    checkpoint_every_rounds: Optional[int] = None
    checkpoint_dir: Optional[str] = None
    keep_checkpoints: int = 2
    # Retry budget for launch/serve failures before the server gives up
    # and fails the in-flight futures (restores from the newest good
    # checkpoint, else re-queues the in-flight requests from scratch).
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 2.0
    # --- steps_per_launch auto-tuning (serve/autotune.py) ---
    # Feedback rule driven by the tier-1 hostcall drain-latency
    # histograms (obs/): expensive drains relative to device launch
    # time grow the chunk (amortize serve overhead), cheap drains with
    # parked lanes shrink it (serve sooner).  Off by default; every
    # adjustment is logged to the flight recorder as an "autotune"
    # instant.  Changing the chunk rebuilds the jitted step loop, so
    # adjustments are power-of-two quantized and bounded.
    autotune: bool = False
    autotune_min_chunk: int = 64
    autotune_max_chunk: int = 1 << 20


@dataclasses.dataclass
class HvConfigure:
    """Knobs for lane-memory virtualization (wasmedge_tpu/hv/).

    The serving layer's hypervisor mode: admitted requests beyond the
    physical lane count (or beyond the resident-bytes budget) wait as
    VIRTUAL lanes whose state lives host-side, swapping onto free
    physical lanes at launch boundaries.  Off (the default: both
    capacity knobs None) the BatchServer behaves exactly as before —
    admission is the free-lane heap, nothing ever swaps."""

    # Concurrent admitted requests (resident + virtual).  None = the
    # physical lane count (no oversubscription).  CLI:
    # --max-virtual-lanes.
    max_virtual_lanes: Optional[int] = None
    # Device bytes the resident population may hold: admission installs
    # at most floor(budget / effective-lane-bytes) physical lanes
    # (effective bytes seeded from DeviceImage.analysis footprint
    # bounds when the analyzer proved them, else the allocated plane
    # geometry — hv/policy.py).  None = every physical lane may be
    # resident.  CLI: --resident-budget-bytes.
    resident_budget_bytes: Optional[int] = None
    # SwapStore spill directory (content-addressed .lane blobs, crash-
    # atomic writes).  None keeps blobs in host memory only — serve
    # checkpoints still embed them, so crash/resume does not depend on
    # this knob.
    swap_dir: Optional[str] = None
    # Anti-thrash: a lane must have held the device for this many
    # serving rounds (launch slices) before it is evictable.
    min_resident_rounds: int = 1
    # Evictions per boundary rebalance (None = up to the lane count).
    max_swaps_per_round: Optional[int] = None

    @property
    def active(self) -> bool:
        return self.max_virtual_lanes is not None \
            or self.resident_budget_bytes is not None


@dataclasses.dataclass
class EffectsConfigure:
    """Knobs for the suspend/resume effect subsystem
    (wasmedge_tpu/effects/, r23).

    Off (the default) the serving stack runs the exact r22 path:
    blocking hostcalls (`poll_oneoff` sleeps, `await_event`) are served
    in place by the host layer and nothing ever parks, so behavior is
    bit-identical by construction."""

    # Master switch: lower blocking hostcalls into a PARKED effect —
    # the lane serializes through the SwapStore at the next launch
    # boundary (zero resident cost) and resumes on wake.  CLI:
    # --effects.
    suspend: bool = False
    # Park a pure-clock poll_oneoff only when its minimum relative
    # timeout is at least this many seconds; shorter sleeps are served
    # in place (parking round-trip would dominate).
    min_park_timeout_s: float = 0.0
    # SwapStore spill directory for parked-session blobs.  None shares
    # the hv store when hv is active, else keeps blobs in host memory —
    # serve checkpoints embed them either way, so crash/resume does not
    # depend on this knob.
    swap_dir: Optional[str] = None
    # Per-session stdout stream replay buffer cap in bytes (the
    # gateway's GET /v1/requests/<id>/stream seam); oldest bytes fall
    # off first once exceeded.
    stream_buffer_bytes: int = 1 << 20

    @property
    def active(self) -> bool:
        return bool(self.suspend)


@dataclasses.dataclass
class ImagestoreConfigure:
    """Knobs for the segmented-image / compile-cache / snapshot
    subsystem (wasmedge_tpu/imagestore/, r22).

    All three default OFF: the off configuration runs the exact r21
    code path (concat_images builds every segment inline, the registry
    consults no disk cache, initial_state carries no overlays), so
    behavior is bit-identical by construction."""

    # Memoize per-module image segments across generation builds: a
    # generation swap re-uses every already-built segment verbatim and
    # only builds the new module's (the indirection table is the bases
    # list).  CLI: --imagestore-segmented.
    segmented: bool = False
    # Persistent cross-process compile cache: registration consults a
    # sha256-keyed serialized-image cache before lowering, and stores
    # fresh lowerings back.  Entries fleet-replicate alongside module
    # blobs (GET /v1/fleet/cache/<sha>).  CLI: --compile-cache.
    compile_cache: bool = False
    # Cache directory.  None + a gateway state_dir -> <state_dir>/
    # compilecache; None without one -> in-memory only (still unifies
    # the probe tier and serves fleet replication, but does not
    # survive a process restart).
    compile_cache_dir: Optional[str] = None
    # Pre-initialized lane snapshots: run a module's _initialize/_start
    # once at registration, capture the post-init plane columns
    # (content-addressed SwapStore entry sized by the r19 page-touch
    # bound), and install that snapshot into admitted lanes through the
    # existing jitted column-set pass.  CLI: --snapshots.
    snapshots: bool = False
    # Snapshot SwapStore spill directory (None = host memory only).
    snapshot_dir: Optional[str] = None
    # Step budget for the one-time registration init run; a module
    # whose init exceeds it (or traps) simply gets no snapshot and
    # admits through the r21 template path.
    snapshot_init_max_steps: int = 2_000_000

    @property
    def active(self) -> bool:
        return self.segmented or self.compile_cache or self.snapshots


@dataclasses.dataclass
class IntegrityConfigure:
    """Knobs for the silent-data-corruption defense subsystem
    (wasmedge_tpu/integrity/, r24).

    Both legs default OFF: with neither the shadow auditor nor the
    scrubber enabled no hook is installed anywhere on the launch path
    and no background thread starts, so behavior is bit-identical to
    r23 by construction."""

    # Shadow-audit lanes: at seeded launch boundaries, export a small
    # lane subset's pre-slice planes, re-execute the identical slice
    # through a reference re-trace of the same step program at the
    # sampled width, and compare the post-slice planes bit-exact.  A
    # divergence raises an SDC incident (FailureRecord "integrity",
    # rollback to the newest good checkpoint, per-device attribution).
    # CLI: --integrity-audit.
    audit: bool = False
    # Seed for the boundary/lane sampler (deterministic given the seed
    # and the boundary index).
    audit_seed: int = 0
    # Audit roughly one in this many launch boundaries (1 = every
    # boundary; the sampler hashes seed+boundary so the audited set is
    # stable, not periodic).
    audit_every: int = 16
    # Lanes sampled per audited boundary.
    audit_lanes: int = 2
    # Divergences attributed to one device before the quarantine
    # ladder ejects it through the r21 reshard path.
    quarantine_threshold: int = 3
    # At-rest scrubber: re-verify sha256 over SwapStore entries
    # (parked r23 sessions included), checkpoint lineage members, and
    # WTIC compile-cache entries before a wake/restore needs them.
    # CLI: --integrity-scrub.
    scrub: bool = False
    # Background scrub cadence in seconds; 0 disables the thread
    # (scrub_once() stays callable — tests and the bench drive it
    # manually).
    scrub_interval_s: float = 0.0
    # Repair a failed local copy from fleet peer replicas
    # (GET /v1/fleet/cache/<sha> for compile-cache entries,
    # GET /v1/fleet/blob/<key> for swap blobs) before falling back to
    # evict + fresh-lower / init-replay.
    scrub_repair: bool = True

    @property
    def active(self) -> bool:
        return bool(self.audit or self.scrub)


@dataclasses.dataclass
class CompilerConfigure:
    """AOT-compiler knobs (reference: CompilerConfigure,
    include/common/configure.h:28-106).  The optimization level and
    native-output knobs are accepted for API parity; the tpu.aot
    artifact path (wasmedge_tpu.aot) is the compiler they configure —
    its universal artifact corresponds to OutputFormat "Universal", and
    "Native" has no TPU analog (XLA owns native codegen), so setting it
    is recorded but compile_module always emits universal twasm."""

    optimization_level: str = "O3"   # O0|O1|O2|O3|Os|Oz
    output_format: str = "Universal"  # Universal | Native
    dump_ir: bool = False
    generic_binary: bool = False
    interruptible: bool = False


@dataclasses.dataclass
class Configure:
    proposals: set = dataclasses.field(default_factory=lambda: set(DEFAULT_PROPOSALS))
    host_registrations: set = dataclasses.field(default_factory=set)
    engine: EngineKind = EngineKind.AUTO
    runtime: RuntimeConfigure = dataclasses.field(default_factory=RuntimeConfigure)
    statistics: StatisticsConfigure = dataclasses.field(default_factory=StatisticsConfigure)
    batch: BatchConfigure = dataclasses.field(default_factory=BatchConfigure)
    supervisor: SupervisorConfigure = dataclasses.field(
        default_factory=SupervisorConfigure)
    obs: ObsConfigure = dataclasses.field(default_factory=ObsConfigure)
    serve: ServeConfigure = dataclasses.field(default_factory=ServeConfigure)
    hv: HvConfigure = dataclasses.field(default_factory=HvConfigure)
    effects: EffectsConfigure = dataclasses.field(
        default_factory=EffectsConfigure)
    imagestore: ImagestoreConfigure = dataclasses.field(
        default_factory=ImagestoreConfigure)
    integrity: IntegrityConfigure = dataclasses.field(
        default_factory=IntegrityConfigure)
    compiler: CompilerConfigure = dataclasses.field(default_factory=CompilerConfigure)

    def add_proposal(self, p: Proposal) -> "Configure":
        self.proposals.add(p)
        return self

    def remove_proposal(self, p: Proposal) -> "Configure":
        self.proposals.discard(p)
        return self

    def has_proposal(self, p: Proposal) -> bool:
        return p in self.proposals

    def proposal_gates(self) -> frozenset:
        """Set of gate-name strings for loader/validator opcode gating."""
        return frozenset(p.gate_name for p in self.proposals)
