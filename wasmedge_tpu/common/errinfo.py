"""Structured error-context records (ErrInfo).

Mirrors the reference's ErrInfo record system
(/root/reference/include/common/errinfo.h:1-299, lib/common/
errinfo.cpp:1-274): a failure site attaches typed context records to the
error as it unwinds — file, byte offset, AST node, instruction, type
mismatch, boundary, proposal — and the CLI prints the chain under the
headline message, so a loader failure reads like

    wasmedge-tpu: load failed: malformed section id
        loading failed at byte offset 0x27
        while parsing section Code
        in file "app.wasm"

Records are plain dataclasses; `WasmError.with_info(...)` appends and
returns the error (usable in a raise expression), `format_records`
renders them.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass
class InfoFile:
    """errinfo.h InfoFile — which file was being processed."""

    path: str

    def __str__(self):
        return f'in file "{self.path}"'


@dataclasses.dataclass
class InfoLoading:
    """errinfo.h InfoLoading — byte offset the loader failed at."""

    offset: int

    def __str__(self):
        return f"loading failed at byte offset 0x{self.offset:x}"


@dataclasses.dataclass
class InfoAST:
    """errinfo.h InfoAST — which AST node was being parsed/checked."""

    node: str

    def __str__(self):
        return f"while parsing {self.node}"


@dataclasses.dataclass
class InfoInstruction:
    """errinfo.h InfoInstruction — opcode + offset/pc context."""

    opcode: str
    offset: Optional[int] = None
    pc: Optional[int] = None

    def __str__(self):
        where = ""
        if self.offset is not None:
            where = f" at byte offset 0x{self.offset:x}"
        elif self.pc is not None:
            where = f" at pc {self.pc}"
        return f"in instruction {self.opcode}{where}"


@dataclasses.dataclass
class InfoMismatch:
    """errinfo.h InfoMismatch — expected vs got (types, arities, limits)."""

    expected: str
    got: str

    def __str__(self):
        return f"expected {self.expected}, got {self.got}"


@dataclasses.dataclass
class InfoBoundary:
    """errinfo.h InfoBoundary — access range vs limit."""

    offset: int
    size: int
    limit: int

    def __str__(self):
        return (f"accessing [0x{self.offset:x}, "
                f"0x{self.offset + self.size:x}) exceeds limit "
                f"0x{self.limit:x}")


@dataclasses.dataclass
class InfoProposal:
    """errinfo.h InfoProposal — feature needs an off proposal."""

    proposal: str

    def __str__(self):
        return f"needs the {self.proposal!r} proposal enabled"


@dataclasses.dataclass
class InfoLimit:
    """errinfo.h InfoLimit — a declared limit is out of range."""

    has_max: bool
    min: int
    max: Optional[int] = None

    def __str__(self):
        if self.has_max and self.max is not None:
            return f"limit {{min {self.min}, max {self.max}}}"
        return f"limit {{min {self.min}}}"


def format_records(records: Sequence) -> str:
    """Render a record chain, one indented line each (errinfo.cpp's
    operator<< chain as printed by the reference CLI)."""
    return "\n".join(f"    {r}" for r in records)
