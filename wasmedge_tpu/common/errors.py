"""Error codes and exception model.

Mirrors the reference's ErrCode enum and Expect<T> discipline
(/root/reference/include/common/enum.inc:573-749, include/common/errcode.h):
every failure carries a stable ErrCode plus a human message. In Python we
raise; the C API layer converts exceptions back to codes. Trap codes double
as the per-lane trap values the batch engine stores in device state.
"""

from __future__ import annotations

import enum


class ErrCode(enum.IntEnum):
    Success = 0x00
    Terminated = 0x01  # stopped by user / StopToken

    # Load phase
    IllegalPath = 0x20
    ReadError = 0x21
    UnexpectedEnd = 0x22
    MalformedMagic = 0x23
    MalformedVersion = 0x24
    MalformedSection = 0x25
    SectionSizeMismatch = 0x26
    LengthOutOfBounds = 0x27
    JunkSection = 0x28
    IncompatibleFuncCode = 0x29
    IncompatibleDataCount = 0x2A
    DataCountRequired = 0x2B
    MalformedImportKind = 0x2C
    MalformedExportKind = 0x2D
    ExpectedZeroByte = 0x2E
    InvalidMut = 0x2F
    TooManyLocals = 0x30
    MalformedValType = 0x31
    MalformedElemType = 0x32
    MalformedRefType = 0x33
    MalformedUTF8 = 0x34
    IntegerTooLarge = 0x35
    IntegerTooLong = 0x36
    IllegalOpCode = 0x37
    IllegalGrammar = 0x38

    # Validation phase
    InvalidAlignment = 0x40
    TypeCheckFailed = 0x41
    InvalidLabelIdx = 0x42
    InvalidLocalIdx = 0x43
    InvalidFuncTypeIdx = 0x44
    InvalidFuncIdx = 0x45
    InvalidTableIdx = 0x46
    InvalidMemoryIdx = 0x47
    InvalidGlobalIdx = 0x48
    InvalidElemIdx = 0x49
    InvalidDataIdx = 0x4A
    InvalidRefIdx = 0x4B
    ConstExprRequired = 0x4C
    DupExportName = 0x4D
    ImmutableGlobal = 0x4E
    InvalidResultArity = 0x4F
    MultiTables = 0x50
    MultiMemories = 0x51
    InvalidLimit = 0x52
    InvalidMemPages = 0x53
    InvalidStartFunc = 0x54
    InvalidLaneIdx = 0x55

    # Instantiation phase
    ModuleNameConflict = 0x60
    IncompatibleImportType = 0x61
    UnknownImport = 0x62
    DataSegDoesNotFit = 0x63
    ElemSegDoesNotFit = 0x64

    # Execution phase (trap codes — these live in device lane state too)
    WrongInstanceAddress = 0x80
    WrongInstanceIndex = 0x81
    InstrTypeMismatch = 0x82
    FuncSigMismatch = 0x83
    DivideByZero = 0x84
    IntegerOverflow = 0x85
    InvalidConvToInt = 0x86
    TableOutOfBounds = 0x87
    MemoryOutOfBounds = 0x88
    Unreachable = 0x89
    UninitializedElement = 0x8A
    UndefinedElement = 0x8B
    IndirectCallTypeMismatch = 0x8C
    HostFuncFailed = 0x8D
    RefTypeMismatch = 0x8E
    UnalignedAtomicAccess = 0x8F
    CallStackExhausted = 0x90
    StackOverflow = 0x91
    CostLimitExceeded = 0x92  # gas / fuel exhausted
    WrongVMWorkflow = 0x93
    FuncNotFound = 0x94
    ExecutionFailed = 0x95
    NotValidated = 0x96
    # Static-analysis admission: a module's static bounds exceed the
    # registering tenant's policy (wasmedge_tpu/analysis/policy.py).
    # Gateway maps it to HTTP 400 with the violation list in the body.
    StaticPolicyViolation = 0x97


# Spec-test-compatible trap messages (the conformance harness matches these,
# reference: lib/common/errinfo.cpp + test/spec/spectest.cpp:150-210).
TRAP_MESSAGES = {
    ErrCode.DivideByZero: "integer divide by zero",
    ErrCode.IntegerOverflow: "integer overflow",
    ErrCode.InvalidConvToInt: "invalid conversion to integer",
    ErrCode.TableOutOfBounds: "out of bounds table access",
    ErrCode.MemoryOutOfBounds: "out of bounds memory access",
    ErrCode.Unreachable: "unreachable",
    ErrCode.UninitializedElement: "uninitialized element",
    ErrCode.UndefinedElement: "undefined element",
    ErrCode.IndirectCallTypeMismatch: "indirect call type mismatch",
    ErrCode.CallStackExhausted: "call stack exhausted",
    ErrCode.CostLimitExceeded: "cost limit exceeded",
    ErrCode.FuncSigMismatch: "function signature mismatch",
}


class WasmError(Exception):
    """Base for all phase errors; carries an ErrCode and an ErrInfo record
    chain (reference: include/common/errinfo.h:1-299 — context records
    attached as the error unwinds, printed by the CLI).

    `retryable` is the machine-readable half of the rejection contract:
    True means the SAME request can succeed later (transient
    backpressure — QueueSaturated sets it), False means retrying
    verbatim can never help (traps, permanent admission blocks,
    deadline expiry).  Callers branch on the flag, never on message
    text; the gateway maps it onto HTTP 429-vs-terminal and the CLI's
    backpressure loop retries only when it is set."""

    retryable: bool = False

    def __init__(self, code: ErrCode, msg: str = "", offset: int | None = None):
        self.code = ErrCode(code)
        self.offset = offset
        self.records: list = []
        text = msg or TRAP_MESSAGES.get(self.code, self.code.name)
        if offset is not None:
            text = f"{text} (at byte offset 0x{offset:x})"
            from wasmedge_tpu.common.errinfo import InfoLoading

            self.records.append(InfoLoading(offset))
        super().__init__(text)

    def with_info(self, *records) -> "WasmError":
        """Append context records; returns self (usable in `raise`)."""
        self.records.extend(records)
        return self

    def formatted(self) -> str:
        from wasmedge_tpu.common.errinfo import format_records

        text = str(self)
        if self.records:
            text += "\n" + format_records(self.records)
        return text


class LoadError(WasmError):
    pass


class ValidationError(WasmError):
    pass


class InstantiationError(WasmError):
    pass


class TrapError(WasmError):
    """Runtime trap: unwinds execution, maps 1:1 to a per-lane trap code."""


class EngineFailure(WasmError):
    """Supervised batch execution exhausted its retry budget and its
    engine-degradation ladder (batch/supervisor.py).  Carries the
    structured FailureRecord list of everything that was attempted so
    callers can export the incident taxonomy."""

    def __init__(self, msg: str = "", failures=()):
        super().__init__(ErrCode.ExecutionFailed, msg or
                         "supervised execution exhausted retries")
        self.failures = list(failures)


def trap(code: ErrCode, msg: str = ""):
    raise TrapError(code, msg)


def rejection_info(exc: BaseException) -> dict:
    """Structured machine-readable view of a rejection: stable ErrCode
    value + name, the retryable flag, an optional retry-after hint, and
    the human message LAST (clients must never parse it).  Non-WasmError
    exceptions map to ExecutionFailed/non-retryable so every failure
    path yields the same shape."""
    if isinstance(exc, WasmError):
        out = {
            "code": int(exc.code),
            "name": exc.code.name,
            "retryable": bool(getattr(exc, "retryable", False)),
            "message": str(exc),
        }
        after = getattr(exc, "retry_after_s", None)
        if after is not None:
            out["retry_after_s"] = float(after)
        detail = getattr(exc, "detail", None)
        if detail:
            # machine-readable sub-taxonomy inside one ErrCode (e.g. a
            # pruned async id is NotFound like an unknown id, but a
            # client that cached the 202 must be able to tell "your id
            # aged out" from "never existed"); stable token, not prose
            out["detail"] = str(detail)
        violations = getattr(exc, "violations", None)
        if violations:
            # static-analysis admission rejections carry the per-limit
            # breakdown (analysis/policy.py AnalysisRejection)
            out["violations"] = list(violations)
        return out
    return {
        "code": int(ErrCode.ExecutionFailed),
        "name": ErrCode.ExecutionFailed.name,
        "retryable": False,
        "message": f"{type(exc).__name__}: {exc}",
    }
