"""WebAssembly opcode table — single source of truth.

Mirrors the reference's X-macro enum table (/root/reference/include/common/
enum.inc:54-541) but as a declarative Python table carrying, per opcode:

  name      canonical spec name ("i32.add")
  page      opcode page: 0 = 1-byte, 0xFC = saturating/bulk page, 0xFD = SIMD
  code      opcode byte (or LEB sub-opcode for 0xFC/0xFD pages)
  imm       immediate kind consumed by the loader
  sig       value signature "pops->pushes" for plain (non-control) ops,
            using i=i32 I=i64 f=f32 F=f64 V=v128 r=funcref e=externref;
            None for ops whose typing needs context (control/var/mem idx ops).
  proposal  gating proposal name or None for MVP

The dense integer id of each opcode (its index in OPCODES) is what the
lowering stage and both engines use; the wire (page, code) pair only exists
in the loader.
"""

from __future__ import annotations

from typing import NamedTuple, Optional


class OpInfo(NamedTuple):
    name: str
    page: int
    code: int
    imm: str  # none|blocktype|labelidx|brtable|funcidx|typeidx_tableidx|
    #           localidx|globalidx|tableidx|tableidx2|elemidx_tableidx|
    #           refnull|select_t|memarg|memidx|dataidx_memidx|memidx2|
    #           dataidx|elemidx|i32|i64|f32|f64|funcref
    sig: Optional[str]
    proposal: Optional[str] = None


def _op(name, page, code, imm="none", sig=None, proposal=None):
    return OpInfo(name, page, code, imm, sig, proposal)


# fmt: off
_TABLE = [
    # ---- control (typing handled specially by the validator) ----
    _op("unreachable",        0, 0x00),
    _op("nop",                0, 0x01),
    _op("block",              0, 0x02, "blocktype"),
    _op("loop",               0, 0x03, "blocktype"),
    _op("if",                 0, 0x04, "blocktype"),
    _op("else",               0, 0x05),
    _op("end",                0, 0x0B),
    _op("br",                 0, 0x0C, "labelidx"),
    _op("br_if",              0, 0x0D, "labelidx"),
    _op("br_table",           0, 0x0E, "brtable"),
    _op("return",             0, 0x0F),
    _op("call",               0, 0x10, "funcidx"),
    _op("call_indirect",      0, 0x11, "typeidx_tableidx"),
    _op("return_call",        0, 0x12, "funcidx", proposal="tail-call"),
    _op("return_call_indirect", 0, 0x13, "typeidx_tableidx", proposal="tail-call"),
    # ---- reference types ----
    _op("ref.null",           0, 0xD0, "refnull"),
    _op("ref.is_null",        0, 0xD1),
    _op("ref.func",           0, 0xD2, "funcidx"),
    # ---- parametric ----
    _op("drop",               0, 0x1A),
    _op("select",             0, 0x1B),
    _op("select_t",           0, 0x1C, "select_t"),
    # ---- variable ----
    _op("local.get",          0, 0x20, "localidx"),
    _op("local.set",          0, 0x21, "localidx"),
    _op("local.tee",          0, 0x22, "localidx"),
    _op("global.get",         0, 0x23, "globalidx"),
    _op("global.set",         0, 0x24, "globalidx"),
    # ---- table ----
    _op("table.get",          0, 0x25, "tableidx"),
    _op("table.set",          0, 0x26, "tableidx"),
    # ---- memory ----
    _op("i32.load",           0, 0x28, "memarg", "i->i"),
    _op("i64.load",           0, 0x29, "memarg", "i->I"),
    _op("f32.load",           0, 0x2A, "memarg", "i->f"),
    _op("f64.load",           0, 0x2B, "memarg", "i->F"),
    _op("i32.load8_s",        0, 0x2C, "memarg", "i->i"),
    _op("i32.load8_u",        0, 0x2D, "memarg", "i->i"),
    _op("i32.load16_s",       0, 0x2E, "memarg", "i->i"),
    _op("i32.load16_u",       0, 0x2F, "memarg", "i->i"),
    _op("i64.load8_s",        0, 0x30, "memarg", "i->I"),
    _op("i64.load8_u",        0, 0x31, "memarg", "i->I"),
    _op("i64.load16_s",       0, 0x32, "memarg", "i->I"),
    _op("i64.load16_u",       0, 0x33, "memarg", "i->I"),
    _op("i64.load32_s",       0, 0x34, "memarg", "i->I"),
    _op("i64.load32_u",       0, 0x35, "memarg", "i->I"),
    _op("i32.store",          0, 0x36, "memarg", "ii->"),
    _op("i64.store",          0, 0x37, "memarg", "iI->"),
    _op("f32.store",          0, 0x38, "memarg", "if->"),
    _op("f64.store",          0, 0x39, "memarg", "iF->"),
    _op("i32.store8",         0, 0x3A, "memarg", "ii->"),
    _op("i32.store16",        0, 0x3B, "memarg", "ii->"),
    _op("i64.store8",         0, 0x3C, "memarg", "iI->"),
    _op("i64.store16",        0, 0x3D, "memarg", "iI->"),
    _op("i64.store32",        0, 0x3E, "memarg", "iI->"),
    _op("memory.size",        0, 0x3F, "memidx", "->i"),
    _op("memory.grow",        0, 0x40, "memidx", "i->i"),
    # ---- const ----
    _op("i32.const",          0, 0x41, "i32", "->i"),
    _op("i64.const",          0, 0x42, "i64", "->I"),
    _op("f32.const",          0, 0x43, "f32", "->f"),
    _op("f64.const",          0, 0x44, "f64", "->F"),
    # ---- i32 compare ----
    _op("i32.eqz",            0, 0x45, "none", "i->i"),
    _op("i32.eq",             0, 0x46, "none", "ii->i"),
    _op("i32.ne",             0, 0x47, "none", "ii->i"),
    _op("i32.lt_s",           0, 0x48, "none", "ii->i"),
    _op("i32.lt_u",           0, 0x49, "none", "ii->i"),
    _op("i32.gt_s",           0, 0x4A, "none", "ii->i"),
    _op("i32.gt_u",           0, 0x4B, "none", "ii->i"),
    _op("i32.le_s",           0, 0x4C, "none", "ii->i"),
    _op("i32.le_u",           0, 0x4D, "none", "ii->i"),
    _op("i32.ge_s",           0, 0x4E, "none", "ii->i"),
    _op("i32.ge_u",           0, 0x4F, "none", "ii->i"),
    # ---- i64 compare ----
    _op("i64.eqz",            0, 0x50, "none", "I->i"),
    _op("i64.eq",             0, 0x51, "none", "II->i"),
    _op("i64.ne",             0, 0x52, "none", "II->i"),
    _op("i64.lt_s",           0, 0x53, "none", "II->i"),
    _op("i64.lt_u",           0, 0x54, "none", "II->i"),
    _op("i64.gt_s",           0, 0x55, "none", "II->i"),
    _op("i64.gt_u",           0, 0x56, "none", "II->i"),
    _op("i64.le_s",           0, 0x57, "none", "II->i"),
    _op("i64.le_u",           0, 0x58, "none", "II->i"),
    _op("i64.ge_s",           0, 0x59, "none", "II->i"),
    _op("i64.ge_u",           0, 0x5A, "none", "II->i"),
    # ---- f32 compare ----
    _op("f32.eq",             0, 0x5B, "none", "ff->i"),
    _op("f32.ne",             0, 0x5C, "none", "ff->i"),
    _op("f32.lt",             0, 0x5D, "none", "ff->i"),
    _op("f32.gt",             0, 0x5E, "none", "ff->i"),
    _op("f32.le",             0, 0x5F, "none", "ff->i"),
    _op("f32.ge",             0, 0x60, "none", "ff->i"),
    # ---- f64 compare ----
    _op("f64.eq",             0, 0x61, "none", "FF->i"),
    _op("f64.ne",             0, 0x62, "none", "FF->i"),
    _op("f64.lt",             0, 0x63, "none", "FF->i"),
    _op("f64.gt",             0, 0x64, "none", "FF->i"),
    _op("f64.le",             0, 0x65, "none", "FF->i"),
    _op("f64.ge",             0, 0x66, "none", "FF->i"),
    # ---- i32 numeric ----
    _op("i32.clz",            0, 0x67, "none", "i->i"),
    _op("i32.ctz",            0, 0x68, "none", "i->i"),
    _op("i32.popcnt",         0, 0x69, "none", "i->i"),
    _op("i32.add",            0, 0x6A, "none", "ii->i"),
    _op("i32.sub",            0, 0x6B, "none", "ii->i"),
    _op("i32.mul",            0, 0x6C, "none", "ii->i"),
    _op("i32.div_s",          0, 0x6D, "none", "ii->i"),
    _op("i32.div_u",          0, 0x6E, "none", "ii->i"),
    _op("i32.rem_s",          0, 0x6F, "none", "ii->i"),
    _op("i32.rem_u",          0, 0x70, "none", "ii->i"),
    _op("i32.and",            0, 0x71, "none", "ii->i"),
    _op("i32.or",             0, 0x72, "none", "ii->i"),
    _op("i32.xor",            0, 0x73, "none", "ii->i"),
    _op("i32.shl",            0, 0x74, "none", "ii->i"),
    _op("i32.shr_s",          0, 0x75, "none", "ii->i"),
    _op("i32.shr_u",          0, 0x76, "none", "ii->i"),
    _op("i32.rotl",           0, 0x77, "none", "ii->i"),
    _op("i32.rotr",           0, 0x78, "none", "ii->i"),
    # ---- i64 numeric ----
    _op("i64.clz",            0, 0x79, "none", "I->I"),
    _op("i64.ctz",            0, 0x7A, "none", "I->I"),
    _op("i64.popcnt",         0, 0x7B, "none", "I->I"),
    _op("i64.add",            0, 0x7C, "none", "II->I"),
    _op("i64.sub",            0, 0x7D, "none", "II->I"),
    _op("i64.mul",            0, 0x7E, "none", "II->I"),
    _op("i64.div_s",          0, 0x7F, "none", "II->I"),
    _op("i64.div_u",          0, 0x80, "none", "II->I"),
    _op("i64.rem_s",          0, 0x81, "none", "II->I"),
    _op("i64.rem_u",          0, 0x82, "none", "II->I"),
    _op("i64.and",            0, 0x83, "none", "II->I"),
    _op("i64.or",             0, 0x84, "none", "II->I"),
    _op("i64.xor",            0, 0x85, "none", "II->I"),
    _op("i64.shl",            0, 0x86, "none", "II->I"),
    _op("i64.shr_s",          0, 0x87, "none", "II->I"),
    _op("i64.shr_u",          0, 0x88, "none", "II->I"),
    _op("i64.rotl",           0, 0x89, "none", "II->I"),
    _op("i64.rotr",           0, 0x8A, "none", "II->I"),
    # ---- f32 numeric ----
    _op("f32.abs",            0, 0x8B, "none", "f->f"),
    _op("f32.neg",            0, 0x8C, "none", "f->f"),
    _op("f32.ceil",           0, 0x8D, "none", "f->f"),
    _op("f32.floor",          0, 0x8E, "none", "f->f"),
    _op("f32.trunc",          0, 0x8F, "none", "f->f"),
    _op("f32.nearest",        0, 0x90, "none", "f->f"),
    _op("f32.sqrt",           0, 0x91, "none", "f->f"),
    _op("f32.add",            0, 0x92, "none", "ff->f"),
    _op("f32.sub",            0, 0x93, "none", "ff->f"),
    _op("f32.mul",            0, 0x94, "none", "ff->f"),
    _op("f32.div",            0, 0x95, "none", "ff->f"),
    _op("f32.min",            0, 0x96, "none", "ff->f"),
    _op("f32.max",            0, 0x97, "none", "ff->f"),
    _op("f32.copysign",       0, 0x98, "none", "ff->f"),
    # ---- f64 numeric ----
    _op("f64.abs",            0, 0x99, "none", "F->F"),
    _op("f64.neg",            0, 0x9A, "none", "F->F"),
    _op("f64.ceil",           0, 0x9B, "none", "F->F"),
    _op("f64.floor",          0, 0x9C, "none", "F->F"),
    _op("f64.trunc",          0, 0x9D, "none", "F->F"),
    _op("f64.nearest",        0, 0x9E, "none", "F->F"),
    _op("f64.sqrt",           0, 0x9F, "none", "F->F"),
    _op("f64.add",            0, 0xA0, "none", "FF->F"),
    _op("f64.sub",            0, 0xA1, "none", "FF->F"),
    _op("f64.mul",            0, 0xA2, "none", "FF->F"),
    _op("f64.div",            0, 0xA3, "none", "FF->F"),
    _op("f64.min",            0, 0xA4, "none", "FF->F"),
    _op("f64.max",            0, 0xA5, "none", "FF->F"),
    _op("f64.copysign",       0, 0xA6, "none", "FF->F"),
    # ---- conversions ----
    _op("i32.wrap_i64",       0, 0xA7, "none", "I->i"),
    _op("i32.trunc_f32_s",    0, 0xA8, "none", "f->i"),
    _op("i32.trunc_f32_u",    0, 0xA9, "none", "f->i"),
    _op("i32.trunc_f64_s",    0, 0xAA, "none", "F->i"),
    _op("i32.trunc_f64_u",    0, 0xAB, "none", "F->i"),
    _op("i64.extend_i32_s",   0, 0xAC, "none", "i->I"),
    _op("i64.extend_i32_u",   0, 0xAD, "none", "i->I"),
    _op("i64.trunc_f32_s",    0, 0xAE, "none", "f->I"),
    _op("i64.trunc_f32_u",    0, 0xAF, "none", "f->I"),
    _op("i64.trunc_f64_s",    0, 0xB0, "none", "F->I"),
    _op("i64.trunc_f64_u",    0, 0xB1, "none", "F->I"),
    _op("f32.convert_i32_s",  0, 0xB2, "none", "i->f"),
    _op("f32.convert_i32_u",  0, 0xB3, "none", "i->f"),
    _op("f32.convert_i64_s",  0, 0xB4, "none", "I->f"),
    _op("f32.convert_i64_u",  0, 0xB5, "none", "I->f"),
    _op("f32.demote_f64",     0, 0xB6, "none", "F->f"),
    _op("f64.convert_i32_s",  0, 0xB7, "none", "i->F"),
    _op("f64.convert_i32_u",  0, 0xB8, "none", "i->F"),
    _op("f64.convert_i64_s",  0, 0xB9, "none", "I->F"),
    _op("f64.convert_i64_u",  0, 0xBA, "none", "I->F"),
    _op("f64.promote_f32",    0, 0xBB, "none", "f->F"),
    _op("i32.reinterpret_f32", 0, 0xBC, "none", "f->i"),
    _op("i64.reinterpret_f64", 0, 0xBD, "none", "F->I"),
    _op("f32.reinterpret_i32", 0, 0xBE, "none", "i->f"),
    _op("f64.reinterpret_i64", 0, 0xBF, "none", "I->F"),
    # ---- sign extension (proposal on by default, like the reference) ----
    _op("i32.extend8_s",      0, 0xC0, "none", "i->i", "sign-extension"),
    _op("i32.extend16_s",     0, 0xC1, "none", "i->i", "sign-extension"),
    _op("i64.extend8_s",      0, 0xC2, "none", "I->I", "sign-extension"),
    _op("i64.extend16_s",     0, 0xC3, "none", "I->I", "sign-extension"),
    _op("i64.extend32_s",     0, 0xC4, "none", "I->I", "sign-extension"),
    # ---- 0xFC page: non-trapping float->int ----
    _op("i32.trunc_sat_f32_s", 0xFC, 0, "none", "f->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f32_u", 0xFC, 1, "none", "f->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f64_s", 0xFC, 2, "none", "F->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f64_u", 0xFC, 3, "none", "F->i", "nontrap-f2i"),
    _op("i64.trunc_sat_f32_s", 0xFC, 4, "none", "f->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f32_u", 0xFC, 5, "none", "f->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f64_s", 0xFC, 6, "none", "F->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f64_u", 0xFC, 7, "none", "F->I", "nontrap-f2i"),
    # ---- 0xFC page: bulk memory ----
    _op("memory.init",        0xFC, 8,  "dataidx_memidx", "iii->", "bulk-memory"),
    _op("data.drop",          0xFC, 9,  "dataidx", "->", "bulk-memory"),
    _op("memory.copy",        0xFC, 10, "memidx2", "iii->", "bulk-memory"),
    _op("memory.fill",        0xFC, 11, "memidx", "iii->", "bulk-memory"),
    _op("table.init",         0xFC, 12, "elemidx_tableidx", "iii->", "bulk-memory"),
    _op("elem.drop",          0xFC, 13, "elemidx", "->", "bulk-memory"),
    _op("table.copy",         0xFC, 14, "tableidx2", "iii->", "bulk-memory"),
    _op("table.grow",         0xFC, 15, "tableidx", None, "reference-types"),
    _op("table.size",         0xFC, 16, "tableidx", "->i", "reference-types"),
    _op("table.fill",         0xFC, 17, "tableidx", None, "reference-types"),
]
# fmt: on

OPCODES: tuple = tuple(_TABLE)

# Dense id assignment: index in OPCODES.
NAME_TO_ID = {info.name: i for i, info in enumerate(OPCODES)}
WIRE_TO_ID = {(info.page, info.code): i for i, info in enumerate(OPCODES)}


class Op:
    """Dense opcode ids as attributes: Op.i32_add etc."""


for _i, _info in enumerate(OPCODES):
    setattr(Op, _info.name.replace(".", "_"), _i)

NUM_OPCODES = len(OPCODES)


def name_of(op_id: int) -> str:
    return OPCODES[op_id].name


def info_of(op_id: int) -> OpInfo:
    return OPCODES[op_id]
