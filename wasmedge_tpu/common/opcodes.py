"""WebAssembly opcode table — single source of truth.

Mirrors the reference's X-macro enum table (/root/reference/include/common/
enum.inc:54-541) but as a declarative Python table carrying, per opcode:

  name      canonical spec name ("i32.add")
  page      opcode page: 0 = 1-byte, 0xFC = saturating/bulk page, 0xFD = SIMD
  code      opcode byte (or LEB sub-opcode for 0xFC/0xFD pages)
  imm       immediate kind consumed by the loader
  sig       value signature "pops->pushes" for plain (non-control) ops,
            using i=i32 I=i64 f=f32 F=f64 V=v128 r=funcref e=externref;
            None for ops whose typing needs context (control/var/mem idx ops).
  proposal  gating proposal name or None for MVP

The dense integer id of each opcode (its index in OPCODES) is what the
lowering stage and both engines use; the wire (page, code) pair only exists
in the loader.
"""

from __future__ import annotations

from typing import NamedTuple, Optional


class OpInfo(NamedTuple):
    name: str
    page: int
    code: int
    imm: str  # none|blocktype|labelidx|brtable|funcidx|typeidx_tableidx|
    #           localidx|globalidx|tableidx|tableidx2|elemidx_tableidx|
    #           refnull|select_t|memarg|memidx|dataidx_memidx|memidx2|
    #           dataidx|elemidx|i32|i64|f32|f64|funcref
    sig: Optional[str]
    proposal: Optional[str] = None


def _op(name, page, code, imm="none", sig=None, proposal=None):
    return OpInfo(name, page, code, imm, sig, proposal)


# fmt: off
_TABLE = [
    # ---- control (typing handled specially by the validator) ----
    _op("unreachable",        0, 0x00),
    _op("nop",                0, 0x01),
    _op("block",              0, 0x02, "blocktype"),
    _op("loop",               0, 0x03, "blocktype"),
    _op("if",                 0, 0x04, "blocktype"),
    _op("else",               0, 0x05),
    _op("end",                0, 0x0B),
    _op("br",                 0, 0x0C, "labelidx"),
    _op("br_if",              0, 0x0D, "labelidx"),
    _op("br_table",           0, 0x0E, "brtable"),
    _op("return",             0, 0x0F),
    _op("call",               0, 0x10, "funcidx"),
    _op("call_indirect",      0, 0x11, "typeidx_tableidx"),
    _op("return_call",        0, 0x12, "funcidx", proposal="tail-call"),
    _op("return_call_indirect", 0, 0x13, "typeidx_tableidx", proposal="tail-call"),
    # ---- reference types ----
    _op("ref.null",           0, 0xD0, "refnull"),
    _op("ref.is_null",        0, 0xD1),
    _op("ref.func",           0, 0xD2, "funcidx"),
    # ---- parametric ----
    _op("drop",               0, 0x1A),
    _op("select",             0, 0x1B),
    _op("select_t",           0, 0x1C, "select_t"),
    # ---- variable ----
    _op("local.get",          0, 0x20, "localidx"),
    _op("local.set",          0, 0x21, "localidx"),
    _op("local.tee",          0, 0x22, "localidx"),
    _op("global.get",         0, 0x23, "globalidx"),
    _op("global.set",         0, 0x24, "globalidx"),
    # ---- table ----
    _op("table.get",          0, 0x25, "tableidx"),
    _op("table.set",          0, 0x26, "tableidx"),
    # ---- memory ----
    _op("i32.load",           0, 0x28, "memarg", "i->i"),
    _op("i64.load",           0, 0x29, "memarg", "i->I"),
    _op("f32.load",           0, 0x2A, "memarg", "i->f"),
    _op("f64.load",           0, 0x2B, "memarg", "i->F"),
    _op("i32.load8_s",        0, 0x2C, "memarg", "i->i"),
    _op("i32.load8_u",        0, 0x2D, "memarg", "i->i"),
    _op("i32.load16_s",       0, 0x2E, "memarg", "i->i"),
    _op("i32.load16_u",       0, 0x2F, "memarg", "i->i"),
    _op("i64.load8_s",        0, 0x30, "memarg", "i->I"),
    _op("i64.load8_u",        0, 0x31, "memarg", "i->I"),
    _op("i64.load16_s",       0, 0x32, "memarg", "i->I"),
    _op("i64.load16_u",       0, 0x33, "memarg", "i->I"),
    _op("i64.load32_s",       0, 0x34, "memarg", "i->I"),
    _op("i64.load32_u",       0, 0x35, "memarg", "i->I"),
    _op("i32.store",          0, 0x36, "memarg", "ii->"),
    _op("i64.store",          0, 0x37, "memarg", "iI->"),
    _op("f32.store",          0, 0x38, "memarg", "if->"),
    _op("f64.store",          0, 0x39, "memarg", "iF->"),
    _op("i32.store8",         0, 0x3A, "memarg", "ii->"),
    _op("i32.store16",        0, 0x3B, "memarg", "ii->"),
    _op("i64.store8",         0, 0x3C, "memarg", "iI->"),
    _op("i64.store16",        0, 0x3D, "memarg", "iI->"),
    _op("i64.store32",        0, 0x3E, "memarg", "iI->"),
    _op("memory.size",        0, 0x3F, "memidx", "->i"),
    _op("memory.grow",        0, 0x40, "memidx", "i->i"),
    # ---- const ----
    _op("i32.const",          0, 0x41, "i32", "->i"),
    _op("i64.const",          0, 0x42, "i64", "->I"),
    _op("f32.const",          0, 0x43, "f32", "->f"),
    _op("f64.const",          0, 0x44, "f64", "->F"),
    # ---- i32 compare ----
    _op("i32.eqz",            0, 0x45, "none", "i->i"),
    _op("i32.eq",             0, 0x46, "none", "ii->i"),
    _op("i32.ne",             0, 0x47, "none", "ii->i"),
    _op("i32.lt_s",           0, 0x48, "none", "ii->i"),
    _op("i32.lt_u",           0, 0x49, "none", "ii->i"),
    _op("i32.gt_s",           0, 0x4A, "none", "ii->i"),
    _op("i32.gt_u",           0, 0x4B, "none", "ii->i"),
    _op("i32.le_s",           0, 0x4C, "none", "ii->i"),
    _op("i32.le_u",           0, 0x4D, "none", "ii->i"),
    _op("i32.ge_s",           0, 0x4E, "none", "ii->i"),
    _op("i32.ge_u",           0, 0x4F, "none", "ii->i"),
    # ---- i64 compare ----
    _op("i64.eqz",            0, 0x50, "none", "I->i"),
    _op("i64.eq",             0, 0x51, "none", "II->i"),
    _op("i64.ne",             0, 0x52, "none", "II->i"),
    _op("i64.lt_s",           0, 0x53, "none", "II->i"),
    _op("i64.lt_u",           0, 0x54, "none", "II->i"),
    _op("i64.gt_s",           0, 0x55, "none", "II->i"),
    _op("i64.gt_u",           0, 0x56, "none", "II->i"),
    _op("i64.le_s",           0, 0x57, "none", "II->i"),
    _op("i64.le_u",           0, 0x58, "none", "II->i"),
    _op("i64.ge_s",           0, 0x59, "none", "II->i"),
    _op("i64.ge_u",           0, 0x5A, "none", "II->i"),
    # ---- f32 compare ----
    _op("f32.eq",             0, 0x5B, "none", "ff->i"),
    _op("f32.ne",             0, 0x5C, "none", "ff->i"),
    _op("f32.lt",             0, 0x5D, "none", "ff->i"),
    _op("f32.gt",             0, 0x5E, "none", "ff->i"),
    _op("f32.le",             0, 0x5F, "none", "ff->i"),
    _op("f32.ge",             0, 0x60, "none", "ff->i"),
    # ---- f64 compare ----
    _op("f64.eq",             0, 0x61, "none", "FF->i"),
    _op("f64.ne",             0, 0x62, "none", "FF->i"),
    _op("f64.lt",             0, 0x63, "none", "FF->i"),
    _op("f64.gt",             0, 0x64, "none", "FF->i"),
    _op("f64.le",             0, 0x65, "none", "FF->i"),
    _op("f64.ge",             0, 0x66, "none", "FF->i"),
    # ---- i32 numeric ----
    _op("i32.clz",            0, 0x67, "none", "i->i"),
    _op("i32.ctz",            0, 0x68, "none", "i->i"),
    _op("i32.popcnt",         0, 0x69, "none", "i->i"),
    _op("i32.add",            0, 0x6A, "none", "ii->i"),
    _op("i32.sub",            0, 0x6B, "none", "ii->i"),
    _op("i32.mul",            0, 0x6C, "none", "ii->i"),
    _op("i32.div_s",          0, 0x6D, "none", "ii->i"),
    _op("i32.div_u",          0, 0x6E, "none", "ii->i"),
    _op("i32.rem_s",          0, 0x6F, "none", "ii->i"),
    _op("i32.rem_u",          0, 0x70, "none", "ii->i"),
    _op("i32.and",            0, 0x71, "none", "ii->i"),
    _op("i32.or",             0, 0x72, "none", "ii->i"),
    _op("i32.xor",            0, 0x73, "none", "ii->i"),
    _op("i32.shl",            0, 0x74, "none", "ii->i"),
    _op("i32.shr_s",          0, 0x75, "none", "ii->i"),
    _op("i32.shr_u",          0, 0x76, "none", "ii->i"),
    _op("i32.rotl",           0, 0x77, "none", "ii->i"),
    _op("i32.rotr",           0, 0x78, "none", "ii->i"),
    # ---- i64 numeric ----
    _op("i64.clz",            0, 0x79, "none", "I->I"),
    _op("i64.ctz",            0, 0x7A, "none", "I->I"),
    _op("i64.popcnt",         0, 0x7B, "none", "I->I"),
    _op("i64.add",            0, 0x7C, "none", "II->I"),
    _op("i64.sub",            0, 0x7D, "none", "II->I"),
    _op("i64.mul",            0, 0x7E, "none", "II->I"),
    _op("i64.div_s",          0, 0x7F, "none", "II->I"),
    _op("i64.div_u",          0, 0x80, "none", "II->I"),
    _op("i64.rem_s",          0, 0x81, "none", "II->I"),
    _op("i64.rem_u",          0, 0x82, "none", "II->I"),
    _op("i64.and",            0, 0x83, "none", "II->I"),
    _op("i64.or",             0, 0x84, "none", "II->I"),
    _op("i64.xor",            0, 0x85, "none", "II->I"),
    _op("i64.shl",            0, 0x86, "none", "II->I"),
    _op("i64.shr_s",          0, 0x87, "none", "II->I"),
    _op("i64.shr_u",          0, 0x88, "none", "II->I"),
    _op("i64.rotl",           0, 0x89, "none", "II->I"),
    _op("i64.rotr",           0, 0x8A, "none", "II->I"),
    # ---- f32 numeric ----
    _op("f32.abs",            0, 0x8B, "none", "f->f"),
    _op("f32.neg",            0, 0x8C, "none", "f->f"),
    _op("f32.ceil",           0, 0x8D, "none", "f->f"),
    _op("f32.floor",          0, 0x8E, "none", "f->f"),
    _op("f32.trunc",          0, 0x8F, "none", "f->f"),
    _op("f32.nearest",        0, 0x90, "none", "f->f"),
    _op("f32.sqrt",           0, 0x91, "none", "f->f"),
    _op("f32.add",            0, 0x92, "none", "ff->f"),
    _op("f32.sub",            0, 0x93, "none", "ff->f"),
    _op("f32.mul",            0, 0x94, "none", "ff->f"),
    _op("f32.div",            0, 0x95, "none", "ff->f"),
    _op("f32.min",            0, 0x96, "none", "ff->f"),
    _op("f32.max",            0, 0x97, "none", "ff->f"),
    _op("f32.copysign",       0, 0x98, "none", "ff->f"),
    # ---- f64 numeric ----
    _op("f64.abs",            0, 0x99, "none", "F->F"),
    _op("f64.neg",            0, 0x9A, "none", "F->F"),
    _op("f64.ceil",           0, 0x9B, "none", "F->F"),
    _op("f64.floor",          0, 0x9C, "none", "F->F"),
    _op("f64.trunc",          0, 0x9D, "none", "F->F"),
    _op("f64.nearest",        0, 0x9E, "none", "F->F"),
    _op("f64.sqrt",           0, 0x9F, "none", "F->F"),
    _op("f64.add",            0, 0xA0, "none", "FF->F"),
    _op("f64.sub",            0, 0xA1, "none", "FF->F"),
    _op("f64.mul",            0, 0xA2, "none", "FF->F"),
    _op("f64.div",            0, 0xA3, "none", "FF->F"),
    _op("f64.min",            0, 0xA4, "none", "FF->F"),
    _op("f64.max",            0, 0xA5, "none", "FF->F"),
    _op("f64.copysign",       0, 0xA6, "none", "FF->F"),
    # ---- conversions ----
    _op("i32.wrap_i64",       0, 0xA7, "none", "I->i"),
    _op("i32.trunc_f32_s",    0, 0xA8, "none", "f->i"),
    _op("i32.trunc_f32_u",    0, 0xA9, "none", "f->i"),
    _op("i32.trunc_f64_s",    0, 0xAA, "none", "F->i"),
    _op("i32.trunc_f64_u",    0, 0xAB, "none", "F->i"),
    _op("i64.extend_i32_s",   0, 0xAC, "none", "i->I"),
    _op("i64.extend_i32_u",   0, 0xAD, "none", "i->I"),
    _op("i64.trunc_f32_s",    0, 0xAE, "none", "f->I"),
    _op("i64.trunc_f32_u",    0, 0xAF, "none", "f->I"),
    _op("i64.trunc_f64_s",    0, 0xB0, "none", "F->I"),
    _op("i64.trunc_f64_u",    0, 0xB1, "none", "F->I"),
    _op("f32.convert_i32_s",  0, 0xB2, "none", "i->f"),
    _op("f32.convert_i32_u",  0, 0xB3, "none", "i->f"),
    _op("f32.convert_i64_s",  0, 0xB4, "none", "I->f"),
    _op("f32.convert_i64_u",  0, 0xB5, "none", "I->f"),
    _op("f32.demote_f64",     0, 0xB6, "none", "F->f"),
    _op("f64.convert_i32_s",  0, 0xB7, "none", "i->F"),
    _op("f64.convert_i32_u",  0, 0xB8, "none", "i->F"),
    _op("f64.convert_i64_s",  0, 0xB9, "none", "I->F"),
    _op("f64.convert_i64_u",  0, 0xBA, "none", "I->F"),
    _op("f64.promote_f32",    0, 0xBB, "none", "f->F"),
    _op("i32.reinterpret_f32", 0, 0xBC, "none", "f->i"),
    _op("i64.reinterpret_f64", 0, 0xBD, "none", "F->I"),
    _op("f32.reinterpret_i32", 0, 0xBE, "none", "i->f"),
    _op("f64.reinterpret_i64", 0, 0xBF, "none", "I->F"),
    # ---- sign extension (proposal on by default, like the reference) ----
    _op("i32.extend8_s",      0, 0xC0, "none", "i->i", "sign-extension"),
    _op("i32.extend16_s",     0, 0xC1, "none", "i->i", "sign-extension"),
    _op("i64.extend8_s",      0, 0xC2, "none", "I->I", "sign-extension"),
    _op("i64.extend16_s",     0, 0xC3, "none", "I->I", "sign-extension"),
    _op("i64.extend32_s",     0, 0xC4, "none", "I->I", "sign-extension"),
    # ---- 0xFC page: non-trapping float->int ----
    _op("i32.trunc_sat_f32_s", 0xFC, 0, "none", "f->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f32_u", 0xFC, 1, "none", "f->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f64_s", 0xFC, 2, "none", "F->i", "nontrap-f2i"),
    _op("i32.trunc_sat_f64_u", 0xFC, 3, "none", "F->i", "nontrap-f2i"),
    _op("i64.trunc_sat_f32_s", 0xFC, 4, "none", "f->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f32_u", 0xFC, 5, "none", "f->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f64_s", 0xFC, 6, "none", "F->I", "nontrap-f2i"),
    _op("i64.trunc_sat_f64_u", 0xFC, 7, "none", "F->I", "nontrap-f2i"),
    # ---- 0xFC page: bulk memory ----
    _op("memory.init",        0xFC, 8,  "dataidx_memidx", "iii->", "bulk-memory"),
    _op("data.drop",          0xFC, 9,  "dataidx", "->", "bulk-memory"),
    _op("memory.copy",        0xFC, 10, "memidx2", "iii->", "bulk-memory"),
    _op("memory.fill",        0xFC, 11, "memidx", "iii->", "bulk-memory"),
    _op("table.init",         0xFC, 12, "elemidx_tableidx", "iii->", "bulk-memory"),
    _op("elem.drop",          0xFC, 13, "elemidx", "->", "bulk-memory"),
    _op("table.copy",         0xFC, 14, "tableidx2", "iii->", "bulk-memory"),
    _op("table.grow",         0xFC, 15, "tableidx", None, "reference-types"),
    _op("table.size",         0xFC, 16, "tableidx", "->i", "reference-types"),
    _op("table.fill",         0xFC, 17, "tableidx", None, "reference-types"),
]


def _simd(name, code, imm="none", sig=None):
    return OpInfo(name, 0xFD, code, imm, sig, "simd")


# 0xFD page: the full 128-bit SIMD proposal (236 ops), same set the
# reference enables by default (enum.inc SIMD block; proposal gate
# configure.h:175-183).
_TABLE += [
    # loads/stores
    _simd("v128.load",            0x00, "memarg", "i->V"),
    _simd("v128.load8x8_s",       0x01, "memarg", "i->V"),
    _simd("v128.load8x8_u",       0x02, "memarg", "i->V"),
    _simd("v128.load16x4_s",      0x03, "memarg", "i->V"),
    _simd("v128.load16x4_u",      0x04, "memarg", "i->V"),
    _simd("v128.load32x2_s",      0x05, "memarg", "i->V"),
    _simd("v128.load32x2_u",      0x06, "memarg", "i->V"),
    _simd("v128.load8_splat",     0x07, "memarg", "i->V"),
    _simd("v128.load16_splat",    0x08, "memarg", "i->V"),
    _simd("v128.load32_splat",    0x09, "memarg", "i->V"),
    _simd("v128.load64_splat",    0x0A, "memarg", "i->V"),
    _simd("v128.store",           0x0B, "memarg", "iV->"),
    _simd("v128.const",           0x0C, "v128const", "->V"),
    _simd("i8x16.shuffle",        0x0D, "shuffle", "VV->V"),
    _simd("i8x16.swizzle",        0x0E, "none", "VV->V"),
    # splats
    _simd("i8x16.splat",          0x0F, "none", "i->V"),
    _simd("i16x8.splat",          0x10, "none", "i->V"),
    _simd("i32x4.splat",          0x11, "none", "i->V"),
    _simd("i64x2.splat",          0x12, "none", "I->V"),
    _simd("f32x4.splat",          0x13, "none", "f->V"),
    _simd("f64x2.splat",          0x14, "none", "F->V"),
    # lane access
    _simd("i8x16.extract_lane_s", 0x15, "lane", "V->i"),
    _simd("i8x16.extract_lane_u", 0x16, "lane", "V->i"),
    _simd("i8x16.replace_lane",   0x17, "lane", "Vi->V"),
    _simd("i16x8.extract_lane_s", 0x18, "lane", "V->i"),
    _simd("i16x8.extract_lane_u", 0x19, "lane", "V->i"),
    _simd("i16x8.replace_lane",   0x1A, "lane", "Vi->V"),
    _simd("i32x4.extract_lane",   0x1B, "lane", "V->i"),
    _simd("i32x4.replace_lane",   0x1C, "lane", "Vi->V"),
    _simd("i64x2.extract_lane",   0x1D, "lane", "V->I"),
    _simd("i64x2.replace_lane",   0x1E, "lane", "VI->V"),
    _simd("f32x4.extract_lane",   0x1F, "lane", "V->f"),
    _simd("f32x4.replace_lane",   0x20, "lane", "Vf->V"),
    _simd("f64x2.extract_lane",   0x21, "lane", "V->F"),
    _simd("f64x2.replace_lane",   0x22, "lane", "VF->V"),
    # i8x16 compares
    _simd("i8x16.eq",   0x23, "none", "VV->V"),
    _simd("i8x16.ne",   0x24, "none", "VV->V"),
    _simd("i8x16.lt_s", 0x25, "none", "VV->V"),
    _simd("i8x16.lt_u", 0x26, "none", "VV->V"),
    _simd("i8x16.gt_s", 0x27, "none", "VV->V"),
    _simd("i8x16.gt_u", 0x28, "none", "VV->V"),
    _simd("i8x16.le_s", 0x29, "none", "VV->V"),
    _simd("i8x16.le_u", 0x2A, "none", "VV->V"),
    _simd("i8x16.ge_s", 0x2B, "none", "VV->V"),
    _simd("i8x16.ge_u", 0x2C, "none", "VV->V"),
    # i16x8 compares
    _simd("i16x8.eq",   0x2D, "none", "VV->V"),
    _simd("i16x8.ne",   0x2E, "none", "VV->V"),
    _simd("i16x8.lt_s", 0x2F, "none", "VV->V"),
    _simd("i16x8.lt_u", 0x30, "none", "VV->V"),
    _simd("i16x8.gt_s", 0x31, "none", "VV->V"),
    _simd("i16x8.gt_u", 0x32, "none", "VV->V"),
    _simd("i16x8.le_s", 0x33, "none", "VV->V"),
    _simd("i16x8.le_u", 0x34, "none", "VV->V"),
    _simd("i16x8.ge_s", 0x35, "none", "VV->V"),
    _simd("i16x8.ge_u", 0x36, "none", "VV->V"),
    # i32x4 compares
    _simd("i32x4.eq",   0x37, "none", "VV->V"),
    _simd("i32x4.ne",   0x38, "none", "VV->V"),
    _simd("i32x4.lt_s", 0x39, "none", "VV->V"),
    _simd("i32x4.lt_u", 0x3A, "none", "VV->V"),
    _simd("i32x4.gt_s", 0x3B, "none", "VV->V"),
    _simd("i32x4.gt_u", 0x3C, "none", "VV->V"),
    _simd("i32x4.le_s", 0x3D, "none", "VV->V"),
    _simd("i32x4.le_u", 0x3E, "none", "VV->V"),
    _simd("i32x4.ge_s", 0x3F, "none", "VV->V"),
    _simd("i32x4.ge_u", 0x40, "none", "VV->V"),
    # f32x4 compares
    _simd("f32x4.eq", 0x41, "none", "VV->V"),
    _simd("f32x4.ne", 0x42, "none", "VV->V"),
    _simd("f32x4.lt", 0x43, "none", "VV->V"),
    _simd("f32x4.gt", 0x44, "none", "VV->V"),
    _simd("f32x4.le", 0x45, "none", "VV->V"),
    _simd("f32x4.ge", 0x46, "none", "VV->V"),
    # f64x2 compares
    _simd("f64x2.eq", 0x47, "none", "VV->V"),
    _simd("f64x2.ne", 0x48, "none", "VV->V"),
    _simd("f64x2.lt", 0x49, "none", "VV->V"),
    _simd("f64x2.gt", 0x4A, "none", "VV->V"),
    _simd("f64x2.le", 0x4B, "none", "VV->V"),
    _simd("f64x2.ge", 0x4C, "none", "VV->V"),
    # bitwise
    _simd("v128.not",       0x4D, "none", "V->V"),
    _simd("v128.and",       0x4E, "none", "VV->V"),
    _simd("v128.andnot",    0x4F, "none", "VV->V"),
    _simd("v128.or",        0x50, "none", "VV->V"),
    _simd("v128.xor",       0x51, "none", "VV->V"),
    _simd("v128.bitselect", 0x52, "none", "VVV->V"),
    _simd("v128.any_true",  0x53, "none", "V->i"),
    # lane memory
    _simd("v128.load8_lane",   0x54, "memarg_lane", "iV->V"),
    _simd("v128.load16_lane",  0x55, "memarg_lane", "iV->V"),
    _simd("v128.load32_lane",  0x56, "memarg_lane", "iV->V"),
    _simd("v128.load64_lane",  0x57, "memarg_lane", "iV->V"),
    _simd("v128.store8_lane",  0x58, "memarg_lane", "iV->"),
    _simd("v128.store16_lane", 0x59, "memarg_lane", "iV->"),
    _simd("v128.store32_lane", 0x5A, "memarg_lane", "iV->"),
    _simd("v128.store64_lane", 0x5B, "memarg_lane", "iV->"),
    _simd("v128.load32_zero",  0x5C, "memarg", "i->V"),
    _simd("v128.load64_zero",  0x5D, "memarg", "i->V"),
    _simd("f32x4.demote_f64x2_zero",  0x5E, "none", "V->V"),
    _simd("f64x2.promote_low_f32x4",  0x5F, "none", "V->V"),
    # i8x16 arith
    _simd("i8x16.abs",            0x60, "none", "V->V"),
    _simd("i8x16.neg",            0x61, "none", "V->V"),
    _simd("i8x16.popcnt",         0x62, "none", "V->V"),
    _simd("i8x16.all_true",       0x63, "none", "V->i"),
    _simd("i8x16.bitmask",        0x64, "none", "V->i"),
    _simd("i8x16.narrow_i16x8_s", 0x65, "none", "VV->V"),
    _simd("i8x16.narrow_i16x8_u", 0x66, "none", "VV->V"),
    _simd("f32x4.ceil",           0x67, "none", "V->V"),
    _simd("f32x4.floor",          0x68, "none", "V->V"),
    _simd("f32x4.trunc",          0x69, "none", "V->V"),
    _simd("f32x4.nearest",        0x6A, "none", "V->V"),
    _simd("i8x16.shl",            0x6B, "none", "Vi->V"),
    _simd("i8x16.shr_s",          0x6C, "none", "Vi->V"),
    _simd("i8x16.shr_u",          0x6D, "none", "Vi->V"),
    _simd("i8x16.add",            0x6E, "none", "VV->V"),
    _simd("i8x16.add_sat_s",      0x6F, "none", "VV->V"),
    _simd("i8x16.add_sat_u",      0x70, "none", "VV->V"),
    _simd("i8x16.sub",            0x71, "none", "VV->V"),
    _simd("i8x16.sub_sat_s",      0x72, "none", "VV->V"),
    _simd("i8x16.sub_sat_u",      0x73, "none", "VV->V"),
    _simd("f64x2.ceil",           0x74, "none", "V->V"),
    _simd("f64x2.floor",          0x75, "none", "V->V"),
    _simd("i8x16.min_s",          0x76, "none", "VV->V"),
    _simd("i8x16.min_u",          0x77, "none", "VV->V"),
    _simd("i8x16.max_s",          0x78, "none", "VV->V"),
    _simd("i8x16.max_u",          0x79, "none", "VV->V"),
    _simd("f64x2.trunc",          0x7A, "none", "V->V"),
    _simd("i8x16.avgr_u",         0x7B, "none", "VV->V"),
    _simd("i16x8.extadd_pairwise_i8x16_s", 0x7C, "none", "V->V"),
    _simd("i16x8.extadd_pairwise_i8x16_u", 0x7D, "none", "V->V"),
    _simd("i32x4.extadd_pairwise_i16x8_s", 0x7E, "none", "V->V"),
    _simd("i32x4.extadd_pairwise_i16x8_u", 0x7F, "none", "V->V"),
    # i16x8 arith
    _simd("i16x8.abs",                0x80, "none", "V->V"),
    _simd("i16x8.neg",                0x81, "none", "V->V"),
    _simd("i16x8.q15mulr_sat_s",      0x82, "none", "VV->V"),
    _simd("i16x8.all_true",           0x83, "none", "V->i"),
    _simd("i16x8.bitmask",            0x84, "none", "V->i"),
    _simd("i16x8.narrow_i32x4_s",     0x85, "none", "VV->V"),
    _simd("i16x8.narrow_i32x4_u",     0x86, "none", "VV->V"),
    _simd("i16x8.extend_low_i8x16_s", 0x87, "none", "V->V"),
    _simd("i16x8.extend_high_i8x16_s", 0x88, "none", "V->V"),
    _simd("i16x8.extend_low_i8x16_u", 0x89, "none", "V->V"),
    _simd("i16x8.extend_high_i8x16_u", 0x8A, "none", "V->V"),
    _simd("i16x8.shl",                0x8B, "none", "Vi->V"),
    _simd("i16x8.shr_s",              0x8C, "none", "Vi->V"),
    _simd("i16x8.shr_u",              0x8D, "none", "Vi->V"),
    _simd("i16x8.add",                0x8E, "none", "VV->V"),
    _simd("i16x8.add_sat_s",          0x8F, "none", "VV->V"),
    _simd("i16x8.add_sat_u",          0x90, "none", "VV->V"),
    _simd("i16x8.sub",                0x91, "none", "VV->V"),
    _simd("i16x8.sub_sat_s",          0x92, "none", "VV->V"),
    _simd("i16x8.sub_sat_u",          0x93, "none", "VV->V"),
    _simd("f64x2.nearest",            0x94, "none", "V->V"),
    _simd("i16x8.mul",                0x95, "none", "VV->V"),
    _simd("i16x8.min_s",              0x96, "none", "VV->V"),
    _simd("i16x8.min_u",              0x97, "none", "VV->V"),
    _simd("i16x8.max_s",              0x98, "none", "VV->V"),
    _simd("i16x8.max_u",              0x99, "none", "VV->V"),
    _simd("i16x8.avgr_u",             0x9B, "none", "VV->V"),
    _simd("i16x8.extmul_low_i8x16_s", 0x9C, "none", "VV->V"),
    _simd("i16x8.extmul_high_i8x16_s", 0x9D, "none", "VV->V"),
    _simd("i16x8.extmul_low_i8x16_u", 0x9E, "none", "VV->V"),
    _simd("i16x8.extmul_high_i8x16_u", 0x9F, "none", "VV->V"),
    # i32x4 arith
    _simd("i32x4.abs",                0xA0, "none", "V->V"),
    _simd("i32x4.neg",                0xA1, "none", "V->V"),
    _simd("i32x4.all_true",           0xA3, "none", "V->i"),
    _simd("i32x4.bitmask",            0xA4, "none", "V->i"),
    _simd("i32x4.extend_low_i16x8_s", 0xA7, "none", "V->V"),
    _simd("i32x4.extend_high_i16x8_s", 0xA8, "none", "V->V"),
    _simd("i32x4.extend_low_i16x8_u", 0xA9, "none", "V->V"),
    _simd("i32x4.extend_high_i16x8_u", 0xAA, "none", "V->V"),
    _simd("i32x4.shl",                0xAB, "none", "Vi->V"),
    _simd("i32x4.shr_s",              0xAC, "none", "Vi->V"),
    _simd("i32x4.shr_u",              0xAD, "none", "Vi->V"),
    _simd("i32x4.add",                0xAE, "none", "VV->V"),
    _simd("i32x4.sub",                0xB1, "none", "VV->V"),
    _simd("i32x4.mul",                0xB5, "none", "VV->V"),
    _simd("i32x4.min_s",              0xB6, "none", "VV->V"),
    _simd("i32x4.min_u",              0xB7, "none", "VV->V"),
    _simd("i32x4.max_s",              0xB8, "none", "VV->V"),
    _simd("i32x4.max_u",              0xB9, "none", "VV->V"),
    _simd("i32x4.dot_i16x8_s",        0xBA, "none", "VV->V"),
    _simd("i32x4.extmul_low_i16x8_s", 0xBC, "none", "VV->V"),
    _simd("i32x4.extmul_high_i16x8_s", 0xBD, "none", "VV->V"),
    _simd("i32x4.extmul_low_i16x8_u", 0xBE, "none", "VV->V"),
    _simd("i32x4.extmul_high_i16x8_u", 0xBF, "none", "VV->V"),
    # i64x2 arith
    _simd("i64x2.abs",                0xC0, "none", "V->V"),
    _simd("i64x2.neg",                0xC1, "none", "V->V"),
    _simd("i64x2.all_true",           0xC3, "none", "V->i"),
    _simd("i64x2.bitmask",            0xC4, "none", "V->i"),
    _simd("i64x2.extend_low_i32x4_s", 0xC7, "none", "V->V"),
    _simd("i64x2.extend_high_i32x4_s", 0xC8, "none", "V->V"),
    _simd("i64x2.extend_low_i32x4_u", 0xC9, "none", "V->V"),
    _simd("i64x2.extend_high_i32x4_u", 0xCA, "none", "V->V"),
    _simd("i64x2.shl",                0xCB, "none", "Vi->V"),
    _simd("i64x2.shr_s",              0xCC, "none", "Vi->V"),
    _simd("i64x2.shr_u",              0xCD, "none", "Vi->V"),
    _simd("i64x2.add",                0xCE, "none", "VV->V"),
    _simd("i64x2.sub",                0xD1, "none", "VV->V"),
    _simd("i64x2.mul",                0xD5, "none", "VV->V"),
    _simd("i64x2.eq",                 0xD6, "none", "VV->V"),
    _simd("i64x2.ne",                 0xD7, "none", "VV->V"),
    _simd("i64x2.lt_s",               0xD8, "none", "VV->V"),
    _simd("i64x2.gt_s",               0xD9, "none", "VV->V"),
    _simd("i64x2.le_s",               0xDA, "none", "VV->V"),
    _simd("i64x2.ge_s",               0xDB, "none", "VV->V"),
    _simd("i64x2.extmul_low_i32x4_s", 0xDC, "none", "VV->V"),
    _simd("i64x2.extmul_high_i32x4_s", 0xDD, "none", "VV->V"),
    _simd("i64x2.extmul_low_i32x4_u", 0xDE, "none", "VV->V"),
    _simd("i64x2.extmul_high_i32x4_u", 0xDF, "none", "VV->V"),
    # f32x4 arith
    _simd("f32x4.abs",  0xE0, "none", "V->V"),
    _simd("f32x4.neg",  0xE1, "none", "V->V"),
    _simd("f32x4.sqrt", 0xE3, "none", "V->V"),
    _simd("f32x4.add",  0xE4, "none", "VV->V"),
    _simd("f32x4.sub",  0xE5, "none", "VV->V"),
    _simd("f32x4.mul",  0xE6, "none", "VV->V"),
    _simd("f32x4.div",  0xE7, "none", "VV->V"),
    _simd("f32x4.min",  0xE8, "none", "VV->V"),
    _simd("f32x4.max",  0xE9, "none", "VV->V"),
    _simd("f32x4.pmin", 0xEA, "none", "VV->V"),
    _simd("f32x4.pmax", 0xEB, "none", "VV->V"),
    # f64x2 arith
    _simd("f64x2.abs",  0xEC, "none", "V->V"),
    _simd("f64x2.neg",  0xED, "none", "V->V"),
    _simd("f64x2.sqrt", 0xEF, "none", "V->V"),
    _simd("f64x2.add",  0xF0, "none", "VV->V"),
    _simd("f64x2.sub",  0xF1, "none", "VV->V"),
    _simd("f64x2.mul",  0xF2, "none", "VV->V"),
    _simd("f64x2.div",  0xF3, "none", "VV->V"),
    _simd("f64x2.min",  0xF4, "none", "VV->V"),
    _simd("f64x2.max",  0xF5, "none", "VV->V"),
    _simd("f64x2.pmin", 0xF6, "none", "VV->V"),
    _simd("f64x2.pmax", 0xF7, "none", "VV->V"),
    # conversions
    _simd("i32x4.trunc_sat_f32x4_s",      0xF8, "none", "V->V"),
    _simd("i32x4.trunc_sat_f32x4_u",      0xF9, "none", "V->V"),
    _simd("f32x4.convert_i32x4_s",        0xFA, "none", "V->V"),
    _simd("f32x4.convert_i32x4_u",        0xFB, "none", "V->V"),
    _simd("i32x4.trunc_sat_f64x2_s_zero", 0xFC, "none", "V->V"),
    _simd("i32x4.trunc_sat_f64x2_u_zero", 0xFD, "none", "V->V"),
    _simd("f64x2.convert_low_i32x4_s",    0xFE, "none", "V->V"),
    _simd("f64x2.convert_low_i32x4_u",    0xFF, "none", "V->V"),
]
# fmt: on

OPCODES: tuple = tuple(_TABLE)

# Dense id assignment: index in OPCODES.
NAME_TO_ID = {info.name: i for i, info in enumerate(OPCODES)}
WIRE_TO_ID = {(info.page, info.code): i for i, info in enumerate(OPCODES)}


class Op:
    """Dense opcode ids as attributes: Op.i32_add etc."""


for _i, _info in enumerate(OPCODES):
    setattr(Op, _info.name.replace(".", "_"), _i)

NUM_OPCODES = len(OPCODES)


def name_of(op_id: int) -> str:
    return OPCODES[op_id].name


def info_of(op_id: int) -> OpInfo:
    return OPCODES[op_id]
