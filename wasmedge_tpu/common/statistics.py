"""Statistics: instruction counting, gas metering, wall-clock timers.

Mirrors the reference Statistics (/root/reference/include/common/
statistics.h:29-191): per-run instruction count, per-opcode cost table with a
limit (gas), and Wasm-vs-host time split. The batch engine keeps per-lane
retired-instruction and fuel counters in device state and folds them in here
on sync (SURVEY.md §5.1 TPU equivalent).

Supervision addition: `FailureRecord` is the structured failure taxonomy
of the supervised batch layer (batch/supervisor.py) — every recovered or
degraded incident (device launch failure, host-serve exception, corrupted
checkpoint, poisoned/runaway lane, tier demotion) lands here, either on a
Statistics instance or in the process-wide bounded log, so long-lived
servers can export what their batches survived.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional, Tuple

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.common.opcodes import NUM_OPCODES

# The cost table covers lowered pseudo-ops (BR/BRZ/BRNZ) appended after the
# wasm opcode space by validator/image.py.
_NUM_COST_SLOTS = NUM_OPCODES + 3


@dataclasses.dataclass
class FailureRecord:
    """One supervised-execution incident.

    fault_class: "launch" (kernel dispatch / XLA failure), "serve"
    (host-side WASI drain raised), "checkpoint" (unreadable/corrupt
    snapshot skipped in the lineage), "poison_lane" (lane set repeatedly
    faulting the kernel, demoted or terminated), "runaway" (lane past the
    per-lane step cap, terminated), "demote" (engine tier given up on),
    "scalar_rerun" (host-side error inside the scalar bottom rung), or
    "integrity" (r24 shadow-audit divergence: a device returned
    wrong-but-plausible planes — silent data corruption detected,
    rolled back, and re-executed).
    """

    fault_class: str
    error: str = ""
    lanes: Tuple[int, ...] = ()      # affected lanes; () = whole batch
    retry: int = 0                   # retry count when the incident fired
    checkpoint: Optional[str] = None  # checkpoint lineage member involved
    tier: str = ""                   # engine tier: "pallas"|"simt"|"scalar"
    # Event timestamp (wall clock, time.time()) — for humans and logs
    # only.  Durations between incidents (retry/backoff intervals, trace
    # span lengths) are derived from `mono_s`, the time.monotonic()
    # stamp, so they survive wall-clock steps (NTP slew, manual resets).
    time_s: float = 0.0
    mono_s: float = 0.0

    def asdict(self) -> dict:
        d = dataclasses.asdict(self)
        d["lanes"] = [int(x) for x in self.lanes]
        return d

    def stamp(self) -> "FailureRecord":
        """Fill any unset clocks (idempotent)."""
        if not self.time_s:
            self.time_s = time.time()
        if not self.mono_s:
            self.mono_s = time.monotonic()
        return self


# Process-wide bounded failure log: components without a Statistics at
# hand (the block scheduler's quarantine, engine internals) record here;
# Statistics instances mirror into it so one export point sees all.
_FAILURE_LOG: deque = deque(maxlen=256)


def record_failure(rec: FailureRecord):
    _FAILURE_LOG.append(rec.stamp())


def recent_failures() -> list:
    return list(_FAILURE_LOG)


def clear_failures():
    _FAILURE_LOG.clear()


class Statistics:
    def __init__(self, conf=None):
        sc = conf.statistics if conf is not None else None
        self.instr_counting = bool(sc.instr_counting) if sc else False
        self.cost_measuring = bool(sc.cost_measuring) if sc else False
        self.time_measuring = bool(sc.time_measuring) if sc else False
        self.cost_limit = sc.cost_limit if sc else (1 << 64) - 1
        self.cost_table = [1] * _NUM_COST_SLOTS
        self.reset()

    def reset(self):
        self.instr_count = 0
        self.total_cost = 0
        self.wasm_ns = 0
        self.host_ns = 0
        self._wasm_t0 = None
        self._host_t0 = None
        self.failures = []  # FailureRecords from supervised runs
        self.opcode_counts = None  # per-opcode retired (obs histogram)

    def add_failure(self, rec: FailureRecord):
        """Attach a supervised-execution incident to this run's stats and
        mirror it into the process-wide log."""
        self.failures.append(rec)
        record_failure(rec)

    # -- counters ----------------------------------------------------------
    def inc_instr(self, n: int = 1):
        self.instr_count += n

    def add_cost(self, cost: int):
        self.total_cost += cost
        if self.total_cost > self.cost_limit:
            raise TrapError(ErrCode.CostLimitExceeded)

    def add_instr_cost(self, op_id: int):
        self.add_cost(self.cost_table[op_id])

    def add_opcode_counts(self, counts):
        """Fold a per-opcode retired histogram (index = opcode id in
        this table's slot domain, from the obs subsystem's device
        histogram plane) into cost_table accounting: counts accumulate
        on `opcode_counts` and their cost_table-weighted sum is exposed
        via dump()["opcode_cost"].  Attribution only — instr_count /
        total_cost (the trap-enforcing gas meter) are not touched, so
        folding never double-counts against a live cost limit."""
        import numpy as _np

        counts = _np.asarray(counts, _np.int64)
        if counts.size > _NUM_COST_SLOTS:
            counts = counts[:_NUM_COST_SLOTS]
        if self.opcode_counts is None:
            self.opcode_counts = _np.zeros(_NUM_COST_SLOTS, _np.int64)
        self.opcode_counts[:counts.size] += counts

    def set_cost_limit(self, limit: int):
        self.cost_limit = limit

    # -- timers ------------------------------------------------------------
    def start_wasm(self):
        if self.time_measuring:
            self._wasm_t0 = time.perf_counter_ns()

    def stop_wasm(self):
        if self.time_measuring and self._wasm_t0 is not None:
            self.wasm_ns += time.perf_counter_ns() - self._wasm_t0
            self._wasm_t0 = None

    def start_host(self):
        if self.time_measuring:
            self._host_t0 = time.perf_counter_ns()

    def stop_host(self):
        if self.time_measuring and self._host_t0 is not None:
            self.host_ns += time.perf_counter_ns() - self._host_t0
            self._host_t0 = None

    @property
    def instr_per_second(self) -> float:
        if self.wasm_ns == 0:
            return 0.0
        return self.instr_count / (self.wasm_ns / 1e9)

    def dump(self) -> dict:
        out = {
            "instr_count": self.instr_count,
            "total_cost": self.total_cost,
            "wasm_ns": self.wasm_ns,
            "host_ns": self.host_ns,
            "instr_per_second": self.instr_per_second,
        }
        if self.failures:
            out["failures"] = [r.asdict() for r in self.failures]
        if self.opcode_counts is not None:
            nz = {int(i): int(n) for i, n in enumerate(self.opcode_counts)
                  if n}
            out["opcode_counts"] = nz
            out["opcode_cost"] = int(sum(
                n * self.cost_table[i] for i, n in nz.items()))
        return out
