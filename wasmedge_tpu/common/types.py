"""Value types and bit-level value helpers.

The reference stores every value as a 16-byte tagged union
(/root/reference/include/common/types.h:84-89). Our runtime representation
is untyped 64-bit cells (the validator has already proven types):

  - scalar engine: Python int holding the raw little-endian bit pattern
    (i32/f32 in the low 32 bits, i64/f64 as 64-bit patterns, refs as
    index+1 with 0 = null)
  - batch engine: two int32 SoA planes (lo, hi) per stack slot

Helpers here convert between bit patterns and typed Python values with
exact Wasm semantics (numpy is used for correctly-rounded f32 arithmetic).
"""

from __future__ import annotations

import enum
import struct

# numpy is needed only by the four float bit-pattern helpers below; it
# is imported lazily so the CLI's scalar/native paths (which pull this
# module for ValType) keep a numpy-free spawn (tests/test_spawn_time.py)


def _np():
    import numpy

    return numpy


class ValType(enum.IntEnum):
    I32 = 0x7F
    I64 = 0x7E
    F32 = 0x7D
    F64 = 0x7C
    V128 = 0x7B
    FuncRef = 0x70
    ExternRef = 0x6F

    @property
    def is_num(self) -> bool:
        return self in (ValType.I32, ValType.I64, ValType.F32, ValType.F64)

    @property
    def is_ref(self) -> bool:
        return self in (ValType.FuncRef, ValType.ExternRef)


# Signature chars used in the opcode table <-> ValType
SIG_CHAR_TO_VALTYPE = {
    "i": ValType.I32,
    "I": ValType.I64,
    "f": ValType.F32,
    "F": ValType.F64,
    "V": ValType.V128,
    "r": ValType.FuncRef,
    "e": ValType.ExternRef,
}
VALTYPE_TO_SIG_CHAR = {v: k for k, v in SIG_CHAR_TO_VALTYPE.items()}

MASK32 = 0xFFFFFFFF
MASK64 = 0xFFFFFFFFFFFFFFFF
I32_MIN = -(2**31)
I64_MIN = -(2**63)

# Null reference encoding: raw 0. Non-null funcref/externref = value + 1.
REF_NULL = 0


def u32(x: int) -> int:
    return x & MASK32


def u64(x: int) -> int:
    return x & MASK64


def s32(x: int) -> int:
    x &= MASK32
    return x - (1 << 32) if x >= (1 << 31) else x


def s64(x: int) -> int:
    x &= MASK64
    return x - (1 << 64) if x >= (1 << 63) else x


def f32_to_bits(v: "float | np.float32") -> int:
    np = _np()
    return struct.unpack("<I", struct.pack("<f", float(np.float32(v))))[0]


def bits_to_f32(b: int) -> "np.float32":
    np = _np()
    return np.float32(struct.unpack("<f", struct.pack("<I", b & MASK32))[0])


def f64_to_bits(v: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", float(v)))[0]


def bits_to_f64(b: int) -> "np.float64":
    np = _np()
    return np.float64(struct.unpack("<d", struct.pack("<Q", b & MASK64))[0])


F32_CANONICAL_NAN = 0x7FC00000
F64_CANONICAL_NAN = 0x7FF8000000000000


def is_canonical_nan32(bits: int) -> bool:
    return (bits & 0x7FFFFFFF) == F32_CANONICAL_NAN


def is_arithmetic_nan32(bits: int) -> bool:
    return (bits & 0x7FC00000) == 0x7FC00000


def is_canonical_nan64(bits: int) -> bool:
    return (bits & 0x7FFFFFFFFFFFFFFF) == F64_CANONICAL_NAN


def is_arithmetic_nan64(bits: int) -> bool:
    return (bits & 0x7FF8000000000000) == 0x7FF8000000000000


def typed_to_bits(ty: ValType, v) -> int:
    """Typed Python/numpy value -> raw cell (64-bit; v128 is 128-bit)."""
    if ty == ValType.I32:
        return int(v) & MASK32
    if ty == ValType.I64:
        return int(v) & MASK64
    if ty == ValType.F32:
        return f32_to_bits(v)
    if ty == ValType.F64:
        return f64_to_bits(v)
    if ty == ValType.V128:
        return int(v) & ((1 << 128) - 1)
    if ty.is_ref:
        return int(v) & MASK64
    raise ValueError(f"unsupported type {ty}")


def bits_to_typed(ty: ValType, b: int):
    """Raw cell -> typed value (ints are signed, floats numpy, v128 raw)."""
    if ty == ValType.I32:
        return s32(b)
    if ty == ValType.I64:
        return s64(b)
    if ty == ValType.F32:
        return bits_to_f32(b)
    if ty == ValType.F64:
        return bits_to_f64(b)
    if ty == ValType.V128:
        return b & ((1 << 128) - 1)
    if ty.is_ref:
        return b & MASK64
    raise ValueError(f"unsupported type {ty}")


_NAME_TO_VALTYPE = {
    "i32": ValType.I32, "i64": ValType.I64, "f32": ValType.F32,
    "f64": ValType.F64, "v128": ValType.V128,
    "funcref": ValType.FuncRef, "externref": ValType.ExternRef,
}


def to_valtype(x) -> ValType:
    """Coerce a ValType, spec name string, or raw byte to ValType."""
    if isinstance(x, ValType):
        return x
    if isinstance(x, str):
        return _NAME_TO_VALTYPE[x]
    return ValType(x)


PAGE_SIZE = 65536
MAX_MEMORY_PAGES = 65536  # 4 GiB / 64 KiB (reference: validator.h:71)
