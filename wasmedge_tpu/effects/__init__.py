"""Guest suspend/resume via effect handlers (r23).

Blocking hostcalls — `poll_oneoff` pure-clock sleeps and the new
`wasmedge.await_event` import — lower into a PARKED effect instead of
blocking the serving thread: the lane rides back to the launch
boundary under a dedicated trap sentinel (batch/image.py TRAP_PARKED),
serializes through the hv SwapStore column path at zero resident cost,
and the physical lane returns to the recycler.  A `ParkedSession`
(request id, wake condition, swap key, stdout cursor) carries the
suspended guest; wakes come from `POST /v1/requests/<id>/wake`
(optional payload delivered into the guest's await_event buffer) or a
deterministic timer wheel, and a woken session re-enters as a swapped
vlane install — bit-identical to never having parked.

Everything is gated on Configure.effects (off by default): the off
configuration runs the exact pre-r23 serving path.
"""

from wasmedge_tpu.effects.hostfuncs import (
    AWAIT_EVENT_MODULE,
    AwaitEvent,
    effects_import_object,
)
from wasmedge_tpu.effects.runtime import EffectsRuntime
from wasmedge_tpu.effects.session import ParkedSession
from wasmedge_tpu.effects.stream import StreamBuf

__all__ = [
    "AWAIT_EVENT_MODULE",
    "AwaitEvent",
    "EffectsRuntime",
    "ParkedSession",
    "StreamBuf",
    "effects_import_object",
]
