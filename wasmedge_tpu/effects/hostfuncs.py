"""The `wasmedge` host module: imports the effect subsystem lowers.

`await_event(buf_ptr, buf_len, nwritten_ptr) -> errno` blocks the
guest until an external wake (`POST /v1/requests/<id>/wake`) delivers
a payload into `buf_ptr` (truncated to `buf_len`; the delivered length
lands at `nwritten_ptr` as a u32).  Under an effects-enabled serving
loop the call never executes host-side at all — the serve-round
intercept (effects/runtime.py) either delivers a pending payload or
parks the lane.  This body is the FALLBACK for every other context
(scalar engine, effects-off serving, a module run outside a server):
it completes immediately with Errno.AGAIN and zero bytes, so linking
against the import never requires the subsystem to be on.
"""

from __future__ import annotations

from wasmedge_tpu.host.wasi.wasi_abi import Errno
from wasmedge_tpu.runtime.hostfunc import HostFunctionBase, ImportObject

MASK32 = 0xFFFFFFFF

# Import-module name guests link against: (import "wasmedge"
# "await_event" (func ...)).
AWAIT_EVENT_MODULE = "wasmedge"


class AwaitEvent(HostFunctionBase):
    """Fallback host body for `wasmedge.await_event` (see module doc)."""

    def __init__(self):
        super().__init__(["i32", "i32", "i32"], ["i32"],
                         name="await_event")

    def body(self, mem, buf_ptr, buf_len, nwritten_ptr):
        if mem is not None:
            mem.store(nwritten_ptr & MASK32, 4, 0)
        return Errno.AGAIN


def effects_import_object() -> ImportObject:
    """The registrable `wasmedge` host module (one per instance, like
    the WASI module — registered unconditionally so modules importing
    await_event always link; the effect lowering itself stays gated on
    Configure.effects)."""
    obj = ImportObject(AWAIT_EVENT_MODULE)
    obj.add_func("await_event", AwaitEvent())
    return obj
