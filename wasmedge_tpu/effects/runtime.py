"""EffectsRuntime: the serving-side effect handler for suspended guests.

One instance rides one BatchServer.  Two halves:

  - the SERVE-ROUND INTERCEPT (`intercept`, called from
    batch/hostcall.py serve_batch_state while the launch slice runs
    off the server lock): classifies waiting hostcall lanes whose
    target is a blocking call.  `wasmedge.await_event` either delivers
    a pending wake payload into the guest's buffer (the exact bytes an
    HTTP wake posted) or marks the lane TRAP_PARKED; a conforming
    pure-clock `poll_oneoff` either synthesizes its single clock event
    (timer already elapsed / zero timeout) or parks with a timer.
    Delivery writes guest memory through the serve round's
    PlaneMemoryCache and pushes the result cell through the same
    stack-set path as a host-served call, so a woken run is
    bit-identical to one where the payload was already waiting.

  - the BOUNDARY PASSES (called by the server under its lock):
    `park_boundary` serializes TRAP_PARKED lanes through the SwapStore
    column path (hv/swapstore.py) and frees the physical lanes;
    `process_wakes` drains queued HTTP wakes, fires due timers, and
    expires timer-parked sessions past their deadline; `install_woken`
    restores woken sessions onto free lanes through the shared
    column-install pass (hv/manager.py install_lane_columns) — or, on
    an hv server, woken sessions hand off into hv.waiting as swapped
    virtual lanes and re-enter through the ordinary swap-in.

Fault seams (testing/faults.py): `session_park` — a faulted park
leaves the lane resident (trap returns to TRAP_HOSTCALL, the intercept
re-marks it next round); `session_wake` — a faulted wake re-queues the
wake (HTTP) or re-arms the timer without losing the session.
"""

from __future__ import annotations

import heapq
import itertools
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from wasmedge_tpu.effects.session import ParkedSession
from wasmedge_tpu.effects.stream import StreamBuf
from wasmedge_tpu.hv.swapstore import (
    SwapCorrupt,
    SwapStore,
    deserialize_lane,
    serialize_lanes,
)

MASK32 = 0xFFFFFFFF

# park-duration histogram bucket upper bounds (seconds)
PARK_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0)


class EffectsRuntime:
    """Suspend/resume state machine for one BatchServer (see module
    doc).  Boundary passes run under the owning server's lock; the
    intercept and `wake()` run on other threads and synchronize on the
    internal lock, which protects the wake queue / pending payloads /
    parked table."""

    def __init__(self, knobs, lanes: int, store: Optional[SwapStore] = None,
                 faults=None, obs=None, record=None, clock=time.monotonic):
        self.k = knobs
        self.lanes = int(lanes)
        self.store = store if store is not None \
            else SwapStore(dir=knobs.swap_dir, faults=faults)
        self.faults = faults
        self.obs = obs
        self._record = record or (lambda fault_class, exc: None)
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = itertools.count()
        # lane -> request id snapshot, set by the server just before
        # each launch (bindings are boundary-stable, so the intercept
        # reads it without the server lock)
        self.lane_rids: Dict[int, int] = {}
        self.parked: Dict[int, ParkedSession] = {}
        self.pending: Dict[int, deque] = {}    # rid -> wake payloads
        self._wakes: deque = deque()           # queued (rid, payload)
        self._elapsed: set = set()             # rids with a fired timer
        self._timers: list = []                # heap (wake_at, seq, rid)
        # rid -> (wake, wake_at) recorded by the intercept, consumed by
        # park_boundary
        self._pending_parks: Dict[int, tuple] = {}
        # rid -> (deadline_left, parked_at) for sessions handed off to
        # hv — note_installed() re-arms the deadline at swap-in
        self._pending_install: Dict[int, tuple] = {}
        self._install_jit = [None]
        self.counters = {
            "parks": 0, "resumes": 0, "delivered": 0,
            "wakes_http": 0, "wakes_timer": 0,
            "park_faults": 0, "wake_faults": 0, "corrupt": 0,
        }
        self._park_obs = [0, 0.0, [0] * (len(PARK_BUCKETS) + 1)]
        self.streams: Dict[int, StreamBuf] = {}
        self._closed_streams: deque = deque()  # FIFO retention pruning

    # -- geometry ----------------------------------------------------------
    def resize(self, lanes: int):
        """Adopt a grown lane pool (live reshard): parked sessions are
        keyed by request id and ride through; the install pass retraces
        at the new shapes."""
        self.lanes = int(lanes)
        self._install_jit = [None]

    # -- serve-round intercept ----------------------------------------------
    def begin_launch(self, lane_rids: Dict[int, int]):
        self.lane_rids = dict(lane_rids)

    def intercept(self, engine, waiting, ks, slab_lo, slab_hi, fp, pc,
                  opbase, sp, cache, new_trap, new_pc, stack_sets):
        """Classify blocking hostcalls among the serve round's waiting
        lanes; returns the set of lane indices consumed (parked or
        completed here) — the normal host drain skips them."""
        from wasmedge_tpu.batch.image import TRAP_PARKED
        from wasmedge_tpu.host.wasi.wasi_abi import Errno

        consumed = set()
        if cache is None:
            return consumed   # both calls need guest memory

        def arg(lane, i):
            base = int(fp[lane]) + i
            lo = int(np.uint32(slab_lo[base, lane]))
            hi = int(np.uint32(slab_hi[base, lane]))
            return lo | (hi << 32)

        def resume(lane, errno):
            ob = int(opbase[lane])
            stack_sets.append((
                np.asarray([ob], np.int64)[None, :],
                np.asarray([int(lane)], np.int64),
                np.asarray([np.int32(np.uint32(errno & MASK32))],
                           np.int32)[None, :],
                np.asarray([np.int32(0)], np.int32)[None, :]))
            sp[lane] = ob + 1
            new_trap[lane] = 0
            new_pc[lane] = pc[lane] + 1   # resume at the stub's RETURN

        for k in np.unique(ks):
            fi = engine.resolve_func(int(k))
            name = getattr(getattr(fi, "host", None), "name", None)
            if name not in ("await_event", "poll_oneoff"):
                continue
            for lane in waiting[ks == k]:
                lane = int(lane)
                rid = self.lane_rids.get(lane)
                if rid is None:
                    continue   # not server-managed: normal host serve
                if name == "await_event":
                    verdict = self._await_event(lane, rid, arg, cache,
                                                resume, Errno)
                else:
                    verdict = self._poll_oneoff(lane, rid, arg, cache,
                                                resume, Errno)
                if verdict == "park":
                    new_trap[lane] = TRAP_PARKED
                    consumed.add(lane)
                elif verdict == "done":
                    consumed.add(lane)
        return consumed

    def _await_event(self, lane, rid, arg, cache, resume, Errno):
        buf_ptr = arg(lane, 0) & MASK32
        buf_len = arg(lane, 1) & MASK32
        nwritten_ptr = arg(lane, 2) & MASK32
        with self._lock:
            q = self.pending.get(rid)
            payload = q.popleft() if q else None
            if payload is None:
                # nothing to deliver: park until an external wake
                self._pending_parks[rid] = ("http", None)
                return "park"
            if not q:
                self.pending.pop(rid, None)
        data = bytes(payload)[:buf_len]
        if data:
            cache.write_bytes(lane, buf_ptr, data)
        cache.write_bytes(lane, nwritten_ptr,
                          struct.pack("<I", len(data)))
        self.counters["delivered"] += 1
        resume(lane, int(Errno.SUCCESS))
        return "done"

    def _poll_oneoff(self, lane, rid, arg, cache, resume, Errno):
        from wasmedge_tpu.host.wasi import wasi_abi as abi

        in_ptr = arg(lane, 0) & MASK32
        out_ptr = arg(lane, 1) & MASK32
        nsubs = arg(lane, 2) & MASK32
        nevents_ptr = arg(lane, 3) & MASK32
        if nsubs == 0 or nsubs > 128:
            return None   # host path handles (INVAL / oversized)
        min_rel = None
        first_userdata = None
        for j in range(nsubs):
            raw = cache.read_bytes(
                lane, in_ptr + j * abi.SUBSCRIPTION_SIZE,
                abi.SUBSCRIPTION_SIZE)
            userdata = int.from_bytes(raw[0:8], "little")
            tag = raw[8]
            if tag != abi.Eventtype.CLOCK:
                return None   # fd / unknown subscriptions: host path
            clock_id = int.from_bytes(raw[16:20], "little")
            timeout = int.from_bytes(raw[24:32], "little")
            flags = int.from_bytes(raw[40:42], "little")
            if flags & abi.Subclockflags.ABSTIME or clock_id > 3:
                return None   # conservative: host path
            if first_userdata is None:
                first_userdata = userdata
            min_rel = timeout if min_rel is None \
                else min(min_rel, timeout)
        with self._lock:
            elapsed = rid in self._elapsed
            if elapsed:
                self._elapsed.discard(rid)
        if elapsed or min_rel == 0:
            # deliver exactly the host tail: ONE event for the first
            # clock subscription in subscription order
            ev = abi.pack_event(first_userdata, Errno.SUCCESS,
                                abi.Eventtype.CLOCK)
            cache.write_bytes(lane, out_ptr, ev)
            cache.write_bytes(lane, nevents_ptr, struct.pack("<I", 1))
            resume(lane, int(Errno.SUCCESS))
            return "done"
        rel_s = min_rel / 1e9
        if rel_s < max(float(self.k.min_park_timeout_s), 0.0):
            return None   # too short to be worth a park round-trip
        with self._lock:
            self._pending_parks[rid] = ("timer", self.clock() + rel_s)
        return "park"

    # -- boundary: park ------------------------------------------------------
    def park_boundary(self, engine, state, bindings, recycler, free_cb):
        """Serialize every TRAP_PARKED lane out through the SwapStore
        and free its physical lane.  A faulted park (seam
        `session_park`, a serialization error, or a store failure)
        leaves the lane RESIDENT — its trap returns to TRAP_HOSTCALL
        and the intercept re-marks it at the next boundary."""
        import jax.numpy as jnp

        from wasmedge_tpu.batch.image import TRAP_HOSTCALL, TRAP_PARKED

        trap = np.asarray(state.trap)
        lanes = [lane for lane in sorted(bindings)
                 if trap[lane] == TRAP_PARKED]
        if not lanes:
            return state
        now = self.clock()
        survivors = []
        for lane in lanes:
            rid = bindings[lane].id
            with self._lock:
                info = self._pending_parks.pop(rid, ("http", None))
            try:
                if self.faults is not None:
                    self.faults.fire("session_park", lane=int(lane),
                                     id=rid)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.counters["park_faults"] += 1
                self._record("effects", e)
                continue   # stays resident; retried next boundary
            survivors.append((lane, rid, info))
        # every TRAP_PARKED lane resumes from TRAP_HOSTCALL: a parked
        # survivor's serialized column must re-enter the hostcall serve
        # on install, and a faulted park retries the intercept
        idx = jnp.asarray(np.asarray(lanes, np.int64))
        state = state._replace(trap=state.trap.at[idx].set(TRAP_HOSTCALL))
        if not survivors:
            return state
        cur = getattr(engine, "_stdout_cursor", None)
        lanes_idx = [lane for lane, _, _ in survivors]
        spos = [int(cur[0][lane]) if cur is not None else 0
                for lane in lanes_idx]
        try:
            payloads = serialize_lanes(state, lanes_idx, self.lanes,
                                       stdout_pos=spos)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            self.counters["park_faults"] += len(survivors)
            self._record("effects", e)
            return state   # whole batch stays resident; retried
        parked_lanes = []
        for (lane, rid, (wake, wake_at)), payload, sp in zip(
                survivors, payloads, spos):
            try:
                key = self.store.put(payload)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.counters["park_faults"] += 1
                self._record("effects", e)
                continue
            req = bindings[lane]
            deadline_left = None
            if wake == "http" and req.deadline is not None:
                # the deadline clock PAUSES while waiting on an
                # explicit wake (ISSUE 19 satellite); timer sleeps
                # keep their absolute deadline
                deadline_left = max(req.deadline - now, 0.0)
                req.deadline = None
            ps = ParkedSession(req, key, sp, wake, wake_at=wake_at,
                               deadline_left=deadline_left,
                               parked_at=now)
            with self._lock:
                self.parked[rid] = ps
                if wake == "timer" and wake_at is not None:
                    heapq.heappush(self._timers,
                                   (wake_at, next(self._seq), rid))
                if self.pending.get(rid):
                    # a wake landed while the park was in flight: the
                    # session is install-ready immediately
                    ps.woken = True
            bindings.pop(lane, None)
            free_cb(lane, req)
            parked_lanes.append(lane)
            self.counters["parks"] += 1
            if self.obs is not None:
                self.obs.instant("session_park", cat="effects",
                                 track="effects", lane=int(lane),
                                 id=rid, wake=wake,
                                 nbytes=len(payload))
        if parked_lanes:
            state = recycler.park(state, parked_lanes)
        return state

    # -- boundary: wakes -----------------------------------------------------
    def wake(self, rid: int, payload: Optional[bytes] = None):
        """Queue an external wake (HTTP thread safe); the serving loop
        applies it at the next boundary."""
        with self._lock:
            self._wakes.append((int(rid), payload))

    def process_wakes(self, now: Optional[float] = None):
        """Drain queued HTTP wakes, fire due timers, expire
        timer-parked sessions past their deadline.  Returns
        (ready, expired): `ready` = sessions newly install-ready,
        `expired` = requests whose deadline lapsed while parked (the
        caller rejects their futures and bumps its counters)."""
        now = self.clock() if now is None else now
        ready: List[ParkedSession] = []
        expired = []
        with self._lock:
            n = len(self._wakes)
            for _ in range(n):
                rid, payload = self._wakes.popleft()
                try:
                    if self.faults is not None:
                        self.faults.fire("session_wake", id=rid,
                                         source="http")
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    self.counters["wake_faults"] += 1
                    self._record("effects", e)
                    # re-queued, not lost: retried next boundary
                    self._wakes.append((rid, payload))
                    continue
                self.pending.setdefault(rid, deque()).append(
                    b"" if payload is None else bytes(payload))
                self.counters["wakes_http"] += 1
                ps = self.parked.get(rid)
                if ps is not None and not ps.woken:
                    ps.woken = True
                    ready.append(ps)
            requeue = []
            while self._timers and self._timers[0][0] <= now:
                ent = heapq.heappop(self._timers)
                rid = ent[2]
                ps = self.parked.get(rid)
                if ps is None or ps.woken or ps.wake != "timer":
                    continue   # superseded (woken another way / gone)
                try:
                    if self.faults is not None:
                        self.faults.fire("session_wake", id=rid,
                                         source="timer")
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    self.counters["wake_faults"] += 1
                    self._record("effects", e)
                    requeue.append(ent)   # re-armed, not lost
                    break
                self._elapsed.add(rid)
                ps.woken = True
                self.counters["wakes_timer"] += 1
                ready.append(ps)
            for ent in requeue:
                heapq.heappush(self._timers, ent)
            for rid, ps in list(self.parked.items()):
                if ps.woken or ps.wake != "timer":
                    continue
                d = ps.req.deadline
                if d is not None and now > d:
                    self.parked.pop(rid)
                    self.store.release(ps.key)
                    self._elapsed.discard(rid)
                    expired.append(ps.req)
        return ready, expired

    def handoff_woken(self):
        """Remove every install-ready session from the parked table for
        hv re-entry (the caller seeds hv.waiting with swapped virtual
        lanes; the store reference transfers with the key).  The
        deadline re-arm + park-duration observation defer to
        note_installed() at swap-in."""
        out = []
        with self._lock:
            for rid in [r for r, ps in self.parked.items() if ps.woken]:
                ps = self.parked.pop(rid)
                self._pending_install[rid] = (ps.deadline_left,
                                              ps.parked_at, ps.wake)
                out.append(ps)
        return out

    # -- boundary: install ---------------------------------------------------
    def install_woken(self, engine, state, free, bindings,
                      install_cb=None):
        """Restore woken sessions onto free physical lanes (the non-hv
        path): fetch + verify + ONE shared column-install pass, stdout
        cursor continuity, bindings update.  A corrupt store entry
        rejects that one request machine-readably; any other failure
        keeps the session woken and retries next boundary."""
        from wasmedge_tpu.hv.manager import install_lane_columns

        with self._lock:
            ready = [ps for ps in self.parked.values() if ps.woken]
        if not ready or not free:
            return state
        pairs = []
        for ps in ready[:len(free)]:
            pairs.append((heapq.heappop(free), ps))
        rows = []
        for lane, ps in pairs:
            req = ps.req
            try:
                payload = self.store.get(ps.key)
                cols, spos = deserialize_lane(payload)
            except (KeyboardInterrupt, SystemExit):
                raise
            except SwapCorrupt as e:
                from wasmedge_tpu.serve.queue import ServeRejected

                self.counters["corrupt"] += 1
                self._record("effects", e)
                with self._lock:
                    self.parked.pop(req.id, None)
                self.store.release(ps.key)
                if not req.future.done:
                    req.future._reject(ServeRejected(
                        f"request {req.id} lost: parked session state "
                        f"corrupt ({e.reason})"))
                self.close_stream(req.id, error="session lost")
                heapq.heappush(free, lane)
                continue
            except Exception as e:
                self.counters["wake_faults"] += 1
                self._record("effects", e)
                heapq.heappush(free, lane)
                continue
            rows.append((lane, ps, cols, spos))
        if not rows:
            return state
        try:
            state = install_lane_columns(
                state, self.lanes, [r[0] for r in rows],
                [r[2] for r in rows], self._install_jit)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            self.counters["wake_faults"] += len(rows)
            self._record("effects", e)
            for lane, *_ in rows:
                heapq.heappush(free, lane)
            return state
        cur = getattr(engine, "_stdout_cursor", None)
        now = self.clock()
        for lane, ps, cols, spos in rows:
            req = ps.req
            if cur is not None:
                # continue the REQUEST's logical output stream on the
                # new physical lane (same rule as an hv swap-in)
                cur[0][lane] = spos
                cur[1][lane] = spos
            self.store.release(ps.key)
            with self._lock:
                self.parked.pop(req.id, None)
            bindings[lane] = req
            if ps.deadline_left is not None:
                req.deadline = now + ps.deadline_left
            self._observe_park(now - ps.parked_at)
            self.counters["resumes"] += 1
            if self.obs is not None:
                self.obs.instant("session_wake", cat="effects",
                                 track="effects", lane=int(lane),
                                 id=req.id, wake=ps.wake)
            if install_cb is not None:
                install_cb(lane, req)
        return state

    def note_installed(self, req):
        """hv-path install hook: re-arm a paused deadline + observe the
        park duration when a handed-off session lands through swap-in."""
        info = self._pending_install.pop(req.id, None)
        if info is None:
            return
        deadline_left, parked_at, _wake = info
        now = self.clock()
        if deadline_left is not None:
            req.deadline = now + deadline_left
        self._observe_park(now - parked_at)
        self.counters["resumes"] += 1

    def _observe_park(self, seconds: float):
        s = max(float(seconds), 0.0)
        obs = self._park_obs
        obs[0] += 1
        obs[1] += s
        for i, ub in enumerate(PARK_BUCKETS):
            if s <= ub:
                obs[2][i] += 1
                break
        else:
            obs[2][-1] += 1

    # -- cross-host migration (fleet/) ---------------------------------------
    def export_parked(self, rid: int):
        """Detach one parked session for migration: (entry, payload)
        where `entry` is the journal record EXTENDED with the wake
        condition and remaining-deadline seconds, `payload` the
        SwapStore blob.  The payload reads BEFORE anything detaches —
        an unreadable blob leaves the session exactly where it was."""
        rid = int(rid)
        with self._lock:
            ps = self.parked.get(rid)
            if ps is None:
                raise KeyError(f"request {rid} is not a parked session")
            key = ps.key
        payload = self.store.get(key)   # SwapCorrupt raises HERE
        now = self.clock()
        with self._lock:
            ps = self.parked.pop(rid, None)
            if ps is None:   # raced another export
                raise KeyError(f"request {rid} is not a parked session")
            # queued-but-unprocessed wakes for this rid migrate with it
            qw = [(b"" if p is None else bytes(p))
                  for r, p in self._wakes if r == rid]
            if qw:
                self._wakes = deque((r, p) for r, p in self._wakes
                                    if r != rid)
            entry = ps.journal(now, list(self.pending.pop(rid, ()))
                               + qw)
            if ps.req.deadline is not None:
                entry["deadline_s"] = max(ps.req.deadline - now, 0.001)
            self._elapsed.discard(rid)
            # a stale timer-heap entry is skipped by process_wakes
            # (parked.get(rid) is None -> superseded)
        self.store.release(key)
        return entry, payload

    def adopt_parked(self, entry: dict, payload: bytes, req):
        """Install a migrated parked session under its ORIGINAL id:
        the payload verifies against its content key (SwapStore.adopt)
        and the wake condition re-arms from the entry — pending
        payloads deliver, a remaining timer re-schedules, a session
        exported mid-wake installs at the next boundary."""
        self.store.adopt(entry["key"], bytes(payload))
        now = self.clock()
        with self._lock:
            ps = ParkedSession.from_journal(entry, req, now)
            self.parked[req.id] = ps
            for hexp in entry.get("payloads", ()):
                self.pending.setdefault(req.id, deque()).append(
                    bytes.fromhex(hexp))
            if ps.woken or self.pending.get(req.id):
                ps.woken = True
                if ps.wake == "timer":
                    self._elapsed.add(req.id)
            elif ps.wake == "timer" and ps.wake_at is not None:
                heapq.heappush(self._timers,
                               (ps.wake_at, next(self._seq), req.id))
        return ps

    # -- scheduling hints ----------------------------------------------------
    def in_flight(self) -> int:
        with self._lock:
            return len(self.parked)

    def parked_ids(self) -> tuple:
        with self._lock:
            return tuple(sorted(self.parked))

    def parked_requests(self) -> List[object]:
        with self._lock:
            return [ps.req for ps in self.parked.values()]

    def has_woken(self) -> bool:
        with self._lock:
            return any(ps.woken for ps in self.parked.values())

    def parked_by_tenant(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        with self._lock:
            for ps in self.parked.values():
                out[ps.req.tenant] = out.get(ps.req.tenant, 0) + 1
        return out

    def runnable(self, now: Optional[float] = None) -> bool:
        """True when a boundary pass would make progress right now
        (queued wakes, a due timer, or an install-ready session) —
        the server's idle wait keys off this plus next_deadline()."""
        now = self.clock() if now is None else now
        with self._lock:
            if self._wakes or self._pending_parks:
                return True
            if any(ps.woken for ps in self.parked.values()):
                return True
            return bool(self._timers) and self._timers[0][0] <= now

    def next_deadline(self) -> Optional[float]:
        """Earliest timer wake (monotonic stamp), or the earliest
        parked-session deadline, whichever is sooner; None = purely
        event-driven (the idle wait blocks on the condvar)."""
        with self._lock:
            out = self._timers[0][0] if self._timers else None
            for ps in self.parked.values():
                d = ps.req.deadline
                if d is not None and (out is None or d < out):
                    out = d
            return out

    # -- streams -------------------------------------------------------------
    def stream_of(self, rid: int, create: bool = False
                  ) -> Optional[StreamBuf]:
        with self._lock:
            buf = self.streams.get(int(rid))
            if buf is None and create:
                buf = StreamBuf(cap=int(self.k.stream_buffer_bytes))
                self.streams[int(rid)] = buf
            return buf

    def stream_append(self, rid: int, pos: int, data: bytes):
        self.stream_of(rid, create=True).append(pos, data)

    def close_stream(self, rid: int, error: Optional[str] = None):
        with self._lock:
            buf = self.streams.get(int(rid))
        if buf is None or buf.closed:
            return
        buf.close(error=error)
        with self._lock:
            # bounded retention of closed streams (late subscribers can
            # still replay a resolved request's window)
            self._closed_streams.append(int(rid))
            while len(self._closed_streams) > 1024:
                self.streams.pop(self._closed_streams.popleft(), None)

    # -- checkpoint / restore ------------------------------------------------
    def _queued_wake_payloads(self) -> Dict[int, list]:
        """Queued-but-unprocessed HTTP wakes by rid (caller holds the
        lock).  A wake 202'd between boundaries must ride the journal
        exactly like an already-delivered pending payload — a crash in
        that window must not strand the parked session."""
        out: Dict[int, list] = {}
        for rid, payload in self._wakes:
            out.setdefault(rid, []).append(
                b"" if payload is None else bytes(payload))
        return out

    def journal_entries(self) -> List[dict]:
        now = self.clock()
        with self._lock:
            qw = self._queued_wake_payloads()
            return [ps.journal(now, list(self.pending.get(rid, ()))
                               + qw.get(rid, []))
                    for rid, ps in self.parked.items()]

    def snapshot_payload(self) -> List[tuple]:
        """In-memory lineage payload: (req, journal-entry) pairs —
        request OBJECTS so an in-process restore resolves the futures
        callers already hold."""
        now = self.clock()
        with self._lock:
            qw = self._queued_wake_payloads()
            return [(ps.req, ps.journal(now,
                                        list(self.pending.get(rid, ()))
                                        + qw.get(rid, [])))
                    for rid, ps in self.parked.items()]

    def blob_arrays(self, record=None) -> Dict[str, np.ndarray]:
        """Parked blobs as npz-ready uint8 arrays (checkpoint-embedded,
        so a restore never depends on store retention)."""
        out = {}
        with self._lock:
            sessions = list(self.parked.values())
        for ps in sessions:
            try:
                payload = self.store.get(ps.key)
            except SwapCorrupt as e:
                (record or self._record)("effects", e)
                continue
            out[f"effblob_{ps.key}"] = np.frombuffer(payload, np.uint8)
        return out

    def restore(self, pairs, blobs: Dict[str, bytes],
                covered_ids) -> List[object]:
        """Reset the parked table to a snapshot's view.  `pairs` are
        (req, journal-entry); `blobs` maps key -> payload bytes; ids in
        `covered_ids` (resident bindings / hv virtual lanes) are
        skipped — a request is never both resident and parked.  Returns
        requests whose parked state could not be restored."""
        now = self.clock()
        lost = []
        with self._lock:
            for ps in self.parked.values():
                self.store.release(ps.key)
            self.parked.clear()
            self._timers = []
            self._elapsed.clear()
            self._pending_parks.clear()
            for req, entry in pairs:
                if req.id in covered_ids or req.future.done:
                    continue
                key = entry["key"]
                payload = blobs.get(key)
                try:
                    if payload is None:
                        raise SwapCorrupt(key, "blob missing from "
                                               "snapshot")
                    self.store.adopt(key, bytes(payload))
                except SwapCorrupt as e:
                    self.counters["corrupt"] += 1
                    self._record("effects", e)
                    lost.append(req)
                    continue
                ps = ParkedSession.from_journal(entry, req, now)
                self.parked[req.id] = ps
                for hexp in entry.get("payloads", ()):
                    self.pending.setdefault(req.id, deque()).append(
                        bytes.fromhex(hexp))
                if ps.woken or self.pending.get(req.id):
                    ps.woken = True
                    if ps.wake == "timer":
                        self._elapsed.add(req.id)
                elif ps.wake == "timer" and ps.wake_at is not None:
                    heapq.heappush(self._timers,
                                   (ps.wake_at, next(self._seq),
                                    req.id))
        return lost

    def drop_all(self) -> List[object]:
        """Shutdown / terminal-failure sweep: release every blob and
        return the parked requests so the server can reject their
        futures."""
        out = []
        with self._lock:
            for ps in self.parked.values():
                self.store.release(ps.key)
                out.append(ps.req)
            self.parked.clear()
            self._timers = []
            self._elapsed.clear()
            self._wakes.clear()
            self._pending_parks.clear()
            self.pending.clear()
        return out

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            parked = len(self.parked)
            woken = sum(1 for ps in self.parked.values() if ps.woken)
            timers = len(self._timers)
            queued_wakes = len(self._wakes)
        count, sum_s, buckets = self._park_obs
        return {
            "parked": parked,
            "woken_pending": woken,
            "timers": timers,
            "queued_wakes": queued_wakes,
            "store_entries": len(self.store),
            "store_bytes": self.store.bytes_held,
            "park_seconds": {
                "count": count, "sum": sum_s,
                "buckets": {("%g" % ub): buckets[i]
                            for i, ub in enumerate(PARK_BUCKETS)},
                "overflow": buckets[-1],
            },
            **self.counters,
        }
