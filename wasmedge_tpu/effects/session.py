"""ParkedSession: one suspended request, off-device.

The serializable record the durable journal carries (serve checkpoint
`parked_sessions` entries + `effblob_<key>` extra arrays), mirroring
hv's VirtualLane journal discipline: monotonic stamps are converted to
REMAINING seconds at journal time and re-armed on restore, futures are
process-local and never journaled, pending wake payloads ride as hex
strings so a payload delivered just before a crash is not lost.
"""

from __future__ import annotations

from typing import List, Optional


class ParkedSession:
    """One admitted request suspended on a blocking effect, its lane
    state parked in the SwapStore under a content key."""

    __slots__ = ("req", "key", "stdout_pos", "wake", "wake_at",
                 "deadline_left", "parked_at", "woken", "swaps")

    def __init__(self, req, key: str, stdout_pos: int, wake: str,
                 wake_at: Optional[float] = None,
                 deadline_left: Optional[float] = None,
                 parked_at: float = 0.0):
        self.req = req
        self.key = key
        self.stdout_pos = int(stdout_pos)
        self.wake = wake              # "http" | "timer"
        self.wake_at = wake_at        # monotonic stamp (timer wakes)
        # remaining deadline budget for an "http" park — the request's
        # deadline clock PAUSES while waiting on an explicit wake and
        # re-arms at install (ISSUE 19 satellite); timer parks keep
        # their absolute deadline and are killed at the boundary when
        # it lapses
        self.deadline_left = deadline_left
        self.parked_at = parked_at    # monotonic stamp (duration obs)
        self.woken = False            # wake observed, install pending
        self.swaps = 1

    def journal(self, now: float, payloads: List[bytes]) -> dict:
        """JSON-serializable checkpoint entry."""
        return {
            "id": self.req.id, "func": self.req.func_name,
            "args": [int(a) for a in self.req.args],
            "tenant": self.req.tenant,
            "key": self.key, "stdout_pos": self.stdout_pos,
            "wake": self.wake,
            "wake_remaining": (max(self.wake_at - now, 0.0)
                               if self.wake_at is not None else None),
            "deadline_left": self.deadline_left,
            "woken": bool(self.woken),
            "payloads": [bytes(p).hex() for p in payloads],
        }

    @classmethod
    def from_journal(cls, entry: dict, req, now: float
                     ) -> "ParkedSession":
        """Rebuild from a journal entry (`req` is the re-created or
        reattached ServeRequest; timer deadlines re-arm from the
        journaled remaining seconds)."""
        wake_remaining = entry.get("wake_remaining")
        ps = cls(req, entry["key"], int(entry.get("stdout_pos", 0)),
                 entry.get("wake", "http"),
                 wake_at=(now + float(wake_remaining)
                          if wake_remaining is not None else None),
                 deadline_left=entry.get("deadline_left"),
                 parked_at=now)
        ps.woken = bool(entry.get("woken", False))
        return ps
