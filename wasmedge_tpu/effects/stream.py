"""Per-request stdout stream buffers (the gateway /stream seam).

Each request with the effects subsystem on gets a StreamBuf fed from
the tier-0 stdout flush (batch/hostcall.py flush_stdout_buffers): the
flush loop hands over each lane's FRESH record bytes together with
their logical stream position, so chunks dedupe by position — a crash
restore collapses the flush high-water mark and replays a window of
output to the host fds (at-least-once there), but the stream buffer
drops the overlap and subscribers see each logical byte once per
connection.  Replay across RECONNECTS is offset-based: a subscriber
passes the last offset it saw and reads forward; bytes older than the
bounded window (EffectsConfigure.stream_buffer_bytes) are gone, and
the read reports the gap instead of silently skipping.

Thread model: the serving/launch thread appends, gateway handler
threads block in read() — one Condition per buffer.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple


class StreamBuf:
    """Bounded, offset-addressed byte window over one request's stdout
    stream."""

    def __init__(self, cap: int = 1 << 20):
        self.cap = max(int(cap), 1)
        self._cond = threading.Condition()
        self._data = bytearray()
        self._start = 0        # logical offset of _data[0]
        self.closed = False
        self.error: Optional[str] = None

    @property
    def end(self) -> int:
        """Logical offset one past the last buffered byte."""
        with self._cond:
            return self._start + len(self._data)

    def append(self, pos: int, data: bytes):
        """Add `data` whose first byte sits at logical stream position
        `pos`.  Overlap with already-buffered positions is a replay
        (crash restore) and is dropped; a forward gap (bytes aged out
        before ever reaching the buffer) cannot happen from the flush
        seam, which always hands positions in order."""
        if not data:
            return
        with self._cond:
            end = self._start + len(self._data)
            if pos < end:
                skip = end - pos
                if skip >= len(data):
                    return
                data = data[skip:]
            self._data.extend(data)
            over = len(self._data) - self.cap
            if over > 0:
                del self._data[:over]
                self._start += over
            self._cond.notify_all()

    def close(self, error: Optional[str] = None):
        """End of stream (request resolved / rejected).  `error` rides
        to subscribers as the stream's terminal note."""
        with self._cond:
            self.closed = True
            if error is not None:
                self.error = error
            self._cond.notify_all()

    def read(self, offset: int, timeout: Optional[float] = None
             ) -> Tuple[Optional[bytes], int, bool]:
        """Block until bytes past `offset` exist (or the stream closes
        / `timeout` lapses).  Returns (chunk, next_offset, closed);
        chunk is None on a bare timeout.  An `offset` older than the
        buffered window snaps forward to the window start — the caller
        sees next_offset jump and can report the gap."""
        with self._cond:
            deadline = None
            while True:
                if offset < self._start:
                    offset = self._start   # aged-out gap: snap forward
                avail = self._start + len(self._data) - offset
                if avail > 0:
                    lo = offset - self._start
                    chunk = bytes(self._data[lo:])
                    return chunk, offset + len(chunk), self.closed
                if self.closed:
                    return b"", offset, True
                if timeout is not None:
                    import time as _t

                    now = _t.monotonic()
                    if deadline is None:
                        deadline = now + timeout
                    left = deadline - now
                    if left <= 0:
                        return None, offset, False
                    self._cond.wait(timeout=left)
                else:
                    self._cond.wait()
