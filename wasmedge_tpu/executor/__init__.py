from wasmedge_tpu.executor.executor import Executor

__all__ = ["Executor"]
