"""Scalar reference engine: the dispatch loop over the lowered image.

Mirrors the reference interpreter loop (/root/reference/lib/executor/engine/
engine.cpp:68-1641): `while pc != end` over a flat instruction array, with
Statistics hooks and a StopToken check at calls and branches
(lib/executor/helper.cpp:24,184). This engine is the bit-exactness oracle
the batch TPU engine is tested against, and the fallback for modules the
batch engine cannot take (SURVEY.md §7 step 3).

Execution state is exactly the SoA the device engine uses, in scalar form:
pc, fp, operand/locals stack (raw 64-bit cells), frame stack. Branches are
{target_pc, keep, pop_to} descriptors; calls are fp-relative frame pushes
(reference analog: stackmgr.h:80-128, helper.cpp:153-176).
"""

from __future__ import annotations

from typing import List, Optional

from wasmedge_tpu.common.errors import ErrCode, TrapError, trap
from wasmedge_tpu.common.opcodes import Op
from wasmedge_tpu.common.types import MASK32, MASK64, s32
from wasmedge_tpu.executor.numeric import HANDLERS
from wasmedge_tpu.executor import simd as _simd
from wasmedge_tpu.runtime.instance import FunctionInstance, ModuleInstance
from wasmedge_tpu.validator.image import LOP_BR, LOP_BRNZ, LOP_BRZ

OP_RETURN = Op.__dict__["return"]
MASK128 = (1 << 128) - 1

# Load/store op metadata: op -> (nbytes, signed, result mask)
_LOAD_INFO = {
    Op.i32_load: (4, False, MASK32), Op.i64_load: (8, False, MASK64),
    Op.f32_load: (4, False, MASK32), Op.f64_load: (8, False, MASK64),
    Op.i32_load8_s: (1, True, MASK32), Op.i32_load8_u: (1, False, MASK32),
    Op.i32_load16_s: (2, True, MASK32), Op.i32_load16_u: (2, False, MASK32),
    Op.i64_load8_s: (1, True, MASK64), Op.i64_load8_u: (1, False, MASK64),
    Op.i64_load16_s: (2, True, MASK64), Op.i64_load16_u: (2, False, MASK64),
    Op.i64_load32_s: (4, True, MASK64), Op.i64_load32_u: (4, False, MASK64),
}
_STORE_INFO = {
    Op.i32_store: 4, Op.i64_store: 8, Op.f32_store: 4, Op.f64_store: 8,
    Op.i32_store8: 1, Op.i32_store16: 2,
    Op.i64_store8: 1, Op.i64_store16: 2, Op.i64_store32: 4,
}

# SIMD wide loads: op -> (src lane bytes, lane count, signed) for NxM loads
_SIMD_EXT_LOAD = {
    Op.v128_load8x8_s: (1, 8, True), Op.v128_load8x8_u: (1, 8, False),
    Op.v128_load16x4_s: (2, 4, True), Op.v128_load16x4_u: (2, 4, False),
    Op.v128_load32x2_s: (4, 2, True), Op.v128_load32x2_u: (4, 2, False),
}
_SIMD_SPLAT_LOAD = {
    Op.v128_load8_splat: 1, Op.v128_load16_splat: 2,
    Op.v128_load32_splat: 4, Op.v128_load64_splat: 8,
}
_SIMD_ZERO_LOAD = {Op.v128_load32_zero: 4, Op.v128_load64_zero: 8}
_SIMD_LANE_LOAD = {
    Op.v128_load8_lane: 1, Op.v128_load16_lane: 2,
    Op.v128_load32_lane: 4, Op.v128_load64_lane: 8,
}
_SIMD_LANE_STORE = {
    Op.v128_store8_lane: 1, Op.v128_store16_lane: 2,
    Op.v128_store32_lane: 4, Op.v128_store64_lane: 8,
}
# lane access: op -> (shape, signed, result mask or None for v128 result)
_SIMD_EXTRACT = {
    Op.i8x16_extract_lane_s: ("i8x16", True, MASK32),
    Op.i8x16_extract_lane_u: ("i8x16", False, MASK32),
    Op.i16x8_extract_lane_s: ("i16x8", True, MASK32),
    Op.i16x8_extract_lane_u: ("i16x8", False, MASK32),
    Op.i32x4_extract_lane: ("i32x4", False, MASK32),
    Op.i64x2_extract_lane: ("i64x2", False, MASK64),
    Op.f32x4_extract_lane: ("f32x4", False, MASK32),
    Op.f64x2_extract_lane: ("f64x2", False, MASK64),
}
_SIMD_REPLACE = {
    Op.i8x16_replace_lane: "i8x16", Op.i16x8_replace_lane: "i16x8",
    Op.i32x4_replace_lane: "i32x4", Op.i64x2_replace_lane: "i64x2",
    Op.f32x4_replace_lane: "f32x4", Op.f64x2_replace_lane: "f64x2",
}


class Thread:
    """One scalar execution context (stack + frames + module cursor)."""

    __slots__ = ("store", "conf", "stat", "stack", "frames", "stop_token",
                 "max_call_depth")

    def __init__(self, store, conf, stat=None):
        self.store = store
        self.conf = conf
        self.stat = stat
        self.stack: List[int] = []
        self.frames: List[tuple] = []
        self.stop_token = False
        self.max_call_depth = conf.runtime.max_call_depth


def run_function(thread: Thread, fi: FunctionInstance, args: List[int]) -> List[int]:
    """Invoke a wasm or host function with raw-cell args; returns raw cells."""
    if fi.kind == "host":
        mem = fi.module.memories[0] if (fi.module and fi.module.memories) else None
        return fi.host.run(mem, list(args))
    return _run_wasm(thread, fi, args)


def _run_wasm(thread: Thread, fi: FunctionInstance, args: List[int]) -> List[int]:
    module = fi.module
    image = module.lowered
    meta = image.funcs[fi.func_idx]
    st = thread.stack
    frames = thread.frames
    base_frames = len(frames)
    stat = thread.stat

    # Entry frame: locals at fp, zero-initialized non-params.
    fp = len(st)
    st.extend(args)
    st.extend([0] * (meta.nlocals - meta.nparams))
    opbase = fp + meta.nlocals
    frames.append((-1, -1, -1, None))  # sentinel
    pc = meta.entry_pc

    ops = image.op
    aa = image.a
    bb = image.b
    cc = image.c
    imm = image.imm
    brt = image.br_table
    v128c = image.v128
    funcs = module.funcs
    memories = module.memories
    globals_ = module.globals
    tables = module.tables
    elems = module.elems
    datas = module.datas
    count_stats = stat is not None and (stat.instr_counting or stat.cost_measuring)

    while True:
        op = ops[pc]
        if count_stats:
            if stat.instr_counting:
                stat.inc_instr()
            if stat.cost_measuring:
                stat.add_instr_cost(op)

        h = HANDLERS.get(op)
        if h is not None:  # numeric fast path
            h(st)
            pc += 1
            continue

        if op == Op.local_get:
            st.append(st[fp + aa[pc]])
            pc += 1
        elif op == Op.local_set:
            st[fp + aa[pc]] = st.pop()
            pc += 1
        elif op == Op.local_tee:
            st[fp + aa[pc]] = st[-1]
            pc += 1
        elif op in (Op.i32_const, Op.i64_const, Op.f32_const, Op.f64_const):
            st.append(imm[pc] if imm[pc] >= 0 else imm[pc] + (1 << 64))
            pc += 1
        elif op == LOP_BR:
            if thread.stop_token:
                trap(ErrCode.Terminated)
            keep = bb[pc]
            kept = st[len(st) - keep:] if keep else []
            del st[opbase + cc[pc]:]
            st.extend(kept)
            pc = aa[pc]
        elif op == LOP_BRZ:
            if st.pop() == 0:
                pc = aa[pc]
            else:
                pc += 1
        elif op == LOP_BRNZ:
            if st.pop() != 0:
                if thread.stop_token:
                    trap(ErrCode.Terminated)
                keep = bb[pc]
                kept = st[len(st) - keep:] if keep else []
                del st[opbase + cc[pc]:]
                st.extend(kept)
                pc = aa[pc]
            else:
                pc += 1
        elif op == Op.br_table:
            if thread.stop_token:
                trap(ErrCode.Terminated)
            i = st.pop() & MASK32
            n = bb[pc]
            entry = (aa[pc] + (i if i < n else n)) * 3
            keep = brt[entry + 1]
            kept = st[len(st) - keep:] if keep else []
            del st[opbase + brt[entry + 2]:]
            st.extend(kept)
            pc = brt[entry]
        elif op == OP_RETURN:
            n = bb[pc]
            results = st[len(st) - n:] if n else []
            del st[fp:]
            st.extend(results)
            ret_pc, prev_fp, prev_opbase, prev_module = frames.pop()
            if len(frames) == base_frames:
                out = st[len(st) - n:] if n else []
                del st[len(st) - n:]
                return out
            pc, fp, opbase = ret_pc, prev_fp, prev_opbase
            if prev_module is not None and prev_module is not module:
                module = prev_module
                image = module.lowered
                ops, aa, bb, cc, imm = image.op, image.a, image.b, image.c, image.imm
                brt = image.br_table
                v128c = image.v128
                funcs, memories = module.funcs, module.memories
                globals_, tables = module.globals, module.tables
                elems, datas = module.elems, module.datas
        elif op in (Op.call, Op.call_indirect, Op.return_call,
                    Op.return_call_indirect):
            if thread.stop_token:
                trap(ErrCode.Terminated)
            tail = op in (Op.return_call, Op.return_call_indirect)
            if op in (Op.call, Op.return_call):
                callee = funcs[aa[pc]]
            else:
                tab = tables[bb[pc]]
                i = st.pop() & MASK32
                if i >= tab.size:
                    trap(ErrCode.UndefinedElement)
                href = tab.refs[i]
                if href == 0:
                    trap(ErrCode.UninitializedElement)
                callee = thread.store.deref_func(href)
                if callee is None:
                    trap(ErrCode.UninitializedElement)
                if callee.functype != module.ast.types[aa[pc]]:
                    trap(ErrCode.IndirectCallTypeMismatch)

            if callee.kind == "host":
                hf = callee.host
                nargs = len(hf.functype.params)
                raw = st[len(st) - nargs:] if nargs else []
                del st[len(st) - nargs:]
                if stat is not None and stat.cost_measuring:
                    stat.add_cost(hf.cost)
                mem = memories[0] if memories else None
                if stat is not None:
                    stat.stop_wasm()
                    stat.start_host()
                try:
                    res = hf.run(mem, raw)
                finally:
                    if stat is not None:
                        stat.stop_host()
                        stat.start_wasm()
                st.extend(res)
                if tail:
                    # host tail call: return results directly
                    n = len(res)
                    results = st[len(st) - n:] if n else []
                    del st[fp:]
                    st.extend(results)
                    ret_pc, prev_fp, prev_opbase, prev_module = frames.pop()
                    if len(frames) == base_frames:
                        out = st[len(st) - n:] if n else []
                        del st[len(st) - n:]
                        return out
                    pc, fp, opbase = ret_pc, prev_fp, prev_opbase
                    if prev_module is not None and prev_module is not module:
                        module = prev_module
                        image = module.lowered
                        ops, aa, bb, cc, imm = image.op, image.a, image.b, image.c, image.imm
                        brt = image.br_table
                        v128c = image.v128
                        funcs, memories = module.funcs, module.memories
                        globals_, tables = module.globals, module.tables
                        elems, datas = module.elems, module.datas
                else:
                    pc += 1
            else:
                cmeta = callee.module.lowered.funcs[callee.func_idx]
                nargs = cmeta.nparams
                if tail:
                    # Replace current frame (reference: stackmgr.h:80-98).
                    tail_args = st[len(st) - nargs:] if nargs else []
                    del st[fp:]
                    st.extend(tail_args)
                    ret_frame = frames.pop()
                else:
                    ret_frame = (pc + 1, fp, opbase, module)
                if len(frames) - base_frames >= thread.max_call_depth:
                    trap(ErrCode.CallStackExhausted)
                frames.append(ret_frame)
                fp = len(st) - nargs
                st.extend([0] * (cmeta.nlocals - nargs))
                opbase = fp + cmeta.nlocals
                if callee.module is not module:
                    module = callee.module
                    image = module.lowered
                    ops, aa, bb, cc, imm = image.op, image.a, image.b, image.c, image.imm
                    brt = image.br_table
                    v128c = image.v128
                    funcs, memories = module.funcs, module.memories
                    globals_, tables = module.globals, module.tables
                    elems, datas = module.elems, module.datas
                pc = cmeta.entry_pc
        elif op == Op.drop:
            st.pop()
            pc += 1
        elif op == Op.select:
            c = st.pop()
            v2 = st.pop()
            if c == 0:
                st[-1] = v2
            pc += 1
        elif op == Op.global_get:
            st.append(globals_[aa[pc]].value)
            pc += 1
        elif op == Op.global_set:
            globals_[aa[pc]].value = st.pop()
            pc += 1
        elif op in _LOAD_INFO:
            nbytes, signed, mask = _LOAD_INFO[op]
            addr = (st[-1] & MASK32) + (imm[pc] & MASK64)
            st[-1] = memories[0].load(addr, nbytes, signed) & mask
            pc += 1
        elif op in _STORE_INFO:
            nbytes = _STORE_INFO[op]
            v = st.pop()
            addr = (st.pop() & MASK32) + (imm[pc] & MASK64)
            memories[0].store(addr, nbytes, v)
            pc += 1
        elif op == Op.memory_size:
            st.append(memories[0].pages)
            pc += 1
        elif op == Op.memory_grow:
            delta = st.pop() & MASK32
            st.append(memories[0].grow(delta) & MASK32)
            pc += 1
        elif op == Op.memory_init:
            n = st.pop() & MASK32
            src = st.pop() & MASK32
            dst = st.pop() & MASK32
            seg = datas[aa[pc]]
            if src + n > len(seg.data):
                trap(ErrCode.MemoryOutOfBounds)
            memories[0].store_bytes(dst, seg.data[src:src + n])
            pc += 1
        elif op == Op.data_drop:
            datas[aa[pc]].clear()
            pc += 1
        elif op == Op.memory_copy:
            n = st.pop() & MASK32
            src = st.pop() & MASK32
            dst = st.pop() & MASK32
            buf = memories[0].load_bytes(src, n)
            memories[0].store_bytes(dst, buf)
            pc += 1
        elif op == Op.memory_fill:
            n = st.pop() & MASK32
            val = st.pop() & 0xFF
            dst = st.pop() & MASK32
            memories[0].check_bounds(dst, n)  # trap before allocating n bytes
            memories[0].store_bytes(dst, bytes([val]) * n)
            pc += 1
        elif op == Op.unreachable:
            trap(ErrCode.Unreachable)
        elif op == Op.ref_null:
            st.append(0)
            pc += 1
        elif op == Op.ref_is_null:
            st[-1] = 1 if st[-1] == 0 else 0
            pc += 1
        elif op == Op.ref_func:
            st.append(thread.store.intern_ref(funcs[aa[pc]]))
            pc += 1
        elif op == Op.table_get:
            i = st[-1] & MASK32
            st[-1] = tables[aa[pc]].get(i)
            pc += 1
        elif op == Op.table_set:
            v = st.pop()
            i = st.pop() & MASK32
            tables[aa[pc]].set(i, v)
            pc += 1
        elif op == Op.table_size:
            st.append(tables[aa[pc]].size)
            pc += 1
        elif op == Op.table_grow:
            delta = st.pop() & MASK32
            init = st.pop()
            st.append(tables[aa[pc]].grow(delta, init) & MASK32)
            pc += 1
        elif op == Op.table_fill:
            n = st.pop() & MASK32
            val = st.pop()
            i = st.pop() & MASK32
            tab = tables[aa[pc]]
            if i + n > tab.size:
                trap(ErrCode.TableOutOfBounds)
            for k in range(n):
                tab.refs[i + k] = val
            pc += 1
        elif op == Op.table_copy:
            n = st.pop() & MASK32
            src = st.pop() & MASK32
            dst = st.pop() & MASK32
            tdst, tsrc = tables[aa[pc]], tables[bb[pc]]
            if src + n > tsrc.size or dst + n > tdst.size:
                trap(ErrCode.TableOutOfBounds)
            chunk = tsrc.refs[src:src + n]
            tdst.refs[dst:dst + n] = chunk
            pc += 1
        elif op == Op.table_init:
            n = st.pop() & MASK32
            src = st.pop() & MASK32
            dst = st.pop() & MASK32
            seg = elems[aa[pc]]
            tab = tables[bb[pc]]
            if src + n > len(seg.refs) or dst + n > tab.size:
                trap(ErrCode.TableOutOfBounds)
            tab.refs[dst:dst + n] = seg.refs[src:src + n]
            pc += 1
        elif op == Op.elem_drop:
            elems[aa[pc]].clear()
            pc += 1
        elif op == Op.v128_const:
            st.append(v128c[aa[pc]])
            pc += 1
        elif op == Op.i8x16_shuffle:
            b = st.pop()
            st[-1] = _simd.shuffle(st[-1], b, v128c[aa[pc]])
            pc += 1
        elif op in _SIMD_EXTRACT:
            shape, signed, mask = _SIMD_EXTRACT[op]
            st[-1] = _simd.extract_lane(st[-1], shape, aa[pc], signed) & mask
            pc += 1
        elif op in _SIMD_REPLACE:
            x = st.pop()
            st[-1] = _simd.replace_lane(st[-1], _SIMD_REPLACE[op], aa[pc], x)
            pc += 1
        elif op == Op.v128_load:
            addr = (st[-1] & MASK32) + (imm[pc] & MASK64)
            st[-1] = memories[0].load(addr, 16, False)
            pc += 1
        elif op == Op.v128_store:
            v = st.pop()
            addr = (st.pop() & MASK32) + (imm[pc] & MASK64)
            memories[0].store(addr, 16, v & MASK128)
            pc += 1
        elif op in _SIMD_EXT_LOAD:
            wbytes, nl, signed = _SIMD_EXT_LOAD[op]
            addr = (st[-1] & MASK32) + (imm[pc] & MASK64)
            raw = memories[0].load_bytes(addr, wbytes * nl)
            vals = [int.from_bytes(raw[k * wbytes:(k + 1) * wbytes],
                                   "little", signed=signed)
                    for k in range(nl)]
            st[-1] = _simd.pack(vals, (16 // nl) * 8)
            pc += 1
        elif op in _SIMD_SPLAT_LOAD:
            wbytes = _SIMD_SPLAT_LOAD[op]
            addr = (st[-1] & MASK32) + (imm[pc] & MASK64)
            x = memories[0].load(addr, wbytes, False)
            st[-1] = _simd.pack([x] * (16 // wbytes), wbytes * 8)
            pc += 1
        elif op in _SIMD_ZERO_LOAD:
            wbytes = _SIMD_ZERO_LOAD[op]
            addr = (st[-1] & MASK32) + (imm[pc] & MASK64)
            st[-1] = memories[0].load(addr, wbytes, False)
            pc += 1
        elif op in _SIMD_LANE_LOAD:
            wbytes = _SIMD_LANE_LOAD[op]
            v = st.pop()
            addr = (st.pop() & MASK32) + (imm[pc] & MASK64)
            x = memories[0].load(addr, wbytes, False)
            shape = {1: "i8x16", 2: "i16x8", 4: "i32x4", 8: "i64x2"}[wbytes]
            st.append(_simd.replace_lane(v, shape, aa[pc], x))
            pc += 1
        elif op in _SIMD_LANE_STORE:
            wbytes = _SIMD_LANE_STORE[op]
            v = st.pop()
            addr = (st.pop() & MASK32) + (imm[pc] & MASK64)
            shape = {1: "i8x16", 2: "i16x8", 4: "i32x4", 8: "i64x2"}[wbytes]
            memories[0].store(addr, wbytes,
                              _simd.extract_lane(v, shape, aa[pc], False))
            pc += 1
        elif op == Op.nop:
            pc += 1
        else:
            raise TrapError(ErrCode.ExecutionFailed,
                            f"scalar engine: unhandled lowered op {op} at pc {pc}")
