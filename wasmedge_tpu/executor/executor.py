"""Executor front door: instantiation + invocation.

Mirrors the reference Executor (/root/reference/lib/executor/executor.cpp:
13-117 and lib/executor/instantiate/*.cpp): section-by-section instantiation
in spec order (types -> imports -> funcs -> tables -> memories -> globals
(init exprs) -> exports -> elements -> data -> start), `invoke` with
parameter type checking, and engine selection. The engine used for a call
is chosen via Configure (scalar oracle / native C++ / tpu_batch) — the
reference's interpreter/AOT seam (include/runtime/instance/function.h).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from wasmedge_tpu.common.configure import Configure, EngineKind
from wasmedge_tpu.common.errors import (
    ErrCode,
    InstantiationError,
    TrapError,
    WasmError,
)
from wasmedge_tpu.common.opcodes import Op
from wasmedge_tpu.common.statistics import Statistics
from wasmedge_tpu.common.types import ValType, bits_to_typed, typed_to_bits
from wasmedge_tpu.executor import engine as scalar_engine
from wasmedge_tpu.loader import ast
from wasmedge_tpu.runtime.hostfunc import ImportObject
from wasmedge_tpu.runtime.instance import (
    DataInstance,
    ElementInstance,
    FunctionInstance,
    GlobalInstance,
    MemoryInstance,
    ModuleInstance,
    TableInstance,
)
from wasmedge_tpu.runtime.store import StoreManager


def _limits_match(provided_min, provided_max, required_min, required_max) -> bool:
    """Import limit matching per spec: provided range within required."""
    if provided_min < required_min:
        return False
    if required_max is not None:
        if provided_max is None or provided_max > required_max:
            return False
    return True


class StopToken:
    """Interruption token polled at calls/branches (reference:
    include/executor/executor.h:637, lib/executor/helper.cpp:24,184).
    Truthiness is the poll, so the engine's `if thread.stop_token:` works
    unchanged whether it holds a plain bool or this shared token. One token
    per execution: a stale stop() cannot poison later runs, and cancelling
    one async handle does not terminate its siblings."""

    __slots__ = ("_flag", "native_cell")

    def __init__(self):
        self._flag = False
        self.native_cell = None  # int32[1] polled by the native engine

    def stop(self):
        self._flag = True
        cell = self.native_cell
        if cell is not None:
            cell[0] = 1

    def __bool__(self) -> bool:
        return self._flag


class Executor:
    def __init__(self, conf: Optional[Configure] = None,
                 stat: Optional[Statistics] = None):
        self.conf = conf or Configure()
        self.stat = stat
        self._active_tokens: set = set()
        self._token_lock = threading.Lock()
        # why the last NATIVE-engine invoke fell back to the Python engine
        # (None after a successful native run)
        self.native_fallback_reason: Optional[str] = None

    def stop(self):
        """Interrupt every execution currently in flight (reference:
        Executor::stop; here fan-out because tokens are per-execution)."""
        with self._token_lock:
            for t in self._active_tokens:
                t.stop()

    # ------------------------------------------------------------------
    # Instantiation
    # ------------------------------------------------------------------
    def register_import_object(self, store: StoreManager, impobj: ImportObject):
        """Host module -> named ModuleInstance (reference: vm.cpp:30-41)."""
        mod = ast.Module()
        mod.validated = True
        inst = ModuleInstance(impobj.name, mod)
        for name, hf in impobj.funcs.items():
            inst.exports[name] = (0, len(inst.funcs))
            inst.funcs.append(FunctionInstance("host", hf.functype,
                                               module=inst, host=hf))
        for name, tab in impobj.tables.items():
            inst.exports[name] = (1, len(inst.tables))
            inst.tables.append(tab)
        for name, mem in impobj.memories.items():
            inst.exports[name] = (2, len(inst.memories))
            inst.memories.append(mem)
        for name, glob in impobj.globals.items():
            inst.exports[name] = (3, len(inst.globals))
            inst.globals.append(glob)
        store.register_named(inst)
        return inst

    def register_module(self, store: StoreManager, mod: ast.Module, name: str):
        if store.find_module(name) is not None:
            raise InstantiationError(ErrCode.ModuleNameConflict, name)
        inst = self.instantiate(store, mod, name)
        store.register_named(inst)
        return inst

    def instantiate(self, store: StoreManager, mod: ast.Module,
                    name: str = "") -> ModuleInstance:
        if not mod.validated or mod.lowered is None:
            raise WasmError(ErrCode.NotValidated, "module not validated")
        inst = ModuleInstance(name, mod)

        # Imports (reference: lib/executor/instantiate/import.cpp).
        for im in mod.imports:
            src = store.find_module(im.module)
            if src is None:
                raise InstantiationError(ErrCode.UnknownImport,
                                         f"{im.module}.{im.name}: unknown module")
            ex = src.exports.get(im.name)
            if ex is None or ex[0] != im.kind:
                raise InstantiationError(ErrCode.UnknownImport,
                                         f"{im.module}.{im.name}")
            kind, idx = ex
            if kind == 0:
                fi = src.funcs[idx]
                want = mod.types[im.type_idx]
                if fi.functype != want:
                    raise InstantiationError(ErrCode.IncompatibleImportType,
                                             f"{im.module}.{im.name}")
                inst.funcs.append(fi)
            elif kind == 1:
                tab = src.tables[idx]
                tt = im.table_type
                if tab.ref_type != tt.ref_type or not _limits_match(
                        tab.size, tab.max, tt.limit.min, tt.limit.max):
                    raise InstantiationError(ErrCode.IncompatibleImportType,
                                             f"{im.module}.{im.name}")
                inst.tables.append(tab)
            elif kind == 2:
                mem = src.memories[idx]
                mt = im.memory_type
                if not _limits_match(mem.pages, mem.max, mt.limit.min, mt.limit.max):
                    raise InstantiationError(ErrCode.IncompatibleImportType,
                                             f"{im.module}.{im.name}")
                inst.memories.append(mem)
            else:
                glob = src.globals[idx]
                gt = im.global_type
                if glob.type.val_type != gt.val_type or glob.type.mutable != gt.mutable:
                    raise InstantiationError(ErrCode.IncompatibleImportType,
                                             f"{im.module}.{im.name}")
                inst.globals.append(glob)

        # Local functions.
        nimp = mod.num_imported_funcs
        for li in range(len(mod.functions)):
            fidx = nimp + li
            inst.funcs.append(FunctionInstance(
                "wasm", mod.func_type_of(fidx), module=inst, func_idx=fidx))

        # Tables and memories.
        for tt in mod.tables:
            inst.tables.append(TableInstance(tt))
        for mt in mod.memories:
            inst.memories.append(
                MemoryInstance(mt, self.conf.runtime.max_memory_pages))

        # Globals (init exprs may reference imported globals / funcs).
        for gseg in mod.globals:
            val = self._eval_const_expr(store, inst, gseg.init)
            inst.globals.append(GlobalInstance(gseg.type, val))

        # Exports.
        for ex in mod.exports:
            inst.exports[ex.name] = (ex.kind, ex.index)

        # Element segments (reference: instantiate/elem.cpp).
        for eseg in mod.elements:
            refs = [self._eval_const_expr(store, inst, expr)
                    for expr in eseg.init_exprs]
            einst = ElementInstance(eseg.ref_type, refs)
            if eseg.mode == 0:  # active: apply then drop
                off = self._eval_const_expr(store, inst, eseg.offset) & 0xFFFFFFFF
                tab = inst.tables[eseg.table_idx]
                if off + len(refs) > tab.size:
                    raise InstantiationError(ErrCode.ElemSegDoesNotFit,
                                             "out of bounds table access")
                tab.refs[off:off + len(refs)] = refs
                einst.clear()
            elif eseg.mode == 2:  # declarative
                einst.clear()
            inst.elems.append(einst)

        # Data segments (reference: instantiate/data.cpp).
        for dseg in mod.datas:
            dinst = DataInstance(dseg.data)
            if dseg.mode == 0:
                off = self._eval_const_expr(store, inst, dseg.offset) & 0xFFFFFFFF
                mem = inst.memories[dseg.memory_idx]
                if off + len(dseg.data) > len(mem.data):
                    raise InstantiationError(ErrCode.DataSegDoesNotFit,
                                             "out of bounds memory access")
                mem.data[off:off + len(dseg.data)] = dseg.data
                dinst.clear()
            inst.datas.append(dinst)

        inst.start = mod.start
        if name:
            store.register_named(inst)
        else:
            store.push_anonymous(inst)

        # Start function runs at instantiation end (instantiate/module.cpp:166).
        if mod.start is not None:
            self.invoke_raw(store, inst.funcs[mod.start], [])
        return inst

    def _eval_const_expr(self, store: StoreManager, inst: ModuleInstance,
                         expr: List[ast.Instruction]) -> int:
        stack: List[int] = []
        for ins in expr:
            if ins.op == Op.end:
                break
            if ins.op in (Op.i32_const, Op.i64_const, Op.f32_const, Op.f64_const):
                stack.append(ins.imm)
            elif ins.op == Op.global_get:
                stack.append(inst.globals[ins.target_idx].value)
            elif ins.op == Op.ref_null:
                stack.append(0)
            elif ins.op == Op.ref_func:
                stack.append(store.intern_ref(inst.funcs[ins.target_idx]))
            else:
                raise InstantiationError(ErrCode.ConstExprRequired)
        return stack[-1] if stack else 0

    # ------------------------------------------------------------------
    # Invocation
    # ------------------------------------------------------------------
    def invoke(self, store: StoreManager, fi: FunctionInstance,
               args: Sequence = (), stop_token: Optional[StopToken] = None) -> list:
        """Typed invoke (reference: executor.cpp:87-97). Arg *count* is
        checked; values are numerically coerced to the declared param types
        (Python args are untagged, unlike the reference's WasmEdge_Value)."""
        ft = fi.functype
        if len(args) != len(ft.params):
            raise TrapError(ErrCode.FuncSigMismatch,
                            f"expected {len(ft.params)} args, got {len(args)}")
        raw = [typed_to_bits(t, v) for t, v in zip(ft.params, args)]
        out = self.invoke_raw(store, fi, raw, stop_token)
        return [bits_to_typed(t, v) for t, v in zip(ft.results, out)]

    def invoke_raw(self, store: StoreManager, fi: FunctionInstance,
                   raw_args: List[int],
                   stop_token: Optional[StopToken] = None) -> List[int]:
        if self.stat is not None:
            self.stat.start_wasm()
        token = stop_token if stop_token is not None else StopToken()
        with self._token_lock:
            self._active_tokens.add(token)
        try:
            if self.conf.engine == EngineKind.NATIVE and fi.kind == "wasm":
                out = self._invoke_native(store, fi, raw_args, token)
                if out is not None:
                    return out
            thread = scalar_engine.Thread(store, self.conf, self.stat)
            thread.stop_token = token
            return scalar_engine.run_function(thread, fi, raw_args)
        finally:
            with self._token_lock:
                self._active_tokens.discard(token)
            if self.stat is not None:
                self.stat.stop_wasm()

    def _invoke_native(self, store, fi, raw_args, token):
        """EngineKind.NATIVE: run on the C++ engine when the module is
        eligible; None = fall back to the Python engine (graceful
        degradation, like the reference's AOT-section fallback at
        lib/loader/ast/module.cpp:279-326).  The NativeModule is cached on
        the module instance."""
        inst = fi.module
        nm = getattr(inst, "_native_module", None)
        if nm is None:
            try:
                from wasmedge_tpu import native

                nm = native.module_for(inst, store)
            except Exception:
                nm = False  # toolchain unavailable; remember that
            inst._native_module = nm
        if nm is False or not nm.eligible:
            self.native_fallback_reason = (
                nm.reason if nm else "native engine unavailable")
            return None
        self.native_fallback_reason = None
        import numpy as np

        cell = np.zeros(1, np.int32)
        # Attach first, THEN mirror the flag: a stop() that lands between
        # the two writes either sees the cell (and sets it) or set _flag
        # before our read — either way the loop observes it.
        token.native_cell = cell
        if token:
            cell[0] = 1
        try:
            out, retired = nm.invoke(
                fi.func_idx, raw_args,
                max_call_depth=self.conf.runtime.max_call_depth,
                stop_cell=cell)
        finally:
            token.native_cell = None
        if self.stat is not None and self.stat.instr_counting:
            self.stat.inc_instr(retired)
        return out
