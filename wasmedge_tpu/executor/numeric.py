"""Exact-semantics numeric op kernels for the scalar oracle engine.

Mirrors the reference's header-inline numeric templates
(/root/reference/include/executor/engine/{binary,unary,cast}_numeric.ipp):
div/rem trap checks, truncation bounds, NaN canonicalization, rounding.
Values are raw 64-bit cells on a Python list stack; floats go through numpy
scalars so f32 arithmetic is correctly rounded (no double rounding).

NaN policy (shared with the batch engine so parity is bit-exact): every
*arithmetic* float op canonicalizes NaN outputs to the positive canonical
NaN; sign-manipulation ops (abs/neg/copysign) and loads/stores/reinterprets
are bit-preserving, as the spec requires.
"""

from __future__ import annotations

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, trap
from wasmedge_tpu.common.opcodes import NAME_TO_ID
from wasmedge_tpu.common.types import (
    F32_CANONICAL_NAN,
    F64_CANONICAL_NAN,
    MASK32,
    MASK64,
    bits_to_f32,
    bits_to_f64,
    f32_to_bits,
    f64_to_bits,
    s32,
    s64,
)

def _np_err():
    return np.errstate(all="ignore")


def _canon32(bits: int) -> int:
    if (bits & 0x7F800000) == 0x7F800000 and (bits & 0x007FFFFF):
        return F32_CANONICAL_NAN
    return bits


def _canon64(bits: int) -> int:
    if (bits & 0x7FF0000000000000) == 0x7FF0000000000000 and (bits & 0x000FFFFFFFFFFFFF):
        return F64_CANONICAL_NAN
    return bits


HANDLERS = {}


def _reg(name):
    def deco(fn):
        HANDLERS[NAME_TO_ID[name]] = fn
        return fn
    return deco


# ---------------------------------------------------------------------------
# integer helpers
# ---------------------------------------------------------------------------

def _idiv_trunc(a: int, b: int) -> int:
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _clz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return bits - v.bit_length()


def _ctz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return (v & -v).bit_length() - 1


def _rotl(v: int, n: int, bits: int, mask: int) -> int:
    n %= bits
    return ((v << n) | (v >> (bits - n))) & mask


# ---------------------------------------------------------------------------
# i32 / i64 binops — generated pairs
# ---------------------------------------------------------------------------

def _gen_int_ops(px: str, bits: int, mask: int, tos, imin: int):
    def binop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            b = st.pop()
            st[-1] = fn(st[-1], b) & mask

    binop("add", lambda a, b: a + b)
    binop("sub", lambda a, b: a - b)
    binop("mul", lambda a, b: a * b)
    binop("and", lambda a, b: a & b)
    binop("or", lambda a, b: a | b)
    binop("xor", lambda a, b: a ^ b)
    binop("shl", lambda a, b: a << (b % bits))
    binop("shr_u", lambda a, b: a >> (b % bits))
    binop("shr_s", lambda a, b: tos(a) >> (b % bits))
    binop("rotl", lambda a, b: _rotl(a, b, bits, mask))
    binop("rotr", lambda a, b: _rotl(a, bits - (b % bits), bits, mask))

    @_reg(f"{px}.div_u")
    def div_u(st):
        b = st.pop()
        if b == 0:
            trap(ErrCode.DivideByZero)
        st[-1] = (st[-1] // b) & mask

    @_reg(f"{px}.rem_u")
    def rem_u(st):
        b = st.pop()
        if b == 0:
            trap(ErrCode.DivideByZero)
        st[-1] = (st[-1] % b) & mask

    @_reg(f"{px}.div_s")
    def div_s(st):
        b = tos(st.pop())
        a = tos(st[-1])
        if b == 0:
            trap(ErrCode.DivideByZero)
        if a == imin and b == -1:
            trap(ErrCode.IntegerOverflow)
        st[-1] = _idiv_trunc(a, b) & mask

    @_reg(f"{px}.rem_s")
    def rem_s(st):
        b = tos(st.pop())
        a = tos(st[-1])
        if b == 0:
            trap(ErrCode.DivideByZero)
        if a == imin and b == -1:
            st[-1] = 0
        else:
            st[-1] = (a - b * _idiv_trunc(a, b)) & mask

    @_reg(f"{px}.clz")
    def clz(st):
        st[-1] = _clz(st[-1], bits)

    @_reg(f"{px}.ctz")
    def ctz(st):
        st[-1] = _ctz(st[-1], bits)

    @_reg(f"{px}.popcnt")
    def popcnt(st):
        st[-1] = bin(st[-1]).count("1")

    @_reg(f"{px}.eqz")
    def eqz(st):
        st[-1] = 1 if st[-1] == 0 else 0

    def cmpop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            b = st.pop()
            st[-1] = 1 if fn(st[-1], b) else 0

    cmpop("eq", lambda a, b: a == b)
    cmpop("ne", lambda a, b: a != b)
    cmpop("lt_u", lambda a, b: a < b)
    cmpop("gt_u", lambda a, b: a > b)
    cmpop("le_u", lambda a, b: a <= b)
    cmpop("ge_u", lambda a, b: a >= b)
    cmpop("lt_s", lambda a, b: tos(a) < tos(b))
    cmpop("gt_s", lambda a, b: tos(a) > tos(b))
    cmpop("le_s", lambda a, b: tos(a) <= tos(b))
    cmpop("ge_s", lambda a, b: tos(a) >= tos(b))


_gen_int_ops("i32", 32, MASK32, s32, -(2**31))
_gen_int_ops("i64", 64, MASK64, s64, -(2**63))


# ---------------------------------------------------------------------------
# float ops — generated for f32/f64
# ---------------------------------------------------------------------------

def _gen_float_ops(px: str, to_f, to_bits, canon, nan_bits: int,
                   sign_bit: int, abs_mask: int):
    def binop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            b = to_f(st.pop())
            a = to_f(st[-1])
            with _np_err():
                r = fn(a, b)
            st[-1] = canon(to_bits(r))

    binop("add", lambda a, b: a + b)
    binop("sub", lambda a, b: a - b)
    binop("mul", lambda a, b: a * b)
    binop("div", lambda a, b: a / b)

    def _minmax(st, pick_min: bool):
        bb = st.pop()
        ab = st[-1]
        a, b = to_f(ab), to_f(bb)
        if np.isnan(a) or np.isnan(b):
            st[-1] = nan_bits
            return
        if a == b:  # handles +0/-0: min picks the sign-set one
            sa, sb = ab & sign_bit, bb & sign_bit
            if pick_min:
                st[-1] = ab if sa else bb
            else:
                st[-1] = ab if not sa else bb
            return
        take_a = (a < b) == pick_min
        st[-1] = ab if take_a else bb

    @_reg(f"{px}.min")
    def fmin(st):
        _minmax(st, True)

    @_reg(f"{px}.max")
    def fmax(st):
        _minmax(st, False)

    # bit-level sign ops: NO canonicalization
    @_reg(f"{px}.abs")
    def fabs(st):
        st[-1] = st[-1] & abs_mask

    @_reg(f"{px}.neg")
    def fneg(st):
        st[-1] = st[-1] ^ sign_bit

    @_reg(f"{px}.copysign")
    def fcopysign(st):
        b = st.pop()
        st[-1] = (st[-1] & abs_mask) | (b & sign_bit)

    def unop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            with _np_err():
                r = fn(to_f(st[-1]))
            st[-1] = canon(to_bits(r))

    unop("ceil", np.ceil)
    unop("floor", np.floor)
    unop("trunc", np.trunc)
    unop("nearest", np.rint)  # round half to even
    unop("sqrt", np.sqrt)

    def cmpop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            b = to_f(st.pop())
            a = to_f(st[-1])
            st[-1] = 1 if fn(a, b) else 0

    cmpop("eq", lambda a, b: a == b)
    cmpop("ne", lambda a, b: a != b)
    cmpop("lt", lambda a, b: a < b)
    cmpop("gt", lambda a, b: a > b)
    cmpop("le", lambda a, b: a <= b)
    cmpop("ge", lambda a, b: a >= b)


_gen_float_ops("f32", bits_to_f32, f32_to_bits, _canon32, F32_CANONICAL_NAN,
               0x80000000, 0x7FFFFFFF)
_gen_float_ops("f64", bits_to_f64, f64_to_bits, _canon64, F64_CANONICAL_NAN,
               0x8000000000000000, 0x7FFFFFFFFFFFFFFF)


# ---------------------------------------------------------------------------
# conversions
# ---------------------------------------------------------------------------

def _trunc_checked(v, lo: float, hi: float, mask: int):
    """Trapping float->int truncation; (lo, hi) are exclusive float bounds."""
    if np.isnan(v):
        trap(ErrCode.InvalidConvToInt)
    t = float(np.trunc(float(v)))
    if not (lo < t < hi):
        trap(ErrCode.IntegerOverflow)
    return int(t) & mask


def _trunc_sat(v, lo_res: int, hi_res: int, lo: float, hi: float, mask: int):
    if np.isnan(v):
        return 0
    t = float(np.trunc(float(v)))
    if t <= lo:
        return lo_res & mask
    if t >= hi:
        return hi_res & mask
    return int(t) & mask


# Exclusive float bounds per the spec tables. The i64_s low bound is the
# largest double strictly below -2^63, so t > lo accepts -2^63 itself.
_TRUNC_BOUNDS = {
    ("i32", "s"): (-(2.0**31) - 1, 2.0**31),
    ("i32", "u"): (-1.0, 2.0**32),
    ("i64", "s"): (-(2.0**63) * (1 + 2**-52), 2.0**63),
    ("i64", "u"): (-1.0, 2.0**64),
}

_SAT_RANGES = {
    ("i32", "s"): (-(2**31), 2**31 - 1),
    ("i32", "u"): (0, 2**32 - 1),
    ("i64", "s"): (-(2**63), 2**63 - 1),
    ("i64", "u"): (0, 2**64 - 1),
}


def _gen_truncs():
    for ity, mask in (("i32", MASK32), ("i64", MASK64)):
        for fty, to_f in (("f32", bits_to_f32), ("f64", bits_to_f64)):
            for sgn in ("s", "u"):
                lo, hi = _TRUNC_BOUNDS[(ity, sgn)]
                lo_res, hi_res = _SAT_RANGES[(ity, sgn)]

                @_reg(f"{ity}.trunc_{fty}_{sgn}")
                def h(st, to_f=to_f, lo=lo, hi=hi, mask=mask):
                    st[-1] = _trunc_checked(to_f(st[-1]), lo, hi, mask)

                @_reg(f"{ity}.trunc_sat_{fty}_{sgn}")
                def hs(st, to_f=to_f, lo=lo, hi=hi, lo_res=lo_res,
                       hi_res=hi_res, mask=mask):
                    st[-1] = _trunc_sat(to_f(st[-1]), lo_res, hi_res, lo, hi, mask)


_gen_truncs()


@_reg("i32.wrap_i64")
def _wrap(st):
    st[-1] = st[-1] & MASK32


@_reg("i64.extend_i32_s")
def _ext_s(st):
    st[-1] = s32(st[-1]) & MASK64


@_reg("i64.extend_i32_u")
def _ext_u(st):
    st[-1] = st[-1] & MASK32


def _gen_sext():
    for name, bits, mask in (
        ("i32.extend8_s", 8, MASK32), ("i32.extend16_s", 16, MASK32),
        ("i64.extend8_s", 8, MASK64), ("i64.extend16_s", 16, MASK64),
        ("i64.extend32_s", 32, MASK64),
    ):
        @_reg(name)
        def h(st, bits=bits, mask=mask):
            v = st[-1] & ((1 << bits) - 1)
            if v >= (1 << (bits - 1)):
                v -= 1 << bits
            st[-1] = v & mask


_gen_sext()


def _gen_converts():
    # int -> float: single correctly-rounded conversion via numpy C casts
    for name, fn in (
        ("f32.convert_i32_s", lambda v: np.float32(np.int64(s32(v)))),
        ("f32.convert_i32_u", lambda v: np.float32(np.int64(v & MASK32))),
        ("f32.convert_i64_s", lambda v: np.float32(np.int64(s64(v)))),
        ("f32.convert_i64_u", lambda v: np.float32(np.uint64(v & MASK64))),
        ("f64.convert_i32_s", lambda v: np.float64(s32(v))),
        ("f64.convert_i32_u", lambda v: np.float64(v & MASK32)),
        ("f64.convert_i64_s", lambda v: np.float64(np.int64(s64(v)))),
        ("f64.convert_i64_u", lambda v: np.float64(np.uint64(v & MASK64))),
    ):
        to_bits = f32_to_bits if name.startswith("f32") else f64_to_bits

        @_reg(name)
        def h(st, fn=fn, to_bits=to_bits):
            st[-1] = to_bits(fn(st[-1]))


_gen_converts()


@_reg("f32.demote_f64")
def _demote(st):
    with _np_err():
        st[-1] = _canon32(f32_to_bits(np.float32(bits_to_f64(st[-1]))))


@_reg("f64.promote_f32")
def _promote(st):
    st[-1] = _canon64(f64_to_bits(np.float64(bits_to_f32(st[-1]))))


@_reg("i32.reinterpret_f32")
def _ri32(st):
    st[-1] = st[-1] & MASK32


@_reg("i64.reinterpret_f64")
def _ri64(st):
    st[-1] = st[-1] & MASK64


@_reg("f32.reinterpret_i32")
def _rf32(st):
    st[-1] = st[-1] & MASK32


@_reg("f64.reinterpret_i64")
def _rf64(st):
    st[-1] = st[-1] & MASK64
