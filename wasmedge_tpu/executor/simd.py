"""SIMD v128 op kernels for the scalar oracle engine.

Mirrors the reference's v128 dispatch block (/root/reference/lib/executor/
engine/engine.cpp ~700-1610 and the SIMD arms of binary/unary_numeric.ipp):
all 236 ops of the final 128-bit SIMD proposal. A v128 value is one
128-bit Python int stack cell (little-endian lane order); lanes are
split/packed exactly, floats go through numpy for correct rounding, and
NaN outputs of arithmetic ops are canonicalized — the same policy as the
scalar numeric kernels so engine parity is bit-exact.
"""

from __future__ import annotations

import struct

import numpy as np

from wasmedge_tpu.common.opcodes import NAME_TO_ID
from wasmedge_tpu.common.types import (
    F32_CANONICAL_NAN,
    F64_CANONICAL_NAN,
    MASK64,
    bits_to_f32,
    bits_to_f64,
    f32_to_bits,
    f64_to_bits,
)
from wasmedge_tpu.executor.numeric import HANDLERS, _canon32, _canon64, _np_err

MASK128 = (1 << 128) - 1


def _reg(name):
    def deco(fn):
        HANDLERS[NAME_TO_ID[name]] = fn
        return fn

    return deco


# -- lane packing -----------------------------------------------------------
def lanes(v: int, n: int, w: int, signed: bool = False):
    """Split a 128-bit int into n lanes of w bits (little-endian)."""
    mask = (1 << w) - 1
    top = 1 << (w - 1)
    out = []
    for k in range(n):
        x = (v >> (w * k)) & mask
        if signed and x & top:
            x -= 1 << w
        out.append(x)
    return out


def pack(vals, w: int) -> int:
    mask = (1 << w) - 1
    v = 0
    for k, x in enumerate(vals):
        v |= (x & mask) << (w * k)
    return v


def _sat(x: int, lo: int, hi: int) -> int:
    return lo if x < lo else (hi if x > hi else x)


# -- int shape families -----------------------------------------------------
# (prefix, lane count, lane bits)
_ISHAPES = [("i8x16", 16, 8), ("i16x8", 8, 16), ("i32x4", 4, 32),
            ("i64x2", 2, 64)]


def _gen_int_shape(px: str, n: int, w: int):
    smin, smax = -(1 << (w - 1)), (1 << (w - 1)) - 1
    umax = (1 << w) - 1
    full = (1 << w) - 1

    def binop(name, fn, signed_a=False, signed_b=False):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn, sa=signed_a, sb=signed_b):
            b = st.pop()
            a = st[-1]
            st[-1] = pack([fn(x, y) for x, y in
                           zip(lanes(a, n, w, sa), lanes(b, n, w, sb))], w)

    def unop(name, fn, signed=False):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn, s=signed):
            st[-1] = pack([fn(x) for x in lanes(st[-1], n, w, s)], w)

    def cmps(name, fn, signed):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn, s=signed):
            b = st.pop()
            a = st[-1]
            st[-1] = pack([full if fn(x, y) else 0 for x, y in
                           zip(lanes(a, n, w, s), lanes(b, n, w, s))], w)

    # arithmetic
    binop("add", lambda a, b: a + b)
    binop("sub", lambda a, b: a - b)
    if px != "i8x16":
        binop("mul", lambda a, b: a * b)
    unop("neg", lambda a: -a)
    unop("abs", lambda a: -a if a < 0 else a, signed=True)

    # compares (eq/ne unsigned; ordered s/u except i64x2 which is s-only)
    cmps("eq", lambda a, b: a == b, False)
    cmps("ne", lambda a, b: a != b, False)
    cmps("lt_s", lambda a, b: a < b, True)
    cmps("gt_s", lambda a, b: a > b, True)
    cmps("le_s", lambda a, b: a <= b, True)
    cmps("ge_s", lambda a, b: a >= b, True)
    if px != "i64x2":
        cmps("lt_u", lambda a, b: a < b, False)
        cmps("gt_u", lambda a, b: a > b, False)
        cmps("le_u", lambda a, b: a <= b, False)
        cmps("ge_u", lambda a, b: a >= b, False)
        binop("min_s", min, True, True)
        binop("max_s", max, True, True)
        binop("min_u", min)
        binop("max_u", max)

    # shifts: amount is a scalar i32, mod lane width
    @_reg(f"{px}.shl")
    def shl(st):
        k = st.pop() % w
        st[-1] = pack([x << k for x in lanes(st[-1], n, w)], w)

    @_reg(f"{px}.shr_u")
    def shr_u(st):
        k = st.pop() % w
        st[-1] = pack([x >> k for x in lanes(st[-1], n, w)], w)

    @_reg(f"{px}.shr_s")
    def shr_s(st):
        k = st.pop() % w
        st[-1] = pack([x >> k for x in lanes(st[-1], n, w, True)], w)

    # reductions
    @_reg(f"{px}.all_true")
    def all_true(st):
        st[-1] = 1 if all(lanes(st[-1], n, w)) else 0

    @_reg(f"{px}.bitmask")
    def bitmask(st):
        v = st[-1]
        m = 0
        for k in range(n):
            if (v >> (w * k + w - 1)) & 1:
                m |= 1 << k
        st[-1] = m

    # splat (operand type varies: i8/i16/i32 take i32, i64 takes i64)
    @_reg(f"{px}.splat")
    def splat(st):
        st[-1] = pack([st[-1]] * n, w)

    # saturating add/sub + avgr for the narrow shapes
    if w <= 16:
        binop("add_sat_s", lambda a, b: _sat(a + b, smin, smax), True, True)
        binop("sub_sat_s", lambda a, b: _sat(a - b, smin, smax), True, True)
        binop("add_sat_u", lambda a, b: _sat(a + b, 0, umax))
        binop("sub_sat_u", lambda a, b: _sat(a - b, 0, umax))
        binop("avgr_u", lambda a, b: (a + b + 1) >> 1)


for _px, _n, _w in _ISHAPES:
    _gen_int_shape(_px, _n, _w)


# -- i8x16 extras -----------------------------------------------------------
@_reg("i8x16.popcnt")
def i8x16_popcnt(st):
    st[-1] = pack([bin(x).count("1") for x in lanes(st[-1], 16, 8)], 8)


@_reg("i8x16.swizzle")
def i8x16_swizzle(st):
    s = lanes(st.pop(), 16, 8)
    a = lanes(st[-1], 16, 8)
    st[-1] = pack([a[i] if i < 16 else 0 for i in s], 8)


# (i8x16.shuffle is dispatched by the engine: it needs the mask immediate.)


# -- v128 bitwise -----------------------------------------------------------
@_reg("v128.not")
def v128_not(st):
    st[-1] = st[-1] ^ MASK128


@_reg("v128.and")
def v128_and(st):
    b = st.pop()
    st[-1] &= b


@_reg("v128.andnot")
def v128_andnot(st):
    b = st.pop()
    st[-1] &= b ^ MASK128


@_reg("v128.or")
def v128_or(st):
    b = st.pop()
    st[-1] |= b


@_reg("v128.xor")
def v128_xor(st):
    b = st.pop()
    st[-1] ^= b


@_reg("v128.bitselect")
def v128_bitselect(st):
    c = st.pop()
    b = st.pop()
    st[-1] = (st[-1] & c) | (b & ~c & MASK128)


@_reg("v128.any_true")
def v128_any_true(st):
    st[-1] = 1 if st[-1] != 0 else 0


# -- narrow / extend / extmul / pairwise ------------------------------------
def _narrow(src_w, dst_w, signed_dst):
    lo = -(1 << (dst_w - 1)) if signed_dst else 0
    hi = (1 << (dst_w - 1)) - 1 if signed_dst else (1 << dst_w) - 1

    def h(st):
        b = lanes(st.pop(), 128 // src_w, src_w, True)
        a = lanes(st[-1], 128 // src_w, src_w, True)
        st[-1] = pack([_sat(x, lo, hi) for x in a + b], dst_w)

    return h


HANDLERS[NAME_TO_ID["i8x16.narrow_i16x8_s"]] = _narrow(16, 8, True)
HANDLERS[NAME_TO_ID["i8x16.narrow_i16x8_u"]] = _narrow(16, 8, False)
HANDLERS[NAME_TO_ID["i16x8.narrow_i32x4_s"]] = _narrow(32, 16, True)
HANDLERS[NAME_TO_ID["i16x8.narrow_i32x4_u"]] = _narrow(32, 16, False)


def _extend(src_w, high, signed):
    n_src = 128 // src_w

    def h(st):
        xs = lanes(st[-1], n_src, src_w, signed)
        half = xs[n_src // 2:] if high else xs[: n_src // 2]
        st[-1] = pack(half, src_w * 2)

    return h


for _sw, _dst in ((8, "i16x8"), (16, "i32x4"), (32, "i64x2")):
    _src = {8: "i8x16", 16: "i16x8", 32: "i32x4"}[_sw]
    for _hi in (False, True):
        for _sgn in (True, False):
            _nm = (f"{_dst}.extend_{'high' if _hi else 'low'}_{_src}_"
                   f"{'s' if _sgn else 'u'}")
            HANDLERS[NAME_TO_ID[_nm]] = _extend(_sw, _hi, _sgn)


def _extmul(src_w, high, signed):
    n_src = 128 // src_w

    def h(st):
        b = lanes(st.pop(), n_src, src_w, signed)
        a = lanes(st[-1], n_src, src_w, signed)
        sl = slice(n_src // 2, None) if high else slice(None, n_src // 2)
        st[-1] = pack([x * y for x, y in zip(a[sl], b[sl])], src_w * 2)

    return h


for _sw, _dst in ((8, "i16x8"), (16, "i32x4"), (32, "i64x2")):
    _src = {8: "i8x16", 16: "i16x8", 32: "i32x4"}[_sw]
    for _hi in (False, True):
        for _sgn in (True, False):
            _nm = (f"{_dst}.extmul_{'high' if _hi else 'low'}_{_src}_"
                   f"{'s' if _sgn else 'u'}")
            HANDLERS[NAME_TO_ID[_nm]] = _extmul(_sw, _hi, _sgn)


def _extadd_pairwise(src_w, signed):
    n_src = 128 // src_w

    def h(st):
        xs = lanes(st[-1], n_src, src_w, signed)
        st[-1] = pack([xs[2 * k] + xs[2 * k + 1] for k in range(n_src // 2)],
                      src_w * 2)

    return h


HANDLERS[NAME_TO_ID["i16x8.extadd_pairwise_i8x16_s"]] = _extadd_pairwise(8, True)
HANDLERS[NAME_TO_ID["i16x8.extadd_pairwise_i8x16_u"]] = _extadd_pairwise(8, False)
HANDLERS[NAME_TO_ID["i32x4.extadd_pairwise_i16x8_s"]] = _extadd_pairwise(16, True)
HANDLERS[NAME_TO_ID["i32x4.extadd_pairwise_i16x8_u"]] = _extadd_pairwise(16, False)


@_reg("i16x8.q15mulr_sat_s")
def q15mulr(st):
    b = lanes(st.pop(), 8, 16, True)
    a = lanes(st[-1], 8, 16, True)
    st[-1] = pack([_sat((x * y + (1 << 14)) >> 15, -(1 << 15), (1 << 15) - 1)
                   for x, y in zip(a, b)], 16)


@_reg("i32x4.dot_i16x8_s")
def dot_i16x8(st):
    b = lanes(st.pop(), 8, 16, True)
    a = lanes(st[-1], 8, 16, True)
    st[-1] = pack([a[2 * k] * b[2 * k] + a[2 * k + 1] * b[2 * k + 1]
                   for k in range(4)], 32)


# -- float shapes -----------------------------------------------------------
def _gen_float_shape(px, n, w, to_f, to_bits, canon, nan_bits, sign_bit,
                     abs_mask):
    def map_bits(st_v, fn):
        return pack([fn((st_v >> (w * k)) & ((1 << w) - 1))
                     for k in range(n)], w)

    def binop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            b = st.pop()
            a = st[-1]

            def one(k):
                x = to_f((a >> (w * k)) & ((1 << w) - 1))
                y = to_f((b >> (w * k)) & ((1 << w) - 1))
                with _np_err():
                    return canon(to_bits(fn(x, y)))

            st[-1] = pack([one(k) for k in range(n)], w)

    binop("add", lambda a, b: a + b)
    binop("sub", lambda a, b: a - b)
    binop("mul", lambda a, b: a * b)
    binop("div", lambda a, b: a / b)

    def unop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            def one(bits):
                with _np_err():
                    return canon(to_bits(fn(to_f(bits))))

            st[-1] = map_bits(st[-1], one)

    unop("ceil", np.ceil)
    unop("floor", np.floor)
    unop("trunc", np.trunc)
    unop("nearest", np.rint)
    unop("sqrt", np.sqrt)

    @_reg(f"{px}.abs")
    def fabs(st):
        st[-1] = map_bits(st[-1], lambda bb: bb & abs_mask)

    @_reg(f"{px}.neg")
    def fneg(st):
        st[-1] = map_bits(st[-1], lambda bb: bb ^ sign_bit)

    def minmax(name, pick_min):
        @_reg(f"{px}.{name}")
        def h(st, pick_min=pick_min):
            bv = st.pop()
            av = st[-1]

            def one(k):
                ab = (av >> (w * k)) & ((1 << w) - 1)
                bb = (bv >> (w * k)) & ((1 << w) - 1)
                a, b = to_f(ab), to_f(bb)
                if np.isnan(a) or np.isnan(b):
                    return nan_bits
                if a == b:
                    sa = ab & sign_bit
                    if pick_min:
                        return ab if sa else bb
                    return ab if not sa else bb
                return ab if (a < b) == pick_min else bb

            st[-1] = pack([one(k) for k in range(n)], w)

    minmax("min", True)
    minmax("max", False)

    def pminmax(name, pick_b):
        # pmin: b < a ? b : a ; pmax: a < b ? b : a (IEEE-style, no NaN fix)
        @_reg(f"{px}.{name}")
        def h(st, pick_b=pick_b):
            bv = st.pop()
            av = st[-1]

            def one(k):
                ab = (av >> (w * k)) & ((1 << w) - 1)
                bb = (bv >> (w * k)) & ((1 << w) - 1)
                a, b = to_f(ab), to_f(bb)
                take_b = (b < a) if pick_b == "pmin" else (a < b)
                return bb if take_b else ab

            st[-1] = pack([one(k) for k in range(n)], w)

    pminmax("pmin", "pmin")
    pminmax("pmax", "pmax")

    def cmpop(name, fn):
        @_reg(f"{px}.{name}")
        def h(st, fn=fn):
            bv = st.pop()
            av = st[-1]

            def one(k):
                a = to_f((av >> (w * k)) & ((1 << w) - 1))
                b = to_f((bv >> (w * k)) & ((1 << w) - 1))
                return (1 << w) - 1 if fn(a, b) else 0

            st[-1] = pack([one(k) for k in range(n)], w)

    cmpop("eq", lambda a, b: a == b)
    cmpop("ne", lambda a, b: a != b)
    cmpop("lt", lambda a, b: a < b)
    cmpop("gt", lambda a, b: a > b)
    cmpop("le", lambda a, b: a <= b)
    cmpop("ge", lambda a, b: a >= b)

    @_reg(f"{px}.splat")
    def splat(st):
        st[-1] = pack([st[-1]] * n, w)


_gen_float_shape("f32x4", 4, 32, bits_to_f32, f32_to_bits, _canon32,
                 F32_CANONICAL_NAN, 0x80000000, 0x7FFFFFFF)
_gen_float_shape("f64x2", 2, 64, bits_to_f64, f64_to_bits, _canon64,
                 F64_CANONICAL_NAN, 1 << 63, (1 << 63) - 1)


# -- conversions ------------------------------------------------------------
def _lane_f32(v, k):
    return bits_to_f32((v >> (32 * k)) & 0xFFFFFFFF)


def _lane_f64(v, k):
    return bits_to_f64((v >> (64 * k)) & MASK64)


def _tsat(x, lo, hi):
    if np.isnan(x):
        return 0
    if x < lo:
        return int(lo)
    if x > hi:
        return int(hi)
    return int(np.trunc(float(x)))


@_reg("i32x4.trunc_sat_f32x4_s")
def trunc_sat_f32_s(st):
    st[-1] = pack([_tsat(_lane_f32(st[-1], k), -(2**31), 2**31 - 1)
                   for k in range(4)], 32)


@_reg("i32x4.trunc_sat_f32x4_u")
def trunc_sat_f32_u(st):
    st[-1] = pack([_tsat(_lane_f32(st[-1], k), 0, 2**32 - 1)
                   for k in range(4)], 32)


@_reg("i32x4.trunc_sat_f64x2_s_zero")
def trunc_sat_f64_s_zero(st):
    st[-1] = pack([_tsat(_lane_f64(st[-1], k), -(2**31), 2**31 - 1)
                   for k in range(2)] + [0, 0], 32)


@_reg("i32x4.trunc_sat_f64x2_u_zero")
def trunc_sat_f64_u_zero(st):
    st[-1] = pack([_tsat(_lane_f64(st[-1], k), 0, 2**32 - 1)
                   for k in range(2)] + [0, 0], 32)


@_reg("f32x4.convert_i32x4_s")
def convert_i32_s(st):
    xs = lanes(st[-1], 4, 32, True)
    st[-1] = pack([f32_to_bits(np.float32(x)) for x in xs], 32)


@_reg("f32x4.convert_i32x4_u")
def convert_i32_u(st):
    xs = lanes(st[-1], 4, 32)
    st[-1] = pack([f32_to_bits(np.float32(x)) for x in xs], 32)


@_reg("f64x2.convert_low_i32x4_s")
def convert_low_s(st):
    xs = lanes(st[-1], 4, 32, True)[:2]
    st[-1] = pack([f64_to_bits(np.float64(x)) for x in xs], 64)


@_reg("f64x2.convert_low_i32x4_u")
def convert_low_u(st):
    xs = lanes(st[-1], 4, 32)[:2]
    st[-1] = pack([f64_to_bits(np.float64(x)) for x in xs], 64)


@_reg("f32x4.demote_f64x2_zero")
def demote_zero(st):
    def one(k):
        with _np_err():
            return _canon32(f32_to_bits(np.float32(_lane_f64(st[-1], k))))

    st[-1] = pack([one(0), one(1), 0, 0], 32)


@_reg("f64x2.promote_low_f32x4")
def promote_low(st):
    def one(k):
        with _np_err():
            return _canon64(f64_to_bits(np.float64(_lane_f32(st[-1], k))))

    st[-1] = pack([one(0), one(1)], 64)


# -- lane extract/replace (lane index via engine a-plane) -------------------
# These need the instruction's lane immediate, so the engine dispatches them
# with the lane; exposed here as parameterized helpers.
def extract_lane(v: int, shape: str, lane: int, signed: bool) -> int:
    """Returns the lane value as a possibly-negative Python int; the engine
    masks it to the destination cell width (i32 vs i64)."""
    n, w = {"i8x16": (16, 8), "i16x8": (8, 16), "i32x4": (4, 32),
            "i64x2": (2, 64), "f32x4": (4, 32), "f64x2": (2, 64)}[shape]
    x = (v >> (w * lane)) & ((1 << w) - 1)
    if signed and x & (1 << (w - 1)):
        x -= 1 << w
    return x


def replace_lane(v: int, shape: str, lane: int, x: int) -> int:
    w = {"i8x16": 8, "i16x8": 16, "i32x4": 32, "i64x2": 64,
         "f32x4": 32, "f64x2": 64}[shape]
    mask = ((1 << w) - 1) << (w * lane)
    return (v & ~mask & MASK128) | ((x & ((1 << w) - 1)) << (w * lane))


def shuffle(a: int, b: int, mask: int) -> int:
    al = lanes(a, 16, 8)
    bl = lanes(b, 16, 8)
    allb = al + bl
    return pack([allb[(mask >> (8 * k)) & 0xFF] for k in range(16)], 8)
