"""Multi-host fleet federation (r16).

Federates >=2 gateway processes into one fault-tolerant serving fleet:
a peer-replicated content-addressed module store, rendezvous-hash
request routing with journal-replicated failover (a dead peer's
accepted ids are adopted by survivors), cross-host lane migration of
parked SwapStore entries (hash-verified end to end), and a fleet-wide
health view with suspect→dead liveness tracking.  A one-host fleet is
bit-identical to the non-federated gateway.

  fleet/routing.py     rendezvous (highest-random-weight) ownership
  fleet/peer.py        peer transport + liveness state machine
  fleet/federation.py  the FleetController riding a GatewayService
"""

from wasmedge_tpu.fleet.federation import (
    FleetConfig,
    FleetController,
    PeerSuspect,
    ReplicationFailed,
)
from wasmedge_tpu.fleet.membership import MembershipView
from wasmedge_tpu.fleet.peer import PeerClient, PeerState, PeerUnreachable
from wasmedge_tpu.fleet.routing import rendezvous_owner, rendezvous_ranked

__all__ = [
    "FleetConfig",
    "FleetController",
    "MembershipView",
    "PeerSuspect",
    "ReplicationFailed",
    "PeerClient",
    "PeerState",
    "PeerUnreachable",
    "rendezvous_owner",
    "rendezvous_ranked",
]
