"""FleetController: federation of gateway processes into one fleet.

One controller rides one GatewayService (gateway/service.py) and owns
everything multi-host (r16 tentpole):

  membership      a seed peer list (CLI --peer / FleetConfig.peers)
                  plus GOSSIP dynamic membership (r21,
                  fleet/membership.py): an epoch-stamped view
                  piggybacks on every heartbeat, a joining peer
                  announces itself to any seed and the view gossips
                  until convergence, POST /v1/fleet/leave departs a
                  member (left dominates up — no resurrection);
                  liveness via the heartbeat loop's suspect→dead state
                  machine with exponential probe backoff
                  (fleet/peer.py); a one-host fleet (no peers) is
                  inert — the submit path, id sequence, and results
                  are bit-identical to a non-federated gateway
  module store    the content-addressed module manifest replicates
                  peer-to-peer: heartbeats exchange {name, sha256}
                  manifests, missing blobs are fetched over
                  GET /v1/fleet/modules/<sha> and verified against
                  their sha before registration (sha keys make
                  replication idempotent and verification free), so a
                  module registered on any gateway is servable on all
  routing         rendezvous hash on the request id over the available
                  membership (fleet/routing.py): the owner executes;
                  a request routed to a SUSPECT owner is refused with
                  a retryable PeerSuspect (Retry-After) instead of
                  being forwarded into a probable black hole; when no
                  remote peer is available everything routes to self
                  (solo fallback)
  durability      every accepted id is journaled durably AND
                  replicated to at least one alive peer BEFORE the
                  202 (strict replication rides the same withdraw-on-
                  failure contract as the r13 durable journal); the
                  replicated journal + result cache are what survivors
                  adopt from
  failover        a peer's death (suspect→dead) triggers adoption of
                  its replicated journal exactly once per incarnation:
                  resolved ids replay exactly-once from the replicated
                  result cache, unresolved ids re-queue at-least-once
                  under their ORIGINAL ids on their rendezvous owner
                  among the survivors (ids forwarded by a still-alive
                  edge are skipped — the edge re-queues its own
                  forwards when it notices the owner died)
  migration       a parked (swapped) virtual lane ships to a peer as
                  its SwapStore payload + metadata, hash-verified end
                  to end (the content key IS the verification), and
                  reinstalls through the existing jitted column-set
                  pass — results bit-identical to the unmigrated run;
                  a failed send re-adopts the lane locally (a request
                  is never lost mid-migration)

Fault seams (testing/faults.py): `peer_send` before every outbound
peer request, `peer_recv` on receipt of every inbound one,
`peer_heartbeat` before each liveness probe, and `membership_gossip`
before a remote membership view is merged (an injected fault drops
exactly that gossip message; the heartbeat it rode on still counts) —
`partition_schedule` builds deterministic one-directional link cuts
from them and `churn_schedule` deterministic join/leave storms.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from typing import Dict, List, Optional

from wasmedge_tpu.common.errors import EngineFailure, ErrCode, WasmError
from wasmedge_tpu.fleet.membership import MembershipView
from wasmedge_tpu.fleet.peer import (
    BACKOFF_BASE_S,
    DEAD_AFTER,
    SUSPECT_AFTER,
    PeerClient,
    PeerState,
    PeerUnreachable,
)
from wasmedge_tpu.fleet.routing import rendezvous_owner


class PeerSuspect(EngineFailure):
    """The request's rendezvous owner is currently SUSPECT (missing
    heartbeats but not yet declared dead): forwarding would probably
    black-hole it, executing locally would double-run it if the owner
    is merely slow.  Retryable with Retry-After — by the next attempt
    the owner is either alive again or dead (and routing has moved
    on), so the client's retry lands.  Never a bare 503 string: the
    body carries the full rejection_info contract with the
    `peer_suspect` detail."""

    retryable = True
    detail = "peer_suspect"

    def __init__(self, peer_id: str, request_id: int):
        super().__init__(
            f"request {request_id} routes to peer {peer_id!r} which is "
            f"suspect (missed heartbeats); retry shortly")
        self.peer = peer_id
        self.retry_after_s = 1.0


class ReplicationFailed(WasmError):
    """Strict journal replication could not reach ANY alive peer: the
    acceptance would not survive this host's death, so it is withdrawn
    (the same contract as a failed durable journal write)."""

    retryable = True

    def __init__(self, msg: str):
        super().__init__(ErrCode.ExecutionFailed, msg)
        self.retry_after_s = 1.0


class FleetConfig:
    """Federation knobs.  `peers` is ["host:port", ...]; the peer id
    IS the address string (unique within a fleet by construction)."""

    def __init__(self, peers=(), self_id: Optional[str] = None,
                 heartbeat_s: float = 0.25,
                 suspect_after: int = SUSPECT_AFTER,
                 dead_after: int = DEAD_AFTER,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 replicate_min_interval_s: float = 0.05,
                 request_timeout_s: float = 10.0,
                 churn_grace_s: float = 2.0,
                 auto_tick: bool = True):
        self.peers = [str(p) for p in peers]
        self.self_id = self_id
        self.heartbeat_s = float(heartbeat_s)
        self.suspect_after = int(suspect_after)
        self.dead_after = int(dead_after)
        self.backoff_base_s = float(backoff_base_s)
        self.replicate_min_interval_s = float(replicate_min_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        # a runtime-joined peer's probation window: inside it, missed
        # heartbeats count as churn-in-progress (gateway/health.py),
        # not degradation — a clean join must not trip shedding
        self.churn_grace_s = float(churn_grace_s)
        # False = no background tick thread; the caller (deterministic
        # fault tests) drives tick() by hand so seam arrival counters
        # never race a timer
        self.auto_tick = bool(auto_tick)


class _Forward:
    """One request this gateway accepted but a peer is executing (a
    routed forward or an outbound migration): the relay polls the
    owner until a terminal outcome resolves the local future, and an
    owner death re-queues the request locally under its original id."""

    __slots__ = ("rid", "owner", "req", "t0")

    def __init__(self, rid: int, owner: str, req):
        self.rid = rid
        self.owner = owner
        self.req = req
        self.t0 = time.monotonic()


def _error_from_payload(status: int, err: dict) -> BaseException:
    """Rebuild a peer-reported failure preserving the class the HTTP
    status mapping branches on (mirror of durable.resolved_error)."""
    from wasmedge_tpu.serve.queue import DeadlineExceeded, ServeRejected

    msg = (err or {}).get("message", "")
    if status == 504:
        return DeadlineExceeded(msg or "deadline exceeded on peer")
    if status == 503:
        return ServeRejected(msg or "rejected by peer lifecycle")
    code = (err or {}).get("code")
    code = ErrCode(code) if code in ErrCode._value2member_map_ \
        else ErrCode.ExecutionFailed
    return WasmError(code, msg)


class FleetController:
    """Federation state machine for one GatewayService.  All peer I/O
    runs on the controller's tick thread or an HTTP handler thread —
    never under the service's locks."""

    def __init__(self, svc, config: FleetConfig):
        self.svc = svc
        self.cfg = config
        self.self_id: str = config.self_id or ""
        self.self_url: str = ""
        # fresh incarnation marker: a peer seeing a NEW epoch knows our
        # journal was resumed from disk and resets its adoption record
        self.epoch = uuid.uuid4().hex[:12]
        self._lock = threading.RLock()
        self.peers: Dict[str, PeerState] = {}
        self._client: Optional[PeerClient] = None
        self._forwards: Dict[int, _Forward] = {}
        self._module_bytes: Dict[str, bytes] = {}
        self._thread: Optional[threading.Thread] = None
        self._ticking = False
        self._stop = threading.Event()
        self._repl_doc: Optional[dict] = None
        self._repl_dirty = False
        self._repl_last = 0.0
        # gossip membership (r21, fleet/membership.py): the epoch-
        # stamped view every heartbeat carries.  self_left flips when
        # THIS gateway announces departure — it keeps serving what it
        # holds, peers stop routing to it
        self.view = MembershipView()
        self.self_left = False
        self.counters = {
            "heartbeats_ok": 0, "heartbeats_missed": 0,
            "modules_synced": 0, "module_conflicts": 0,
            "cache_synced": 0,
            "adoptions": 0, "adoptions_replayed": 0,
            "forwards": 0, "forward_requeues": 0,
            "migrations_out": 0, "migrations_in": 0,
            "replication_errors": 0, "suspect_rejections": 0,
            "joins": 0, "leaves": 0, "gossip_merges": 0,
            "wakes_forwarded": 0, "wakes_received": 0,
            "blob_repairs_served": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self, host: str, port: int):
        """Bind the fleet identity to the gateway's LISTENING address
        (known only after the HTTP server binds) and start the tick
        thread.  Idempotent."""
        self.self_url = f"{host}:{port}"
        if not self.self_id:
            self.self_id = self.self_url
        self._client = PeerClient(self.self_id, faults=self.svc.faults,
                                  timeout_s=self.cfg.request_timeout_s)
        with self._lock:
            # boot-configured membership lives at epoch 0 on every
            # host (seeding is not an origin event — a static fleet
            # keeps epoch 0 forever, bit-identical to r16)
            self.view.members.setdefault(
                self.self_id, {"url": self.self_url, "status": "up"})
            for url in self.cfg.peers:
                pid = str(url)
                if pid != self.self_id and pid not in self.peers:
                    self.peers[pid] = PeerState(pid, pid)
                if pid != self.self_id:
                    self.view.members.setdefault(
                        pid, {"url": pid, "status": "up"})
        if self.peers:
            self._ensure_ticking()
        return self

    def _ensure_ticking(self):
        """Become an ACTIVE fleet member: offset the id space and spawn
        the heartbeat loop.  Runs once — at start() for a
        boot-configured peer list, or at FIRST runtime admission for a
        seed that booted with no peers (r21 dynamic join: a peerless
        gateway is inert and bit-identical to a non-federated one, but
        the moment another gateway announces itself the seed must
        heartbeat back, or it would never probe the joiner, never gossip
        the view onward, and never detect its death for adoption)."""
        with self._lock:
            if self._ticking:
                return
            self._ticking = True
        # fleet-unique id space: fresh ids allocate above a 40-bit
        # hash of the peer identity so two peers' original-id re-queues
        # can never collide (adoption preserves ids across hosts; the
        # advance is monotonic, so ids issued while solo stay valid)
        from wasmedge_tpu.serve.queue import advance_request_ids

        base = (int.from_bytes(
            hashlib.sha256(self.self_id.encode()).digest()[:5],
            "big") << 20)
        advance_request_ids(base)
        if self.cfg.auto_tick and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"fleet:{self.self_id}")
            self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    @property
    def started(self) -> bool:
        return self._client is not None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                pass   # a tick must never kill the loop; the next
            #            heartbeat re-observes whatever went wrong
            self._stop.wait(self.cfg.heartbeat_s)

    # -- membership view ---------------------------------------------------
    def members(self) -> List[str]:
        """Routable membership: self plus every non-dead peer (the
        rendezvous universe — stable across a suspect flap)."""
        with self._lock:
            return [self.self_id] + [p.peer_id
                                     for p in self.peers.values()
                                     if p.available()]

    def remote_available(self) -> bool:
        with self._lock:
            return any(p.available() for p in self.peers.values())

    def peer_states(self) -> Dict[str, dict]:
        with self._lock:
            return {p.peer_id: {"url": p.url, "state": p.state,
                                "streak": p.streak,
                                "epoch": p.epoch,
                                "left": p.left,
                                "transitions": p.transitions}
                    for p in self.peers.values()}

    # -- tick: heartbeat / sync / relay ------------------------------------
    def tick(self):
        """One federation round (the background thread calls this
        every heartbeat_s; tests call it directly for determinism):
        probe due peers, sync missing modules, push a dirty journal
        replica, poll outstanding forwards."""
        now = time.monotonic()
        with self._lock:
            due = [p for p in self.peers.values() if now >= p.next_probe]
        for p in due:
            self._probe(p)
        self._sync_modules()
        self._push_replica()
        self.poll_forwards()

    def _probe(self, p: PeerState):
        """One heartbeat probe: exchange identity, manifests, and (as
        the response piggyback) the peer's current journal replica."""
        try:
            if self.svc.faults is not None:
                self.svc.faults.fire("peer_heartbeat",
                                     src=self.self_id, dst=p.peer_id)
            st, doc = self._client.request(
                p.peer_id, p.url, "POST", "/v1/fleet/heartbeat",
                body=self._hello())
            if st != 200 or not isinstance(doc, dict):
                raise PeerUnreachable(p.peer_id, f"heartbeat HTTP {st}")
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            self._note_miss(p)
            return
        self._note_ok(p, doc)

    def _hello(self) -> dict:
        """Heartbeat body: who we are + what we serve + (catch-up
        only) our LAST journal snapshot.  The journal's primary
        channel is the push path (`replicate`/`_push_replica`) — the
        heartbeat reuses the already-built stashed doc rather than
        taking a fresh svc-locked snapshot per probe, so a big result
        cache is serialized once per change, not once per heartbeat."""
        with self._lock:
            membership = self.view.to_doc()
            doc = self._repl_doc
        out = {"peer_id": self.self_id, "epoch": self.epoch,
               "url": self.self_url,
               "generation": self.svc.generation,
               "modules": self._manifest(),
               "membership": membership}
        if doc is not None:
            out["journal"] = doc
        return out

    def _manifest(self) -> List[dict]:
        out = []
        for rm in self.svc.registry.modules_snapshot():
            if rm.sha256:
                out.append({"name": rm.name, "sha256": rm.sha256})
        return out

    def _note_ok(self, p: PeerState, doc: dict):
        now = time.monotonic()
        with self._lock:
            fresh = p.note_ok(now, doc.get("epoch"))
            if fresh:
                # new incarnation: its journal replica restarts, and a
                # future death of THIS incarnation adopts again
                p.adopted_epoch = None
                p.replica = None
            if isinstance(doc.get("modules"), list):
                p.modules = doc["modules"]
            if isinstance(doc.get("journal"), dict):
                p.replica = doc["journal"]
            self.counters["heartbeats_ok"] += 1
        self._merge_view(doc.get("membership"), src=p.peer_id)

    def _merge_view(self, doc, src: Optional[str] = None):
        """Fold a peer's membership view into ours (the gossip step).
        The `membership_gossip` seam fires FIRST: an injected fault
        drops exactly this gossip message — the heartbeat it rode on
        still counted, and the next exchange re-gossips (convergence
        is delayed, never broken).  Newly-learned up members get
        PeerStates (probing + replication reach them on the next
        tick); members the view marks left stop being routable."""
        if not isinstance(doc, dict):
            return
        if self.svc.faults is not None:
            try:
                self.svc.faults.fire("membership_gossip",
                                     src=src or "?", dst=self.self_id,
                                     epoch=doc.get("epoch"))
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                return   # this gossip message was lost on the wire
        now = time.monotonic()
        joined, left = [], []
        with self._lock:
            if not self.view.merge(doc):
                return
            self.counters["gossip_merges"] += 1
            for pid, info in self.view.members.items():
                if pid == self.self_id:
                    if info.get("status") == "left":
                        self.self_left = True
                    continue
                p = self.peers.get(pid)
                if info.get("status") == "left":
                    if p is not None and not p.left:
                        p.left = True
                        left.append(pid)
                    continue
                if p is None:
                    p = self.peers[pid] = PeerState(
                        pid, str(info.get("url") or pid))
                    p.joined_at = now
                    joined.append(pid)
            self.counters["joins"] += len(joined)
            self.counters["leaves"] += len(left)
            epoch = self.view.epoch
        if joined:
            self._ensure_ticking()
        for pid in joined:
            self.svc.obs.instant("fleet_join", cat="fleet",
                                 track="fleet", peer=pid, epoch=epoch,
                                 via=src)
        for pid in left:
            self.svc.obs.instant("fleet_leave", cat="fleet",
                                 track="fleet", peer=pid, epoch=epoch,
                                 via=src)

    def _note_miss(self, p: PeerState):
        now = time.monotonic()
        with self._lock:
            transition = p.note_miss(
                now, suspect_after=self.cfg.suspect_after,
                dead_after=self.cfg.dead_after,
                backoff_base_s=self.cfg.backoff_base_s)
            self.counters["heartbeats_missed"] += 1
        if transition is not None:
            self.svc.obs.instant("peer_" + transition, cat="fleet",
                                 track="fleet", peer=p.peer_id,
                                 streak=p.streak)
        if transition == "dead":
            self._adopt_peer(p)
            self._requeue_forwards(p.peer_id)

    # -- inbound peer protocol (called from gateway/http.py) ---------------
    def _recv(self, route: str, src: Optional[str]):
        if self.svc.faults is not None:
            self.svc.faults.fire("peer_recv", dst=self.self_id,
                                 src=src or "?", route=route)

    def on_heartbeat(self, body: dict) -> dict:
        """Inbound heartbeat: a probe FROM a peer proves its liveness
        as well as ours — record it, absorb its manifest/journal, and
        answer with our own (bidirectional sync from either side's
        probe)."""
        self._recv("heartbeat", body.get("peer_id"))
        pid = str(body.get("peer_id", ""))
        if pid and pid != self.self_id:
            admitted = False
            with self._lock:
                p = self.peers.get(pid)
                if p is None:
                    # a peer introduced itself directly: admit it.
                    # This is a membership ORIGIN event — the r21 join
                    # path (a new gateway announces itself to any
                    # seed) and the r16 asymmetric-static-list case
                    # are the same mechanism; the bumped view gossips
                    # out on every subsequent heartbeat until the
                    # fleet converges
                    url = str(body.get("url") or pid)
                    p = self.peers[pid] = PeerState(pid, url)
                    p.joined_at = time.monotonic()
                    if self.view.add(pid, url):
                        admitted = True
                        self.counters["joins"] += 1
                        epoch = self.view.epoch
                    elif self.view.is_left(pid):
                        # a departed identity heartbeating again: it
                        # stays unroutable (left dominates; a rejoin
                        # is a NEW host:port identity)
                        p.left = True
            if admitted:
                self._ensure_ticking()
                self.svc.obs.instant("fleet_join", cat="fleet",
                                     track="fleet", peer=pid,
                                     epoch=epoch, via="direct")
            self._note_ok(p, body)
        return self._hello()

    def on_journal(self, body: dict) -> dict:
        """Inbound journal replica push (the strict-replication path a
        202 waits on)."""
        self._recv("journal", body.get("peer_id"))
        pid = str(body.get("peer_id", ""))
        with self._lock:
            p = self.peers.get(pid)
            if p is None and pid and pid != self.self_id:
                # a peer we have not met may push its journal before
                # its first heartbeat lands here: ADMIT it rather than
                # drop the replica — acking a push we discarded would
                # fake the sender's strict-replication guarantee
                # (peer ids default to addresses, so pid doubles as
                # the url until a heartbeat supplies a better one)
                p = self.peers[pid] = PeerState(pid, pid)
            if p is not None:
                if body.get("epoch") and body["epoch"] != p.epoch:
                    p.adopted_epoch = None
                    p.epoch = body["epoch"]
                # seq-gated: the sender pushes OUTSIDE its journal
                # mutex, so a slow older push can arrive after a newer
                # one — storing it would regress the replica and could
                # lose a durably-accepted id on adoption
                have = (p.replica or {}).get("seq", -1) \
                    if (p.replica or {}).get("epoch") \
                    == body.get("epoch") else -1
                if int(body.get("seq", 0)) >= have:
                    p.replica = body
                p.last_seen = time.monotonic()
        return {"ok": True, "peer_id": self.self_id}

    def on_leave(self, body: dict) -> dict:
        """Inbound departure announcement (POST /v1/fleet/leave): mark
        `peer_id` (default: the receiving gateway itself) as left — a
        membership ORIGIN event.  A self-leave additionally broadcasts
        one best-effort leave to every alive peer so the fleet stops
        routing to us within a round trip instead of a gossip round;
        either way the bumped view rides every later heartbeat."""
        self._recv("leave", body.get("peer_id") or body.get("edge"))
        pid = str(body.get("peer_id") or self.self_id)
        changed = False
        with self._lock:
            if self.view.leave(pid):
                changed = True
                self.counters["leaves"] += 1
                epoch = self.view.epoch
                if pid == self.self_id:
                    self.self_left = True
                else:
                    p = self.peers.get(pid)
                    if p is not None:
                        p.left = True
            alive = [p for p in self.peers.values()
                     if p.state == "alive" and not p.left] \
                if changed and pid == self.self_id else []
        if not changed:
            return {"ok": True, "peer_id": pid, "dedup": True,
                    "epoch": self.view.epoch}
        self.svc.obs.instant("fleet_leave", cat="fleet", track="fleet",
                             peer=pid, epoch=epoch, via="direct")
        for p in alive:
            try:
                self._client.request(p.peer_id, p.url, "POST",
                                     "/v1/fleet/leave",
                                     body={"peer_id": pid,
                                           "edge": self.self_id})
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                pass   # gossip on the next heartbeat converges it
        return {"ok": True, "peer_id": pid, "epoch": epoch}

    def owner_hint(self, request_id: int) -> Optional[dict]:
        """Poll-redirection hint for GET /v1/requests/<id> on a
        non-owner: where the id's rendezvous owner currently lives, so
        a client whose issuing peer died polls THERE instead of trying
        survivors blindly.  None when the hint is this gateway itself
        (no redirection to give) or the fleet is inert."""
        if not self.started or not self.remote_available():
            return None
        rid = int(request_id)
        owner = rendezvous_owner(rid, self.members())
        if owner == self.self_id:
            return None
        with self._lock:
            p = self.peers.get(owner)
            url = p.url if p is not None else self.view.url_of(owner)
            epoch = self.view.epoch
        return {"peer": owner, "url": url or owner,
                "membership_epoch": epoch}

    def on_execute(self, body: dict):
        """Inbound routed request: execute locally under the edge's
        ORIGINAL id.  Idempotent — a retried forward of a known id is
        acknowledged, not double-queued."""
        self._recv("execute", body.get("edge"))
        rid = int(body["id"])
        state, _ = self.svc.request_state(rid)
        if state == "ok":
            return {"ok": True, "request_id": rid, "dedup": True}
        req = self.svc._submit_local(
            body.get("func", ""), body.get("args", []),
            module=body.get("module"),
            tenant=body.get("tenant", "default"),
            deadline_s=body.get("deadline_s"),
            request_id=rid, edge=body.get("edge"))
        return {"ok": True, "request_id": req.id}

    def on_migrate(self, body: dict):
        """Inbound lane migration: verify the payload against its
        content key (hash verification IS the end-to-end integrity
        check), adopt the blob into the local SwapStore, and park the
        request as a swapped virtual lane — it reinstalls through the
        existing jitted column-set pass at a coming boundary."""
        import base64

        self._recv("migrate", body.get("edge"))
        entry = body.get("entry") or {}
        # journal the sender as this request's edge: it keeps the
        # client-facing future and re-queues on OUR death, so adoption
        # elsewhere must skip the entry while the sender lives
        entry.setdefault("edge", body.get("edge"))
        rid = int(entry["id"])
        payload = None
        if body.get("blob_b64"):
            # hash verification lives in ONE place: SwapStore.adopt
            # (inside adopt_vlane) checks the payload against its
            # content key BEFORE any server state moves and raises
            # SwapCorrupt on mismatch — the sender sees a non-2xx and
            # keeps its copy
            payload = base64.b64decode(body["blob_b64"])
        gen = self.svc.current
        if gen is None:
            raise KeyError("no serving generation to migrate onto")
        fut = gen.server.adopt_vlane(entry, payload)
        self.svc._wrap_foreign(fut, entry, gen)
        with self._lock:
            self.counters["migrations_in"] += 1
        self.svc.obs.instant("fleet_migrate_in", cat="fleet",
                             track="fleet", id=rid,
                             src=body.get("edge"))
        # the id is ours now: make it durable (and replicated) before
        # the sender drops its copy on our ack
        self.svc._journal_sync()
        return {"ok": True, "request_id": rid}

    def module_bytes(self, sha256: str) -> Optional[bytes]:
        """Serve a module blob to a peer: the durable store when one
        is attached, else the in-memory fleet cache."""
        if self.svc.durable is not None:
            try:
                return self.svc.durable.module_bytes(sha256)
            except OSError:
                pass
        return self._module_bytes.get(sha256)

    def note_modules(self, entries):
        """Keep blob bytes for peer fetches (non-durable gateways have
        no disk copy to serve from).  `entries` is [(rm, bytes|None)]."""
        for rm, data in entries:
            if data is not None and rm.sha256:
                self._module_bytes[rm.sha256] = bytes(data)

    def cache_bytes(self, sha256: str) -> Optional[bytes]:
        """Serve a compile-cache entry (raw header+payload,
        imagestore/compilecache.py) to a peer; None when the cache is
        off or has no entry for this sha."""
        cc = self.svc.registry.compile_cache
        if not cc.enabled:
            return None
        try:
            return cc.entry_bytes(sha256)
        except KeyError:
            return None

    # -- fleet-routed wakes + blob repair (r24) ----------------------------
    def route_wake(self, request_id: int, payload) -> Optional[dict]:
        """Forward a wake the local generation does not know to the
        id's rendezvous owner (the r16 routing table).  Returns the
        owner's resolution dict, or None when there is nothing to
        forward to (inert fleet, self-owned id, unreachable owner) —
        the caller's local "unknown" answer then stands, with the wake
        queued at-least-once for a session that may still land here.
        A SUSPECT owner raises PeerSuspect: the edge answers 503 +
        Retry-After rather than guessing about a wake that may apply
        the moment the owner's probes recover."""
        if not self.started or not self.remote_available():
            return None
        rid = int(request_id)
        owner = rendezvous_owner(rid, self.members())
        if owner == self.self_id:
            return None
        with self._lock:
            p = self.peers.get(owner)
            if p is not None and p.state == "suspect":
                self.counters["suspect_rejections"] += 1
                raise PeerSuspect(owner, rid)
        if p is None:
            return None
        import base64

        body = {"id": rid, "edge": self.self_id}
        if payload:
            body["payload_b64"] = base64.b64encode(payload).decode()
        try:
            st, doc = self._client.request(p.peer_id, p.url, "POST",
                                           "/v1/fleet/wake", body=body)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            return None
        if st == 200 and isinstance(doc, dict) and doc.get("ok"):
            with self._lock:
                self.counters["wakes_forwarded"] += 1
            self.svc.obs.instant("fleet_wake_forward", cat="fleet",
                                 track="fleet", id=rid, owner=owner)
            return {"ok": True, "request_id": rid,
                    "state": doc.get("state", "forwarded"),
                    "owner": owner}
        return None

    def on_wake(self, body: dict) -> dict:
        """Inbound forwarded wake: apply locally (never re-forwarded —
        the sender already resolved ownership, so a second hop could
        only loop)."""
        import base64

        self._recv("wake", body.get("edge"))
        rid = int(body["id"])
        payload = base64.b64decode(body["payload_b64"]) \
            if body.get("payload_b64") else None
        out = self.svc.wake(rid, payload, _forward=False)
        with self._lock:
            self.counters["wakes_received"] += 1
        return out

    def blob_bytes(self, key: str) -> Optional[bytes]:
        """Serve a content-addressed swap blob to a repairing peer
        (GET /v1/fleet/blob/<key>).  Every local copy is VERIFIED
        against the key before serving — corruption must never
        propagate through the repair channel."""
        gen = self.svc.current
        stores = []
        if gen is not None:
            srv = gen.server
            if srv.effects is not None:
                stores.append(srv.effects.store)
            if srv.hv is not None:
                stores.append(srv.hv.store)
        snap = getattr(self.svc, "snapshot_store", None)
        if snap is not None:
            stores.append(snap)
        seen = set()
        for store in stores:
            if store is None or id(store) in seen:
                continue
            seen.add(id(store))
            payload = store.peek(key)
            if payload is not None:
                with self._lock:
                    self.counters["blob_repairs_served"] += 1
                return payload
        return None

    def fetch_blob(self, key: str) -> Optional[bytes]:
        """Repair channel for the at-rest scrubber: try every alive
        peer for a verified replica of a content-addressed blob."""
        if not self.started or not self.remote_available():
            return None
        with self._lock:
            alive = [p for p in self.peers.values()
                     if p.state == "alive" and not p.left]
        for p in alive:
            try:
                st, data = self._client.request(
                    p.peer_id, p.url, "GET",
                    f"/v1/fleet/blob/{key}", raw=True)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                continue
            if st == 200 and \
                    hashlib.sha256(data).hexdigest() == str(key):
                return bytes(data)
        return None

    def fetch_cache_entry(self, sha: str) -> Optional[bytes]:
        """Repair channel for rotted compile-cache entries: a peer's
        raw WTIC envelope (adopt_entry re-verifies the embedded digest
        before it is trusted)."""
        if not self.started or not self.remote_available():
            return None
        with self._lock:
            alive = [p for p in self.peers.values()
                     if p.state == "alive" and not p.left]
        for p in alive:
            try:
                st, data = self._client.request(
                    p.peer_id, p.url, "GET",
                    f"/v1/fleet/cache/{sha}", raw=True)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                continue
            if st == 200 and data:
                return bytes(data)
        return None

    # -- module replication ------------------------------------------------
    def _sync_modules(self):
        """Fetch + register every module a peer advertises that we do
        not serve.  Content-addressed: the sha verifies the transfer
        and makes a re-fetch idempotent; same-name/different-sha is a
        conflict (counted, skipped — first registration wins fleet-wide
        the same way a duplicate POST /v1/modules 409s)."""
        with self._lock:
            wanted = []
            for p in self.peers.values():
                if p.state == "dead":
                    continue
                for m in p.modules:
                    wanted.append((p, str(m.get("name", "")),
                                   str(m.get("sha256", ""))))
        for p, name, sha in wanted:
            if not name or not sha:
                continue
            have = self.svc.registry.get(name) \
                if name in self.svc.registry.names else None
            if have is not None:
                if have.sha256 != sha:
                    with self._lock:
                        self.counters["module_conflicts"] += 1
                continue
            try:
                st, data = self._client.request(
                    p.peer_id, p.url, "GET",
                    f"/v1/fleet/modules/{sha}", raw=True)
                if st != 200:
                    continue
                if hashlib.sha256(data).hexdigest() != sha:
                    continue   # corrupt transfer: the next tick refetches
                # compile-cache replication (r22): pull the peer's
                # lowered-image entry FIRST so the registration below
                # adopts it instead of re-lowering.  Best-effort — a
                # peer without the entry (or a corrupt one, rejected by
                # adopt_entry's digest check) just means a local lower.
                cc = self.svc.registry.compile_cache
                if cc.enabled:
                    try:
                        cst, craw = self._client.request(
                            p.peer_id, p.url, "GET",
                            f"/v1/fleet/cache/{sha}", raw=True)
                        if cst == 200 and cc.adopt_entry(sha, craw):
                            with self._lock:
                                self.counters["cache_synced"] += 1
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except BaseException:
                        pass
                self.svc.register_module(name, wasm_bytes=bytes(data),
                                         source=f"fleet/{p.peer_id}")
                with self._lock:
                    self.counters["modules_synced"] += 1
                self.svc.obs.instant("fleet_module_sync", cat="fleet",
                                     track="fleet", module=name,
                                     src=p.peer_id)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                continue   # unreachable peer / racing registration:
            #                the next tick re-evaluates

    # -- journal replication -----------------------------------------------
    def replicate(self, unresolved, resolved, max_id, strict: bool,
                  seq: int = 0):
        """Ship the current journal snapshot to peers.  `strict` (the
        202 path) must land on >=1 ALIVE peer — total failure raises
        ReplicationFailed and the acceptance is withdrawn upstream.
        Non-strict updates are throttled: the snapshot is stashed and
        pushed by the next tick (resolved-result replication is
        allowed to lag; adoption re-queues at-least-once either way).
        `seq` was drawn under the sender's journal mutex — receivers
        discard older-seq snapshots, so the HTTP here is safe to run
        outside it."""
        doc = {"peer_id": self.self_id, "epoch": self.epoch,
               "seq": int(seq),
               "max_id": int(max_id),
               "unresolved": list(unresolved),
               "resolved": list(resolved)}
        with self._lock:
            # strict replication targets the CURRENT membership view:
            # a mid-churn acceptance lands on peers that will still be
            # fleet members after the churn settles (left peers are
            # about to disappear — a copy there survives nothing)
            alive = [p for p in self.peers.values()
                     if p.state == "alive" and not p.left]
            self._repl_doc = doc
            self._repl_dirty = True
        if not strict:
            now = time.monotonic()
            if now - self._repl_last < self.cfg.replicate_min_interval_s:
                return
            self._push_replica()
            return
        if not alive:
            # no alive peer: solo mode — local durability is the whole
            # story, exactly like the non-federated gateway
            return
        ok = 0
        errs = []
        for p in alive:
            if self._send_replica(p, doc):
                ok += 1
            else:
                errs.append(p.peer_id)
        if ok == 0:
            with self._lock:
                self.counters["replication_errors"] += 1
            raise ReplicationFailed(
                f"journal replication reached no peer "
                f"(tried {errs})")

    def _send_replica(self, p: PeerState, doc: dict) -> bool:
        try:
            st, _ = self._client.request(p.peer_id, p.url, "POST",
                                         "/v1/fleet/journal", body=doc)
            return st == 200
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            return False

    def _push_replica(self):
        with self._lock:
            if not self._repl_dirty or self._repl_doc is None:
                return
            doc = self._repl_doc
            self._repl_dirty = False
            alive = [p for p in self.peers.values()
                     if p.state == "alive" and not p.left]
        self._repl_last = time.monotonic()
        for p in alive:
            self._send_replica(p, doc)

    # -- routing -----------------------------------------------------------
    def maybe_route(self, func, args, module=None, tenant="default",
                    deadline_s=None):
        """Fleet routing for one edge submission.  Returns the
        GatewayRequest when the fleet handled it (locally under a
        fleet-allocated id, or forwarded to its owner), or None to let
        the plain local path run — which is exactly what happens with
        no peers configured (solo fleets are bit-identical to a
        non-federated gateway, id sequence included) or with every
        peer dead (solo fallback)."""
        if not self.started or not self.remote_available():
            return None
        from wasmedge_tpu.serve.queue import _next_request_id

        rid = _next_request_id()
        owner = rendezvous_owner(rid, self.members())
        if owner == self.self_id:
            return self.svc._submit_local(func, args, module=module,
                                          tenant=tenant,
                                          deadline_s=deadline_s,
                                          request_id=rid)
        with self._lock:
            p = self.peers.get(owner)
            if p is not None and p.state == "suspect":
                self.counters["suspect_rejections"] += 1
                raise PeerSuspect(owner, rid)
        return self._forward(p, rid, func, args, module, tenant,
                             deadline_s)

    def _forward(self, p: PeerState, rid: int, func, args, module,
                 tenant, deadline_s):
        """Accept rid at this edge (durable + replicated BEFORE any
        dispatch), then hand execution to its owner.  An unreachable
        owner falls back to local execution — at-least-once, never a
        stranded acceptance."""
        from wasmedge_tpu.serve.queue import ServeFuture

        svc = self.svc
        qualified = f"{module}:{func}" if module else func
        fut = ServeFuture(rid)
        req = svc._stash_request(fut, tenant, module, qualified,
                                 args, deadline_s)
        try:
            svc._journal_sync(strict_req=req)
        except BaseException:
            raise   # withdrawn upstream; the id was never accepted
        body = {"id": rid, "edge": self.self_id, "module": module,
                "func": func, "args": [int(a) for a in args],
                "tenant": tenant, "deadline_s": deadline_s}
        try:
            st, doc = self._client.request(p.peer_id, p.url, "POST",
                                           "/v1/fleet/execute",
                                           body=body)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            st, doc = None, None
        if st == 200 and isinstance(doc, dict) and doc.get("ok"):
            with self._lock:
                self._forwards[rid] = _Forward(rid, p.peer_id, req)
                self.counters["forwards"] += 1
            svc.obs.instant("fleet_forward", cat="fleet", track="fleet",
                            id=rid, owner=p.peer_id)
            return req
        if st is not None and isinstance(doc, dict) \
                and isinstance(doc.get("err"), dict):
            # the owner REFUSED machine-readably (queue saturated,
            # unknown module, ...): surface its taxonomy to the client
            # and take the acceptance back — the id never ran anywhere
            svc._withdraw(req)
            err = _error_from_payload(st, doc["err"])
            fut._reject(err)
            raise err
        # wire failure: execute locally under the original id instead
        return self._local_fallback(req)

    def _local_fallback(self, req):
        """Run a forward-owned request on the local server under its
        original id (owner unreachable/dead).  At-least-once: the
        owner MAY also have started it; the client still observes one
        stable outcome through this (the accepting) gateway."""
        svc = self.svc
        gen = svc.current
        if gen is None:
            from wasmedge_tpu.serve.queue import ServeRejected

            req.future._reject(ServeRejected(
                f"request {req.id}: owner unreachable and no local "
                f"generation to fall back to"))
            return req
        try:
            fut = gen.server.submit(req.func, req.args,
                                    tenant=req.tenant,
                                    deadline_s=req.deadline_s,
                                    request_id=req.id)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            svc._withdraw(req)
            req.future._reject(e if isinstance(e, WasmError)
                               else WasmError(ErrCode.ExecutionFailed,
                                              repr(e)))
            raise
        svc._relink_future(req, fut)
        with self._lock:
            self.counters["forward_requeues"] += 1
        return req

    # -- forward relay -----------------------------------------------------
    def poll_forwards(self):
        """Resolve outstanding forwarded/migrated requests from their
        owners' poll route; re-queue the ones whose owner died."""
        with self._lock:
            todo = list(self._forwards.values())
        for fw in todo:
            if fw.req.future.done:
                with self._lock:
                    self._forwards.pop(fw.rid, None)
                continue
            with self._lock:
                p = self.peers.get(fw.owner)
            if p is None or p.state == "dead":
                with self._lock:
                    self._forwards.pop(fw.rid, None)
                self._local_fallback(fw.req)
                continue
            try:
                # allow_5xx: a 503/504 poll body IS a terminal outcome
                # (lifecycle/deadline) — only a transport failure or a
                # bodyless 5xx means "can't tell", and liveness is the
                # heartbeat's job either way
                st, doc = self._client.request(
                    fw.owner, p.url, "GET",
                    f"/v1/requests/{fw.rid}", allow_5xx=True)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                continue   # transient; liveness is the heartbeat's job
            if not isinstance(doc, dict) \
                    or not ("ok" in doc or "err" in doc) \
                    or doc.get("status") == "pending":
                continue
            if st == 200 and doc.get("ok"):
                fw.req.future._resolve(
                    [int(c) for c in doc.get("result", [])])
            elif st == 404:
                # the owner does not know the id (it never accepted or
                # already pruned it): reclaim and run locally
                with self._lock:
                    self._forwards.pop(fw.rid, None)
                self._local_fallback(fw.req)
                continue
            else:
                fw.req.future._reject(
                    _error_from_payload(st, doc.get("err")))
            with self._lock:
                self._forwards.pop(fw.rid, None)
            self.svc.finalize(fw.req)

    def _requeue_forwards(self, dead_peer: str):
        """A peer died: every forward it owned re-queues locally under
        its original id (at-least-once)."""
        with self._lock:
            mine = [fw for fw in self._forwards.values()
                    if fw.owner == dead_peer]
            for fw in mine:
                self._forwards.pop(fw.rid, None)
        for fw in mine:
            if not fw.req.future.done:
                self._local_fallback(fw.req)

    # -- failover adoption -------------------------------------------------
    def _adopt_peer(self, p: PeerState):
        """A peer was declared dead: adopt its replicated journal.
        Resolved ids replay exactly-once from the replicated result
        cache (every survivor replays — replay is locally idempotent
        and each survivor then answers polls for them); unresolved ids
        re-queue at-least-once under their ORIGINAL ids on their
        rendezvous owner among the survivors.  Once per incarnation:
        a heartbeat flap cannot re-adopt."""
        with self._lock:
            if p.adopted_epoch is not None \
                    and p.adopted_epoch == (p.epoch or ""):
                return
            p.adopted_epoch = p.epoch or ""
            replica = p.replica
            members = [self.self_id] + [
                q.peer_id for q in self.peers.values() if q.available()]
            alive = {q.peer_id for q in self.peers.values()
                     if q.state == "alive"}
        if not replica:
            return
        svc = self.svc
        gen = svc.current
        replayed = adopted = 0
        for entry in replica.get("resolved", []):
            svc._install_replay(entry, gen)
            replayed += 1
        for entry in replica.get("unresolved", []):
            rid = int(entry.get("id", 0))
            edge = entry.get("edge")
            if edge and edge != p.peer_id and edge in alive:
                continue   # the accepting edge is alive: it re-queues
            #                its own forward when it notices the death
            if rendezvous_owner(rid, members) != self.self_id:
                continue   # another survivor owns this id
            svc.adopt_foreign(entry, src=p.peer_id)
            adopted += 1
        with self._lock:
            self.counters["adoptions"] += adopted
            self.counters["adoptions_replayed"] += replayed
        if adopted or replayed:
            svc.obs.instant("fleet_adopt", cat="fleet", track="fleet",
                            peer=p.peer_id, adopted=adopted,
                            replayed=replayed)
            svc._journal_sync()

    # -- migration ---------------------------------------------------------
    def migrate_out(self, request_id: int, peer_id: str) -> dict:
        """Ship one PARKED (swapped) virtual lane to `peer_id`: export
        the SwapStore payload, send it with its content key, and on
        ack hand the request over to the forward relay (polls answer
        from this gateway until the peer resolves it).  Any failure
        re-adopts the lane locally — the request is never lost
        mid-migration, and a dead receiver just means the lane stays
        (or re-queues) here."""
        import base64

        svc = self.svc
        with self._lock:
            p = self.peers.get(str(peer_id))
        if p is None or not p.available():
            raise KeyError(f"no available peer {peer_id!r}")
        gen = svc.current
        if gen is None:
            raise KeyError("no serving generation")
        rid = int(request_id)
        entry, payload = gen.server.export_vlane(rid)
        body = {"edge": self.self_id, "entry": entry,
                "blob_b64": base64.b64encode(payload).decode()
                if payload is not None else None}
        try:
            st, doc = self._client.request(p.peer_id, p.url, "POST",
                                           "/v1/fleet/migrate",
                                           body=body)
            ok = st == 200 and isinstance(doc, dict) and doc.get("ok")
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            ok = False
        if not ok:
            # mid-migration failure: the lane never leaves this host —
            # re-adopt it exactly as exported and let the boundary
            # rebalance reinstall it.  The re-adopted vlane runs under
            # a FRESH server future; the client still waits on the one
            # its 202 was issued against, so bridge the outcome across
            fut = gen.server.adopt_vlane(entry, payload, requeue=True)
            req = svc.get_request(rid)
            if req is not None and fut is not req.future:
                fut.mirror(req.future)
            raise PeerUnreachable(p.peer_id,
                                  f"migration of {rid} not acked")
        req = svc.get_request(rid)
        if req is not None and not req.future.done:
            with self._lock:
                self._forwards[rid] = _Forward(rid, p.peer_id, req)
        with self._lock:
            self.counters["migrations_out"] += 1
        svc.obs.instant("fleet_migrate_out", cat="fleet", track="fleet",
                        id=rid, dst=p.peer_id,
                        nbytes=len(payload) if payload else 0)
        return {"ok": True, "request_id": rid, "peer": p.peer_id}

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            by_state = {"alive": 0, "suspect": 0, "dead": 0,
                        "joining": 0}
            n_left = 0
            for p in self.peers.values():
                if p.left:
                    n_left += 1
                    continue   # departed members leave the liveness
                #                tally (health reads it for shedding)
                if p.state != "alive" and p.joined_at is not None \
                        and now - p.joined_at < self.cfg.churn_grace_s:
                    # a runtime join inside its probation window:
                    # missed probes here are churn-in-progress (the
                    # peer may still be compiling its first
                    # generation), not degradation
                    by_state["joining"] += 1
                    continue
                by_state[p.state] = by_state.get(p.state, 0) + 1
            return {
                "self_id": self.self_id,
                "epoch": self.epoch,
                "membership_epoch": self.view.epoch,
                "peers": dict(by_state),
                "left_peers": n_left,
                "self_left": self.self_left,
                "configured_peers": len(self.peers),
                "forwards_outstanding": len(self._forwards),
                **self.counters,
            }
