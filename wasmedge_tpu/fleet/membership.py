"""Epoch-stamped gossip membership (r21).

The fleet's peer set stops being a list frozen at boot: every
controller carries a `MembershipView` — a monotone epoch plus a
member table `{peer_id: {"url", "status"}}` — piggybacked on the
existing heartbeat exchange.  A joining peer announces itself to any
seed; the seed admits it (an ORIGIN event: epoch bumps), and the new
view gossips outward on every subsequent heartbeat until the fleet
converges.  A leave is the other origin event: the member's status
flips to "left" and the epoch bumps.

The merge is a join-semilattice, so gossip converges regardless of
message order or loss:

  * epoch      = max(ours, theirs)
  * member set = union
  * status     = "left" dominates "up" (a departed peer can never be
                 resurrected by a stale view that still says "up" —
                 peer ids are host:port incarnations, a rejoin is a
                 NEW identity)

Merging a remote view never bumps the epoch — only origin events do.
A fleet whose membership never changes therefore keeps epoch 0
forever, and the static-membership configuration is behaviorally
identical to r16.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["MembershipView"]


class MembershipView:
    """The convergent membership CRDT one controller carries.

    Not thread-safe by itself — the FleetController serializes all
    mutation under its own lock (heartbeats, leaves, and admissions
    all run on the controller's tick/HTTP paths)."""

    __slots__ = ("epoch", "members")

    def __init__(self):
        self.epoch = 0
        self.members: Dict[str, dict] = {}

    # -- origin events (the ONLY places the epoch advances) ---------------
    def add(self, peer_id: str, url: Optional[str] = None) -> bool:
        """Admit `peer_id` as an up member.  Returns True (and bumps
        the epoch) only when this is NEW information — re-admitting a
        known up member is a no-op, and a departed member stays
        departed (left dominates)."""
        cur = self.members.get(peer_id)
        if cur is not None:
            if cur.get("status") == "left":
                return False
            if url and not cur.get("url"):
                cur["url"] = url   # learned the address; not an event
            return False
        self.members[peer_id] = {"url": url, "status": "up"}
        self.epoch += 1
        return True

    def leave(self, peer_id: str) -> bool:
        """Mark `peer_id` departed.  Returns True (and bumps the
        epoch) when the member was present and not already left."""
        cur = self.members.get(peer_id)
        if cur is None or cur.get("status") == "left":
            return False
        cur["status"] = "left"
        self.epoch += 1
        return True

    # -- gossip ------------------------------------------------------------
    def merge(self, doc) -> bool:
        """Fold a remote view into this one (max epoch, member union,
        left dominates).  Returns whether anything changed.  Malformed
        docs are ignored — gossip must never take a controller down."""
        if not isinstance(doc, dict):
            return False
        changed = False
        remote_epoch = doc.get("epoch")
        if isinstance(remote_epoch, int) and remote_epoch > self.epoch:
            self.epoch = remote_epoch
            changed = True
        remote = doc.get("members")
        if not isinstance(remote, dict):
            return changed
        for pid, info in remote.items():
            if not isinstance(pid, str) or not isinstance(info, dict):
                continue
            status = info.get("status")
            if status not in ("up", "left"):
                continue
            url = info.get("url")
            cur = self.members.get(pid)
            if cur is None:
                self.members[pid] = {"url": url, "status": status}
                changed = True
            else:
                if status == "left" and cur.get("status") != "left":
                    cur["status"] = "left"
                    changed = True
                if url and not cur.get("url"):
                    cur["url"] = url
                    changed = True
        return changed

    # -- queries -----------------------------------------------------------
    def status_of(self, peer_id: str) -> Optional[str]:
        cur = self.members.get(peer_id)
        return cur.get("status") if cur is not None else None

    def is_left(self, peer_id: str) -> bool:
        return self.status_of(peer_id) == "left"

    def url_of(self, peer_id: str) -> Optional[str]:
        cur = self.members.get(peer_id)
        return cur.get("url") if cur is not None else None

    def up_members(self):
        return [pid for pid, info in self.members.items()
                if info.get("status") == "up"]

    def to_doc(self) -> dict:
        return {"epoch": self.epoch,
                "members": {pid: {"url": info.get("url"),
                                  "status": info.get("status")}
                            for pid, info in self.members.items()}}
