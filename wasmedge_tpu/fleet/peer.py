"""Peer transport + liveness state machine for fleet federation.

`PeerClient` is the fleet's entire wire layer: stdlib http.client
requests against a peer gateway's `/v1/fleet/*` routes, with the
`peer_send` fault seam fired before every request (testing/faults.py)
so a test can sever exactly one direction of one link at one moment —
the deterministic half of a network partition.  Every transport
failure (injected or real: refused, reset, timeout, non-2xx) surfaces
as `PeerUnreachable`; callers never see raw socket errors.

`PeerState` is one peer's liveness record driven by the heartbeat
loop's suspect→dead state machine:

    alive ──(miss)──> alive(streak) ──(streak>=suspect_after)──> suspect
    suspect ──(streak>=dead_after)──> dead ──(probe succeeds)──> alive

  - probes back off exponentially with the miss streak (base * 2^k,
    capped), so a dead peer costs one cheap connect attempt per
    backoff window, not one per heartbeat tick
  - a successful probe from ANY state returns the peer to `alive` and
    zeroes the streak — dead is not a terminal state, it is "currently
    believed gone" (the peer may restart)
  - each gateway process draws a random `epoch` at boot; a peer that
    comes back with a NEW epoch is a fresh incarnation (its journal
    was resumed from disk, adoption bookkeeping resets)

The `dead` transition is the fleet's failover trigger: the federation
controller adopts the dead peer's replicated journal exactly once per
incarnation (fleet/federation.py).
"""

from __future__ import annotations

import json
import time
from typing import Optional, Tuple

# liveness state machine defaults (overridable via FleetConfig)
SUSPECT_AFTER = 2      # consecutive missed probes -> suspect
DEAD_AFTER = 4         # consecutive missed probes -> dead
BACKOFF_BASE_S = 0.05  # probe backoff: base * 2^streak, capped
BACKOFF_CAP_S = 2.0


class PeerUnreachable(RuntimeError):
    """A peer request failed at the transport layer (connect/read
    error, injected partition fault, or a non-2xx fleet response).
    The liveness state machine consumes these; they never escape to a
    client-facing route."""

    def __init__(self, peer: str, reason: str):
        super().__init__(f"peer {peer} unreachable: {reason}")
        self.peer = peer
        self.reason = reason


class PeerClient:
    """Minimal HTTP client for the peer protocol.  One instance per
    federation controller; stateless between calls (a fresh connection
    per request — peer traffic is low-rate control plane, and a cached
    connection would turn one partition into a poisoned socket)."""

    def __init__(self, self_id: str, faults=None, timeout_s: float = 10.0):
        self.self_id = self_id
        self.faults = faults
        self.timeout_s = float(timeout_s)

    def _fire(self, point: str, **ctx):
        if self.faults is not None:
            self.faults.fire(point, **ctx)

    def request(self, peer_id: str, url: str, method: str, path: str,
                body: Optional[dict] = None,
                raw: bool = False,
                allow_5xx: bool = False) -> Tuple[int, object]:
        """One peer HTTP round trip.  `url` is "host:port".  Returns
        (status, parsed-JSON) — or (status, bytes) with `raw=True`.
        Raises PeerUnreachable on ANY transport failure, including an
        injected `peer_send` fault (the deterministic severed link).
        A >=500 response counts as unreachable too (a peer_recv fault
        surfaces as one) UNLESS `allow_5xx` — the forward relay polls
        /v1/requests/<id>, where 503/504 bodies ARE the terminal
        outcome (deadline/lifecycle classes) and must reach the
        caller, not be mistaken for a dead peer."""
        import http.client

        route = path.strip("/").split("/")[-1].split("?")[0]
        if path.startswith("/v1/fleet/modules/"):
            route = "modules"
        elif path.startswith("/v1/requests/"):
            route = "requests"
        try:
            self._fire("peer_send", src=self.self_id, dst=peer_id,
                       route=route)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            raise PeerUnreachable(peer_id, f"injected: {e}") from e
        host, _, port = url.rpartition(":")
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self.timeout_s)
            try:
                data = None
                headers = {"X-Fleet-Peer": self.self_id}
                if body is not None:
                    data = json.dumps(body).encode()
                    headers["Content-Type"] = "application/json"
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            finally:
                conn.close()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            raise PeerUnreachable(peer_id, repr(e)) from e
        if resp.status >= 500 and not allow_5xx:
            raise PeerUnreachable(peer_id,
                                  f"HTTP {resp.status} on {path}")
        if raw:
            return resp.status, payload
        try:
            return resp.status, json.loads(payload) if payload else {}
        except ValueError as e:
            raise PeerUnreachable(peer_id,
                                  f"bad JSON from {path}: {e}") from e


class PeerState:
    """One peer's liveness + replication record."""

    __slots__ = ("peer_id", "url", "state", "streak", "last_seen",
                 "next_probe", "epoch", "replica", "adopted_epoch",
                 "modules", "transitions", "left", "joined_at")

    def __init__(self, peer_id: str, url: str):
        self.peer_id = peer_id
        self.url = url                 # "host:port"
        self.state = "alive"           # optimistic until proven missing
        self.streak = 0                # consecutive missed probes
        self.last_seen = -1.0
        self.next_probe = 0.0          # monotonic gate (backoff)
        self.epoch: Optional[str] = None
        self.replica: Optional[dict] = None   # last journal snapshot
        self.adopted_epoch: Optional[str] = None
        self.modules: list = []        # last manifest [{name, sha256}]
        self.transitions = 0           # state changes (flap visibility)
        # gossip membership (r21): a departed member is excluded from
        # routing and health accounting but still probed — its eventual
        # death must trigger normal journal adoption for any ids it
        # accepted before leaving.  `joined_at` is None for a
        # boot-configured peer and the monotonic admission time for a
        # runtime join (health.py grants it a churn grace window).
        self.left = False
        self.joined_at: Optional[float] = None

    def available(self) -> bool:
        """Routable: requests may be owned by (and forwarded to) this
        peer.  Suspect peers stay in the membership view so routing is
        stable across a flap — but a submit routed to one is refused
        retryably (fleet/federation.py PeerSuspect) rather than
        forwarded into a probable black hole.  A departed (left)
        member is never routable, whatever its liveness."""
        return self.state != "dead" and not self.left

    def note_ok(self, now: float, epoch: Optional[str]) -> bool:
        """Record a successful probe; returns True when the peer came
        back as a NEW incarnation (fresh epoch — reset adoption)."""
        fresh = epoch is not None and self.epoch is not None \
            and epoch != self.epoch
        if self.state != "alive":
            self.transitions += 1
        self.state = "alive"
        self.streak = 0
        self.last_seen = now
        self.next_probe = now
        if epoch is not None:
            self.epoch = epoch
        return fresh

    def note_miss(self, now: float, suspect_after: int = SUSPECT_AFTER,
                  dead_after: int = DEAD_AFTER,
                  backoff_base_s: float = BACKOFF_BASE_S) -> Optional[str]:
        """Record a missed probe; advances the state machine and arms
        the exponential probe backoff.  Returns the NEW state when this
        miss caused a transition (the "dead" return is the federation
        controller's adoption trigger), else None."""
        self.streak += 1
        self.next_probe = now + min(
            backoff_base_s * (2 ** min(self.streak, 16)), BACKOFF_CAP_S)
        new = None
        if self.streak >= dead_after:
            new = "dead"
        elif self.streak >= suspect_after:
            new = "suspect"
        if new is not None and new != self.state:
            self.state = new
            self.transitions += 1
            return new
        return None
