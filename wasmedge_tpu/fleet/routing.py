"""Consistent request routing across fleet peers.

Rendezvous (highest-random-weight) hashing: every peer scores each
routing key as sha256(peer_id | key) and the highest score owns the
key.  Properties the failover design leans on:

  - deterministic: every peer computes the SAME owner from the same
    membership view, with no coordination and no shared state
  - minimal churn: when a peer dies, only the keys it owned move (each
    to its runner-up peer) — survivors' keys never reshuffle, so a
    peer death re-routes exactly the dead peer's share of traffic
  - no ring state: membership is just the set of peer ids; a one-entry
    set trivially routes everything to self (solo mode falls out for
    free)

Keys are request ids (one request = one owner) so adoption after a
peer death can deterministically partition the dead peer's journal
among survivors: every survivor adopts exactly the ids it now owns,
and no id is adopted twice or by nobody.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional


def _score(peer_id: str, key: str) -> bytes:
    return hashlib.sha256(f"{peer_id}|{key}".encode()).digest()


def rendezvous_owner(key, peer_ids: Iterable[str]) -> Optional[str]:
    """The peer that owns `key` under rendezvous hashing, or None for
    an empty membership."""
    best = None
    best_score = b""
    for pid in peer_ids:
        s = _score(pid, str(key))
        if best is None or s > best_score:
            best, best_score = pid, s
    return best


def rendezvous_ranked(key, peer_ids: Iterable[str]) -> List[str]:
    """Full preference order for `key` (owner first) — the runner-up
    is the failover target when the owner is down."""
    return sorted(peer_ids, key=lambda pid: _score(pid, str(key)),
                  reverse=True)
