"""Network-facing multi-tenant serving gateway (r11).

The front door of the "millions of users" story: a stdlib HTTP server
(gateway/http.py) over a generation-swapped fleet of BatchServers
(gateway/service.py), with runtime guest-module registration through
the full loader -> validator -> image pipeline (gateway/registry.py)
and per-tenant auth/rate/quota edge policy (gateway/tenants.py).

    from wasmedge_tpu.gateway import Gateway, GatewayService

    svc = GatewayService(lanes=64)
    svc.register_module("fib", wasm_bytes=data)
    gw = Gateway(svc, port=8080).start()
    # POST /v1/invoke {"module": "fib", "func": "fib", "args": [30]}

or `wasmedge-tpu gateway app.wasm --port 8080` from the CLI.
"""

from wasmedge_tpu.gateway.http import Gateway  # noqa: F401
from wasmedge_tpu.gateway.registry import ModuleRegistry  # noqa: F401
from wasmedge_tpu.gateway.service import (  # noqa: F401
    GatewayRequest,
    GatewayService,
)
from wasmedge_tpu.gateway.tenants import (  # noqa: F401
    AuthError,
    GatewayTenants,
    RateLimited,
    TenantPolicy,
)
