"""Network-facing multi-tenant serving gateway (r11, durable r13,
federated r16).

The front door of the "millions of users" story: a stdlib HTTP server
(gateway/http.py) over a generation-swapped fleet of BatchServers
(gateway/service.py), with runtime guest-module registration through
the full loader -> validator -> image pipeline (gateway/registry.py),
per-tenant auth/rate/quota edge policy (gateway/tenants.py),
crash/restart durability over an on-disk module store + async-request
journal (gateway/durable.py), truthful health + degraded-mode load
shedding (gateway/health.py), and multi-host fleet federation —
peer-replicated module store, journal-replicated failover, cross-host
lane migration (wasmedge_tpu/fleet/; `GatewayService(fleet=[...])` or
CLI `--peer host:port`).

    from wasmedge_tpu.gateway import Gateway, GatewayService

    svc = GatewayService(lanes=64, state_dir="/var/lib/wasmedge-gw")
    svc.register_module("fib", wasm_bytes=data)
    gw = Gateway(svc, port=8080).start()
    # POST /v1/invoke {"module": "fib", "func": "fib", "args": [30]}
    # ... crash ...
    svc = GatewayService(lanes=64, state_dir="/var/lib/wasmedge-gw",
                         resume=True)   # modules + 202 ids come back

or `wasmedge-tpu gateway app.wasm --port 8080 --state-dir d [--resume]`
from the CLI.
"""

from wasmedge_tpu.gateway.durable import (  # noqa: F401
    DurabilityError,
    DurableStore,
)
from wasmedge_tpu.gateway.health import (  # noqa: F401
    HealthGate,
    ShedLoad,
    health_of,
)
from wasmedge_tpu.gateway.http import Gateway  # noqa: F401
from wasmedge_tpu.gateway.registry import ModuleRegistry  # noqa: F401
from wasmedge_tpu.gateway.service import (  # noqa: F401
    GatewayRequest,
    GatewayService,
    GenerationBuildFailed,
)
from wasmedge_tpu.gateway.tenants import (  # noqa: F401
    AuthError,
    GatewayTenants,
    RateLimited,
    TenantPolicy,
)
