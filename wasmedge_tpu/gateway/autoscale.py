"""Traffic-driven autoscale policy (r21 tentpole leg c).

A deterministic controller rides one GatewayService and drives the
three capacity actuators the stack already exposes, from the
queue-depth/occupancy signals the obs layer already exports:

  raise_virtual    grow the hv oversubscription ratio (admission
                   headroom IS the virtual-lane cap — hv admission
                   gates on it) up to `max_virtual_factor` × the
                   physical lane pool
  reshard_grow     recruit devices: a live reshard of the running
                   generation up the `device_ladder`
                   (gateway/service.py reshard — no drain)
  shed             last resort under sustained saturation with no
                   capacity left to recruit: flip the gateway into
                   degraded-mode shedding (gateway/health.py —
                   lowest-weight tier rejected 429-retryable at the
                   edge) instead of timing everyone out

and the reverse ladder when traffic calms: `unshed`, then
`reshard_shrink` back down the ladder, then `lower_virtual`.

The controller is DETERMINISTIC and cheap: one `tick()` reads the
queue ratio + occupancy, takes at most ONE action, and then holds for
`cooldown_ticks` — tests drive `tick()` by hand (auto_tick=False) and
assert the exact action sequence; production runs it on a small timer
thread.  `enabled=False` (the default) constructs nothing: the
autoscale-off configuration is behaviorally identical to r16 by
construction.

Every action increments `actions["<name>"]` — rendered as
`wasmedge_autoscale_actions_total{action=...}` (obs/metrics.py).
"""

from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["AutoscaleConfig", "AutoscaleController"]


class AutoscaleConfig:
    """Policy knobs.  `device_ladder` is the ordered device-count
    rungs reshard actions walk (e.g. [2, 4, 8]); empty disables
    reshard actions.  Watermarks are queued/capacity ratios."""

    def __init__(self, enabled: bool = False,
                 tick_s: float = 0.5,
                 high_queue_ratio: float = 0.75,
                 low_queue_ratio: float = 0.10,
                 cooldown_ticks: int = 4,
                 max_virtual_factor: float = 4.0,
                 virtual_step: Optional[int] = None,
                 device_ladder: Optional[List[int]] = None,
                 shed_when_exhausted: bool = True,
                 auto_tick: bool = True):
        self.enabled = bool(enabled)
        self.tick_s = float(tick_s)
        self.high_queue_ratio = float(high_queue_ratio)
        self.low_queue_ratio = float(low_queue_ratio)
        self.cooldown_ticks = int(cooldown_ticks)
        self.max_virtual_factor = float(max_virtual_factor)
        # virtual-cap increment per raise action; None = one physical
        # pool width per step
        self.virtual_step = virtual_step
        self.device_ladder = sorted(int(d) for d in device_ladder) \
            if device_ladder else []
        self.shed_when_exhausted = bool(shed_when_exhausted)
        self.auto_tick = bool(auto_tick)


class AutoscaleController:
    """One deterministic control loop over a GatewayService."""

    def __init__(self, svc, cfg: AutoscaleConfig):
        self.svc = svc
        self.cfg = cfg
        self.actions = {"raise_virtual": 0, "lower_virtual": 0,
                        "reshard_grow": 0, "reshard_shrink": 0,
                        "shed": 0, "unshed": 0}
        self.last_action: Optional[str] = None
        self._cooldown = 0
        self._base_virtual: Optional[int] = None
        self._shedding = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if not self.cfg.enabled or not self.cfg.auto_tick \
                or self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gw-autoscale")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                pass   # one bad tick never kills the loop
            self._stop.wait(self.cfg.tick_s)

    # -- signals -----------------------------------------------------------
    def _signals(self):
        """(server, queue_ratio, occupancy) of the CURRENT generation,
        or None while nothing serves."""
        gen = self.svc.current
        if gen is None:
            return None
        srv = gen.server
        cap = max(int(srv.k.queue_capacity), 1)
        ratio = len(srv.queue) / cap
        occ = srv.in_flight / max(srv.lanes, 1)
        return srv, ratio, occ

    # -- the ladder --------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One control round: read signals, take at most one action,
        hold through the cooldown.  Returns the action taken (None
        when holding or in band) — tests assert on this directly."""
        if not self.cfg.enabled:
            return None
        sig = self._signals()
        if sig is None:
            return None
        srv, ratio, occ = sig
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        action = None
        if ratio >= self.cfg.high_queue_ratio:
            action = self._spike(srv)
        elif ratio <= self.cfg.low_queue_ratio:
            action = self._calm(srv, occ)
        if action is not None:
            self.actions[action] += 1
            self.last_action = action
            self._cooldown = self.cfg.cooldown_ticks
            self.svc.obs.instant("autoscale", cat="gateway",
                                 track="gateway", action=action,
                                 queue_ratio=round(ratio, 3),
                                 occupancy=round(occ, 3))
        return action

    def _spike(self, srv) -> Optional[str]:
        # rung 1: raise the hv oversubscription ratio (admission
        # headroom) while under the configured ceiling
        hv = getattr(srv, "hv", None)
        if hv is not None:
            ceil = int(self.cfg.max_virtual_factor * srv.lanes)
            if hv.virtual_cap < ceil:
                if self._base_virtual is None:
                    self._base_virtual = int(hv.virtual_cap)
                step = self.cfg.virtual_step or srv.lanes
                with srv._lock:
                    hv.virtual_cap = min(hv.virtual_cap + int(step),
                                         ceil)
                return "raise_virtual"
        # rung 2: recruit devices — live reshard up the ladder
        nxt = self._next_rung(up=True)
        if nxt is not None:
            try:
                self.svc.reshard(n_devices=nxt)
                return "reshard_grow"
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:
                pass   # rolled back intact; fall through to shed
        # rung 3: nothing left to recruit — degrade gracefully by
        # shedding the lowest tier instead of timing everyone out
        if self.cfg.shed_when_exhausted and not self._shedding:
            self._shedding = True
            self.svc.force_degraded = True
            return "shed"
        return None

    def _calm(self, srv, occ: float) -> Optional[str]:
        # reverse order: stop shedding first, then give devices back,
        # then relax the oversubscription ratio
        if self._shedding:
            self._shedding = False
            self.svc.force_degraded = False
            return "unshed"
        if occ < 0.5:
            prev = self._next_rung(up=False)
            if prev is not None:
                try:
                    self.svc.reshard(n_devices=prev)
                    return "reshard_shrink"
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException:
                    pass
        hv = getattr(srv, "hv", None)
        if hv is not None and self._base_virtual is not None \
                and hv.virtual_cap > self._base_virtual:
            step = self.cfg.virtual_step or srv.lanes
            with srv._lock:
                hv.virtual_cap = max(hv.virtual_cap - int(step),
                                     self._base_virtual)
            return "lower_virtual"
        return None

    def _next_rung(self, up: bool) -> Optional[int]:
        """The device-ladder rung above/below the service's CURRENT
        device count, or None at the end of the ladder (or with no
        ladder configured)."""
        ladder = self.cfg.device_ladder
        if not ladder:
            return None
        cur = len(self.svc.devices) if self.svc.devices else 1
        if up:
            for d in ladder:
                if d > cur:
                    return d
            return None
        for d in reversed(ladder):
            if d < cur:
                return d
        return None

    def stats(self) -> dict:
        return {"enabled": self.cfg.enabled,
                "actions": dict(self.actions),
                "last_action": self.last_action,
                "cooldown": self._cooldown,
                "shedding": self._shedding}
