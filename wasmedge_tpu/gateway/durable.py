"""DurableStore: the gateway's crash-survivable state (r13 tentpole).

Everything the r11 gateway kept only in process memory — which modules
are registered, which async 202 ids are still owed an answer — lands
on disk here, so `GatewayService(resume=True)` can rebuild the front
door after a crash without losing a single client-visible id:

  <state_dir>/
    modules/<sha256>.wasm     registered wasm bytes, content-addressed
                              (two tenants registering identical bytes
                              share one blob)
    manifest-<seq>.json       the module set + attribution (name,
                              sha256, tenant, source), the current
                              generation's serve-checkpoint directory,
                              and the cumulative restart count
    journal-<seq>.json        the async-request journal: every
                              accepted-but-unresolved request id with
                              its tenant/module/func/args/deadline,
                              plus a bounded durable RESULT CACHE of
                              recently resolved entries
    serve/gen-<n>/            the generation's BatchServer checkpoint
                              lineage (serve-*.npz, owned by
                              serve/server.py)

Manifest and journal are sequence-numbered snapshot files riding the
shared `batch/lineage.py` machinery: every write is a NEW member
(crash-atomic via utils/fsio.atomic_write_bytes), the newest-good
walk skips a corrupt/truncated newest on load, and the prune pass
bounds the directory.  Writes are full-state snapshots, not appends —
one torn write can never orphan the log.

Resume semantics per request state (the README table):

  resolved, in the result cache   replayed verbatim  (exactly-once)
  in flight at the last serve     adopted from the checkpoint lineage
  checkpoint                      and finished        (exactly-once
                                  from the snapshot's point of view;
                                  post-snapshot progress re-executes)
  accepted, not in a checkpoint   re-queued under the SAME id
                                  (at-least-once: the guest may have
                                  partially run before the crash)
  resolved but aged out of the    polls answer 404 with the distinct
  result cache                    "pruned" detail (the journaled
                                  max_id floor marks the id as
                                  issued-and-aged, never "unknown")

The `journal_write` fault seam (testing/faults.py) fires before every
manifest/journal write; a submit whose journal write faults is rejected
with a retryable DurabilityError — the gateway never issues a 202 id it
could not make durable.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List, Optional, Tuple

from wasmedge_tpu.batch.lineage import Lineage
from wasmedge_tpu.common.errors import ErrCode, WasmError
from wasmedge_tpu.utils.fsio import atomic_write_bytes

FORMAT_VERSION = 1


class DurabilityError(WasmError):
    """A durable write (module blob, manifest, journal) failed.
    Retryable: the condition is environmental (full disk, injected
    fault), not a property of the request — the HTTP layer maps it to
    503 + Retry-After so a client re-submits against a recovered
    gateway."""

    retryable = True

    def __init__(self, msg: str = "gateway durable write failed"):
        super().__init__(ErrCode.ExecutionFailed, msg)
        self.retry_after_s = 1.0


def _resolved_entry(req) -> dict:
    """Durable result-cache record for a finalized GatewayRequest."""
    err = req.future.error
    out = {"id": req.id, "tenant": req.tenant, "func": req.func}
    if err is None:
        out["ok"] = True
        out["result"] = [int(c) for c in req.future.result(0)]
        return out
    from wasmedge_tpu.serve.queue import DeadlineExceeded, ServeRejected

    if isinstance(err, DeadlineExceeded):
        kind = "deadline"
    elif isinstance(err, ServeRejected):
        kind = "lifecycle"
    else:
        kind = "trap" if isinstance(err, WasmError) else "error"
    out["ok"] = False
    out["err"] = {"kind": kind,
                  "code": int(getattr(err, "code", ErrCode.ExecutionFailed)),
                  "message": str(err)}
    return out


def resolved_error(entry: dict) -> BaseException:
    """Rebuild a replayable exception from a durable result-cache
    record, preserving the class the HTTP status mapping branches on
    (a deadline that 504'd before the crash must 504 after it)."""
    err = entry.get("err") or {}
    kind = err.get("kind", "error")
    code = ErrCode(err["code"]) if err.get("code") in \
        ErrCode._value2member_map_ else ErrCode.ExecutionFailed
    msg = err.get("message", "")
    if kind == "deadline":
        from wasmedge_tpu.serve.queue import DeadlineExceeded

        return DeadlineExceeded(msg or "request deadline exceeded")
    if kind == "lifecycle":
        from wasmedge_tpu.serve.queue import ServeRejected

        return ServeRejected(msg or "rejected by a previous gateway "
                                    "process")
    return WasmError(code, msg)


class DurableStore:
    """On-disk module store + async-request journal for one gateway.

    Thread-safe: HTTP handler threads journal submits concurrently; one
    lock serializes snapshot writes (each write is the FULL current
    state, so serialization is also what makes the newest file
    authoritative)."""

    def __init__(self, state_dir: str, faults=None, keep: int = 2,
                 result_cache: int = 256):
        self.dir = os.fspath(state_dir)
        self.modules_dir = os.path.join(self.dir, "modules")
        os.makedirs(self.modules_dir, exist_ok=True)
        self.faults = faults
        self.keep = max(int(keep), 1)
        self.result_cache = max(int(result_cache), 0)
        self._lock = threading.Lock()
        self._manifest = Lineage()
        self._manifest.install(Lineage.scan(self.dir,
                                            r"manifest-(\d+)\.json"))
        self._journal = Lineage()
        self._journal.install(Lineage.scan(self.dir,
                                           r"journal-(\d+)\.json"))
        # snapshot members that failed to parse on load (skipped by the
        # newest-good walk); surfaced through gateway health
        self.load_errors = 0

    # -- module blobs ------------------------------------------------------
    def save_module_bytes(self, sha256: str, data: bytes):
        """Content-addressed: an existing blob is already the bytes
        (sha-keyed), so re-registration of known content is free."""
        path = os.path.join(self.modules_dir, f"{sha256}.wasm")
        if os.path.exists(path):
            return
        self._fire("journal_write", kind="module", sha256=sha256)
        atomic_write_bytes(path, data)

    def module_bytes(self, sha256: str) -> bytes:
        with open(os.path.join(self.modules_dir, f"{sha256}.wasm"),
                  "rb") as f:
            return f.read()

    def compile_cache_dir(self) -> str:
        """Directory for the persistent compile cache's entry files,
        beside the module blobs (same crash-survivability story: a
        resumed gateway re-registers the manifest's modules and every
        lowering comes off this cache instead of the validator)."""
        path = os.path.join(self.dir, "compilecache")
        os.makedirs(path, exist_ok=True)
        return path

    # -- snapshots ---------------------------------------------------------
    def write_manifest(self, modules: List[dict], generation: int,
                       serve_dir: str, restarts: int):
        """Persist the module set (written after every successful
        generation swap, before the 201 is returned — a crash between
        swap and manifest simply resumes the previous set, and the
        client never saw a 201 for the module that vanished)."""
        doc = {"format": FORMAT_VERSION, "generation": int(generation),
               "serve_dir": serve_dir, "restarts": int(restarts),
               "modules": list(modules)}
        self._write(self._manifest, "manifest", doc)

    def write_journal(self, unresolved: List[dict],
                      resolved: List[dict], max_id: int = 0,
                      min_id: int = 0):
        """Persist the request journal: every accepted-but-unresolved
        id, the bounded durable result cache (newest last; the depth
        cap is applied here so the on-disk cache can never outgrow the
        knob), and the id RANGE ever issued (`min_id`/`max_id`) — the
        resumed process's pruned-vs-never-issued window (min_id
        matters since r16: fleet id-space rebasing means a gateway's
        ids need not start anywhere near 1)."""
        doc = {"format": FORMAT_VERSION,
               "max_id": int(max_id),
               "min_id": int(min_id),
               "unresolved": list(unresolved),
               "resolved": list(resolved)[-self.result_cache:]}
        self._write(self._journal, "journal", doc)

    def _write(self, lineage: Lineage, stem: str, doc: dict):
        with self._lock:
            self._fire("journal_write", kind=stem)
            seq = lineage.next_seq()
            path = os.path.join(self.dir, f"{stem}-{seq:08d}.json")
            atomic_write_bytes(path, (json.dumps(doc) + "\n").encode())
            lineage.add(path, seq)
            lineage.prune(self.keep)

    def _fire(self, point: str, **ctx):
        if self.faults is not None:
            self.faults.fire(point, **ctx)

    # -- load (resume) -----------------------------------------------------
    def load(self) -> Tuple[Optional[dict], Optional[dict]]:
        """(manifest, journal) — each the newest member that parses,
        walked newest-first with corrupt members skipped and counted
        (the lineage contract; a half-written pre-atomic-era file can
        only cost one fallback, never the resume)."""
        return (self._load_one(self._manifest),
                self._load_one(self._journal))

    def _load_one(self, lineage: Lineage) -> Optional[dict]:
        def load(m):
            with open(m.path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict) or "format" not in doc:
                raise ValueError(f"not a gateway snapshot: {m.path}")
            return doc

        def bad(exc, m):
            self.load_errors += 1

        with self._lock:
            return lineage.walk_newest(load, bad)

    # -- serve checkpoint dirs ---------------------------------------------
    def serve_dir_for(self, generation: int) -> str:
        return os.path.join(self.dir, "serve", f"gen-{int(generation):06d}")

    def drop_serve_dir(self, path: str):
        """Best-effort removal of a drained generation's checkpoint
        lineage by path (the new generation checkpoints into its own
        dir; a failed delete never fails the gateway).  Refuses paths
        outside this store's serve/ tree."""
        import shutil

        root = os.path.abspath(os.path.join(self.dir, "serve"))
        if os.path.commonpath([root, os.path.abspath(path)]) != root:
            return
        shutil.rmtree(path, ignore_errors=True)
