"""Truthful gateway health + degraded-mode load shedding (r13).

The r11 `/healthz` was a static liveness stub: a dead BatchServer
driver thread, a failed generation swap, or a gateway that could no
longer persist a checkpoint all still answered `200 {"ok": true}`.
This module computes the real thing — a machine-readable report over
the signals that actually predict whether the NEXT request will be
served:

  driver       the current generation's serving thread is alive and
               the server has not terminally failed       (unhealthy)
  generation   a serving generation exists at all          (degraded —
               the gateway can still register modules)
  last_swap    the most recent generation build/swap
               succeeded (a rollback leaves the PRIOR
               generation serving: degraded, not dead)     (degraded)
  queue        queued depth / capacity below the
               saturation ratio                            (degraded)
  checkpoint   the serving state's last snapshot write
               succeeded (a server that cannot persist
               cannot promise crash recovery)              (degraded)
  journal      the durable manifest/journal writes are
               succeeding (durability-enabled gateways)    (degraded)

`status` is the worst level across checks: "healthy" -> HTTP 200,
"degraded" -> HTTP 200 with the failing checks in the body (load
balancers keep routing, operators see why), "unhealthy" -> HTTP 503.

Degraded gateways optionally SHED: rather than admitting everyone into
a queue that will time them all out, submissions from the lowest-weight
tenant tier are rejected up front with a retryable 429 (ShedLoad), so
paying traffic keeps its latency and shed clients get a machine-
readable "come back later" instead of a 504 after the wait.
"""

from __future__ import annotations

import time
from typing import Optional

from wasmedge_tpu.common.errors import ErrCode, WasmError

# queued/capacity ratio beyond which the queue check degrades (and
# shedding, when enabled, kicks in)
QUEUE_SATURATION_RATIO = 0.8

_LEVELS = {"healthy": 0, "degraded": 1, "unhealthy": 2}


class ShedLoad(WasmError):
    """Degraded-mode load shedding rejected this submission at the
    edge.  Retryable — the same request is welcome once the gateway
    recovers (HTTP 429 + Retry-After, like backpressure, but carrying
    the `shed` detail so clients can tell policy from pressure)."""

    retryable = True
    detail = "shed"

    def __init__(self, tenant: str, reason: str):
        super().__init__(
            ErrCode.CostLimitExceeded,
            f"tenant {tenant!r} shed while gateway degraded ({reason})")
        self.tenant = tenant
        self.retry_after_s = 1.0


def _check(ok: bool, level: str, detail: str) -> dict:
    return {"ok": bool(ok),
            "level": "healthy" if ok else level,
            "detail": detail}


def health_of(svc) -> dict:
    """One machine-readable health report over a GatewayService.
    Pure read — safe from any thread, including the HTTP pool."""
    checks = {}
    gen = svc.current
    if gen is None:
        checks["generation"] = _check(
            False, "degraded",
            "no serving generation (no modules registered)")
    else:
        srv = gen.server
        if srv.failed is not None:
            checks["driver"] = _check(
                False, "unhealthy",
                f"serving generation {gen.gen_id} terminally failed: "
                f"{srv.failed!r}")
        else:
            t = srv._thread
            dead = t is not None and not t.is_alive() and not srv._stop
            checks["driver"] = _check(
                not dead, "unhealthy",
                f"generation {gen.gen_id} driver thread died"
                if dead else f"generation {gen.gen_id} driver alive")
        cap = max(int(srv.k.queue_capacity), 1)
        depth = len(srv.queue)
        ratio = depth / cap
        saturated = ratio >= QUEUE_SATURATION_RATIO
        detail = f"queue {depth}/{cap} ({ratio:.0%} of capacity)"
        hv = getattr(srv, "hv", None)
        if saturated and hv is not None:
            # an oversubscribed server drains the queue into VIRTUAL
            # lanes at every boundary: "no physical lane free but
            # resident budget / virtual headroom available" is
            # backpressure the next rebalance absorbs, not saturation
            # — the pre-hv free-lane-heap reading would misclassify an
            # oversubscribed-but-healthy server as degraded here.  The
            # headroom must cover the QUEUED depth though: 2 open
            # virtual slots against 950 queued is still saturation,
            # or health would flap with probe timing and shedding
            # would never engage on a genuinely overloaded server.
            headroom = hv.headroom(srv._bindings)
            if headroom >= depth:
                saturated = False
                detail += f" (hv headroom {headroom})"
        checks["queue"] = _check(not saturated, "degraded", detail)
        streak = int(getattr(srv, "checkpoint_fail_streak", 0))
        checks["checkpoint"] = _check(
            streak == 0, "degraded",
            f"{streak} consecutive serve-checkpoint write failures"
            if streak else "serve checkpoints writing")
    last = svc.last_swap
    if last is not None:
        checks["last_swap"] = _check(
            bool(last.get("ok")), "degraded",
            last.get("error") or f"generation {last.get('generation')} "
                                 f"swap ok")
    if svc.durable is not None:
        bad = int(svc.counters.get("journal_errors", 0))
        streak = int(getattr(svc, "_journal_fail_streak", 0))
        checks["journal"] = _check(
            streak == 0, "degraded",
            f"durable journal writes failing (streak {streak}, "
            f"total {bad})" if streak else "durable journal writing")
    fleet = getattr(svc, "fleet", None)
    if fleet is not None and fleet.started:
        snap = fleet.stats()
        peers = snap.get("peers", {})
        total = int(snap.get("configured_peers", 0)) \
            - int(snap.get("left_peers", 0))
        if total > 0:
            # fleet capacity view: a suspect/dead peer is lost
            # aggregate capacity — DEGRADED, which (with shedding on)
            # sheds the lowest weight tier fleet-wide until the peer
            # recovers or its load is adopted.  A fleet with no peers
            # configured adds NO check at all: solo mode must look
            # exactly like the non-federated gateway.
            #
            # Churn is NOT degradation (r21): a departed (left) member
            # is expected absence and leaves the tally entirely, and a
            # runtime-joined peer inside its churn grace window counts
            # as "joining", not missing — a clean join/leave must not
            # trip degraded-mode shedding.  A genuinely missing
            # boot-configured peer still degrades.
            missing = int(peers.get("suspect", 0)) \
                + int(peers.get("dead", 0))
            checks["fleet"] = _check(
                missing == 0, "degraded",
                f"{peers.get('alive', 0)}/{total} peers alive "
                f"({peers.get('suspect', 0)} suspect, "
                f"{peers.get('dead', 0)} dead, "
                f"{peers.get('joining', 0)} joining, "
                f"{snap.get('left_peers', 0)} left)")
        _fleet_churn = int(peers.get("joining", 0)) \
            + int(snap.get("left_peers", 0))
    else:
        peers, snap, _fleet_churn = {}, {}, 0
    resharding = int(getattr(svc, "_resharding", 0))
    if _fleet_churn or resharding:
        # informational, always healthy: operators (and tests) can
        # see churn-in-progress distinctly from degradation
        checks["churn"] = _check(
            True, "degraded",
            f"churn in progress: {peers.get('joining', 0)} joining, "
            f"{snap.get('left_peers', 0)} left, "
            f"{resharding} reshard(s) in flight")
    if getattr(svc, "force_degraded", False):
        checks["forced"] = _check(False, "degraded",
                                  "operator forced degraded mode")
    status = "healthy"
    for c in checks.values():
        if _LEVELS[c["level"]] > _LEVELS[status]:
            status = c["level"]
    return {"ok": status != "unhealthy", "status": status,
            "checks": checks}


class HealthGate:
    """Cheap memoized health for the submit hot path: re-evaluates at
    most every `ttl_s`, so a thousand concurrent submits cost one
    health walk, not a thousand."""

    def __init__(self, svc, ttl_s: float = 0.1):
        self.svc = svc
        self.ttl_s = float(ttl_s)
        self._t = -1.0
        self._cached: Optional[dict] = None

    def health(self, fresh: bool = False) -> dict:
        now = time.monotonic()
        if fresh or self._cached is None or now - self._t > self.ttl_s:
            self._cached = health_of(self.svc)
            self._t = now
        return self._cached

    def maybe_shed(self, tenant: str):
        """Raise ShedLoad when the gateway is degraded, shedding is
        enabled, and `tenant` rides the lowest weight tier.  Healthy
        gateways return immediately (one memoized dict read)."""
        if not self.svc.shed_on_degraded:
            return
        h = self.health()
        if h["status"] == "healthy":
            return
        floor = self.svc.tenants.shed_weight_floor()
        if floor is None:
            return   # single tier: shedding would be an outage
        if self.svc.tenants.effective_weight(tenant) <= floor:
            reasons = [c["detail"] for c in h["checks"].values()
                       if not c["ok"]]
            raise ShedLoad(tenant, "; ".join(reasons) or h["status"])
