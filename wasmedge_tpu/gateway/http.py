"""HTTP front door: the wire protocol over GatewayService.

Stdlib-only (http.server ThreadingHTTPServer — one thread per
connection, keep-alive), because the container bakes no web framework
and the protocol is deliberately small:

  POST /v1/invoke        {"module","func","args","tenant","deadline_ms",
                          "async"} -> 200 result | 202 + poll URL
  GET  /v1/requests/<id> poll an async/timed-out request
  POST /v1/modules       register a guest module at runtime (JSON
                          {"name","wasm_b64"} or raw application/wasm
                          with ?name=) -> 201 + generation
  GET  /v1/status        queue/occupancy/generation counters (JSON)
  GET  /metrics          Prometheus text exposition
  GET  /healthz          TRUTHFUL health (gateway/health.py): driver
                          liveness, last-swap outcome, queue
                          saturation, checkpoint/journal write health.
                          200 healthy, 200 + status "degraded" with
                          the failing checks in the body, 503
                          unhealthy — machine-readable either way.

Status-code contract (the machine-readable rejection taxonomy of
common/errors.rejection_info on the wire):

  429 + Retry-After   QueueSaturated backpressure / tenant rate limit
                      / degraded-mode load shedding (ShedLoad carries
                      detail "shed") — the retryable classes
  504                 DeadlineExceeded (queued or in flight)
  401 / 403           auth stub rejection / permanent admission block,
                      registration not allowed
  404                 unknown module, function, or request id; a
                      PRUNED async id carries detail "pruned" so a
                      client holding a real 202 can tell "aged out"
                      from "never existed"
  400                 malformed request, bad/unbatchable wasm
                      (Load/Validation ErrCode in the body), or a
                      static admission policy violation
                      (StaticPolicyViolation + per-limit violations
                      list, analysis/policy.py)
  409                 duplicate module name
  503 + Retry-After   retryable infrastructure: a rolled-back
                      generation build/swap (GenerationBuildFailed),
                      a failed durable journal write (the 202 id was
                      never issued), gateway shutting down
  503                 server terminal failure
  200 {"ok": false}   the request RAN and trapped — guest-level
                      failures carry the ErrCode taxonomy in the body,
                      exactly like the CLI's per-request reporting

Auth: `Authorization: Bearer <key>` or `X-Api-Key: <key>`; the key
resolves the tenant (gateway/tenants.py).

Chaos seams: with a FaultInjector armed on the service, the
`http_response_delay` / `http_response_drop` seams fire per response —
delay sleeps ~50ms before the bytes, drop severs the connection with
no response written (testing/faults.py; absorbed here, never raised
to the route handlers).
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from wasmedge_tpu.common.errors import (
    EngineFailure,
    ErrCode,
    InstantiationError,
    LoadError,
    ValidationError,
    WasmError,
    rejection_info,
)
from wasmedge_tpu.gateway.durable import DurabilityError
from wasmedge_tpu.gateway.health import ShedLoad
from wasmedge_tpu.gateway.service import (
    GatewayClosed,
    GatewayRequest,
    GatewayService,
)
from wasmedge_tpu.gateway.tenants import AuthError, RateLimited
from wasmedge_tpu.serve.queue import (
    DeadlineExceeded,
    QueueSaturated,
    ServeRejected,
)


def error_payload(exc: BaseException) -> dict:
    """The body half of the rejection contract: WasmErrors carry their
    ErrCode taxonomy (rejection_info); edge-layer rejections carry a
    stable name + the same retryable flag shape."""
    if isinstance(exc, RateLimited):
        out = {"name": "RateLimited", "retryable": True,
               "message": str(exc)}
        if math.isfinite(exc.retry_after_s):
            out["retry_after_s"] = exc.retry_after_s
        return out
    if isinstance(exc, AuthError):
        return {"name": "AuthError", "retryable": False,
                "message": str(exc)}
    if isinstance(exc, KeyError):
        return {"name": "NotFound", "retryable": False,
                "message": str(exc.args[0]) if exc.args else "not found"}
    return rejection_info(exc)


def submit_status_of(exc: BaseException) -> int:
    """HTTP status for a rejection BEFORE the request ran (auth, rate,
    admission, registration, routing)."""
    if isinstance(exc, AuthError):
        return 401
    if isinstance(exc, (RateLimited, QueueSaturated, ShedLoad)):
        return 429
    if isinstance(exc, DeadlineExceeded):
        return 504
    if isinstance(exc, KeyError):
        return 404
    if isinstance(exc, DurabilityError):
        # the journal write failed, so the id was never accepted:
        # service unavailable, retry against a recovered gateway
        return 503
    if isinstance(exc, (EngineFailure, GatewayClosed)):
        # terminal generation failure, a rolled-back generation
        # build/swap (GenerationBuildFailed, retryable), or the
        # gateway going down: service unavailable, NOT a permission
        # problem — clients may retry against a recovered gateway
        return 503
    if isinstance(exc, (LoadError, ValidationError, InstantiationError)):
        return 400
    if isinstance(exc, WasmError):
        if exc.code == ErrCode.ModuleNameConflict:
            return 409
        if exc.code == ErrCode.Terminated and not exc.retryable:
            # the tenant's own policy forbids this request permanently
            # (quota/weight <= 0 admission block)
            return 403
        return 400
    if isinstance(exc, (ValueError, TypeError)):
        return 400
    return 500


def retry_after_of(exc: BaseException) -> Optional[str]:
    """Retry-After for every retryable rejection (backpressure, rate
    limit, shedding, rolled-back swap, failed journal write) — the
    header IS the machine-readable half of "try again"."""
    after = getattr(exc, "retry_after_s", None)
    if isinstance(exc, RateLimited) or getattr(exc, "retryable", False):
        if after is None or not math.isfinite(after):
            return "1"
        return str(max(1, math.ceil(after)))
    return None


def result_response(req: GatewayRequest) -> Tuple[int, dict]:
    """Response for a COMPLETED request.  Transport-level failures map
    to 5xx (deadline 504, server terminal 503); a guest that ran and
    trapped is a 200 with ok=false + the ErrCode taxonomy in the body
    — the same per-request reporting discipline as the CLI."""
    base = {"request_id": req.id, "func": req.func,
            "tenant": req.tenant, "generation": req.gen_id}
    err = req.future.error
    if err is None:
        return 200, dict(base, ok=True, status="done",
                         result=[int(c) for c in req.future.result(0)])
    body = dict(base, ok=False, status="error", err=error_payload(err))
    if isinstance(err, DeadlineExceeded):
        return 504, body
    if isinstance(err, (EngineFailure, ServeRejected)):
        # the guest never ran: terminal generation failure, non-drain
        # shutdown kill, or the unservable-after-acceptance sweep —
        # 5xx, never the 200 ok:false reserved for real guest traps
        return 503, body
    return 200, body


class GatewayHandler(BaseHTTPRequestHandler):
    """One request per invocation; the service does the thinking."""

    server_version = "wasmedge-tpu-gateway"
    protocol_version = "HTTP/1.1"

    # the HTTP server is a serving surface, not a logger: access lines
    # go to the flight recorder (count_http + gateway spans), never to
    # stderr where they would interleave with the CLI's JSON
    def log_message(self, fmt, *args):
        pass

    @property
    def svc(self) -> GatewayService:
        return self.server.service

    # -- plumbing ----------------------------------------------------------
    def _chaos_edge(self, code: int) -> bool:
        """Fire the HTTP edge fault seams (absorbed, never raised to
        the routes): delay sleeps before the response bytes; drop
        severs the connection with nothing written.  Returns True when
        the response must be dropped."""
        faults = self.svc.faults
        if faults is None:
            return False
        from wasmedge_tpu.testing.faults import InjectedFault

        import time as _time

        # coarse route tag so Fault.match can target e.g. only the
        # polling traffic ({"route": "requests"}) without enumerating
        # per-id paths
        path = self.path.split("?", 1)[0]
        route = path.strip("/").split("/")[-1] if path != "/" else ""
        if path.startswith("/v1/requests/"):
            route = "requests"
        try:
            faults.fire("http_response_delay", path=self.path,
                        route=route, code=int(code))
        except InjectedFault:
            _time.sleep(0.05)
        try:
            faults.fire("http_response_drop", path=self.path,
                        route=route, code=int(code))
        except InjectedFault:
            return True
        return False

    def _reply(self, code: int, body, content_type="application/json",
               headers=None):
        if self._chaos_edge(code):
            # injected wire failure: close with no response (the client
            # sees a severed connection, exactly like a dropped packet)
            self.close_connection = True
            try:
                self.wfile.flush()
            except OSError:
                pass
            return
        data = body if isinstance(body, (bytes, bytearray)) \
            else json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        self.svc.count_http(code)

    def _reject(self, exc: BaseException, code: Optional[int] = None):
        code = submit_status_of(exc) if code is None else code
        headers = {}
        after = retry_after_of(exc)
        if after is not None:
            headers["Retry-After"] = after
        self._reply(code, {"ok": False, "err": error_payload(exc)},
                    headers=headers)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _api_key(self) -> Optional[str]:
        auth = self.headers.get("Authorization")
        if auth and auth.lower().startswith("bearer "):
            return auth[7:].strip()
        return self.headers.get("X-Api-Key")

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        url = urlparse(self.path)
        try:
            if url.path == "/v1/status":
                return self._reply(200, self.svc.status())
            if url.path == "/metrics":
                return self._reply(200, self.svc.metrics_text().encode(),
                                   content_type="text/plain; version=0.0.4")
            if url.path == "/healthz":
                # truthful health: a dead driver thread or terminally
                # failed generation answers 503, a degraded gateway
                # answers 200 with the failing checks in the body
                h = self.svc.health()
                return self._reply(200 if h["ok"] else 503, h)
            if url.path.startswith("/v1/requests/") \
                    and url.path.endswith("/stream"):
                # routed BEFORE _get_request: its id parse takes the
                # LAST path segment, which here is "stream"
                return self._stream_request(url)
            if url.path.startswith("/v1/requests/"):
                return self._get_request(url.path)
            if url.path.startswith("/v1/fleet/"):
                return self._fleet_get(url.path)
            return self._reply(404, {"ok": False, "err": {
                "name": "NotFound", "retryable": False,
                "message": f"no route {url.path}"}})
        except Exception as e:  # route handlers raise the taxonomy
            return self._reject(e)

    def do_POST(self):
        url = urlparse(self.path)
        try:
            if url.path == "/v1/invoke":
                return self._invoke(url)
            if url.path == "/v1/modules":
                return self._register(url)
            if url.path.startswith("/v1/requests/") \
                    and url.path.endswith("/wake"):
                return self._wake_request(url)
            if url.path.startswith("/v1/fleet/"):
                return self._fleet_post(url.path)
            if url.path == "/v1/reshard":
                return self._reshard()
            return self._reply(404, {"ok": False, "err": {
                "name": "NotFound", "retryable": False,
                "message": f"no route {url.path}"}})
        except Exception as e:
            return self._reject(e)

    # -- peer protocol (wasmedge_tpu/fleet/, r16) --------------------------
    # Operator/peer control plane: no tenant auth (like /healthz), and
    # every handler fires the `peer_recv` fault seam so a test can
    # sever exactly the inbound half of one link (an injected fault
    # surfaces as a 5xx the SENDING peer counts as unreachable).
    def _fleet(self):
        fl = self.svc.fleet
        if fl is None:
            raise KeyError("fleet federation is not enabled")
        return fl

    def _fleet_get(self, path: str):
        fl = self._fleet()
        if path.startswith("/v1/fleet/modules/"):
            sha = path.rsplit("/", 1)[1]
            fl._recv("modules", self.headers.get("X-Fleet-Peer"))
            data = fl.module_bytes(sha)
            if data is None:
                raise KeyError(f"no module blob {sha[:12]}")
            return self._reply(200, data,
                               content_type="application/wasm")
        if path.startswith("/v1/fleet/cache/"):
            # compile-cache replication (r22): raw entry bytes, digest
            # verified end to end by the receiver's adopt_entry
            sha = path.rsplit("/", 1)[1]
            fl._recv("cache", self.headers.get("X-Fleet-Peer"))
            data = fl.cache_bytes(sha)
            if data is None:
                raise KeyError(f"no cache entry {sha[:12]}")
            return self._reply(200, data,
                               content_type="application/octet-stream")
        if path.startswith("/v1/fleet/blob/"):
            # at-rest scrub repair (r24): a verified replica of a
            # content-addressed swap blob (parked session payloads)
            key = path.rsplit("/", 1)[1]
            fl._recv("blob", self.headers.get("X-Fleet-Peer"))
            data = fl.blob_bytes(key)
            if data is None:
                raise KeyError(f"no blob {key[:12]}")
            return self._reply(200, data,
                               content_type="application/octet-stream")
        if path == "/v1/fleet/manifest":
            fl._recv("manifest", self.headers.get("X-Fleet-Peer"))
            return self._reply(200, fl._hello())
        if path == "/v1/fleet/status":
            return self._reply(200, dict(
                fl.stats(), peer_states=fl.peer_states(),
                swapped=[int(x) for x in
                         (self.svc.current.server.list_swapped()
                          if self.svc.current else [])]))
        raise KeyError(f"no fleet route {path}")

    def _fleet_post(self, path: str):
        import json as _json

        fl = self._fleet()
        body = self._read_body()
        try:
            doc = _json.loads(body or b"{}")
        except _json.JSONDecodeError as e:
            raise ValueError(f"malformed JSON body: {e}") from e
        if path == "/v1/fleet/heartbeat":
            return self._reply(200, fl.on_heartbeat(doc))
        if path == "/v1/fleet/journal":
            return self._reply(200, fl.on_journal(doc))
        if path == "/v1/fleet/execute":
            return self._reply(200, fl.on_execute(doc))
        if path == "/v1/fleet/migrate":
            return self._reply(200, fl.on_migrate(doc))
        if path == "/v1/fleet/wake":
            # fleet-routed wake (r24): an edge member forwarded an
            # external wake to this gateway as the id's rendezvous
            # owner; applied locally, never re-forwarded
            return self._reply(200, fl.on_wake(doc))
        if path == "/v1/fleet/migrate_out":
            # operator/bench trigger: ship one parked virtual lane
            return self._reply(200, fl.migrate_out(
                int(doc["id"]), str(doc["peer"])))
        if path == "/v1/fleet/leave":
            # departure announcement (r21 gossip membership): mark a
            # member — default: this gateway — as left and gossip it
            return self._reply(200, fl.on_leave(doc))
        raise KeyError(f"no fleet route {path}")

    def _reshard(self):
        """Operator/bench trigger for a live device-set change (r21):
        POST /v1/reshard {"devices": N} rebuilds the CURRENT serving
        generation over the first N local devices at a launch boundary
        — no drain, no request re-queue (gateway/service.py
        reshard)."""
        import json as _json

        body = self._read_body()
        try:
            doc = _json.loads(body or b"{}")
        except _json.JSONDecodeError as e:
            raise ValueError(f"malformed JSON body: {e}") from e
        n = doc.get("devices")
        if not isinstance(n, int) or n < 1:
            raise ValueError('"devices" must be a positive integer')
        return self._reply(200, self.svc.reshard(n_devices=n))

    # -- handlers ----------------------------------------------------------
    def _invoke(self, url):
        body = self._read_body()
        try:
            doc = json.loads(body or b"{}")
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
        except json.JSONDecodeError as e:
            raise ValueError(f"malformed JSON body: {e}") from e
        func = doc.get("func")
        if not func or not isinstance(func, str):
            raise ValueError('missing required field "func"')
        args = doc.get("args", [])
        if not isinstance(args, list):
            raise ValueError('"args" must be a list of integers')
        args = [int(a) for a in args]
        module = doc.get("module")
        deadline_ms = doc.get("deadline_ms")
        deadline_s = float(deadline_ms) / 1000.0 \
            if deadline_ms is not None else None
        q = parse_qs(url.query)
        async_ = bool(doc.get("async")) or q.get("async", ["0"])[0] \
            in ("1", "true")
        tenant = self.svc.tenants.authenticate(self._api_key(),
                                               doc.get("tenant"))
        req = self.svc.submit(func, args, module=module, tenant=tenant,
                              deadline_s=deadline_s)
        if async_:
            return self._reply(202, {
                "ok": True, "status": "pending", "request_id": req.id,
                "poll": f"/v1/requests/{req.id}"})
        # sync: wait for the future — a deadline bounds the wait (the
        # serving loop kills the lane at the deadline, plus scheduling
        # grace); otherwise the gateway's sync cap applies, and a
        # still-running request degrades to the async contract
        timeout = (deadline_s + 5.0) if deadline_s is not None else None
        if not self.svc.wait(req, timeout_s=timeout):
            return self._reply(202, {
                "ok": True, "status": "pending", "request_id": req.id,
                "poll": f"/v1/requests/{req.id}"})
        code, out = result_response(req)
        return self._reply(code, out)

    def _get_request(self, path: str):
        try:
            rid = int(path.rsplit("/", 1)[1])
        except ValueError:
            raise ValueError(f"bad request id in {path!r}") from None
        state, req = self.svc.request_state(rid)
        if req is None:
            if state == "pruned":
                # the id WAS real; its resolved entry aged out of the
                # result cache — distinct detail so a polling client
                # can stop retrying instead of doubting its own 202
                return self._reply(404, {"ok": False, "err": {
                    "name": "NotFound", "retryable": False,
                    "detail": "pruned",
                    "message": f"request {rid} was resolved and its "
                               f"result pruned from the cache"}})
            # poll-redirection hint (r21): an id this gateway never
            # accepted may live on its rendezvous owner — tell the
            # client WHERE to poll (303-style detail in the 404 body)
            # instead of forcing blind survivor polling
            hint = self.svc.fleet.owner_hint(rid) \
                if self.svc.fleet is not None else None
            if hint is not None:
                return self._reply(404, {"ok": False, "err": {
                    "name": "NotFound", "retryable": True,
                    "detail": "not_owner",
                    "owner_hint": hint,
                    "message": f"request {rid} is unknown here; its "
                               f"rendezvous owner is "
                               f"{hint['peer']}"}})
            raise KeyError(f"no request {rid}")
        if not req.future.done:
            return self._reply(200, {"ok": True, "status": "pending",
                                     "request_id": req.id})
        code, out = result_response(req)
        return self._reply(code, out)

    # -- durable sessions (wasmedge_tpu/effects/) --------------------------
    def _rid_of(self, path: str) -> int:
        """/v1/requests/<id>/<verb> -> id."""
        parts = path.strip("/").split("/")
        try:
            return int(parts[2])
        except (IndexError, ValueError):
            raise ValueError(f"bad request id in {path!r}") from None

    def _wake_request(self, url):
        """POST /v1/requests/<id>/wake: deliver an external wake; the
        raw body (may be empty) rides to the guest's await_event
        return buffer.  202 — the wake applies at the next serving
        boundary, at-least-once even when the id is not parked yet."""
        rid = self._rid_of(url.path)
        payload = self._read_body()
        out = self.svc.wake(rid, payload if payload else None)
        return self._reply(202, out)

    def _stream_request(self, url):
        """GET /v1/requests/<id>/stream: the request's stdout as a
        chunked byte stream (default) or SSE (`?sse=1` / Accept:
        text/event-stream).  `?offset=N` resumes after a reconnect —
        each logical stdout byte is delivered once per connection;
        replay after a crash restore is deduped by logical position at
        the buffer, so scoping is at-least-once across a restore only
        when the window aged out.  `?timeout=S` bounds the handler
        (default 30s); the client reconnects from its last offset."""
        import base64 as _b64
        import time as _time

        rid = self._rid_of(url.path)
        q = parse_qs(url.query)
        offset = int(q.get("offset", ["0"])[0])
        timeout = float(q.get("timeout", ["30"])[0])
        sse = q.get("sse", ["0"])[0] in ("1", "true") \
            or "text/event-stream" in (self.headers.get("Accept") or "")
        buf = self.svc.stream_of(rid)
        if buf is None:
            state, req = self.svc.request_state(rid)
            if state == "ok":
                # known request, no stream: effects off or no output
                return self._reply(200, b"",
                                   content_type="application/octet-stream")
            raise KeyError(f"no stream for request {rid}")
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/event-stream" if sse
                         else "application/octet-stream")
        self.send_header("Cache-Control", "no-store")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Stream-Offset", str(offset))
        self.end_headers()

        def chunk(b: bytes):
            self.wfile.write(("%x\r\n" % len(b)).encode())
            self.wfile.write(b)
            self.wfile.write(b"\r\n")

        deadline = _time.monotonic() + timeout
        try:
            while True:
                left = deadline - _time.monotonic()
                if left <= 0:
                    break
                data, nxt, closed = buf.read(offset,
                                             timeout=min(left, 1.0))
                if data:
                    if sse:
                        chunk(b"id: %d\ndata: %s\n\n"
                              % (nxt, _b64.b64encode(data)))
                    else:
                        chunk(data)
                    offset = nxt
                elif data is None:
                    # bare wait timeout: SSE keepalive, then re-read
                    if sse:
                        chunk(b": keepalive\n\n")
                    continue
                if closed and buf.end <= offset:
                    if sse:
                        err = buf.error
                        chunk(b"event: end\ndata: %s\n\n"
                              % json.dumps({"error": err}).encode())
                    break
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass   # subscriber went away: nothing to clean up
        self.close_connection = True
        self.svc.count_http(200)

    def _register(self, url):
        q = parse_qs(url.query)
        body = self._read_body()
        ctype = (self.headers.get("Content-Type") or "").split(";")[0] \
            .strip().lower()
        claimed = q.get("tenant", [None])[0]
        name = q.get("name", [None])[0]
        if ctype in ("application/wasm", "application/octet-stream"):
            data = body
        else:
            try:
                doc = json.loads(body or b"{}")
            except json.JSONDecodeError as e:
                raise ValueError(f"malformed JSON body: {e}") from e
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            name = doc.get("name", name)
            claimed = doc.get("tenant", claimed)
            b64 = doc.get("wasm_b64")
            if not b64:
                raise ValueError(
                    'missing "wasm_b64" (or POST raw bytes with '
                    'Content-Type: application/wasm and ?name=)')
            import base64

            try:
                data = base64.b64decode(b64, validate=True)
            except Exception as e:
                raise ValueError(f"bad wasm_b64: {e}") from e
        if not name:
            raise ValueError('missing module "name"')
        tenant = self.svc.tenants.authenticate(self._api_key(), claimed)
        if not self.svc.tenants.can_register(tenant):
            return self._reply(403, {"ok": False, "err": {
                "name": "Forbidden", "retryable": False,
                "message": f"tenant {tenant!r} may not register "
                           f"modules"}})
        info = self.svc.register_module(name, wasm_bytes=data,
                                        source=f"http/{tenant}",
                                        tenant=tenant)
        return self._reply(201, dict(info, ok=True))


class Gateway:
    """Service + HTTP server + background accept loop in one handle.

    `port=0` binds an ephemeral port (tests, smoke); the bound address
    is `gw.host`/`gw.port` after construction.  `start()` returns self;
    `shutdown()` stops accepting, then drains the serving generations.
    """

    def __init__(self, service: GatewayService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self.httpd = ThreadingHTTPServer((host, port), GatewayHandler)
        self.httpd.daemon_threads = True
        self.httpd.service = service
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Gateway":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self.httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                name=f"wasmedge-gateway:{self.port}", daemon=True)
            self._thread.start()
        if self.service.fleet is not None:
            # the fleet identity is the LISTENING address — known only
            # now that the socket is bound
            self.service.fleet.start(self.host, self.port)
        return self

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.service.shutdown(drain=drain, timeout_s=timeout_s)

    def kill(self):
        """Simulated SIGKILL (chaos harness): close the listening
        socket and stop the serving threads with NO drain, NO future
        resolution, NO journal flush — on-disk state is exactly what a
        real crash leaves.  Restart with GatewayService(resume=True)
        over the same state_dir."""
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.service.kill()
