"""Runtime guest-module registry for the serving gateway.

`POST /v1/modules` lands here: raw Wasm bytes go through the SAME
loader -> validator -> executor -> DeviceImage pipeline every other
entry point uses (no gateway-special compilation path), each module in
its own StoreManager with its own WASI instance (the per-tenant
sandbox model of batch/multitenant.py), and the registry's current
module set concatenates into one `MultiModuleBatchEngine` per serving
generation (`build_engine`).

Registration is VALIDATING: a module that fails to parse, validate,
instantiate, or batch (build_device_image raises for v128 entries,
cross-module table refs, ...) is rejected with the load/validation
ErrCode taxonomy and never reaches an engine — the serving generations
only ever see known-good images.

Guest stdout/stderr are sunk to /dev/null by default: a network server
must not let thousands of guest lanes write to ITS stdout.  (A later
PR can stream fd_write output back over the wire; the per-module
WasiEnviron here is exactly the seam for it.)
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

from wasmedge_tpu.common.errors import ErrCode, WasmError


class RegisteredModule:
    """One registered guest: its instantiated module + private store,
    plus the per-module BatchEngine built once at registration (the
    normalized DeviceImage every later generation concatenation
    reuses — registering module N must not re-lower modules 1..N-1)."""

    __slots__ = ("name", "inst", "store", "engine", "sha256", "nbytes",
                 "source", "tenant", "wasi", "snapshot", "_sink_fds")

    def __init__(self, name, inst, store, engine, sha256="", nbytes=0,
                 source="boot", tenant=None, sink_fds=(), wasi=None):
        self.name = name
        self.inst = inst
        self.store = store
        self.engine = engine
        self.sha256 = sha256
        self.nbytes = nbytes
        self.source = source
        # the tenant that registered this module (None = operator/boot).
        # Rides the durable manifest so a resumed gateway re-registers
        # under the same attribution (gateway/durable.py).
        self.tenant = tenant
        self.wasi = wasi  # per-module WasiModule (None on boot path)
        # imagestore SnapshotEntry captured at registration (None =
        # no usable init export / snapshots off / capture skipped)
        self.snapshot = None
        self._sink_fds = list(sink_fds)

    def rename(self, name: str):
        """Adopt a new registration name (the probe-cache reuse path):
        the guest-visible argv[0] must track it — a cache hit may not
        be observably different from a fresh registration."""
        self.name = name
        if self.wasi is not None and self.wasi.env.args:
            self.wasi.env.args[0] = name

    def exported_funcs(self) -> List[str]:
        return self.inst.func_names()

    def close(self):
        import os

        for fd in self._sink_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._sink_fds = []


class ModuleRegistry:
    """Ordered name -> RegisteredModule map + engine builder."""

    def __init__(self, conf=None, sink_stdout: bool = True):
        from wasmedge_tpu.common.configure import Configure

        self.conf = conf or Configure()
        self.sink_stdout = sink_stdout
        self._mods: Dict[str, RegisteredModule] = {}
        self._order: List[str] = []
        self._lock = threading.Lock()
        # ONE sha256-keyed lowering cache (imagestore/compilecache.py):
        # its probe tier is the r12 rejected-registration stash (a later
        # add_wasm of identical bytes adopts the parked engine instead
        # of lowering twice); its persistent tier — inert until the
        # gateway enables it — holds aot image payloads that survive
        # restarts and replicate across the fleet.
        from wasmedge_tpu.imagestore.compilecache import CompileCache

        self.compile_cache = CompileCache()
        # generation-segment memoization (imagestore/segments.py); the
        # gateway installs one when Configure.imagestore.segmented is on
        self.segment_cache = None
        # lowerings actually performed (probe-cache and compile-cache
        # hits don't count) — pinned by tests to prove the reject path
        # and the persistent cache reuse the engine/image
        self.lowered_count = 0

    def __len__(self) -> int:
        return len(self._order)

    @property
    def names(self) -> List[str]:
        return list(self._order)

    def get(self, name: str) -> Optional[RegisteredModule]:
        return self._mods.get(name)

    # -- registration ------------------------------------------------------
    def add_wasm(self, name: str, data: bytes,
                 source: str = "http",
                 tenant: Optional[str] = None) -> RegisteredModule:
        """Validate + compile + instantiate `data` and register it under
        `name`.  Raises WasmError(ModuleNameConflict) for a duplicate
        name, Load/Validation/Instantiation errors for bad wasm, and
        ValueError for a module the batch pipeline cannot image."""
        self._check_name(name)
        from wasmedge_tpu.executor import Executor
        from wasmedge_tpu.loader import Loader
        from wasmedge_tpu.runtime.store import StoreManager
        from wasmedge_tpu.validator import Validator

        data = bytes(data)
        sha = hashlib.sha256(data).hexdigest()
        cached = self.compile_cache.pop_probe(sha)
        if cached is not None:
            # an identical module was lowered and then rolled back
            # (policy rejection, failed generation build): adopt the
            # probe's engine under the new name instead of re-lowering
            cached.rename(name)
            cached.source = source
            cached.tenant = tenant
            return self._install(cached)
        # persistent tier: a verified cached image payload replaces the
        # body-validation + lowering pass entirely (restart survival,
        # fleet replication); any mismatch silently lowers fresh
        payload = self.compile_cache.load(sha) \
            if self.compile_cache.enabled else None
        mod = Validator(self.conf).validate(
            Loader(self.conf).parse_module(data), precompiled=payload)
        store = StoreManager()
        ex = Executor(self.conf)
        wasi, sinks = self._register_wasi(ex, store, name)
        try:
            inst = ex.instantiate(store, mod)
            # prove batchability NOW (image build raises on v128
            # entries, non-local table refs, ...) so a bad module 400s
            # at POST time instead of sinking the next generation
            # build — and KEEP the engine: its normalized image is
            # what every later generation concatenates
            from wasmedge_tpu.batch.engine import BatchEngine

            eng = BatchEngine(inst, store=store, conf=self.conf,
                              lanes=1)
            if getattr(mod, "precompiled_src", None) == "cache":
                pass  # adopted the cached lowering: not a fresh lower
            else:
                self.lowered_count += 1
                if self.compile_cache.enabled and mod.lowered is not None:
                    from wasmedge_tpu.aot import serialize_image

                    try:
                        self.compile_cache.store(
                            sha, serialize_image(mod.lowered, mod=mod))
                    except Exception:
                        pass  # cache write is never load-bearing
        except BaseException:
            # the sink fds were opened before instantiation — a
            # rejected module (unlinkable import, unbatchable image)
            # must not leak two fds per POST
            import os

            for fd in sinks:
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise
        rm = RegisteredModule(
            name, inst, store, eng, sha256=sha,
            nbytes=len(data), source=source, tenant=tenant,
            sink_fds=sinks, wasi=wasi)
        return self._install(rm)

    def add_instance(self, name: str, inst, store,
                     source: str = "boot") -> RegisteredModule:
        """Register an already-instantiated module (the VM/CLI boot
        path); batchability is proven the same way as add_wasm."""
        self._check_name(name)
        from wasmedge_tpu.batch.engine import BatchEngine

        eng = BatchEngine(inst, store=store, conf=self.conf, lanes=1)
        self.lowered_count += 1
        return self._install(RegisteredModule(name, inst, store, eng,
                                              source=source))

    def remove(self, name: str, stash: bool = False):
        """Unregister `name`.  With stash=True a wasm-sourced module's
        lowered engine is parked in the probe cache (keyed by content
        sha256) instead of discarded — the reject-path call of
        gateway/service.py, so a rejected-then-fixed registration of
        the same bytes never pays for a second lowering."""
        with self._lock:
            rm = self._mods.pop(name, None)
            if rm is not None:
                self._order.remove(name)
        if rm is None:
            return
        if stash and rm.sha256:
            # the cache closes any same-bytes entry it displaces (e.g.
            # two copies in one rolled-back preload) and LRU evictions,
            # or their sink fds would leak
            self.compile_cache.stash_probe(rm.sha256, rm)
        else:
            rm.close()

    def _check_name(self, name: str):
        if not name or ":" in name or "/" in name:
            raise WasmError(ErrCode.IllegalPath,
                            f"invalid module name {name!r} (non-empty, "
                            f"no ':' or '/')")
        if name in self._mods:
            raise WasmError(ErrCode.ModuleNameConflict,
                            f"module {name!r} already registered")

    def _install(self, rm: RegisteredModule) -> RegisteredModule:
        with self._lock:
            if rm.name in self._mods:   # lost a registration race
                rm.close()
                raise WasmError(ErrCode.ModuleNameConflict,
                                f"module {rm.name!r} already registered")
            self._mods[rm.name] = rm
            self._order.append(rm.name)
        return rm

    def _register_wasi(self, ex, store, prog_name: str) \
            -> Tuple[object, List[int]]:
        """A fresh per-module WASI instance (per-module environ =
        per-module sandbox), stdout/stderr sunk to /dev/null when
        configured.  Registered unconditionally — modules that import
        nothing are unaffected, modules importing
        wasi_snapshot_preview1 resolve.  Returns (wasi, sink_fds);
        the WasiModule rides the RegisteredModule so probe-cache
        adoption can retarget argv[0]."""
        import os

        from wasmedge_tpu.host.wasi import WasiModule

        wasi = WasiModule()
        wasi.init_wasi(dirs=[], prog_name=prog_name)
        sinks = []
        if self.sink_stdout:
            for fd in (1, 2):
                e = wasi.env.fds.get(fd)
                if e is not None:
                    sink = os.open(os.devnull, os.O_WRONLY)
                    e.os_fd = sink
                    sinks.append(sink)
        ex.register_import_object(store, wasi)
        # the "wasmedge" effect-handler module registers alongside WASI
        # — unconditionally, like WASI itself: modules importing
        # await_event always LINK; the suspend lowering stays gated on
        # Configure.effects (off, the fallback body completes with
        # Errno.AGAIN immediately)
        from wasmedge_tpu.effects import effects_import_object

        ex.register_import_object(store, effects_import_object())
        return wasi, sinks

    # -- engine builder ----------------------------------------------------
    def modules_snapshot(self) -> List[RegisteredModule]:
        with self._lock:
            return [self._mods[n] for n in self._order]

    def build_engine(self, conf, lanes: int, devices=None,
                     init_overlays=None, snapshot_counts=None):
        """Concatenated multi-module engine over the CURRENT module set
        (one serving generation's engine; gateway/service.py swaps
        generations at a launch boundary).  The per-module engines
        cached at registration time are reused, so a swap costs one
        image concatenation — not a re-lower of every module.
        `devices` builds the engine over a lane-sharded named mesh
        (mesh-tier continuous batching: the gateway's serving pool
        spans every device, parallel/shard_drive.py)."""
        from wasmedge_tpu.batch.multitenant import MultiModuleBatchEngine

        mods = self.modules_snapshot()
        if not mods:
            raise WasmError(ErrCode.WrongVMWorkflow,
                            "no modules registered")
        mesh = None
        if devices is not None:
            from wasmedge_tpu.parallel.mesh import lane_mesh

            mesh = lane_mesh(devices=devices)
        return MultiModuleBatchEngine(
            [(rm.name, rm.inst, rm.store) for rm in mods],
            conf=conf, lanes=lanes,
            engines=[rm.engine for rm in mods], mesh=mesh,
            segment_cache=self.segment_cache,
            init_overlays=init_overlays,
            snapshot_counts=snapshot_counts)

    def close(self):
        with self._lock:
            for rm in self._mods.values():
                rm.close()
        self.compile_cache.close()
