"""GatewayService: multi-tenant serving generations over BatchServer.

The long-lived core the HTTP layer (gateway/http.py) is a thin skin
over.  One *generation* = one `MultiModuleBatchEngine` (the
concatenated image of every registered module, batch/multitenant.py)
driven by one `BatchServer` on a background thread.  Runtime module
registration is a **generation swap**:

    POST /v1/modules
      -> registry.add_wasm()       (loader -> validator -> image, 400s
                                    on bad/unbatchable wasm)
      -> build generation N+1      (image rebuilt WITH the new module;
                                    freed lanes recycle onto the new
                                    function via the LaneRecycler /
                                    initial_state template seam)
      -> atomic pointer swap       (new submissions -> generation N+1)
      -> generation N drains       (in-flight AND queued requests
                                    finish on the OLD image — results
                                    stay bit-identical to solo runs —
                                    then the old server shuts down at
                                    its launch boundary)

The swap is wait-free for submitters: the swap holds the submit lock
only for the pointer write; the expensive parts (validation, image
concatenation) happen outside it, and the new engine's first jit
compile happens on its serving thread's first launch.

Request lifecycle: `submit()` stamps a GatewayRequest into the stash
keyed by the process-global request id (shared with ServeFuture), so
`202 Accepted` clients poll `GET /v1/requests/<id>` against the same
object the sync path waits on.  Resolved requests are kept for
`result_cache` completions and then pruned oldest-first.

Observability (off by default, like every other obs track): a
`gateway/<tenant>` span per request (receive -> resolve, with the
module/func/outcome in args) on the shared flight recorder, plus
`wasmedge_gateway_http_requests_total{code}` counters in the
Prometheus export fed by the HTTP layer's `count_http`.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from wasmedge_tpu.common.errors import ErrCode, WasmError
from wasmedge_tpu.gateway.registry import ModuleRegistry
from wasmedge_tpu.gateway.tenants import GatewayTenants


class GatewayClosed(WasmError):
    """The gateway is shutting down — distinct from a tenant's
    permanent admission block (both ride ErrCode.Terminated): the HTTP
    layer maps THIS to 503 (restarting service, come back) and the
    admission block to 403 (your policy forbids it, don't)."""

    def __init__(self):
        super().__init__(ErrCode.Terminated, "gateway shut down")


class GatewayRequest:
    """Stash entry for one gateway request (sync waiters and async
    pollers share it)."""

    __slots__ = ("id", "tenant", "module", "func", "future", "t_recv",
                 "gen_id", "finalized")

    def __init__(self, future, tenant, module, func, gen_id, t_recv):
        self.id = future.request_id
        self.future = future
        self.tenant = tenant
        self.module = module
        self.func = func
        self.gen_id = gen_id
        self.t_recv = t_recv
        self.finalized = False


class _Generation:
    __slots__ = ("gen_id", "engine", "server", "modules")

    def __init__(self, gen_id, engine, server, modules):
        self.gen_id = gen_id
        self.engine = engine
        self.server = server
        self.modules = tuple(modules)


class GatewayService:
    """The gateway's engine room (transport-free; see gateway/http.py).

    `conf` is the template Configure every generation deep-copies (the
    BatchServer mutates serve knobs on its copy); `tenants` the edge
    policy table; `lanes` the per-generation serving pool width."""

    def __init__(self, conf=None, lanes: int = 64,
                 tenants: Optional[GatewayTenants] = None,
                 result_cache: int = 4096,
                 sync_wait_s: float = 60.0,
                 sink_stdout: bool = True):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.obs.recorder import recorder_of

        self.template = conf or Configure()
        # instantiate the shared ring BEFORE any generation deepcopies
        # its Configure, so every generation reports into ONE recorder
        self.obs = recorder_of(self.template)
        self.lanes = int(lanes)
        self.tenants = tenants or GatewayTenants()
        self.registry = ModuleRegistry(conf=self.template,
                                       sink_stdout=sink_stdout)
        self.result_cache = int(result_cache)
        self.sync_wait_s = float(sync_wait_s)
        self._lock = threading.RLock()
        self._reg_lock = threading.Lock()   # one registration at a time
        self._gens: List[_Generation] = []  # current is last
        self._gen_seq = 0
        self._reapers: List[threading.Thread] = []
        self._requests: Dict[int, GatewayRequest] = {}
        self._resolved = deque()
        self._closed = False
        self.http_counts: Dict[str, int] = {}
        self.counters = {
            "received": 0, "completed": 0, "failed": 0, "deadline": 0,
            "rejected": 0, "rate_limited": 0, "registered_modules": 0,
            "generations": 0, "policy_rejected": 0,
        }
        # static-analysis admission summary (obs/metrics.py renders it
        # as wasmedge_analysis_* counters): verdicts of every module
        # that reached the policy gate + rejections it issued
        self.analysis_counts = {"bounded": 0, "unbounded": 0,
                                "policy_rejected": 0}

    # -- generations -------------------------------------------------------
    @property
    def current(self) -> Optional[_Generation]:
        with self._lock:
            return self._gens[-1] if self._gens else None

    @property
    def generation(self) -> int:
        with self._lock:
            return self._gens[-1].gen_id if self._gens else 0

    def _build_generation(self) -> _Generation:
        from wasmedge_tpu.serve.server import BatchServer

        conf = copy.deepcopy(self.template)
        if conf.serve.autotune:
            # the tuner reads the drain-latency histograms: the flag
            # must flip BEFORE the engine captures its recorder, or
            # the engine holds NULL_RECORDER forever and autotune is a
            # silent no-op (the injected-engine path cannot fix this
            # up afterwards the way BatchServer's own build can)
            conf.obs.enabled = True
        engine = self.registry.build_engine(conf, self.lanes)
        server = BatchServer(engine=engine,
                             weights=self.tenants.weights(),
                             quotas=self.tenants.quotas())
        self._gen_seq += 1
        self.counters["generations"] += 1
        return _Generation(self._gen_seq, engine, server,
                           self.registry.names)

    def _swap_in(self, gen: _Generation):
        """Install `gen` as current; the displaced generation drains in
        the background (its in-flight lanes finish on the old image at
        their own launch boundaries) and is reaped once idle."""
        gen.server.start()
        with self._lock:
            old = self._gens[-1] if self._gens else None
            self._gens.append(gen)
        if old is not None:
            t = threading.Thread(target=self._drain_old, args=(old,),
                                 name=f"gw-drain-gen{old.gen_id}",
                                 daemon=True)
            t.start()
            self._reapers.append(t)
        self.obs.instant("generation_swap", cat="gateway",
                         track="gateway", generation=gen.gen_id,
                         modules=list(gen.modules))

    def _drain_old(self, old: _Generation):
        try:
            old.server.shutdown(drain=True)
        finally:
            with self._lock:
                if old in self._gens:
                    self._gens.remove(old)

    # -- module registration ----------------------------------------------
    def register_module(self, name: str, wasm_bytes: Optional[bytes] = None,
                        inst=None, store=None,
                        source: str = "http",
                        tenant: Optional[str] = None) -> dict:
        """Register a module and swap in a fresh generation.  Either
        raw `wasm_bytes` (the HTTP path: full validation pipeline) or a
        pre-instantiated (inst, store) pair (the VM/CLI boot path).
        `tenant` selects the static-analysis admission policy (the
        tenant's own, else the file-level default)."""
        return self._register([(name, wasm_bytes, inst, store)],
                              source=source, tenant=tenant)

    def preload(self, entries, source: str = "boot") -> dict:
        """Register several modules with ONE generation build — the
        boot path (`--module a=.. --module b=..`) must not pay for and
        immediately drain N-1 throwaway generations.  `entries` is
        [(name, wasm_bytes)]."""
        return self._register([(n, b, None, None) for n, b in entries],
                              source=source)

    def _vet(self, rm, tenant: Optional[str]) -> List[dict]:
        """Static-analysis admission: evaluate the already-built
        image's ModuleAnalysis (one lowering — shared with the
        batchability probe) against the registering tenant's policy.
        Raises AnalysisRejection in enforce mode; returns the
        violation list in flag mode (surfaced as analysis_warnings).

        Boot/preload registrations (tenant None — the CLI --module
        set, VM.gateway()) are operator-trusted and only COUNTED, never
        policy-gated: a strict file-level default aimed at runtime
        HTTP registrants must not abort gateway startup on the
        operator's own modules."""
        from wasmedge_tpu.analysis.policy import AnalysisRejection

        analysis = getattr(rm.engine.img, "analysis", None)
        with self._lock:
            if analysis is not None:
                key = "bounded" if analysis.bounded else "unbounded"
                self.analysis_counts[key] += 1
        if tenant is None:
            return []
        policy = self.tenants.admission_policy(tenant)
        if policy is None:
            return []
        violations = policy.evaluate(analysis)
        if violations and policy.enforce:
            with self._lock:
                self.counters["policy_rejected"] += 1
                self.analysis_counts["policy_rejected"] += 1
            raise AnalysisRejection(rm.name, violations)
        return violations

    def _register(self, entries, source: str,
                  tenant: Optional[str] = None) -> dict:
        with self._reg_lock:
            if self._closed:
                raise GatewayClosed()
            added = []
            warnings: List[dict] = []
            try:
                for name, wasm_bytes, inst, store in entries:
                    if wasm_bytes is not None:
                        rm = self.registry.add_wasm(name, wasm_bytes,
                                                    source=source)
                    else:
                        rm = self.registry.add_instance(name, inst,
                                                        store,
                                                        source=source)
                    added.append(rm)
                    warnings.extend(self._vet(rm, tenant))
                gen = self._build_generation()
            except BaseException:
                # never leave a module registered that no generation
                # serves — the registry and the serving set must agree.
                # stash=True parks the already-lowered engine in the
                # registry's probe cache: a re-POST of the same bytes
                # (fixed policy, different tenant/name) reuses it
                # instead of lowering twice
                for rm in added:
                    self.registry.remove(rm.name, stash=True)
                raise
            self._swap_in(gen)
        with self._lock:
            self.counters["registered_modules"] += len(added)
        last = added[-1]
        out = {
            "module": last.name,
            "sha256": last.sha256,
            "exports": last.exported_funcs(),
            "generation": gen.gen_id,
            "modules": list(gen.modules),
        }
        analysis = getattr(last.engine.img, "analysis", None)
        if analysis is not None:
            out["analysis"] = analysis.summary()
        if warnings:
            # flag-mode policy (enforce=false): registered, but the
            # violations ride the 201 body so operators can see them
            out["analysis_warnings"] = warnings
        return out

    # -- requests ----------------------------------------------------------
    def submit(self, func: str, args, module: Optional[str] = None,
               tenant: str = "default",
               deadline_s: Optional[float] = None) -> GatewayRequest:
        """Edge admission: rate limit, then the current generation's
        BatchServer.  Raises RateLimited, QueueSaturated (retryable),
        KeyError (unknown module/func), or the serving taxonomy."""
        from wasmedge_tpu.gateway.tenants import RateLimited

        try:
            self.tenants.check_rate(tenant)
        except RateLimited:
            with self._lock:
                self.counters["rate_limited"] += 1
            raise
        with self._lock:
            if self._closed:
                raise GatewayClosed()
            gen = self._gens[-1] if self._gens else None
        if gen is None:
            raise KeyError("no modules registered")
        qualified = f"{module}:{func}" if module else func
        t_recv = time.monotonic()
        while True:
            try:
                fut = gen.server.submit(qualified, args, tenant=tenant,
                                        deadline_s=deadline_s)
                break
            except WasmError:
                # a submit can race a generation swap: the generation
                # captured above starts DRAINING the moment its
                # successor is installed, and rejects submissions with
                # a permanent (non-retryable) error.  That rejection
                # belongs to the stale generation, not the request —
                # re-resolve and retry on the successor.  Only a
                # still-current generation's rejection is authoritative.
                with self._lock:
                    cur = self._gens[-1] if self._gens else None
                    closed = self._closed
                if cur is gen or cur is None:
                    with self._lock:
                        self.counters["rejected"] += 1
                    if closed:
                        # the generation rejected because the GATEWAY
                        # is going down, not because of the tenant's
                        # policy — surface the lifecycle class (503)
                        raise GatewayClosed() from None
                    raise
                gen = cur
            except BaseException:
                with self._lock:
                    self.counters["rejected"] += 1
                raise
        req = GatewayRequest(fut, tenant, module, qualified, gen.gen_id,
                             t_recv)
        with self._lock:
            self.counters["received"] += 1
            self._requests[req.id] = req
        self.obs.instant("gateway_receive", cat="gateway",
                         track="gateway", id=req.id, tenant=tenant,
                         func=qualified)
        return req

    def get_request(self, request_id: int) -> Optional[GatewayRequest]:
        with self._lock:
            req = self._requests.get(int(request_id))
        if req is not None:
            self.finalize(req)
        return req

    def wait(self, req: GatewayRequest,
             timeout_s: Optional[float] = None) -> bool:
        """Block on the request's future (the sync-invoke path); the
        gateway-level cap applies when the caller sets none."""
        done = req.future.wait(self.sync_wait_s if timeout_s is None
                               else timeout_s)
        if done:
            self.finalize(req)
        return done

    def finalize(self, req: GatewayRequest):
        """Account + trace a completed request exactly once (called
        from every path that observes completion, and by the pruning
        sweep for never-polled async requests)."""
        if req.finalized or not req.future.done:
            return
        with self._lock:
            if req.finalized:
                return
            req.finalized = True
            self._resolved.append(req.id)
            err = req.future.error
            from wasmedge_tpu.serve.queue import DeadlineExceeded

            if err is None:
                self.counters["completed"] += 1
            elif isinstance(err, DeadlineExceeded):
                self.counters["deadline"] += 1
            else:
                self.counters["failed"] += 1
            while len(self._resolved) > self.result_cache:
                self._requests.pop(self._resolved.popleft(), None)
        self.obs.span(f"gateway/{req.tenant}", req.t_recv,
                      cat="gateway", track="gateway", id=req.id,
                      func=req.func, generation=req.gen_id,
                      ok=req.future.error is None)

    def sweep(self):
        """Finalize any resolved-but-unpolled async requests (keeps the
        gateway spans/counters complete without a per-future callback
        seam; called from status/metrics)."""
        with self._lock:
            pending = [r for r in self._requests.values()
                       if not r.finalized and r.future.done]
        for r in pending:
            self.finalize(r)

    # -- edge accounting ---------------------------------------------------
    def count_http(self, code: int):
        with self._lock:
            key = str(int(code))
            self.http_counts[key] = self.http_counts.get(key, 0) + 1

    # -- introspection -----------------------------------------------------
    def status(self) -> dict:
        self.sweep()
        with self._lock:
            gen = self._gens[-1] if self._gens else None
            draining = max(len(self._gens) - 1, 0)
            out = {
                "generation": gen.gen_id if gen else 0,
                "modules": {
                    name: self.registry.get(name).exported_funcs()
                    for name in (gen.modules if gen else ())},
                "lanes": self.lanes,
                "draining_generations": draining,
                "gateway": dict(self.counters),
                "analysis": dict(self.analysis_counts),
                "http": dict(self.http_counts),
                "tenants": sorted(self.tenants.policies),
            }
            if gen is not None:
                out["queue_depth"] = len(gen.server.queue)
                out["in_flight"] = gen.server.in_flight
                out["serve"] = dict(gen.server.counters)
        return out

    def metrics_text(self) -> str:
        self.sweep()
        from wasmedge_tpu.obs.metrics import render_prometheus

        gen = self.current
        return render_prometheus(
            recorder=self.obs if self.obs.enabled else None,
            hostcall_stats=gen.engine.hostcall_stats if gen else None,
            http_requests=dict(self.http_counts),
            analysis_counts=dict(self.analysis_counts))

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        # _reg_lock first: an in-flight registration finishes its swap
        # (its generation lands in the snapshot below) and later ones
        # see _closed — otherwise a generation swapped in after the
        # snapshot would keep serving on registry fds close() is about
        # to invalidate, while shutdown() reports a clean stop
        with self._reg_lock:
            with self._lock:
                self._closed = True
                gens = list(self._gens)
        for g in gens:
            g.server.shutdown(drain=drain, timeout_s=timeout_s)
        for t in self._reapers:
            t.join(timeout=5.0)
        self.sweep()
        self.registry.close()
