"""GatewayService: multi-tenant serving generations over BatchServer.

The long-lived core the HTTP layer (gateway/http.py) is a thin skin
over.  One *generation* = one `MultiModuleBatchEngine` (the
concatenated image of every registered module, batch/multitenant.py)
driven by one `BatchServer` on a background thread.  Runtime module
registration is a **generation swap**:

    POST /v1/modules
      -> registry.add_wasm()       (loader -> validator -> image, 400s
                                    on bad/unbatchable wasm)
      -> build generation N+1      (image rebuilt WITH the new module;
                                    freed lanes recycle onto the new
                                    function via the LaneRecycler /
                                    initial_state template seam)
      -> atomic pointer swap       (new submissions -> generation N+1)
      -> generation N drains       (in-flight AND queued requests
                                    finish on the OLD image — results
                                    stay bit-identical to solo runs —
                                    then the old server shuts down at
                                    its launch boundary)

The swap is wait-free for submitters: the swap holds the submit lock
only for the pointer write; the expensive parts (validation, image
concatenation) happen outside it, and the new engine's first jit
compile happens on its serving thread's first launch.

Request lifecycle: `submit()` stamps a GatewayRequest into the stash
keyed by the process-global request id (shared with ServeFuture), so
`202 Accepted` clients poll `GET /v1/requests/<id>` against the same
object the sync path waits on.  Resolved requests are kept for
`result_cache` completions and then pruned oldest-first.

Observability (off by default, like every other obs track): a
`gateway/<tenant>` span per request (receive -> resolve, with the
module/func/outcome in args) on the shared flight recorder, plus
`wasmedge_gateway_http_requests_total{code}` counters in the
Prometheus export fed by the HTTP layer's `count_http`.

r13 made the front door crash-survivable and self-degrading:

  durability    `state_dir=` attaches a gateway/durable.py DurableStore
                (module blobs + manifest + async-request journal, all
                crash-atomic); `resume=True` re-registers the stored
                module set under ONE boot generation, adopts the
                previous generation's BatchServer checkpoint lineage,
                replays resolved ids from the durable result cache and
                re-queues the rest under their ORIGINAL ids — a
                polling client's 202 id survives the restart
  swap safety   generation builds run against a build timeout on a
                worker thread; a build/swap that fails or times out
                rolls back ATOMICALLY (registry stash kept for the
                retry, submit pointer untouched, prior generation
                keeps serving bit-identically) and the registration
                returns a retryable GenerationBuildFailed (HTTP 503)
  health        `health()` (gateway/health.py) is the truthful
                /healthz: driver liveness, last-swap outcome, queue
                saturation, checkpoint/journal write health -> one of
                healthy / degraded / unhealthy
  shedding      while degraded, submissions from the lowest-weight
                tenant tier reject up front with a retryable 429
                (ShedLoad) instead of queueing into a timeout
  chaos seams   a testing/faults.py FaultInjector handed in as
                `faults=` arms gateway_register / generation_build /
                generation_swap / journal_write (plus the engine-tier
                launch/serve/checkpoint seams on every generation's
                BatchServer); `kill()` is the supported simulated
                SIGKILL the chaos harness restarts from
"""

from __future__ import annotations

import copy
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from wasmedge_tpu.common.errors import EngineFailure, ErrCode, WasmError
from wasmedge_tpu.gateway.durable import (
    DurabilityError,
    DurableStore,
    _resolved_entry,
    resolved_error,
)
from wasmedge_tpu.gateway.health import HealthGate
from wasmedge_tpu.gateway.registry import ModuleRegistry
from wasmedge_tpu.gateway.tenants import GatewayTenants

# ids remembered as "pruned" (vs never-issued) for the distinct 404
# detail; bounded so a long-lived gateway can't grow it forever
_PRUNED_MEMORY = 65536


class GenerationBuildFailed(EngineFailure):
    """A serving-generation build or swap failed (or exceeded the build
    timeout) and was rolled back: the PRIOR generation kept serving and
    nothing was half-swapped.  Retryable — the lowered module is
    stashed in the registry's probe cache, so a re-POST of the same
    bytes skips the lowering and retries only the build."""

    retryable = True

    def __init__(self, msg: str):
        super().__init__(msg)
        self.retry_after_s = 1.0


class GatewayClosed(WasmError):
    """The gateway is shutting down — distinct from a tenant's
    permanent admission block (both ride ErrCode.Terminated): the HTTP
    layer maps THIS to 503 (restarting service, come back) and the
    admission block to 403 (your policy forbids it, don't).
    Retryable: the SAME request is welcome at the restarted gateway,
    so the 503 carries Retry-After like the other transient classes."""

    retryable = True

    def __init__(self):
        super().__init__(ErrCode.Terminated, "gateway shut down")
        self.retry_after_s = 1.0


class GatewayRequest:
    """Stash entry for one gateway request (sync waiters and async
    pollers share it).  `args`/`deadline_s` ride along for the durable
    journal — a re-queued request must be re-executable verbatim."""

    __slots__ = ("id", "tenant", "module", "func", "future", "t_recv",
                 "gen_id", "finalized", "args", "deadline_s", "edge")

    def __init__(self, future, tenant, module, func, gen_id, t_recv,
                 args=(), deadline_s=None, edge=None):
        self.id = future.request_id
        self.future = future
        self.tenant = tenant
        self.module = module
        self.func = func
        self.gen_id = gen_id
        self.t_recv = t_recv
        self.finalized = False
        self.args = tuple(int(a) for a in args)
        self.deadline_s = deadline_s
        # fleet routing: the peer that ACCEPTED this request (its 202
        # came from there) when it differs from the executing gateway —
        # journaled so failover adoption can tell "the edge re-queues
        # its own forward" from "nobody is left to re-queue this"
        self.edge = edge


class _Generation:
    __slots__ = ("gen_id", "engine", "server", "modules", "serve_dir")

    def __init__(self, gen_id, engine, server, modules, serve_dir=None):
        self.gen_id = gen_id
        self.engine = engine
        self.server = server
        self.modules = tuple(modules)
        self.serve_dir = serve_dir


class GatewayService:
    """The gateway's engine room (transport-free; see gateway/http.py).

    `conf` is the template Configure every generation deep-copies (the
    BatchServer mutates serve knobs on its copy); `tenants` the edge
    policy table; `lanes` the per-generation serving pool width."""

    def __init__(self, conf=None, lanes: int = 64,
                 tenants: Optional[GatewayTenants] = None,
                 result_cache: int = 4096,
                 sync_wait_s: float = 60.0,
                 sink_stdout: bool = True,
                 faults=None,
                 state_dir: Optional[str] = None,
                 resume: bool = False,
                 build_timeout_s: Optional[float] = 120.0,
                 shed_on_degraded: bool = True,
                 devices=None,
                 fleet=None,
                 autoscale=None):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.obs.recorder import recorder_of

        self.template = conf or Configure()
        # instantiate the shared ring BEFORE any generation deepcopies
        # its Configure, so every generation reports into ONE recorder
        self.obs = recorder_of(self.template)
        self.lanes = int(lanes)
        # mesh-tier serving (ROADMAP #1): every generation's engine is
        # built over this lane-sharded device mesh and driven by the
        # single-program shard drive; the pool rounds up to a device
        # multiple (MultiModuleBatchEngine does the rounding)
        self.devices = None
        if devices is not None:
            from wasmedge_tpu.parallel.mesh import normalize_devices

            self.devices = normalize_devices(devices)
        self.tenants = tenants or GatewayTenants()
        self.registry = ModuleRegistry(conf=self.template,
                                       sink_stdout=sink_stdout)
        self.result_cache = int(result_cache)
        self.sync_wait_s = float(sync_wait_s)
        self.faults = faults
        self.build_timeout_s = build_timeout_s
        self.shed_on_degraded = bool(shed_on_degraded)
        self.force_degraded = False   # operator/test switch
        self._lock = threading.RLock()
        self._reg_lock = threading.Lock()   # one registration at a time
        self._gens: List[_Generation] = []  # current is last
        self._gen_seq = 0
        self._reapers: List[threading.Thread] = []
        self._requests: Dict[int, GatewayRequest] = {}
        self._resolved = deque()
        self._pruned: "deque[int]" = deque(maxlen=_PRUNED_MEMORY)
        self._pruned_set = set()
        self._closed = False
        self.http_counts: Dict[str, int] = {}
        self.last_swap: Optional[dict] = None
        self.shed_counts: Dict[str, int] = {}
        self.counters = {
            "received": 0, "completed": 0, "failed": 0, "deadline": 0,
            "rejected": 0, "rate_limited": 0, "registered_modules": 0,
            "generations": 0, "policy_rejected": 0,
            "restarts": 0, "rollbacks": 0, "shed": 0,
            "journal_errors": 0, "resumed": 0,
        }
        # static-analysis admission summary (obs/metrics.py renders it
        # as wasmedge_analysis_* counters): verdicts of every module
        # that reached the policy gate + rejections it issued
        self.analysis_counts = {"bounded": 0, "unbounded": 0,
                                "policy_rejected": 0}
        # durable result cache mirrored to the journal: finalized
        # request outcomes a resumed gateway replays verbatim.  Capped
        # below the (in-memory) stash depth — every journal write
        # serializes this list, so its size is hot-path cost, and the
        # ISSUE contract is a SMALL durable cache with older ids
        # degrading to the pruned-404 answer
        self._durable_cache_depth = min(max(self.result_cache, 1), 512)
        self._result_cache = deque(maxlen=self._durable_cache_depth)
        self._journal_fail_streak = 0
        self._manifest_dirty = False
        # serializes snapshot->write so an older journal snapshot can
        # never land a NEWER sequence number (which would make it the
        # authoritative journal and lose a durably-accepted id)
        self._journal_mutex = threading.Lock()
        # replication sequence (drawn under _journal_mutex): stamps
        # fleet journal pushes so a receiver can discard an older
        # snapshot that arrives after a newer one — which frees the
        # peer HTTP to run OUTSIDE the mutex
        self._repl_seq = 0
        # ids at/below this were issued by a pre-crash process: an
        # unknown id under the floor answers the pruned 404 detail,
        # not "never existed" (journaled as max_id)
        self._resume_floor = 0
        # id range THIS gateway ever stashed — journaled as
        # min_id/max_id, the resumed process's pruned-404 window.
        # Deliberately not the process-global counter: the fleet's
        # id-space rebase (and any sibling gateway in-process) pushes
        # the global high-water far past ids this gateway issued —
        # journaling the global counter (or assuming ids start near 1)
        # would make a resumed gateway answer the pruned 404 for ids
        # it never accepted
        self._max_issued = 0
        self._min_issued = 0   # 0 = nothing issued yet
        self._resume_min = 1   # legacy journals: ids start near 1
        # pending serve-lineage adoption consumed by the next
        # generation build (set only during _resume_from_disk)
        self._pending_resume: Optional[str] = None
        self.durable = DurableStore(
            state_dir, faults=faults,
            result_cache=self._durable_cache_depth) \
            if state_dir else None
        # imagestore (r22): segmented device images, the persistent
        # compile cache, and pre-initialized lane snapshots.  All three
        # knobs default off, leaving this block inert — the registry's
        # segment_cache stays None, the compile cache's persistent tier
        # never enables, no snapshot store exists — so the default
        # gateway is bit-identical r21 by construction.
        self.snapshot_store = None
        self.snapshot_counts: Dict[str, int] = {}
        ist = getattr(self.template, "imagestore", None)
        self.imagestore_enabled = bool(ist is not None and ist.active)
        if self.imagestore_enabled:
            # the cache_read fault seam fires through the registry's
            # cache; wire the gateway's injector in
            self.registry.compile_cache.faults = faults
            if ist.segmented:
                from wasmedge_tpu.imagestore import SegmentCache

                self.registry.segment_cache = SegmentCache()
            if ist.compile_cache:
                cc_dir = ist.compile_cache_dir or \
                    (self.durable.compile_cache_dir()
                     if self.durable is not None else None)
                self.registry.compile_cache.enable(cc_dir)
            if ist.snapshots:
                from wasmedge_tpu.hv.swapstore import SwapStore

                self.snapshot_store = SwapStore(dir=ist.snapshot_dir,
                                                faults=faults)
        # fleet federation (wasmedge_tpu/fleet/, r16): `fleet` is a
        # FleetConfig or a plain list of "host:port" peers.  The
        # controller starts when the HTTP layer binds (Gateway.start
        # knows the port); a fleet with no peers is inert — the submit
        # path and id sequence stay bit-identical to a non-federated
        # gateway.
        self.fleet = None
        if fleet is not None:
            from wasmedge_tpu.fleet import FleetConfig, FleetController

            cfg = fleet if isinstance(fleet, FleetConfig) \
                else FleetConfig(peers=list(fleet))
            self.fleet = FleetController(self, cfg)
        # live resharding (r21): reshards currently installing (health
        # reports them as churn, not degradation) + per-direction
        # totals (wasmedge_reshards_total{direction})
        self._resharding = 0
        self.reshard_counts: Dict[str, int] = {}
        # traffic-driven autoscale (r21): `autoscale` is an
        # AutoscaleConfig; the default (None / enabled=False) builds
        # no controller — behaviorally identical to r16
        self.autoscale = None
        if autoscale is not None:
            from wasmedge_tpu.gateway.autoscale import (AutoscaleConfig,
                                                        AutoscaleController)

            acfg = autoscale if isinstance(autoscale, AutoscaleConfig) \
                else AutoscaleConfig(**dict(autoscale))
            if acfg.enabled:
                self.autoscale = AutoscaleController(self, acfg).start()
        # integrity (r24): the at-rest scrubber re-verifies every
        # content-addressed byte this gateway holds — parked-session
        # swap blobs, compile-cache entries, checkpoint lineage
        # members — repairing from fleet peer replicas where it can
        # and evicting (forcing a fresh lower / older-member restore)
        # where it cannot.  Default off: the scrubber object does not
        # exist and no byte of behavior changes.
        self.scrubber = None
        integ = getattr(self.template, "integrity", None)
        if integ is not None and integ.scrub:
            from wasmedge_tpu.integrity import Scrubber

            self.scrubber = Scrubber(
                integ, obs=self.obs, faults=faults,
                swap_stores=self._scrub_swap_stores,
                checkpoints=self._scrub_checkpoints,
                compile_cache=lambda: (
                    self.registry.compile_cache
                    if self.imagestore_enabled
                    and self.registry.compile_cache.enabled else None),
                fetch_blob=lambda key: (
                    self.fleet.fetch_blob(key)
                    if self.fleet is not None else None),
                fetch_cache_entry=lambda sha: (
                    self.fleet.fetch_cache_entry(sha)
                    if self.fleet is not None else None))
            self.scrubber.start()   # inert unless scrub_interval_s > 0
        self._health = HealthGate(self)
        if resume:
            if self.durable is None:
                raise ValueError("resume=True requires a state_dir")
            self._resume_from_disk()

    # -- generations -------------------------------------------------------
    @property
    def current(self) -> Optional[_Generation]:
        with self._lock:
            return self._gens[-1] if self._gens else None

    @property
    def generation(self) -> int:
        with self._lock:
            return self._gens[-1].gen_id if self._gens else 0

    def _make_generation(self, gen_id: int, serve_dir: Optional[str],
                         resume: bool) -> _Generation:
        """Pure build of generation `gen_id` (no shared-state commit
        and NO disk mutation — the timed wrapper may abandon this work
        on timeout, and the retry reuses `gen_id`, so an abandoned
        thread must not be able to touch the retry's live
        serve-checkpoint directory)."""
        from wasmedge_tpu.serve.server import BatchServer

        if self.faults is not None:
            self.faults.fire("generation_build", generation=gen_id,
                             modules=self.registry.names)
        conf = copy.deepcopy(self.template)
        if conf.serve.autotune:
            # the tuner reads the drain-latency histograms: the flag
            # must flip BEFORE the engine captures its recorder, or
            # the engine holds NULL_RECORDER forever and autotune is a
            # silent no-op (the injected-engine path cannot fix this
            # up afterwards the way BatchServer's own build can)
            conf.obs.enabled = True
        if serve_dir is not None \
                and conf.serve.checkpoint_every_rounds is None:
            # durability implies a checkpoint cadence — resume has
            # nothing to adopt otherwise
            conf.serve.checkpoint_every_rounds = 1
        init_overlays = None
        snapshot_counts = None
        if self.snapshot_store is not None:
            # decode every registered module's post-init snapshot into
            # a plane overlay for this generation's initial_state; a
            # faulted/corrupt entry drops to template init replay for
            # that module (counted, never wrong state)
            from wasmedge_tpu.imagestore import decode_overlay

            snapshot_counts = self.snapshot_counts
            init_overlays = {}
            for rm in self.registry.modules_snapshot():
                if rm.snapshot is None:
                    continue
                ov = decode_overlay(rm, self.snapshot_store,
                                    faults=self.faults,
                                    counts=self.snapshot_counts)
                if ov is not None:
                    init_overlays[rm.name] = ov
        engine = self.registry.build_engine(
            conf, self.lanes, devices=self.devices,
            init_overlays=init_overlays,
            snapshot_counts=snapshot_counts)
        server = BatchServer(engine=engine,
                             weights=self.tenants.weights(),
                             quotas=self.tenants.quotas(),
                             faults=self.faults,
                             checkpoint_dir=serve_dir,
                             resume=resume,
                             resident_budgets=self.tenants
                             .resident_budgets())
        return _Generation(gen_id, engine, server, self.registry.names,
                           serve_dir=serve_dir)

    def _build_generation_timed(self) -> _Generation:
        """Build the next generation against `build_timeout_s` on a
        worker thread, so one wedged compile cannot hold the
        registration lock forever.  A timed-out build is abandoned
        (daemon thread; it commits nothing and mutates no disk state —
        the serve-dir wipe happens HERE, on the caller thread, before
        the worker starts) and surfaces as a retryable
        GenerationBuildFailed; only a build that returns in time
        commits the generation counters."""
        gen_id = self._gen_seq + 1   # under _reg_lock: race-free
        serve_dir = None
        resume = False
        if self._pending_resume is not None:
            # the resume boot generation adopts the previous process's
            # serve-checkpoint lineage (in-flight requests come back)
            serve_dir, resume = self._pending_resume, True
        elif self.durable is not None:
            serve_dir = self.durable.serve_dir_for(gen_id)
            # a non-resume generation owns a FRESH lineage: stale
            # serve-*.npz from an earlier process in this slot would
            # otherwise be adoptable by the NEXT resume as phantom state
            import shutil

            shutil.rmtree(serve_dir, ignore_errors=True)
        timeout = self.build_timeout_s
        if timeout is None:
            gen = self._make_generation(gen_id, serve_dir, resume)
        else:
            box: dict = {}
            done = threading.Event()

            def build():
                try:
                    box["gen"] = self._make_generation(gen_id,
                                                       serve_dir,
                                                       resume)
                except BaseException as e:
                    box["err"] = e
                finally:
                    done.set()

            t = threading.Thread(target=build, daemon=True,
                                 name=f"gw-build-gen{gen_id}")
            t.start()
            if not done.wait(float(timeout)):
                raise GenerationBuildFailed(
                    f"generation {gen_id} build exceeded the "
                    f"{timeout}s build timeout")
            err = box.get("err")
            if err is not None:
                if isinstance(err, (KeyboardInterrupt, SystemExit)):
                    raise err
                raise GenerationBuildFailed(
                    f"generation {gen_id} build failed: {err!r}") from err
            gen = box["gen"]
        self._gen_seq = gen_id
        self.counters["generations"] += 1
        return gen

    def _swap_in(self, gen: _Generation):
        """Install `gen` as current; the displaced generation drains in
        the background (its in-flight lanes finish on the old image at
        their own launch boundaries) and is reaped once idle.  The
        `generation_swap` fault seam fires BEFORE the server starts or
        the pointer moves — an injected swap fault rolls back with the
        submit pointer untouched, never half-swapped."""
        if self.faults is not None:
            self.faults.fire("generation_swap", generation=gen.gen_id,
                             modules=list(gen.modules))
        gen.server.start()
        with self._lock:
            old = self._gens[-1] if self._gens else None
            self._gens.append(gen)
        if old is not None:
            t = threading.Thread(target=self._drain_old, args=(old,),
                                 name=f"gw-drain-gen{old.gen_id}",
                                 daemon=True)
            t.start()
            self._reapers.append(t)
        self.obs.instant("generation_swap", cat="gateway",
                         track="gateway", generation=gen.gen_id,
                         modules=list(gen.modules))

    def _drain_old(self, old: _Generation):
        try:
            old.server.shutdown(drain=True)
        finally:
            with self._lock:
                if old in self._gens:
                    self._gens.remove(old)
            if self.durable is not None and old.serve_dir \
                    and not any(g.serve_dir == old.serve_dir
                                for g in self._gens):
                self.durable.drop_serve_dir(old.serve_dir)

    # -- module registration ----------------------------------------------
    def register_module(self, name: str, wasm_bytes: Optional[bytes] = None,
                        inst=None, store=None,
                        source: str = "http",
                        tenant: Optional[str] = None) -> dict:
        """Register a module and swap in a fresh generation.  Either
        raw `wasm_bytes` (the HTTP path: full validation pipeline) or a
        pre-instantiated (inst, store) pair (the VM/CLI boot path).
        `tenant` selects the static-analysis admission policy (the
        tenant's own, else the file-level default)."""
        return self._register([(name, wasm_bytes, inst, store, tenant)],
                              source=source, vet_tenant=tenant)

    def preload(self, entries, source: str = "boot") -> dict:
        """Register several modules with ONE generation build — the
        boot path (`--module a=.. --module b=..`) must not pay for and
        immediately drain N-1 throwaway generations.  `entries` is
        [(name, wasm_bytes)]."""
        return self._register(
            [(n, b, None, None, None) for n, b in entries],
            source=source)

    def _vet(self, rm, tenant: Optional[str]) -> List[dict]:
        """Static-analysis admission: evaluate the already-built
        image's ModuleAnalysis (one lowering — shared with the
        batchability probe) against the registering tenant's policy.
        Raises AnalysisRejection in enforce mode; returns the
        violation list in flag mode (surfaced as analysis_warnings).

        Boot/preload registrations (tenant None — the CLI --module
        set, VM.gateway()) are operator-trusted and only COUNTED, never
        policy-gated: a strict file-level default aimed at runtime
        HTTP registrants must not abort gateway startup on the
        operator's own modules."""
        from wasmedge_tpu.analysis.policy import AnalysisRejection

        analysis = getattr(rm.engine.img, "analysis", None)
        with self._lock:
            if analysis is not None:
                key = "bounded" if analysis.bounded else "unbounded"
                self.analysis_counts[key] += 1
        if tenant is None:
            return []
        policy = self.tenants.admission_policy(tenant)
        if policy is None:
            return []
        violations = policy.evaluate(analysis)
        if violations and policy.enforce:
            with self._lock:
                self.counters["policy_rejected"] += 1
                self.analysis_counts["policy_rejected"] += 1
            raise AnalysisRejection(rm.name, violations)
        return violations

    def _register(self, entries, source: str,
                  vet_tenant: Optional[str] = None) -> dict:
        """One registration transaction: add -> vet -> timed build ->
        swap -> persist.  Every failure before the pointer swap rolls
        back ATOMICALLY (registry stash kept, prior generation serving
        bit-identically); build/swap infrastructure failures surface
        as a retryable GenerationBuildFailed (HTTP 503), while the
        wasm/policy taxonomy of the add/vet phase passes through
        unchanged (400s)."""
        with self._reg_lock:
            if self._closed:
                raise GatewayClosed()
            if self.faults is not None:
                self.faults.fire("gateway_register",
                                 names=[e[0] for e in entries])
            added = []
            warnings: List[dict] = []
            try:
                for name, wasm_bytes, inst, store, owner in entries:
                    if wasm_bytes is not None:
                        rm = self.registry.add_wasm(name, wasm_bytes,
                                                    source=source,
                                                    tenant=owner)
                    else:
                        rm = self.registry.add_instance(name, inst,
                                                        store,
                                                        source=source)
                    added.append((rm, wasm_bytes))
                    warnings.extend(self._vet(rm, vet_tenant))
            except BaseException:
                # never leave a module registered that no generation
                # serves — the registry and the serving set must agree.
                # stash=True parks the already-lowered engine in the
                # registry's probe cache: a re-POST of the same bytes
                # (fixed policy, different tenant/name) reuses it
                # instead of lowering twice
                for rm, _ in added:
                    self.registry.remove(rm.name, stash=True)
                raise
            if self.snapshot_store is not None:
                # one-time init run per freshly-added module: capture
                # the post-_start plane columns as a content-addressed
                # snapshot (imagestore/snapshot.py).  Best-effort — a
                # module with no init export, a parked/trapped init, or
                # a store failure just admits through template init.
                # A probe-cache re-adoption keeps its earlier capture.
                from wasmedge_tpu.imagestore import capture_snapshot

                ist = self.template.imagestore
                for rm, _ in added:
                    if rm.snapshot is not None:
                        continue
                    try:
                        rm.snapshot = capture_snapshot(
                            rm, self.snapshot_store,
                            self.snapshot_counts,
                            max_steps=ist.snapshot_init_max_steps)
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except Exception:
                        self.snapshot_counts["skipped"] = \
                            self.snapshot_counts.get("skipped", 0) + 1
            try:
                gen = self._build_generation_timed()
                self._swap_in(gen)
            except BaseException as e:
                for rm, _ in added:
                    self.registry.remove(rm.name, stash=True)
                self._note_rollback(e)
                if isinstance(e, (KeyboardInterrupt, SystemExit,
                                  GatewayClosed, GenerationBuildFailed)):
                    raise
                raise GenerationBuildFailed(
                    f"generation swap failed: {e!r}") from e
            self.last_swap = {"ok": True, "generation": gen.gen_id,
                              "error": None, "t": time.monotonic()}
            durable_ok = self._persist_registration(added, gen)
            if self.fleet is not None:
                # keep blob bytes servable to peers (non-durable
                # gateways have no disk copy to answer
                # GET /v1/fleet/modules/<sha> from)
                self.fleet.note_modules(added)
        with self._lock:
            self.counters["registered_modules"] += len(added)
        last = added[-1][0]
        out = {
            "module": last.name,
            "sha256": last.sha256,
            "exports": last.exported_funcs(),
            "generation": gen.gen_id,
            "modules": list(gen.modules),
        }
        if self.durable is not None:
            out["durable"] = durable_ok
        analysis = getattr(last.engine.img, "analysis", None)
        if analysis is not None:
            out["analysis"] = analysis.summary()
        if warnings:
            # flag-mode policy (enforce=false): registered, but the
            # violations ride the 201 body so operators can see them
            out["analysis_warnings"] = warnings
        return out

    def _note_rollback(self, exc: BaseException):
        with self._lock:
            self.counters["rollbacks"] += 1
        self.last_swap = {"ok": False, "generation": self.generation,
                          "error": repr(exc), "t": time.monotonic()}
        self.obs.instant("generation_rollback", cat="gateway",
                         track="gateway", error=repr(exc),
                         serving_generation=self.generation)

    # -- durability --------------------------------------------------------
    def _persist_registration(self, added, gen: _Generation) -> bool:
        """Module blobs + manifest, written BEFORE the 201 returns.  A
        failed write degrades health (and the body says durable:false)
        but does not un-swap the generation — the next successful
        durable write self-heals via the dirty flag, since every
        manifest is a full-set snapshot."""
        if self.durable is None:
            return True
        try:
            for rm, data in added:
                if data is not None and rm.sha256:
                    self.durable.save_module_bytes(rm.sha256,
                                                   bytes(data))
            self._write_manifest(gen)
            return True
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException:
            with self._lock:
                self.counters["journal_errors"] += 1
                self._journal_fail_streak += 1
                self._manifest_dirty = True
            return False

    def _write_manifest(self, gen: _Generation):
        mods = [{"name": rm.name, "sha256": rm.sha256,
                 "tenant": rm.tenant, "source": rm.source}
                for rm in self.registry.modules_snapshot()
                if rm.sha256]   # instance-registered modules (VM boot
        #                         path) have no bytes to restore from
        rel = os.path.relpath(gen.serve_dir, self.durable.dir) \
            if gen.serve_dir else None
        self.durable.write_manifest(mods, gen.gen_id, rel,
                                    self.counters["restarts"])
        self._manifest_dirty = False

    def _journal_snapshot(self):
        with self._lock:
            unresolved = []
            for r in self._requests.values():
                if r.future.done:
                    continue
                entry = {"id": r.id, "tenant": r.tenant,
                         "module": r.module, "func": r.func,
                         "args": list(r.args),
                         "deadline_s": r.deadline_s}
                if r.edge:
                    entry["edge"] = r.edge
                unresolved.append(entry)
            resolved = list(self._result_cache)
            # a resolved-but-not-yet-finalized async id (nobody polled
            # it HERE — its client may be polling a fleet peer) must
            # not vanish from the journal: it is no longer unresolved,
            # and without its outcome in the resolved cache a peer
            # adopting this journal after our death would answer 404
            # for an id we actually completed.  Include the outcome
            # inline; finalize() later re-appends it to the capped
            # cache idempotently (replay installs guard by id).
            seen = {e.get("id") for e in resolved}
            for r in self._requests.values():
                if r.future.done and not r.finalized \
                        and r.id not in seen:
                    try:
                        resolved.append(_resolved_entry(r))
                    except Exception:
                        pass
            max_id = max([self._resume_floor, self._max_issued]
                         + [r.id for r in self._requests.values()])
            # lower edge of the pruned-404 window: the smallest id
            # this gateway (or the lineage it resumed) ever issued
            mins = [self._min_issued]
            if self._resume_floor:
                mins.append(self._resume_min)
            min_id = min([m for m in mins if m] or [0])
        return unresolved, resolved, max_id, min_id

    def _journal_sync(self, strict_req: Optional[GatewayRequest] = None):
        """Write the request journal (and a dirty manifest, if one is
        owed).  With `strict_req`, a failed write WITHDRAWS that
        request's acceptance — pulled back out of the serving queue,
        its future rejected, and a retryable DurabilityError raised —
        so the gateway never issues a 202 id that would not survive a
        restart (and never burns a lane on work it disowned).  Without
        it (the finalize path), failures only degrade health.

        `_journal_mutex` serializes snapshot->write: two concurrent
        syncs could otherwise snapshot in one order and acquire the
        store's sequence numbers in the other, making an OLDER
        snapshot the authoritative (newest) journal and losing a
        durably-accepted id across a crash."""
        fleet = self.fleet if self.fleet is not None \
            and self.fleet.started else None
        if self.durable is None and fleet is None:
            return
        try:
            with self._journal_mutex:
                unresolved, resolved, max_id, min_id = \
                    self._journal_snapshot()
                if self.durable is not None:
                    if self._manifest_dirty:
                        cur = self.current
                        if cur is not None:
                            self._write_manifest(cur)
                    self.durable.write_journal(unresolved, resolved,
                                               max_id=max_id,
                                               min_id=min_id)
                self._repl_seq += 1
                seq = self._repl_seq
            if fleet is not None:
                # cross-host durability: a STRICT sync (the 202 path)
                # must land the snapshot on >=1 alive peer — total
                # failure raises and the acceptance is withdrawn
                # below, exactly like a failed local journal write.
                # The peer HTTP happens OUTSIDE _journal_mutex (one
                # slow peer must not stall every accept behind the
                # mutex); `seq` — drawn under the mutex, so ordered
                # like the disk writes — lets receivers discard an
                # out-of-order older snapshot (fleet on_journal)
                fleet.replicate(unresolved, resolved, max_id,
                                strict=strict_req is not None,
                                seq=seq)
            with self._lock:
                self._journal_fail_streak = 0
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            with self._lock:
                self.counters["journal_errors"] += 1
                self._journal_fail_streak += 1
            if strict_req is not None:
                self._withdraw(strict_req)
                err = DurabilityError(
                    f"request journal write failed: {e!r}")
                strict_req.future._reject(err)
                raise err from e

    def _withdraw(self, req: GatewayRequest):
        """Take back an acceptance that could not be made durable: the
        request comes OUT of the serving queue (if not yet admitted —
        the guest must not run work whose id the client was told never
        existed), out of the stash, and out of the received tally."""
        with self._lock:
            gen = next((g for g in self._gens
                        if g.gen_id == req.gen_id), None)
            if self._requests.pop(req.id, None) is not None:
                self.counters["received"] -= 1
        if gen is not None:
            gen.server.withdraw(req.id)

    def _resume_from_disk(self):
        """Crash/restart resume: re-register the stored module set
        under ONE boot generation (adopting the previous generation's
        serve-checkpoint lineage), then re-install the async-request
        journal — resolved ids replay from the durable result cache
        (exactly-once), everything else re-queues under its original id
        (at-least-once, README table)."""
        manifest, journal = self.durable.load()
        self.counters["restarts"] = \
            int((manifest or {}).get("restarts", 0)) + 1
        mods = (manifest or {}).get("modules") or []
        gen = None
        if mods:
            # continue the generation numbering so a fresh generation
            # in this process can never collide with (and later adopt)
            # a dead process's serve-checkpoint slot
            self._gen_seq = max(int(manifest.get("generation", 0)),
                                self._gen_seq)
            entries = []
            for m in mods:
                entries.append((m["name"],
                                self.durable.module_bytes(m["sha256"]),
                                None, None, m.get("tenant")))
            rel = manifest.get("serve_dir")
            self._pending_resume = \
                os.path.join(self.durable.dir, rel) if rel else None
            try:
                self._register(entries, source="resume")
            finally:
                self._pending_resume = None
            gen = self.current
        else:
            # nothing to restore; still make the restart count durable
            self.durable.write_manifest([], 0, None,
                                        self.counters["restarts"])
        self._restore_journal(journal or {}, gen)
        self.obs.instant("gateway_resume", cat="gateway",
                         track="gateway",
                         restarts=self.counters["restarts"],
                         modules=[m["name"] for m in mods],
                         resumed_requests=self.counters["resumed"])
        self._journal_sync()

    def _restore_journal(self, journal: dict, gen: Optional[_Generation]):
        from wasmedge_tpu.serve.queue import advance_request_ids

        floor = int(journal.get("max_id", 0))
        self._resume_min = max(int(journal.get("min_id", 0) or 1), 1)
        if floor:
            # every id at/below the floor was issued by a dead
            # process: unknown ones answer the pruned 404 detail, and
            # fresh ids must allocate above them
            self._resume_floor = floor
            advance_request_ids(floor)
        for entry in journal.get("resolved", []):
            # durable result cache: replay verbatim so a poll of an id
            # resolved before the crash is exactly-once observable
            self._result_cache.append(entry)
            self._install_replay(entry, gen)
        if gen is None:
            return
        adopted = dict(gen.server.adopted)
        with gen.server._lock:
            bind_by_id = {r.id: r
                          for r in gen.server._bindings.values()}
        for entry in journal.get("unresolved", []):
            rid = int(entry["id"])
            with self._lock:
                if rid in self._requests:
                    continue
            tenant = entry.get("tenant", "default")
            module = entry.get("module")
            func = entry.get("func", "")
            args = entry.get("args", [])
            fut = adopted.pop(rid, None)
            if fut is None:
                # accepted but not covered by the serve checkpoint:
                # re-queue under the ORIGINAL id.  At-least-once — the
                # guest may have partially run before the crash.  The
                # journaled deadline restarts its clock here: after a
                # restart, completing late beats expiring work the
                # client is still polling for.
                try:
                    fut = gen.server.submit(
                        func, args, tenant=tenant,
                        deadline_s=entry.get("deadline_s"),
                        request_id=rid)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    # unservable after resume (export gone from the
                    # restored set): machine-readable rejection, never
                    # a silently-lost id
                    from wasmedge_tpu.serve.queue import (
                        ServeFuture,
                        ServeRejected,
                    )

                    fut = ServeFuture(rid)
                    fut._reject(ServeRejected(
                        f"request {rid} could not be re-queued after "
                        f"gateway restart: {e}"))
                    advance_request_ids(rid)
            req = GatewayRequest(fut, tenant, module, func, gen.gen_id,
                                 time.monotonic(), args=args,
                                 deadline_s=entry.get("deadline_s"))
            with self._lock:
                self._requests[req.id] = req
                self._note_issued(req.id)
                self.counters["received"] += 1
                self.counters["resumed"] += 1
        # adopted serve-checkpoint requests the journal missed (a
        # faulted journal write raced the snapshot): wrap them too —
        # their futures resolve as the resumed serving loop finishes
        for rid, fut in adopted.items():
            with self._lock:
                if rid in self._requests:
                    continue
            sr = bind_by_id.get(rid)
            req = GatewayRequest(
                fut, sr.tenant if sr else "default", None,
                sr.func_name if sr else "", gen.gen_id,
                time.monotonic(),
                args=(sr.args if sr else ()))
            with self._lock:
                self._requests[req.id] = req
                self._note_issued(req.id)
                self.counters["received"] += 1
                self.counters["resumed"] += 1

    def _install_replay(self, entry: dict, gen: Optional[_Generation]):
        from wasmedge_tpu.serve.queue import ServeFuture, \
            advance_request_ids

        rid = int(entry["id"])
        with self._lock:
            if rid in self._requests:
                return
        fut = ServeFuture(rid)
        if entry.get("ok"):
            fut._resolve([int(c) for c in entry.get("result", [])])
        else:
            fut._reject(resolved_error(entry))
        advance_request_ids(rid)
        req = GatewayRequest(fut, entry.get("tenant", "default"), None,
                             entry.get("func", ""),
                             gen.gen_id if gen else 0, time.monotonic())
        # outcome counted by the PREVIOUS process; replay only
        req.finalized = True
        with self._lock:
            self._requests[rid] = req
            self._note_issued(rid)
            self._resolved.append(rid)

    # -- requests ----------------------------------------------------------
    def submit(self, func: str, args, module: Optional[str] = None,
               tenant: str = "default",
               deadline_s: Optional[float] = None) -> GatewayRequest:
        """Edge admission: rate limit, degraded-mode shedding, then the
        current generation's BatchServer.  Raises RateLimited,
        ShedLoad / QueueSaturated (retryable), KeyError (unknown
        module/func), DurabilityError (journal write failed — the id
        was never accepted), or the serving taxonomy."""
        from wasmedge_tpu.gateway.health import ShedLoad
        from wasmedge_tpu.gateway.tenants import RateLimited

        try:
            self.tenants.check_rate(tenant)
        except RateLimited:
            with self._lock:
                self.counters["rate_limited"] += 1
            raise
        try:
            self._health.maybe_shed(tenant)
        except ShedLoad:
            with self._lock:
                self.counters["shed"] += 1
                self.shed_counts[tenant] = \
                    self.shed_counts.get(tenant, 0) + 1
            self.obs.instant("shed", cat="gateway", track="gateway",
                             tenant=tenant)
            raise
        if self.fleet is not None and self.fleet.started:
            # consistent fleet routing (rendezvous hash on the request
            # id): the owner executes; a suspect owner refuses
            # retryably; no remote available falls through to the
            # plain local path (solo fallback, bit-identical)
            try:
                routed = self.fleet.maybe_route(
                    func, args, module=module, tenant=tenant,
                    deadline_s=deadline_s)
            except WasmError:
                with self._lock:
                    self.counters["rejected"] += 1
                raise
            if routed is not None:
                self.obs.instant("gateway_receive", cat="gateway",
                                 track="gateway", id=routed.id,
                                 tenant=tenant, func=routed.func)
                return routed
        return self._submit_local(func, args, module=module,
                                  tenant=tenant, deadline_s=deadline_s)

    def _submit_local(self, func: str, args,
                      module: Optional[str] = None,
                      tenant: str = "default",
                      deadline_s: Optional[float] = None,
                      request_id: Optional[int] = None,
                      edge: Optional[str] = None) -> GatewayRequest:
        """Queue on the LOCAL serving generation (edge policy already
        applied by submit(); the fleet's execute route calls this
        directly — the edge peer enforced its own policy before
        forwarding).  `request_id` submits under a fleet-allocated or
        forwarded ORIGINAL id; `edge` journals the accepting peer."""
        with self._lock:
            if self._closed:
                raise GatewayClosed()
            gen = self._gens[-1] if self._gens else None
        if gen is None:
            raise KeyError("no modules registered")
        qualified = f"{module}:{func}" if module else func
        t_recv = time.monotonic()
        while True:
            try:
                fut = gen.server.submit(qualified, args, tenant=tenant,
                                        deadline_s=deadline_s,
                                        request_id=request_id)
                break
            except WasmError:
                # a submit can race a generation swap: the generation
                # captured above starts DRAINING the moment its
                # successor is installed, and rejects submissions with
                # a permanent (non-retryable) error.  That rejection
                # belongs to the stale generation, not the request —
                # re-resolve and retry on the successor.  Only a
                # still-current generation's rejection is authoritative.
                with self._lock:
                    cur = self._gens[-1] if self._gens else None
                    closed = self._closed
                if cur is gen or cur is None:
                    with self._lock:
                        self.counters["rejected"] += 1
                    if closed:
                        # the generation rejected because the GATEWAY
                        # is going down, not because of the tenant's
                        # policy — surface the lifecycle class (503)
                        raise GatewayClosed() from None
                    raise
                gen = cur
            except BaseException:
                with self._lock:
                    self.counters["rejected"] += 1
                raise
        req = GatewayRequest(fut, tenant, module, qualified, gen.gen_id,
                             t_recv, args=args, deadline_s=deadline_s,
                             edge=edge)
        with self._lock:
            self.counters["received"] += 1
            self._requests[req.id] = req
            self._note_issued(req.id)
        # the acceptance is not real until it is durable: a journal
        # write failure rejects THIS request retryably (the id was
        # never handed out, so a restart owes nothing for it)
        self._journal_sync(strict_req=req)
        self.obs.instant("gateway_receive", cat="gateway",
                         track="gateway", id=req.id, tenant=tenant,
                         func=qualified)
        return req

    def _note_issued(self, rid: int):
        """Track the id range this gateway has stashed (callers hold
        self._lock); journaled so the resumed pruned-404 window is
        exactly [min_id, max_id], not 'everything below the counter'."""
        rid = int(rid)
        self._max_issued = max(self._max_issued, rid)
        if self._min_issued == 0 or rid < self._min_issued:
            self._min_issued = rid

    # -- fleet seams (wasmedge_tpu/fleet/federation.py) --------------------
    def _stash_request(self, fut, tenant, module, qualified, args,
                       deadline_s, edge=None) -> GatewayRequest:
        """Register an acceptance whose EXECUTION lives elsewhere (a
        forwarded request): same stash/counters as a local submit, no
        server involvement."""
        req = GatewayRequest(fut, tenant, module, qualified,
                             self.generation, time.monotonic(),
                             args=args, deadline_s=deadline_s,
                             edge=edge)
        with self._lock:
            self.counters["received"] += 1
            self._requests[req.id] = req
            self._note_issued(req.id)
        return req

    def _relink_future(self, req: GatewayRequest, fut):
        """Bridge a fresh server future into the future the client's
        202 was issued against (fleet local-fallback: the re-queued
        request resolves the ORIGINAL handle)."""
        fut.mirror(req.future)

    def _wrap_foreign(self, fut, entry: dict, gen) -> GatewayRequest:
        """Stash a request adopted from a peer (migration/execute):
        polls against THIS gateway answer for it from now on."""
        req = GatewayRequest(fut, entry.get("tenant", "default"), None,
                             entry.get("func", ""),
                             gen.gen_id if gen else 0,
                             time.monotonic(),
                             args=tuple(entry.get("args", ())),
                             deadline_s=entry.get("deadline_s"),
                             edge=entry.get("edge"))
        with self._lock:
            if req.id in self._requests:
                return self._requests[req.id]
            self.counters["received"] += 1
            self._requests[req.id] = req
            self._note_issued(req.id)
        return req

    def adopt_foreign(self, entry: dict, src: str = "") -> GatewayRequest:
        """Failover adoption of one unresolved journal entry from a
        DEAD peer: re-queue under the ORIGINAL id (at-least-once — the
        dead peer may have partially run it).  Unservable entries
        reject machine-readably; an id is never silently lost."""
        from wasmedge_tpu.serve.queue import (
            ServeFuture,
            ServeRejected,
            advance_request_ids,
        )

        rid = int(entry["id"])
        with self._lock:
            if rid in self._requests:
                return self._requests[rid]
        gen = self.current
        fut = None
        if gen is not None:
            try:
                fut = gen.server.submit(
                    entry.get("func", ""), entry.get("args", []),
                    tenant=entry.get("tenant", "default"),
                    deadline_s=entry.get("deadline_s"),
                    request_id=rid)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                fut = None
        if fut is None:
            fut = ServeFuture(rid)
            fut._reject(ServeRejected(
                f"request {rid} adopted from dead peer {src!r} could "
                f"not be re-queued"))
            advance_request_ids(rid)
        req = self._wrap_foreign(fut, entry, gen)
        with self._lock:
            self.counters["resumed"] += 1
        return req

    def get_request(self, request_id: int) -> Optional[GatewayRequest]:
        with self._lock:
            req = self._requests.get(int(request_id))
        if req is not None:
            self.finalize(req)
        return req

    def request_state(self, request_id: int):
        """('ok', req) for a live/stash-resident id, ('pruned', None)
        for an id whose resolved entry aged out of the result cache
        (the HTTP layer's distinct 404 detail — a client that cached a
        202 can tell "aged out" from "never existed"), ('unknown',
        None) otherwise."""
        rid = int(request_id)
        with self._lock:
            req = self._requests.get(rid)
            # ids inside the resumed [min_id, max_id] window were
            # issued by a pre-crash process: anything unknown there
            # has aged out, it did not "never exist".  The window has
            # a LOWER edge too — fleet id-space rebasing means ids do
            # not start near 1, and an id below everything this
            # lineage ever issued really is unknown
            pruned = req is None and (
                rid in self._pruned_set
                or self._resume_min <= rid <= self._resume_floor)
        if req is not None:
            self.finalize(req)
            return "ok", req
        return ("pruned" if pruned else "unknown"), None

    def wake(self, request_id: int,
             payload: Optional[bytes] = None,
             _forward: bool = True) -> dict:
        """Deliver an external wake to a (possibly parked) request —
        the POST /v1/requests/<id>/wake body rides to the guest's
        await_event return buffer.  At-least-once: the wake queues
        even when the id is not currently parked (it pre-delivers at
        the request's next await_event), so a wake racing the park is
        never lost.

        Fleet-routed (r24): when this member does not know the id and
        a fleet is active, the wake forwards to the id's rendezvous
        owner over the r16 routing table — any member is a valid edge
        for POST /v1/requests/<id>/wake.  `_forward=False` marks an
        already-forwarded arrival (FleetController.on_wake) so a
        misrouted wake can never loop."""
        rid = int(request_id)
        gen = self.current
        if gen is None:
            raise KeyError(f"no serving generation to wake request "
                           f"{rid}")
        state = gen.server.wake(rid, payload)
        if state == "unknown" and _forward and self.fleet is not None:
            fwd = self.fleet.route_wake(rid, payload)
            if fwd is not None:
                self.obs.instant("gateway_wake", cat="gateway",
                                 track="gateway", id=rid,
                                 state="forwarded",
                                 owner=fwd.get("owner"),
                                 nbytes=len(payload or b""))
                return fwd
        self.obs.instant("gateway_wake", cat="gateway",
                         track="gateway", id=rid, state=state,
                         nbytes=len(payload or b""))
        return {"ok": True, "request_id": rid, "state": state}

    def stream_of(self, request_id: int):
        """The request's stdout StreamBuf (None when the effects
        subsystem is off or no generation serves) — the
        GET /v1/requests/<id>/stream handler blocks on it."""
        gen = self.current
        if gen is None:
            return None
        return gen.server.stream_of(int(request_id))

    def wait(self, req: GatewayRequest,
             timeout_s: Optional[float] = None) -> bool:
        """Block on the request's future (the sync-invoke path); the
        gateway-level cap applies when the caller sets none."""
        done = req.future.wait(self.sync_wait_s if timeout_s is None
                               else timeout_s)
        if done:
            self.finalize(req)
        return done

    def finalize(self, req: GatewayRequest, journal: bool = True):
        """Account + trace a completed request exactly once (called
        from every path that observes completion, and by the pruning
        sweep for never-polled async requests).  `journal=False` lets
        a batch caller (sweep) coalesce many resolutions into one
        durable write."""
        if req.finalized or not req.future.done:
            return
        with self._lock:
            if req.finalized:
                return
            req.finalized = True
            self._resolved.append(req.id)
            err = req.future.error
            from wasmedge_tpu.serve.queue import DeadlineExceeded

            if err is None:
                self.counters["completed"] += 1
            elif isinstance(err, DeadlineExceeded):
                self.counters["deadline"] += 1
            else:
                self.counters["failed"] += 1
            if self.durable is not None or self.fleet is not None:
                # the durable result cache also feeds the FLEET's
                # replicated journal: peers replay these exactly-once
                # when this gateway dies, so fleet-only (no state_dir)
                # gateways populate it too
                try:
                    self._result_cache.append(_resolved_entry(req))
                except Exception:
                    pass   # an unserializable outcome never blocks
                #            finalization; the entry just isn't cached
            while len(self._resolved) > self.result_cache:
                pruned_id = self._resolved.popleft()
                self._requests.pop(pruned_id, None)
                # remember the id as PRUNED (bounded memory) so a late
                # poll draws the distinct 404 detail, not "unknown id"
                if len(self._pruned) == self._pruned.maxlen:
                    self._pruned_set.discard(self._pruned[0])
                self._pruned.append(pruned_id)
                self._pruned_set.add(pruned_id)
        # journal the resolution (never strict: a completed request's
        # durability failure degrades health, it cannot un-complete)
        if journal:
            self._journal_sync()
        self.obs.span(f"gateway/{req.tenant}", req.t_recv,
                      cat="gateway", track="gateway", id=req.id,
                      func=req.func, generation=req.gen_id,
                      ok=req.future.error is None)

    def sweep(self):
        """Finalize any resolved-but-unpolled async requests (keeps the
        gateway spans/counters complete without a per-future callback
        seam; called from status/metrics)."""
        with self._lock:
            pending = [r for r in self._requests.values()
                       if not r.finalized and r.future.done]
        for r in pending:
            self.finalize(r, journal=False)
        if pending:
            self._journal_sync()   # one durable write for the batch

    # -- edge accounting ---------------------------------------------------
    def count_http(self, code: int):
        with self._lock:
            key = str(int(code))
            self.http_counts[key] = self.http_counts.get(key, 0) + 1

    # -- integrity (r24) ---------------------------------------------------
    def _scrub_swap_stores(self):
        """(kind, store, evict_on_fail) triples for the scrubber.  The
        hv/effects stores never evict: their get() already refuses rot
        and checkpoints embed payload copies, so an unrepairable entry
        is counted and left for the restore path to route around.  The
        snapshot store DOES evict — a rotted pre-initialized snapshot
        silently poisons every lane built from it, and eviction just
        costs one init replay."""
        out, seen = [], set()
        gen = self.current
        if gen is not None:
            srv = gen.server
            if srv.hv is not None and srv.hv.store is not None:
                out.append(("hv", srv.hv.store, False))
                seen.add(id(srv.hv.store))
            if srv.effects is not None \
                    and srv.effects.store is not None \
                    and id(srv.effects.store) not in seen:
                out.append(("effects", srv.effects.store, False))
                seen.add(id(srv.effects.store))
        if self.snapshot_store is not None \
                and id(self.snapshot_store) not in seen:
            out.append(("snapshot", self.snapshot_store, True))
        return out

    def _scrub_checkpoints(self):
        """Checkpoint lineage member paths of the current generation
        (real on-disk files only)."""
        gen = self.current
        if gen is None:
            return []
        with gen.server._lock:
            members = list(gen.server._lineage.members)
        return [m.path for m in members
                if isinstance(m.path, (str, os.PathLike))
                and os.path.isfile(m.path)]

    def scrub_once(self) -> Optional[dict]:
        """One synchronous at-rest scrub pass (the cadence thread runs
        the same walk); None when the scrubber is off."""
        if self.scrubber is None:
            return None
        return self.scrubber.scrub_once()

    def integrity_stats(self) -> Optional[dict]:
        """The /v1/status "integrity" block: shadow-audit verdicts +
        device quarantine from the serving generation, scrub totals
        from the gateway-wide scrubber.  None when the whole subsystem
        is off — the default status body is bit-identical r23."""
        out = {}
        gen = self.current
        if gen is not None:
            audit = gen.server.integrity_stats()
            if audit is not None:
                out.update(audit)
        if self.scrubber is not None:
            out["scrub"] = self.scrubber.snapshot()
        return out or None

    # -- introspection -----------------------------------------------------
    def reshard(self, n_devices: Optional[int] = None,
                devices=None) -> dict:
        """Live-reshard the CURRENT generation onto a new device set
        (r21 tentpole leg b) — no drain, no re-queue: resident lanes
        ride through with their state bit-identical (grow-only lane
        pool; a device SHRINK keeps the lane width and re-splits it
        across fewer devices).  Future generations build at the new
        geometry too.  A mid-install fault rolls the server back onto
        the old mesh and this raises — the gateway keeps serving at
        the OLD geometry."""
        import jax

        from wasmedge_tpu.parallel.mesh import normalize_devices

        if devices is not None:
            devs = normalize_devices(devices)
        else:
            n = 1 if n_devices is None else int(n_devices)
            if n < 1:
                raise ValueError("n_devices must be positive")
            avail = jax.devices()
            if n > len(avail):
                raise ValueError(
                    f"reshard wants {n} devices, only {len(avail)} "
                    f"visible")
            devs = normalize_devices(avail[:n])
        gen = self.current
        if gen is None:
            raise RuntimeError("no serving generation to reshard")
        old_ndev = len(self.devices) if self.devices else 1
        # health surfaces in-flight reshards as churn (not
        # degradation) while the install runs
        with self._lock:
            self._resharding += 1
        try:
            out = gen.server.reshard(devices=devs)
        finally:
            with self._lock:
                self._resharding -= 1
        direction = "grow" if len(devs) >= old_ndev else "shrink"
        with self._lock:
            # future generations (module registrations trigger a fresh
            # build) inherit the new geometry
            self.devices = devs if len(devs) > 1 else None
            self.lanes = int(out["lanes"])
            self.reshard_counts[direction] = \
                self.reshard_counts.get(direction, 0) + 1
        self.obs.instant("gateway_reshard", cat="gateway",
                         track="gateway", direction=direction,
                         devices=len(devs), old_devices=old_ndev,
                         lanes=out["lanes"], generation=gen.gen_id)
        return dict(out, direction=direction, generation=gen.gen_id)

    def health(self, fresh: bool = True) -> dict:
        """The truthful /healthz body (gateway/health.py): driver
        liveness, last-swap outcome, queue saturation, checkpoint +
        journal write health -> healthy / degraded / unhealthy."""
        return self._health.health(fresh=fresh)

    def status(self) -> dict:
        self.sweep()
        with self._lock:
            gen = self._gens[-1] if self._gens else None
            draining = max(len(self._gens) - 1, 0)
            out = {
                "generation": gen.gen_id if gen else 0,
                "modules": {
                    name: self.registry.get(name).exported_funcs()
                    for name in (gen.modules if gen else ())},
                "lanes": self.lanes,
                "draining_generations": draining,
                "gateway": dict(self.counters),
                "analysis": dict(self.analysis_counts),
                "http": dict(self.http_counts),
                "tenants": sorted(self.tenants.policies),
                "shed": dict(self.shed_counts),
                "last_swap": dict(self.last_swap)
                if self.last_swap else None,
                "durable": self.durable is not None,
                "devices": len(self.devices) if self.devices else 1,
                "reshards": dict(self.reshard_counts),
                "resharding": self._resharding,
            }
            if gen is not None:
                out["queue_depth"] = len(gen.server.queue)
                out["in_flight"] = gen.server.in_flight
                out["serve"] = dict(gen.server.counters)
        if self.fleet is not None:
            out["fleet"] = dict(self.fleet.stats(),
                                peer_states=self.fleet.peer_states())
        if gen is not None:
            # resident/virtual occupancy (lane virtualization, hv/) —
            # absent when the gateway runs without oversubscription
            hv = gen.server.hv_stats()
            if hv is not None:
                out["hv"] = hv
            # parked-session occupancy (effects/) — absent when the
            # suspend subsystem is off, so the default status body
            # stays bit-identical to the pre-effects gateway
            sessions = gen.server.session_stats()
            if sessions is not None:
                out["sessions"] = sessions
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.stats()
        if self.imagestore_enabled:
            # cold-start telemetry (r22): present only when a knob is
            # on, so the default status body stays bit-identical r21
            sc = self.registry.segment_cache
            out["coldstart"] = {
                "compile_cache": self.registry.compile_cache.stats(),
                "segments": sc.stats() if sc is not None else None,
                "snapshots": dict(self.snapshot_counts),
                "lowered_count": self.registry.lowered_count,
            }
        integ = self.integrity_stats()
        if integ is not None:
            # integrity telemetry (r24): absent unless audit/scrub is
            # on, so the default status body stays bit-identical r23
            out["integrity"] = integ
        out["health"] = self.health()
        return out

    def metrics_text(self) -> str:
        self.sweep()
        from wasmedge_tpu.obs.metrics import render_prometheus

        gen = self.current
        with self._lock:
            gateway_counts = {
                "restarts": self.counters["restarts"],
                "rollbacks": self.counters["rollbacks"],
            }
            shed_counts = dict(self.shed_counts)
            reshard_counts = dict(self.reshard_counts)
        return render_prometheus(
            recorder=self.obs if self.obs.enabled else None,
            hostcall_stats=gen.engine.hostcall_stats if gen else None,
            http_requests=dict(self.http_counts),
            analysis_counts=dict(self.analysis_counts),
            gateway_counts=gateway_counts,
            shed_counts=shed_counts,
            hv_stats=gen.server.hv_stats() if gen else None,
            session_stats=gen.server.session_stats() if gen else None,
            fleet_stats=self.fleet.stats()
            if self.fleet is not None else None,
            reshard_counts=reshard_counts or None,
            autoscale_actions=dict(self.autoscale.actions)
            if self.autoscale is not None else None,
            compile_cache_counts=dict(self.registry.compile_cache.counts)
            if self.imagestore_enabled else None,
            snapshot_counts=dict(self.snapshot_counts)
            if self.snapshot_store is not None else None,
            integrity_stats=self.integrity_stats())

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None):
        # _reg_lock first: an in-flight registration finishes its swap
        # (its generation lands in the snapshot below) and later ones
        # see _closed — otherwise a generation swapped in after the
        # snapshot would keep serving on registry fds close() is about
        # to invalidate, while shutdown() reports a clean stop
        with self._reg_lock:
            with self._lock:
                self._closed = True
                gens = list(self._gens)
        if self.autoscale is not None:
            self.autoscale.stop()
        if self.scrubber is not None:
            self.scrubber.stop()
        if self.fleet is not None:
            self.fleet.stop()
        for g in gens:
            g.server.shutdown(drain=drain, timeout_s=timeout_s)
        for t in self._reapers:
            t.join(timeout=5.0)
        self.sweep()
        self._journal_sync()   # the journal reflects the final state
        self.registry.close()

    def kill(self):
        """Simulated SIGKILL (the chaos harness's supported in-process
        crash): stop every serving thread WITHOUT draining, rejecting
        futures, or flushing the journal — exactly the state a real
        kill -9 leaves on disk, so `GatewayService(resume=True)` over
        the same state_dir is the honest recovery test.  Registry fds
        are closed (a real dead process drops them too)."""
        with self._lock:
            self._closed = True   # later registrations see it and stop
        if self.autoscale is not None:
            self.autoscale.stop()
        if self.scrubber is not None:
            self.scrubber.stop()
        if self.fleet is not None:
            # a killed process's heartbeats just STOP (no goodbye, no
            # final replication) — peers discover the death the honest
            # way, through the suspect→dead state machine
            self.fleet.stop()
        with self._reg_lock:
            pass   # let an in-flight registration's swap finish or fail
        with self._lock:
            gens = list(self._gens)
        for g in gens:
            srv = g.server
            with srv._lock:
                srv._stop = True
                srv._draining = True
                srv._wake.notify_all()
            t = srv._thread
            if t is not None:
                t.join(timeout=30.0)
        self.registry.close()
