"""Per-tenant gateway policy: auth, rate/quota/weight, registration.

The FairQueue already enforces weighted-DRR admission and in-flight
quotas INSIDE the serving loop; this module is the network-edge half of
tenancy — who a request belongs to (API-key auth stub), how fast it may
arrive (token-bucket rate limiting, checked before the request ever
touches the queue), and whether the tenant may register modules.

Policies load from a JSON or TOML file (`GatewayTenants.from_file`):

    {
      "require_auth": true,
      "default_tenant": "anon",
      "tenants": {
        "alice": {"api_key": "sk-alice", "weight": 2.0, "quota": 8,
                   "rate_per_s": 50, "burst": 100, "can_register": true},
        "bob":   {"api_key": "sk-bob", "weight": 1.0}
      }
    }

`weight` / `quota` map straight onto the FairQueue's DRR weights and
in-flight quotas (serve/queue.py); `rate_per_s`/`burst` gate the HTTP
edge.  Auth is a deliberate STUB — a bearer-token equality check, the
seam where a real deployment plugs mTLS/JWT — but the taxonomy
(AuthError -> 401, RateLimited -> 429 + Retry-After) is final.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional


class AuthError(Exception):
    """Missing/unknown API key, or a claimed tenant that does not match
    the key's tenant.  HTTP layer maps to 401."""


class RateLimited(Exception):
    """Token bucket empty: transient, carries the refill hint the HTTP
    layer forwards as Retry-After (429)."""

    def __init__(self, tenant: str, retry_after_s: float):
        super().__init__(f"tenant {tenant!r} rate-limited")
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)


class TokenBucket:
    """Classic token bucket: `rate` tokens/s up to `burst` capacity.
    Monotonic-clock based; thread-safe (one bucket is hit from every
    HTTP handler thread of its tenant)."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0):
        """Take `n` tokens; returns None on success, else the seconds
        until enough tokens will have refilled."""
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
            self._t = now
            if self.tokens >= n:
                self.tokens -= n
                return None
            if self.rate <= 0:
                return float("inf")
            return (n - self.tokens) / self.rate


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's edge policy (None = unlimited / default)."""

    name: str
    api_key: Optional[str] = None
    weight: float = 1.0
    quota: Optional[int] = None        # max in-flight lanes (FairQueue)
    rate_per_s: Optional[float] = None  # HTTP-edge request rate
    burst: Optional[float] = None       # bucket capacity (default 2*rate)
    can_register: bool = True           # POST /v1/modules allowed
    # Static-analysis admission limits for modules THIS tenant registers
    # (analysis/policy.py AnalysisPolicy; None = inherit the file's
    # top-level "analysis" default, which itself defaults to no vetting)
    analysis: Optional[object] = None
    # Lane-virtualization resident-bytes budget (wasmedge_tpu/hv/):
    # caps how many PHYSICAL lanes this tenant's requests may hold at
    # once (budget / effective-lane-bytes); over-cap requests wait as
    # swapped-out virtual lanes instead of being rejected.  None =
    # unlimited.  Only meaningful on an hv-enabled gateway.
    resident_budget_bytes: Optional[int] = None

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantPolicy":
        known = {"api_key", "weight", "quota", "rate_per_s", "burst",
                 "can_register", "analysis", "resident_budget_bytes"}
        bad = set(d) - known
        if bad:
            raise ValueError(
                f"tenant {name!r}: unknown policy keys {sorted(bad)}")
        analysis = None
        if d.get("analysis") is not None:
            from wasmedge_tpu.analysis.policy import AnalysisPolicy

            analysis = AnalysisPolicy.from_dict(
                d["analysis"], where=f"tenant {name!r} analysis")
        return cls(name=name,
                   api_key=d.get("api_key"),
                   weight=float(d.get("weight", 1.0)),
                   quota=(int(d["quota"]) if d.get("quota") is not None
                          else None),
                   rate_per_s=(float(d["rate_per_s"])
                               if d.get("rate_per_s") is not None
                               else None),
                   burst=(float(d["burst"]) if d.get("burst") is not None
                          else None),
                   can_register=bool(d.get("can_register", True)),
                   analysis=analysis,
                   resident_budget_bytes=(
                       int(d["resident_budget_bytes"])
                       if d.get("resident_budget_bytes") is not None
                       else None))


class GatewayTenants:
    """The gateway's tenant table: auth, rate buckets, FairQueue maps.

    With `require_auth=False` and no policies (the default when no
    config file is given) every request is accepted under the tenant
    name it claims — the open configuration the smoke/bench modes and
    single-operator setups use."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 require_auth: bool = False,
                 default_tenant: str = "default",
                 analysis_default: Optional[object] = None):
        self.policies = dict(policies or {})
        self.require_auth = bool(require_auth)
        self.default_tenant = default_tenant
        # top-level "analysis" table: the AnalysisPolicy for tenants
        # without their own (None = no static vetting)
        self.analysis_default = analysis_default
        self._by_key = {p.api_key: p for p in self.policies.values()
                        if p.api_key}
        self._buckets: Dict[str, TokenBucket] = {}
        for p in self.policies.values():
            if p.rate_per_s is not None:
                self._buckets[p.name] = TokenBucket(
                    p.rate_per_s, p.burst if p.burst is not None
                    else 2.0 * p.rate_per_s)

    # -- config file -------------------------------------------------------
    @classmethod
    def from_file(cls, path: str) -> "GatewayTenants":
        """JSON (default) or TOML (*.toml, stdlib tomllib) tenant file."""
        if str(path).endswith(".toml"):
            import tomllib

            with open(path, "rb") as f:
                doc = tomllib.load(f)
        else:
            import json

            with open(path) as f:
                doc = json.load(f)
        return cls.from_dict(doc)

    @classmethod
    def from_dict(cls, doc: dict) -> "GatewayTenants":
        policies = {name: TenantPolicy.from_dict(name, d)
                    for name, d in (doc.get("tenants") or {}).items()}
        analysis_default = None
        if doc.get("analysis") is not None:
            from wasmedge_tpu.analysis.policy import AnalysisPolicy

            analysis_default = AnalysisPolicy.from_dict(doc["analysis"])
        return cls(policies=policies,
                   require_auth=bool(doc.get("require_auth", False)),
                   default_tenant=doc.get("default_tenant", "default"),
                   analysis_default=analysis_default)

    # -- FairQueue bridge --------------------------------------------------
    def weights(self) -> Dict[str, float]:
        return {p.name: p.weight for p in self.policies.values()}

    def quotas(self) -> Dict[str, int]:
        return {p.name: p.quota for p in self.policies.values()
                if p.quota is not None}

    def resident_budgets(self) -> Dict[str, int]:
        """tenant -> resident-bytes budget for the lane-virtualization
        layer (BatchServer resident_budgets=); tenants without one are
        uncapped."""
        return {p.name: p.resident_budget_bytes
                for p in self.policies.values()
                if p.resident_budget_bytes is not None}

    # -- load shedding -----------------------------------------------------
    def effective_weight(self, tenant: str) -> float:
        """The DRR weight a tenant submits under (1.0 when it has no
        policy — the FairQueue default)."""
        p = self.policies.get(tenant)
        return p.weight if p is not None else 1.0

    def shed_weight_floor(self) -> Optional[float]:
        """The weight tier a degraded gateway sheds: the LOWEST
        effective weight across the tiers that can actually submit —
        the configured policies, plus the 1.0 default tier ONLY in an
        open (no-require_auth) configuration where unlisted tenants
        exist.  None when only one tier exists: with every tenant
        equal there is no "lowest" to sacrifice, and shedding everyone
        would turn degradation into an outage (under require_auth, two
        tenants both at weight 0.5 are ONE tier — the phantom 1.0
        default must not make them sheddable)."""
        tiers = {p.weight for p in self.policies.values()}
        if not self.require_auth:
            tiers.add(1.0)   # unlisted tenants ride the default tier
        if len(tiers) < 2:
            return None
        return min(tiers)

    # -- edge checks -------------------------------------------------------
    def authenticate(self, api_key: Optional[str],
                     claimed: Optional[str]) -> str:
        """Resolve the request's tenant.  A presented key must be known
        and wins over (must agree with) any claimed tenant name; with
        require_auth no key is a 401 — and even WITHOUT require_auth, a
        tenant that has an api_key configured can only be claimed by
        presenting it (a keyless claim must not inherit the tenant's
        weight/quota/registration privilege)."""
        if api_key is not None:
            p = self._by_key.get(api_key)
            if p is None:
                raise AuthError("unknown API key")
            if claimed and claimed != p.name:
                raise AuthError(
                    f"API key belongs to tenant {p.name!r}, "
                    f"not {claimed!r}")
            return p.name
        if self.require_auth:
            raise AuthError("missing API key")
        claimed = claimed or self.default_tenant
        p = self.policies.get(claimed)
        if p is not None and p.api_key:
            raise AuthError(
                f"tenant {claimed!r} requires an API key")
        return claimed

    def check_rate(self, tenant: str):
        """Raise RateLimited when the tenant's bucket is empty."""
        b = self._buckets.get(tenant)
        if b is None:
            return
        after = b.try_take()
        if after is not None:
            raise RateLimited(tenant, after)

    def admission_policy(self, tenant: Optional[str]):
        """The AnalysisPolicy governing modules `tenant` registers:
        the tenant's own `analysis` table, else the file-level default,
        else None (no static vetting).  The gateway only consults it
        for tenant-attributed registrations — boot/preload modules
        (tenant None) are operator-trusted and never policy-gated."""
        p = self.policies.get(tenant) if tenant else None
        if p is not None and p.analysis is not None:
            return p.analysis
        return self.analysis_default

    def can_register(self, tenant: str) -> bool:
        p = self.policies.get(tenant)
        if p is None:
            # unknown tenants may register only in the open (no-auth,
            # no-policy) configuration
            return not self.require_auth and not self.policies
        return p.can_register
