"""Host modules: WASI preview1 + wasmedge_process.

Mirrors the reference's lib/host/ tree. Host functions serve both engines:
the scalar engine calls them inline (helper.cpp:35-97 analog) and the batch
engine reaches them through the device->host outcall buffer (SURVEY.md §5.8).
"""
