"""wasmedge_process host module: sandboxed subprocess execution.

Mirrors /root/reference/lib/host/wasmedge_process/{processmodule.cpp:15-35,
processfunc.cpp:1-343} and processenv.h:15-41: staged command construction
(set_prog_name/add_arg/add_env/add_stdin/set_timeout), run with an
allow-list policy (AllowedCmd / AllowedAll), and exit-code/stdout/stderr
retrieval.
"""

from __future__ import annotations

import subprocess
from typing import List, Optional, Set

from wasmedge_tpu.runtime.hostfunc import HostFunctionBase, ImportObject

MASK32 = 0xFFFFFFFF


class ProcessEnviron:
    """reference: include/host/wasmedge_process/processenv.h:15-41"""

    TIMEOUT_CODE = 0xFFFFFFFF  # reference: ExpectedLifeTime exceeded marker

    def __init__(self):
        self.name: str = ""
        self.args: List[str] = []
        self.envs: dict = {}
        self.stdin: bytes = b""
        self.timeout_ms: int = 10_000  # reference default DEFAULT_TIMEOUT
        self.exit_code: int = 0
        self.stdout: bytes = b""
        self.stderr: bytes = b""
        self.allowed_cmds: Set[str] = set()
        self.allowed_all: bool = False

    def reset_staging(self):
        self.name = ""
        self.args = []
        self.envs = {}
        self.stdin = b""
        self.timeout_ms = 10_000


class _ProcFn(HostFunctionBase):
    def __init__(self, name, params, results, fn):
        super().__init__(params, results, name=name)
        self._fn = fn

    def body(self, mem, *args):
        return self._fn(mem, *args)


class WasmEdgeProcessModule(ImportObject):
    MODULE_NAME = "wasmedge_process"

    def __init__(self, allowed_cmds: Optional[List[str]] = None,
                 allow_all: bool = False):
        super().__init__(self.MODULE_NAME)
        self.env = ProcessEnviron()
        self.env.allowed_cmds = set(allowed_cmds or [])
        self.env.allowed_all = allow_all
        e = self.env

        def set_prog_name(mem, ptr, ln):
            e.name = mem.load_bytes(ptr & MASK32, ln & MASK32).decode()

        def add_arg(mem, ptr, ln):
            e.args.append(mem.load_bytes(ptr & MASK32, ln & MASK32).decode())

        def add_env(mem, nptr, nlen, vptr, vlen):
            key = mem.load_bytes(nptr & MASK32, nlen & MASK32).decode()
            val = mem.load_bytes(vptr & MASK32, vlen & MASK32).decode()
            e.envs[key] = val

        def add_stdin(mem, ptr, ln):
            e.stdin += mem.load_bytes(ptr & MASK32, ln & MASK32)

        def set_timeout(mem, ms):
            e.timeout_ms = ms & MASK32

        def run(mem):
            # Allow-list policy (reference: processfunc.cpp run policy).
            if not e.allowed_all and e.name not in e.allowed_cmds:
                e.stdout = b""
                e.stderr = (f"Permission denied: command \"{e.name}\" is not "
                            f"in the white list. Please use --allow-command="
                            f"{e.name} or --allow-command-all to config it."
                            ).encode()
                e.exit_code = 0xFFFFFFFF
                e.reset_staging()
                return -1 & MASK32
            try:
                # env is always the staged dict — an empty dict means an
                # empty child environment, never host-environ inheritance
                # (the reference builds envp solely from staged entries).
                cp = subprocess.run(
                    [e.name] + e.args, input=e.stdin, env=e.envs,
                    capture_output=True, timeout=e.timeout_ms / 1000.0)
                e.exit_code = cp.returncode & MASK32
                e.stdout, e.stderr = cp.stdout, cp.stderr
            except subprocess.TimeoutExpired as te:
                e.exit_code = ProcessEnviron.TIMEOUT_CODE
                e.stdout = te.stdout or b""
                e.stderr = te.stderr or b""
            except OSError as ex:
                e.exit_code = 0xFFFFFFFF
                e.stdout = b""
                e.stderr = str(ex).encode()
            e.reset_staging()
            return e.exit_code

        def get_exit_code(mem):
            return e.exit_code

        def get_stdout_len(mem):
            return len(e.stdout)

        def get_stdout(mem, ptr):
            mem.store_bytes(ptr & MASK32, e.stdout)

        def get_stderr_len(mem):
            return len(e.stderr)

        def get_stderr(mem, ptr):
            mem.store_bytes(ptr & MASK32, e.stderr)

        for name, params, results, fn in [
            ("wasmedge_process_set_prog_name", ["i32", "i32"], [], set_prog_name),
            ("wasmedge_process_add_arg", ["i32", "i32"], [], add_arg),
            ("wasmedge_process_add_env", ["i32"] * 4, [], add_env),
            ("wasmedge_process_add_stdin", ["i32", "i32"], [], add_stdin),
            ("wasmedge_process_set_timeout", ["i32"], [], set_timeout),
            ("wasmedge_process_run", [], ["i32"], run),
            ("wasmedge_process_get_exit_code", [], ["i32"], get_exit_code),
            ("wasmedge_process_get_stdout_len", [], ["i32"], get_stdout_len),
            ("wasmedge_process_get_stdout", ["i32"], [], get_stdout),
            ("wasmedge_process_get_stderr_len", [], ["i32"], get_stderr_len),
            ("wasmedge_process_get_stderr", ["i32"], [], get_stderr),
        ]:
            self.add_func(name, _ProcFn(name, params, results, fn))


__all__ = ["WasmEdgeProcessModule", "ProcessEnviron"]
