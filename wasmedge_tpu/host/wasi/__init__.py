"""WasiModule: the wasi_snapshot_preview1 host module.

Mirrors /root/reference/lib/host/wasi/wasimodule.cpp:12-76 — registers the
same 60 host functions over a shared WASI::Environ. WasiError unwinds are
converted to errno returns at this boundary (the reference does the same
inside each body); WasiExit (proc_exit) propagates to terminate execution.
"""

from __future__ import annotations

from typing import Optional

from wasmedge_tpu.host.wasi.environ import WasiEnviron, WasiError, WasiExit
from wasmedge_tpu.host.wasi.wasifunc import WASI_FUNCS
from wasmedge_tpu.runtime.hostfunc import HostFunctionBase, ImportObject


class WasiHostFunction(HostFunctionBase):
    def __init__(self, name: str, fn, params, results, env: WasiEnviron):
        super().__init__(params, results, cost=0, name=name)
        self._fn = fn
        self._env = env

    def body(self, mem, *args):
        from wasmedge_tpu.common.errors import ErrCode, TrapError
        from wasmedge_tpu.host.wasi.wasi_abi import Errno

        try:
            out = self._fn(self._env, mem, *args)
        except WasiError as e:
            out = e.errno
        except TrapError as e:
            # Bad guest pointers become EFAULT, matching the reference's
            # pointer validation (wasifunc.cpp MemInst->getPointer checks).
            if e.code != ErrCode.MemoryOutOfBounds:
                raise
            out = Errno.FAULT
        if not self.functype.results:
            return None
        return out


class WasiModule(ImportObject):
    """Import object "wasi_snapshot_preview1" with live Environ state."""

    MODULE_NAME = "wasi_snapshot_preview1"

    def __init__(self):
        super().__init__(self.MODULE_NAME)
        self.env = WasiEnviron()
        self.env.init()
        for name, (fn, params, results) in WASI_FUNCS.items():
            self.add_func(name, WasiHostFunction(name, fn, params, results,
                                                 self.env))

    def get_env(self) -> WasiEnviron:
        return self.env

    def init_wasi(self, dirs=None, prog_name: str = "wasm", args=None,
                  envs=None):
        """reference: WasiModule->getEnv().init (wasmedger.cpp:216-221)."""
        self.env.fini()
        self.env.init(dirs=dirs, prog_name=prog_name, args=args, envs=envs)

    @property
    def exit_code(self) -> int:
        return self.env.exit_code


__all__ = ["WasiModule", "WasiEnviron", "WasiError", "WasiExit"]
