"""Per-VM WASI state: args/envs/preopens, capability fd table, exit code.

Mirrors the reference WASI::Environ + VINode/VFS + INode stack
(/root/reference/include/host/wasi/environ.h:38-1156, vinode.h:1-765,
inode.h:160-698) collapsed into one POSIX layer: each fd carries
{base rights, inheriting rights} capabilities checked before every
operation, guest paths resolve against preopened directory roots with
sandbox-escape prevention, and proc_exit records the exit code.
"""

from __future__ import annotations

import os
import stat as stat_mod
import time
from typing import Dict, List, Optional, Tuple

from wasmedge_tpu.host.wasi.wasi_abi import (
    Errno,
    Fdflags,
    Filetype,
    Rights,
    from_oserror,
)


class WasiError(Exception):
    """Internal unwinding for WASI syscall failures; becomes an errno."""

    def __init__(self, errno: int):
        self.errno = errno


class WasiExit(Exception):
    """proc_exit: unwinds the whole execution with an exit code."""

    def __init__(self, code: int):
        self.code = code
        super().__init__(f"wasi proc_exit({code})")


class FdEntry:
    """One open descriptor with its capability set (environ.h fd table)."""

    __slots__ = ("kind", "os_fd", "sock", "rights_base", "rights_inheriting",
                 "fdflags", "preopen_name", "host_path", "dir_cache")

    def __init__(self, kind: str, os_fd: int = -1, sock=None,
                 rights_base: int = 0, rights_inheriting: int = 0,
                 fdflags: int = 0, preopen_name: Optional[str] = None,
                 host_path: Optional[str] = None):
        self.kind = kind  # "file" | "dir" | "stdio" | "socket" | "prestat-dir"
        self.os_fd = os_fd
        self.sock = sock
        self.rights_base = rights_base
        self.rights_inheriting = rights_inheriting
        self.fdflags = fdflags
        self.preopen_name = preopen_name  # guest-visible preopen path
        self.host_path = host_path
        self.dir_cache = None  # readdir snapshot


_NSEC = 1_000_000_000


def _filetype_of_mode(mode: int) -> int:
    if stat_mod.S_ISREG(mode):
        return Filetype.REGULAR_FILE
    if stat_mod.S_ISDIR(mode):
        return Filetype.DIRECTORY
    if stat_mod.S_ISLNK(mode):
        return Filetype.SYMBOLIC_LINK
    if stat_mod.S_ISCHR(mode):
        return Filetype.CHARACTER_DEVICE
    if stat_mod.S_ISBLK(mode):
        return Filetype.BLOCK_DEVICE
    if stat_mod.S_ISSOCK(mode):
        return Filetype.SOCKET_STREAM
    return Filetype.UNKNOWN


class WasiEnviron:
    """reference: WASI::Environ (init/fini, lib/host/wasi/environ.cpp)."""

    def __init__(self):
        self.args: List[str] = []
        self.envs: List[str] = []
        self.fds: Dict[int, FdEntry] = {}
        self.exit_code: int = 0
        self.exited: bool = False
        self._next_fd = 3

    # -- lifecycle (environ.h init/fini) -----------------------------------
    def init(self, dirs: Optional[List[str]] = None, prog_name: str = "wasm",
             args: Optional[List[str]] = None,
             envs: Optional[List[str]] = None):
        """dirs entries are "guest_path:host_path" or "path" (both sides
        equal) — the CLI --dir syntax (tools/wasmedge/wasmedger.cpp:41-47)."""
        self.args = [prog_name] + list(args or [])
        self.envs = list(envs or [])
        self.fds = {
            0: FdEntry("stdio", os_fd=0, rights_base=Rights.FD_READ
                       | Rights.FD_FDSTAT_SET_FLAGS | Rights.POLL_FD_READWRITE
                       | Rights.FD_FILESTAT_GET),
            1: FdEntry("stdio", os_fd=1, rights_base=Rights.FD_WRITE
                       | Rights.FD_FDSTAT_SET_FLAGS | Rights.POLL_FD_READWRITE
                       | Rights.FD_FILESTAT_GET),
            2: FdEntry("stdio", os_fd=2, rights_base=Rights.FD_WRITE
                       | Rights.FD_FDSTAT_SET_FLAGS | Rights.POLL_FD_READWRITE
                       | Rights.FD_FILESTAT_GET),
        }
        self._next_fd = 3
        self.exit_code = 0
        self.exited = False
        for spec in dirs or []:
            guest, sep, host = spec.partition(":")
            if not sep:
                host = guest
            self._add_preopen(guest or "/", host)

    def fini(self):
        for fd, e in list(self.fds.items()):
            if e.kind in ("file", "dir", "prestat-dir") and e.os_fd >= 0:
                try:
                    os.close(e.os_fd)
                except OSError:
                    pass
            if e.sock is not None:
                try:
                    e.sock.close()
                except OSError:
                    pass
        self.fds.clear()

    def _add_preopen(self, guest: str, host: str):
        fd = os.open(host, os.O_RDONLY | os.O_DIRECTORY)
        entry = FdEntry(
            "prestat-dir", os_fd=fd,
            rights_base=Rights.DIR_BASE,
            rights_inheriting=Rights.DIR_BASE | Rights.FILE_BASE,
            preopen_name=guest, host_path=os.path.realpath(host))
        self.fds[self._alloc_fd()] = entry

    def _alloc_fd(self) -> int:
        fd = self._next_fd
        while fd in self.fds:
            fd += 1
        self._next_fd = fd + 1
        return fd

    # -- fd helpers --------------------------------------------------------
    def get_fd(self, fd: int, required_rights: int = 0) -> FdEntry:
        e = self.fds.get(fd)
        if e is None:
            raise WasiError(Errno.BADF)
        if required_rights & ~e.rights_base:
            raise WasiError(Errno.NOTCAPABLE)
        return e

    def insert_entry(self, entry: FdEntry) -> int:
        fd = self._alloc_fd()
        self.fds[fd] = entry
        return fd

    def close_fd(self, fd: int):
        e = self.fds.pop(fd, None)
        if e is None:
            raise WasiError(Errno.BADF)
        try:
            if e.sock is not None:
                e.sock.close()
            elif e.kind != "stdio" and e.os_fd >= 0:
                os.close(e.os_fd)
        except OSError as ex:
            raise WasiError(from_oserror(ex))

    # -- path resolution (VINode::resolvePath analog) ----------------------
    def resolve_path(self, dirfd_entry: FdEntry, guest_path: str,
                     follow_final: bool = True) -> str:
        """Resolve a guest path against a preopened dir into a host path,
        refusing escapes (reference: lib/host/wasi/vinode.cpp path walk).

        Every intermediate symlink is resolved and re-checked against the
        sandbox root, so `a/../../x` and absolute/rooted symlinks cannot
        break out.

        Known limitation (TOCTOU): the walk is check-then-use over string
        paths — a component swapped for a symlink between this check and
        the caller's open() can escape the preopen. The reference walks
        with per-component openat()-style fds (lib/host/wasi/vinode.cpp);
        matching that here needs os.open(O_NOFOLLOW|O_DIRECTORY) dir_fd
        plumbing through every caller. Single-tenant CLI use (trusted
        host filesystem, untrusted guest) is unaffected; do not rely on
        this sandbox against an adversary that can mutate the preopened
        tree concurrently.
        """
        if dirfd_entry.host_path is None:
            raise WasiError(Errno.NOTDIR)
        root = dirfd_entry.host_path
        parts = [p for p in guest_path.split("/") if p not in ("", ".")]
        cur = root
        i = 0
        depth = 0
        last_was_dotdot = False
        while i < len(parts):
            if depth > 64:
                raise WasiError(Errno.LOOP)
            part = parts[i]
            if part == "..":
                if os.path.realpath(cur) == root:
                    raise WasiError(Errno.NOTCAPABLE)  # escape attempt
                cur = os.path.dirname(cur)
                last_was_dotdot = True
                i += 1
                continue
            nxt = os.path.join(cur, part)
            is_final = i == len(parts) - 1
            if os.path.islink(nxt) and (follow_final or not is_final):
                target = os.readlink(nxt)
                if target.startswith("/"):
                    raise WasiError(Errno.NOTCAPABLE)
                parts = target.split("/") + parts[i + 1:]
                parts = [p for p in parts if p not in ("", ".")]
                i = 0
                depth += 1
                continue
            cur = nxt
            last_was_dotdot = False
            i += 1
        # Final containment check. After a trailing ".." `cur` itself is the
        # already-walked target directory; otherwise the directory that will
        # contain the final component must be inside the root.
        if not parts:
            rp = root
        elif last_was_dotdot:
            rp = os.path.realpath(cur)
        else:
            rp = os.path.realpath(os.path.dirname(cur))
        if not (rp == root or rp.startswith(root + os.sep)):
            raise WasiError(Errno.NOTCAPABLE)
        return cur

    # -- clocks ------------------------------------------------------------
    @staticmethod
    def clock_time(clock_id: int) -> int:
        from wasmedge_tpu.host.wasi.wasi_abi import Clockid

        if clock_id == Clockid.REALTIME:
            return time.time_ns()
        if clock_id == Clockid.MONOTONIC:
            return time.monotonic_ns()
        if clock_id == Clockid.PROCESS_CPUTIME_ID:
            return time.process_time_ns()
        if clock_id == Clockid.THREAD_CPUTIME_ID:
            return time.thread_time_ns()
        raise WasiError(Errno.INVAL)

    @staticmethod
    def clock_res(clock_id: int) -> int:
        from wasmedge_tpu.host.wasi.wasi_abi import Clockid

        if clock_id in (Clockid.REALTIME, Clockid.MONOTONIC,
                        Clockid.PROCESS_CPUTIME_ID, Clockid.THREAD_CPUTIME_ID):
            return 1  # nanosecond clocks on linux
        raise WasiError(Errno.INVAL)

    # -- stat helpers ------------------------------------------------------
    @staticmethod
    def filestat_tuple(st: os.stat_result) -> Tuple[int, ...]:
        return (st.st_dev, st.st_ino, _filetype_of_mode(st.st_mode),
                st.st_nlink, st.st_size,
                st.st_atime_ns, st.st_mtime_ns, st.st_ctime_ns)
