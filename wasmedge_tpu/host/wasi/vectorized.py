"""SoA-vectorized WASI implementations for the batch outcall channel.

Tier 1 of the three-tier hostcall pipeline (batch/hostcall.py): when the
batch engines drain parked lanes, lanes are grouped by hostcall id and
each group of a recognized WASI function is served by ONE vectorized
NumPy implementation over the [words, lanes] memory plane — replacing
the per-lane Python loop through host/wasi/wasifunc.py that materialized
a 64 KiB bytearray per lane per call.  Semantics mirror the scalar
functions (same errno surface, same pointer-fault behavior: a bad guest
pointer is EFAULT, matching WasiHostFunction's TrapError translation).

Implementations receive:
  env   the group's WasiEnviron (per-tenant in multi-tenant batches)
  view  a MemView over the group's lane columns (vectorized byte access)
  args  int64 [nargs, n] raw argument cells

and return (results [nres, n] int64, trap_codes [n] int32).  Raising
NotVectorizable routes the whole group to the per-lane fallback loop
(e.g. sockets, oversized iovec arrays).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict

import numpy as np

from wasmedge_tpu.host.wasi.environ import WasiEnviron, WasiError
from wasmedge_tpu.host.wasi.wasi_abi import Errno, Rights

MASK32 = 0xFFFFFFFF

# iovec arrays longer than this are rare enough that the per-lane loop
# is fine (and keeps the vectorized path's word gathers bounded)
MAX_VEC_IOVS = 8


class NotVectorizable(Exception):
    """Group cannot be served vectorized; use the per-lane loop."""


class MemView:
    """Vectorized byte accessor over a word-major int32 plane restricted
    to a set of lane columns.

    `_words` / per-lane byte stores are the only backend-specific
    primitives: SoAMemView indexes a NumPy plane directly (SIMT serve),
    CachedPlaneView (batch/hostcall.py) goes through the chunked device
    cache so a tunneled TPU only downloads touched 4 KiB windows."""

    def __init__(self, lanes, pages):
        self.lanes = np.asarray(lanes, np.int64)
        self.n = int(self.lanes.size)
        self.pages = np.broadcast_to(
            np.asarray(pages, np.int64), (self.n,))

    # -- backend primitives -------------------------------------------------
    def _words(self, widx: np.ndarray) -> np.ndarray:
        """Gather int32 words: widx [k, n] row indices -> [k, n]."""
        raise NotImplementedError

    def _store_bytes_one(self, i: int, off: int, data: bytes):
        """Store bytes into view-lane i's memory at byte offset off."""
        raise NotImplementedError

    # -- shared vectorized layer --------------------------------------------
    def bounds_ok(self, off, ln) -> np.ndarray:
        off = np.asarray(off, np.uint64)
        ln = np.broadcast_to(np.asarray(ln, np.uint64), off.shape)
        end = off + ln
        return (end >= off) & (end <= self.pages.astype(np.uint64)
                               * np.uint64(65536))

    def load_u32(self, off) -> np.ndarray:
        off = np.asarray(off, np.int64)
        w0 = off >> 2
        ws = self._words(np.stack([w0, w0 + 1]))
        lo = ws[0].view(np.uint32).astype(np.uint64)
        hi = ws[1].view(np.uint32).astype(np.uint64)
        sh = ((off & 3) * 8).astype(np.uint64)
        return ((lo | (hi << np.uint64(32))) >> sh).astype(np.uint32)

    def gather_bytes(self, off, ln) -> list:
        """Per-lane bytes objects for ranges [off, off+ln); caller has
        bounds-checked.  One fancy gather covers every lane."""
        off = np.asarray(off, np.int64)
        ln = np.asarray(ln, np.int64)
        if self.n == 0:
            return []
        maxb = int(((off & 3) + ln).max(initial=0))
        if maxb == 0:
            return [b""] * self.n
        maxw = (maxb + 3) // 4
        idx = (off >> 2)[None, :] + np.arange(maxw, dtype=np.int64)[:, None]
        words = self._words(idx)                       # [maxw, n]
        raw = np.ascontiguousarray(words.T).view(np.uint8)  # [n, maxw*4]
        out = []
        for i in range(self.n):
            s = int(off[i] & 3)
            out.append(raw[i, s:s + int(ln[i])].tobytes())
        return out

    def store_u32(self, off, vals, mask=None):
        self._store_scalar(off, np.asarray(vals, np.uint64), 4, mask)

    def store_u64(self, off, vals, mask=None):
        self._store_scalar(off, np.asarray(vals, np.uint64), 8, mask)

    def _store_scalar(self, off, vals, nbytes, mask):
        off = np.asarray(off, np.int64)
        m = np.ones(self.n, bool) if mask is None \
            else np.asarray(mask, bool).copy()
        m &= np.asarray(self.bounds_ok(off, nbytes))
        for i in np.nonzero(m)[0]:
            self._store_bytes_one(
                int(i), int(off[i]),
                int(vals[i]).to_bytes(nbytes, "little"))

    def store_bytes(self, off, datas, mask=None):
        off = np.asarray(off, np.int64)
        m = np.ones(self.n, bool) if mask is None else np.asarray(mask, bool)
        for i in np.nonzero(m)[0]:
            if datas[i]:
                self._store_bytes_one(int(i), int(off[i]), datas[i])


class SoAMemView(MemView):
    """MemView over a host-resident NumPy [W, L] plane (mutated in
    place; the SIMT serve uploads the plane back once per round)."""

    def __init__(self, plane: np.ndarray, lanes, pages):
        super().__init__(lanes, pages)
        self.plane = plane
        self.W = int(plane.shape[0])
        self.dirty = False

    def _words(self, widx):
        w = np.clip(widx, 0, self.W - 1)
        return self.plane[w, self.lanes[None, :]]

    def _store_bytes_one(self, i, off, data):
        lane = int(self.lanes[i])
        w0 = off >> 2
        w1 = (off + len(data) - 1) >> 2
        cur = bytearray(
            np.ascontiguousarray(self.plane[w0:w1 + 1, lane]).tobytes())
        s = off & 3
        cur[s:s + len(data)] = data
        self.plane[w0:w1 + 1, lane] = np.frombuffer(bytes(cur), np.int32)
        self.dirty = True


# ---------------------------------------------------------------------------
# vectorized implementations
# ---------------------------------------------------------------------------
VEC_WASI: Dict[str, Callable] = {}

# Flight recorder the tier-1 drain reports per-hostcall-kind latency
# histograms into (obs/recorder.py).  Installed by the serving loops
# (batch/hostcall.py serve_batch_state, pallas_engine's block serve)
# for the duration of one drain round; None when observability is off,
# so the registered implementations run with zero timing overhead.
# THREAD-LOCAL: concurrent serves (mesh per-device threads, multiple
# VMs in one process) each install/restore their own engine's recorder
# without clobbering another thread's attribution.
_DRAIN = threading.local()


def set_drain_recorder(rec):
    """Install this thread's recorder for the drain round (None = off);
    returns the previous one so callers can restore it."""
    prev = getattr(_DRAIN, "rec", None)
    _DRAIN.rec = rec if (rec is not None
                         and getattr(rec, "enabled", False)) else None
    return prev


def _vec(name: str):
    def deco(fn):
        def timed(env, view, args):
            rec = getattr(_DRAIN, "rec", None)
            if rec is None:
                return fn(env, view, args)
            t0 = rec.now()
            # NotVectorizable propagates untimed: the group re-runs on
            # the per-lane loop, which records its own observation
            out = fn(env, view, args)
            rec.hostcall(name, rec.now() - t0, lanes=view.n,
                         vectorized=True)
            return out

        timed.__name__ = f"vec_{name}"
        timed.inner = fn
        VEC_WASI[name] = timed
        return fn
    return deco


def _zeros_res(n: int, nres: int = 1):
    return np.zeros((nres, n), np.int64), np.zeros(n, np.int32)


@_vec("sched_yield")
def vec_sched_yield(env: WasiEnviron, view: MemView, args):
    os.sched_yield()
    return _zeros_res(view.n)


@_vec("proc_exit")
def vec_proc_exit(env: WasiEnviron, view: MemView, args):
    """Every lane in the group terminates (ErrCode.Terminated); the
    environ records the last lane's code like the scalar path records
    the (single) instance's."""
    from wasmedge_tpu.common.errors import ErrCode

    env.exit_code = int(args[0][-1] & MASK32)
    env.exited = True
    res = np.zeros((0, view.n), np.int64)
    return res, np.full(view.n, int(ErrCode.Terminated), np.int32)


@_vec("clock_time_get")
def vec_clock_time_get(env: WasiEnviron, view: MemView, args):
    n = view.n
    ids = (args[0] & MASK32).astype(np.int64)
    ptrs = (args[2] & MASK32).astype(np.int64)
    res = np.zeros(n, np.int64)
    ok = np.ones(n, bool)
    times = np.zeros(n, np.uint64)
    for cid in np.unique(ids):
        m = ids == cid
        try:
            times[m] = np.uint64(env.clock_time(int(cid)))
        except WasiError as werr:
            res[m] = int(werr.errno)
            ok[m] = False
    bok = view.bounds_ok(ptrs, 8)
    res[ok & ~bok] = int(Errno.FAULT)
    view.store_u64(ptrs, times, ok & bok)
    return res.reshape(1, n), np.zeros(n, np.int32)


@_vec("random_get")
def vec_random_get(env: WasiEnviron, view: MemView, args):
    n = view.n
    bufs = (args[0] & MASK32).astype(np.int64)
    lens = (args[1] & MASK32).astype(np.int64)
    bok = np.asarray(view.bounds_ok(bufs, lens))
    res = np.where(bok, 0, int(Errno.FAULT)).astype(np.int64)
    total = int(lens[bok].sum())
    blob = os.urandom(total)
    datas = [b""] * n
    pos = 0
    for i in np.nonzero(bok)[0]:
        ln = int(lens[i])
        datas[i] = blob[pos:pos + ln]
        pos += ln
    view.store_bytes(bufs, datas, bok)
    return res.reshape(1, n), np.zeros(n, np.int32)


@_vec("fd_write")
def vec_fd_write(env: WasiEnviron, view: MemView, args):
    n = view.n
    fds = (args[0] & MASK32).astype(np.int64)
    iovs = (args[1] & MASK32).astype(np.int64)
    cnt = (args[2] & MASK32).astype(np.int64)
    nwp = (args[3] & MASK32).astype(np.int64)
    if int(cnt.max(initial=0)) > MAX_VEC_IOVS:
        raise NotVectorizable("iovec array too long")
    res = np.zeros(n, np.int64)
    live = np.ones(n, bool)

    # resolve fds once per distinct value; sockets keep scalar semantics
    entries = {}
    for fd in np.unique(fds):
        try:
            e = env.get_fd(int(fd), Rights.FD_WRITE)
        except WasiError as werr:
            m = fds == fd
            res[m] = int(werr.errno)
            live[m] = False
            continue
        if e.kind == "socket":
            raise NotVectorizable("socket write")
        entries[int(fd)] = e

    # iovec array bounds (scalar: _read_iovs check_bounds -> EFAULT)
    arr_ok = np.asarray(view.bounds_ok(iovs, 8 * cnt))
    res[live & ~arr_ok] = int(Errno.FAULT)
    live &= arr_ok

    datas = [[] for _ in range(n)]
    total = np.zeros(n, np.int64)
    for j in range(int(cnt.max(initial=0))):
        has = live & (j < cnt)
        if not has.any():
            break
        bufs = view.load_u32(iovs + 8 * j).astype(np.int64)
        lens = view.load_u32(iovs + 8 * j + 4).astype(np.int64)
        lens = np.where(has, lens, 0)
        dok = np.asarray(view.bounds_ok(bufs, lens))
        bad = has & ~dok
        # scalar: load_bytes faults -> EFAULT; earlier iovecs were
        # already written (same here: collected chunks still go out)
        res[bad] = int(Errno.FAULT)
        live &= dok | ~has
        lens = np.where(has & dok, lens, 0)
        chunks = view.gather_bytes(bufs, lens)
        for i in np.nonzero(has & dok)[0]:
            if chunks[i]:
                datas[i].append(chunks[i])
                total[i] += len(chunks[i])

    # one write per fd, lane-ascending (matches per-lane serve order)
    for fd, e in sorted(entries.items()):
        out = b"".join(b"".join(datas[i])
                       for i in np.nonzero(fds == fd)[0])
        _write_all(e, out)

    wrote = total.astype(np.uint64)
    np_ok = np.asarray(view.bounds_ok(nwp, 4))
    res[live & ~np_ok] = int(Errno.FAULT)
    view.store_u32(nwp, wrote, live & np_ok)
    return res.reshape(1, n), np.zeros(n, np.int32)


def _write_all(entry, data: bytes):
    off = 0
    while off < len(data):
        off += os.write(entry.os_fd, data[off:])
