"""WASI snapshot_preview1 ABI constants and struct layouts.

The reference vendors a witx-generated header (thirdparty/wasi/api.hpp,
see /root/reference/lib/host/wasi/wasifunc.cpp for usage). These are the
same wire-stable constants, transcribed from the public WASI preview1 spec.
All structs are little-endian, matching wasm linear memory.
"""

from __future__ import annotations

import struct


# -- errno ------------------------------------------------------------------
class Errno:
    SUCCESS = 0
    TOOBIG = 1          # 2BIG
    ACCES = 2
    ADDRINUSE = 3
    ADDRNOTAVAIL = 4
    AFNOSUPPORT = 5
    AGAIN = 6
    ALREADY = 7
    BADF = 8
    BADMSG = 9
    BUSY = 10
    CANCELED = 11
    CHILD = 12
    CONNABORTED = 13
    CONNREFUSED = 14
    CONNRESET = 15
    DEADLK = 16
    DESTADDRREQ = 17
    DOM = 18
    DQUOT = 19
    EXIST = 20
    FAULT = 21
    FBIG = 22
    HOSTUNREACH = 23
    IDRM = 24
    ILSEQ = 25
    INPROGRESS = 26
    INTR = 27
    INVAL = 28
    IO = 29
    ISCONN = 30
    ISDIR = 31
    LOOP = 32
    MFILE = 33
    MLINK = 34
    MSGSIZE = 35
    MULTIHOP = 36
    NAMETOOLONG = 37
    NETDOWN = 38
    NETRESET = 39
    NETUNREACH = 40
    NFILE = 41
    NOBUFS = 42
    NODEV = 43
    NOENT = 44
    NOEXEC = 45
    NOLCK = 46
    NOLINK = 47
    NOMEM = 48
    NOMSG = 49
    NOPROTOOPT = 50
    NOSPC = 51
    NOSYS = 52
    NOTCONN = 53
    NOTDIR = 54
    NOTEMPTY = 55
    NOTRECOVERABLE = 56
    NOTSOCK = 57
    NOTSUP = 58
    NOTTY = 59
    NXIO = 60
    OVERFLOW = 61
    OWNERDEAD = 62
    PERM = 63
    PIPE = 64
    PROTO = 65
    PROTONOSUPPORT = 66
    PROTOTYPE = 67
    RANGE = 68
    ROFS = 69
    SPIPE = 70
    SRCH = 71
    STALE = 72
    TIMEDOUT = 73
    TXTBSY = 74
    XDEV = 75
    NOTCAPABLE = 76


import errno as _os_errno

# host OSError.errno -> wasi errno
_ERRNO_MAP = {
    _os_errno.E2BIG: Errno.TOOBIG, _os_errno.EACCES: Errno.ACCES,
    _os_errno.EADDRINUSE: Errno.ADDRINUSE,
    _os_errno.EADDRNOTAVAIL: Errno.ADDRNOTAVAIL,
    _os_errno.EAFNOSUPPORT: Errno.AFNOSUPPORT,
    _os_errno.EAGAIN: Errno.AGAIN, _os_errno.EALREADY: Errno.ALREADY,
    _os_errno.EBADF: Errno.BADF, _os_errno.EBADMSG: Errno.BADMSG,
    _os_errno.EBUSY: Errno.BUSY, _os_errno.ECANCELED: Errno.CANCELED,
    _os_errno.ECHILD: Errno.CHILD, _os_errno.ECONNABORTED: Errno.CONNABORTED,
    _os_errno.ECONNREFUSED: Errno.CONNREFUSED,
    _os_errno.ECONNRESET: Errno.CONNRESET,
    _os_errno.EDEADLK: Errno.DEADLK, _os_errno.EDESTADDRREQ: Errno.DESTADDRREQ,
    _os_errno.EDOM: Errno.DOM, _os_errno.EDQUOT: Errno.DQUOT,
    _os_errno.EEXIST: Errno.EXIST, _os_errno.EFAULT: Errno.FAULT,
    _os_errno.EFBIG: Errno.FBIG, _os_errno.EHOSTUNREACH: Errno.HOSTUNREACH,
    _os_errno.EIDRM: Errno.IDRM, _os_errno.EILSEQ: Errno.ILSEQ,
    _os_errno.EINPROGRESS: Errno.INPROGRESS, _os_errno.EINTR: Errno.INTR,
    _os_errno.EINVAL: Errno.INVAL, _os_errno.EIO: Errno.IO,
    _os_errno.EISCONN: Errno.ISCONN, _os_errno.EISDIR: Errno.ISDIR,
    _os_errno.ELOOP: Errno.LOOP, _os_errno.EMFILE: Errno.MFILE,
    _os_errno.EMLINK: Errno.MLINK, _os_errno.EMSGSIZE: Errno.MSGSIZE,
    _os_errno.EMULTIHOP: Errno.MULTIHOP,
    _os_errno.ENAMETOOLONG: Errno.NAMETOOLONG,
    _os_errno.ENETDOWN: Errno.NETDOWN, _os_errno.ENETRESET: Errno.NETRESET,
    _os_errno.ENETUNREACH: Errno.NETUNREACH, _os_errno.ENFILE: Errno.NFILE,
    _os_errno.ENOBUFS: Errno.NOBUFS, _os_errno.ENODEV: Errno.NODEV,
    _os_errno.ENOENT: Errno.NOENT, _os_errno.ENOEXEC: Errno.NOEXEC,
    _os_errno.ENOLCK: Errno.NOLCK, _os_errno.ENOLINK: Errno.NOLINK,
    _os_errno.ENOMEM: Errno.NOMEM, _os_errno.ENOMSG: Errno.NOMSG,
    _os_errno.ENOPROTOOPT: Errno.NOPROTOOPT, _os_errno.ENOSPC: Errno.NOSPC,
    _os_errno.ENOSYS: Errno.NOSYS, _os_errno.ENOTCONN: Errno.NOTCONN,
    _os_errno.ENOTDIR: Errno.NOTDIR, _os_errno.ENOTEMPTY: Errno.NOTEMPTY,
    _os_errno.ENOTSOCK: Errno.NOTSOCK, _os_errno.ENOTSUP: Errno.NOTSUP,
    _os_errno.ENOTTY: Errno.NOTTY, _os_errno.ENXIO: Errno.NXIO,
    _os_errno.EOVERFLOW: Errno.OVERFLOW, _os_errno.EPERM: Errno.PERM,
    _os_errno.EPIPE: Errno.PIPE, _os_errno.EPROTO: Errno.PROTO,
    _os_errno.EPROTONOSUPPORT: Errno.PROTONOSUPPORT,
    _os_errno.EPROTOTYPE: Errno.PROTOTYPE, _os_errno.ERANGE: Errno.RANGE,
    _os_errno.EROFS: Errno.ROFS, _os_errno.ESPIPE: Errno.SPIPE,
    _os_errno.ESRCH: Errno.SRCH, _os_errno.ESTALE: Errno.STALE,
    _os_errno.ETIMEDOUT: Errno.TIMEDOUT, _os_errno.ETXTBSY: Errno.TXTBSY,
    _os_errno.EXDEV: Errno.XDEV,
}


def from_oserror(e: OSError) -> int:
    return _ERRNO_MAP.get(e.errno, Errno.IO)


# -- rights (capability bits) ----------------------------------------------
class Rights:
    FD_DATASYNC = 1 << 0
    FD_READ = 1 << 1
    FD_SEEK = 1 << 2
    FD_FDSTAT_SET_FLAGS = 1 << 3
    FD_SYNC = 1 << 4
    FD_TELL = 1 << 5
    FD_WRITE = 1 << 6
    FD_ADVISE = 1 << 7
    FD_ALLOCATE = 1 << 8
    PATH_CREATE_DIRECTORY = 1 << 9
    PATH_CREATE_FILE = 1 << 10
    PATH_LINK_SOURCE = 1 << 11
    PATH_LINK_TARGET = 1 << 12
    PATH_OPEN = 1 << 13
    FD_READDIR = 1 << 14
    PATH_READLINK = 1 << 15
    PATH_RENAME_SOURCE = 1 << 16
    PATH_RENAME_TARGET = 1 << 17
    PATH_FILESTAT_GET = 1 << 18
    PATH_FILESTAT_SET_SIZE = 1 << 19
    PATH_FILESTAT_SET_TIMES = 1 << 20
    FD_FILESTAT_GET = 1 << 21
    FD_FILESTAT_SET_SIZE = 1 << 22
    FD_FILESTAT_SET_TIMES = 1 << 23
    PATH_SYMLINK = 1 << 24
    PATH_REMOVE_DIRECTORY = 1 << 25
    PATH_UNLINK_FILE = 1 << 26
    POLL_FD_READWRITE = 1 << 27
    SOCK_SHUTDOWN = 1 << 28
    SOCK_OPEN = 1 << 29
    SOCK_CLOSE = 1 << 30
    SOCK_RECV = 1 << 31
    SOCK_SEND = 1 << 32
    SOCK_BIND = 1 << 33

    ALL = (1 << 34) - 1
    # Directory-vs-file splits per the preview1 spec's recommended sets.
    DIR_BASE = (PATH_CREATE_DIRECTORY | PATH_CREATE_FILE | PATH_LINK_SOURCE
                | PATH_LINK_TARGET | PATH_OPEN | FD_READDIR | PATH_READLINK
                | PATH_RENAME_SOURCE | PATH_RENAME_TARGET | PATH_FILESTAT_GET
                | PATH_FILESTAT_SET_SIZE | PATH_FILESTAT_SET_TIMES
                | FD_FILESTAT_GET | FD_FILESTAT_SET_TIMES | PATH_SYMLINK
                | PATH_REMOVE_DIRECTORY | PATH_UNLINK_FILE)
    FILE_BASE = (FD_DATASYNC | FD_READ | FD_SEEK | FD_FDSTAT_SET_FLAGS
                 | FD_SYNC | FD_TELL | FD_WRITE | FD_ADVISE | FD_ALLOCATE
                 | FD_FILESTAT_GET | FD_FILESTAT_SET_SIZE
                 | FD_FILESTAT_SET_TIMES | POLL_FD_READWRITE)


# -- misc enums -------------------------------------------------------------
class Filetype:
    UNKNOWN = 0
    BLOCK_DEVICE = 1
    CHARACTER_DEVICE = 2
    DIRECTORY = 3
    REGULAR_FILE = 4
    SOCKET_DGRAM = 5
    SOCKET_STREAM = 6
    SYMBOLIC_LINK = 7


class Fdflags:
    APPEND = 1 << 0
    DSYNC = 1 << 1
    NONBLOCK = 1 << 2
    RSYNC = 1 << 3
    SYNC = 1 << 4


class Oflags:
    CREAT = 1 << 0
    DIRECTORY = 1 << 1
    EXCL = 1 << 2
    TRUNC = 1 << 3


class Lookupflags:
    SYMLINK_FOLLOW = 1 << 0


class Whence:
    SET = 0
    CUR = 1
    END = 2


class Clockid:
    REALTIME = 0
    MONOTONIC = 1
    PROCESS_CPUTIME_ID = 2
    THREAD_CPUTIME_ID = 3


class Eventtype:
    CLOCK = 0
    FD_READ = 1
    FD_WRITE = 2


class Subclockflags:
    ABSTIME = 1 << 0


class Fstflags:
    ATIM = 1 << 0
    ATIM_NOW = 1 << 1
    MTIM = 1 << 2
    MTIM_NOW = 1 << 3


class Preopentype:
    DIR = 0


class Sdflags:  # sock_shutdown how
    RD = 1 << 0
    WR = 1 << 1


# -- struct packers ---------------------------------------------------------
def pack_prestat_dir(name_len: int) -> bytes:
    return struct.pack("<BxxxI", Preopentype.DIR, name_len)


def pack_fdstat(filetype: int, flags: int, rights_base: int,
                rights_inheriting: int) -> bytes:
    return struct.pack("<BxHxxxxQQ", filetype, flags,
                       rights_base & 0xFFFFFFFFFFFFFFFF,
                       rights_inheriting & 0xFFFFFFFFFFFFFFFF)


def pack_filestat(dev: int, ino: int, filetype: int, nlink: int, size: int,
                  atim: int, mtim: int, ctim: int) -> bytes:
    return struct.pack("<QQBxxxxxxxQQQQQ", dev & (2**64 - 1), ino & (2**64 - 1),
                       filetype, nlink, size, atim, mtim, ctim)


def pack_dirent(next_cookie: int, ino: int, namlen: int, dtype: int) -> bytes:
    return struct.pack("<QQIBxxx", next_cookie, ino & (2**64 - 1), namlen, dtype)


DIRENT_SIZE = 24
FILESTAT_SIZE = 64
FDSTAT_SIZE = 24
PRESTAT_SIZE = 8
EVENT_SIZE = 32
SUBSCRIPTION_SIZE = 48


def pack_event(userdata: int, error: int, etype: int,
               nbytes: int = 0, evflags: int = 0) -> bytes:
    return struct.pack("<QHBxxxxxQHxxxxxx", userdata, error, etype,
                       nbytes, evflags)
