"""WASI snapshot_preview1 host functions (incl. the wasmedge socket ext).

Mirrors /root/reference/lib/host/wasi/wasifunc.cpp:1-2317 — the same 60
functions the reference registers (lib/host/wasi/wasimodule.cpp:12-76),
with pointer validation, rights checks, and errno returns. Each function
receives the caller's MemoryInstance and typed ints; failures become wasi
errno values, never Python exceptions (except proc_exit's WasiExit).
"""

from __future__ import annotations

import os
import select
import socket
import struct
from typing import Callable, Dict, List, Tuple

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.host.wasi import wasi_abi as abi
from wasmedge_tpu.host.wasi.environ import (
    FdEntry,
    WasiEnviron,
    WasiError,
    WasiExit,
)
from wasmedge_tpu.host.wasi.wasi_abi import (
    Clockid,
    Errno,
    Fdflags,
    Filetype,
    Lookupflags,
    Oflags,
    Rights,
    Whence,
    from_oserror,
)

MASK32 = 0xFFFFFFFF

# registry: name -> (fn(env, mem, *args), params, results)
WASI_FUNCS: Dict[str, Tuple[Callable, list, list]] = {}


def wasi_fn(name: str, params: str, results: str = "i"):
    """params is a string of i (i32) / I (i64) chars."""
    tmap = {"i": "i32", "I": "i64"}

    def deco(fn):
        WASI_FUNCS[name] = (fn, [tmap[c] for c in params],
                            [tmap[c] for c in results])
        return fn

    return deco


def _mem_required(mem):
    if mem is None:
        raise TrapError(ErrCode.ExecutionFailed, "wasi call with no memory")
    return mem


def _read_iovs(mem, iovs_ptr: int, iovs_len: int) -> List[Tuple[int, int]]:
    # Bound the iovec *array* before materializing it: the count is
    # guest-controlled and the per-entry address wrap (& MASK32) would
    # otherwise let a huge count spin the host unboundedly.  The reference
    # validates the full iovs span up front (wasifunc.cpp getIOVS).
    mem.check_bounds(iovs_ptr, 8 * iovs_len)
    out = []
    for k in range(iovs_len):
        base = (iovs_ptr + 8 * k) & MASK32
        buf = mem.load(base, 4, False)
        ln = mem.load(base + 4, 4, False)
        out.append((buf, ln))
    return out


def _load_str(mem, ptr: int, ln: int) -> str:
    raw = mem.load_bytes(ptr & MASK32, ln & MASK32)
    try:
        return raw.decode("utf-8", errors="strict")
    except UnicodeDecodeError:
        raise WasiError(Errno.ILSEQ)  # non-UTF-8 guest path


# ---------------------------------------------------------------------------
# args / environ
# ---------------------------------------------------------------------------
@wasi_fn("args_get", "ii")
def args_get(env: WasiEnviron, mem, argv, argv_buf):
    mem = _mem_required(mem)
    off = argv_buf & MASK32
    for i, a in enumerate(env.args):
        raw = a.encode() + b"\0"
        mem.store((argv & MASK32) + 4 * i, 4, off)
        mem.store_bytes(off, raw)
        off += len(raw)
    return Errno.SUCCESS


@wasi_fn("args_sizes_get", "ii")
def args_sizes_get(env: WasiEnviron, mem, nptr, szptr):
    mem = _mem_required(mem)
    mem.store(nptr & MASK32, 4, len(env.args))
    mem.store(szptr & MASK32, 4, sum(len(a.encode()) + 1 for a in env.args))
    return Errno.SUCCESS


@wasi_fn("environ_get", "ii")
def environ_get(env: WasiEnviron, mem, eptr, ebuf):
    mem = _mem_required(mem)
    off = ebuf & MASK32
    for i, e in enumerate(env.envs):
        raw = e.encode() + b"\0"
        mem.store((eptr & MASK32) + 4 * i, 4, off)
        mem.store_bytes(off, raw)
        off += len(raw)
    return Errno.SUCCESS


@wasi_fn("environ_sizes_get", "ii")
def environ_sizes_get(env: WasiEnviron, mem, nptr, szptr):
    mem = _mem_required(mem)
    mem.store(nptr & MASK32, 4, len(env.envs))
    mem.store(szptr & MASK32, 4, sum(len(e.encode()) + 1 for e in env.envs))
    return Errno.SUCCESS


# ---------------------------------------------------------------------------
# clocks / random / sched
# ---------------------------------------------------------------------------
@wasi_fn("clock_res_get", "ii")
def clock_res_get(env: WasiEnviron, mem, clock_id, res_ptr):
    mem = _mem_required(mem)
    mem.store(res_ptr & MASK32, 8, env.clock_res(clock_id & MASK32))
    return Errno.SUCCESS


@wasi_fn("clock_time_get", "iIi")
def clock_time_get(env: WasiEnviron, mem, clock_id, _precision, time_ptr):
    mem = _mem_required(mem)
    mem.store(time_ptr & MASK32, 8, env.clock_time(clock_id & MASK32))
    return Errno.SUCCESS


@wasi_fn("random_get", "ii")
def random_get(env: WasiEnviron, mem, buf, buf_len):
    mem = _mem_required(mem)
    # Bounds first: a guest-controlled length must not size a host
    # allocation before it is validated against linear memory.
    mem.check_bounds(buf & MASK32, buf_len & MASK32)
    mem.store_bytes(buf & MASK32, os.urandom(buf_len & MASK32))
    return Errno.SUCCESS


@wasi_fn("sched_yield", "")
def sched_yield(env: WasiEnviron, mem):
    os.sched_yield()
    return Errno.SUCCESS


# ---------------------------------------------------------------------------
# fd family
# ---------------------------------------------------------------------------
@wasi_fn("fd_advise", "iIIi")
def fd_advise(env: WasiEnviron, mem, fd, offset, length, advice):
    e = env.get_fd(fd, Rights.FD_ADVISE)
    if advice & MASK32 > 5:
        return Errno.INVAL
    try:
        if hasattr(os, "posix_fadvise") and e.kind == "file":
            os.posix_fadvise(e.os_fd, offset, length, advice & MASK32)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("fd_allocate", "iII")
def fd_allocate(env: WasiEnviron, mem, fd, offset, length):
    e = env.get_fd(fd, Rights.FD_ALLOCATE)
    try:
        os.posix_fallocate(e.os_fd, offset, length)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("fd_close", "i")
def fd_close(env: WasiEnviron, mem, fd):
    env.get_fd(fd)
    env.close_fd(fd)
    return Errno.SUCCESS


@wasi_fn("fd_datasync", "i")
def fd_datasync(env: WasiEnviron, mem, fd):
    e = env.get_fd(fd, Rights.FD_DATASYNC)
    try:
        os.fdatasync(e.os_fd)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("fd_fdstat_get", "ii")
def fd_fdstat_get(env: WasiEnviron, mem, fd, buf):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.kind == "socket":
        ft = Filetype.SOCKET_STREAM if e.sock.type == socket.SOCK_STREAM \
            else Filetype.SOCKET_DGRAM
    elif e.kind in ("dir", "prestat-dir"):
        ft = Filetype.DIRECTORY
    elif e.kind == "stdio":
        ft = Filetype.CHARACTER_DEVICE
    else:
        try:
            ft = abi.Filetype.UNKNOWN
            st = os.fstat(e.os_fd)
            from wasmedge_tpu.host.wasi.environ import _filetype_of_mode

            ft = _filetype_of_mode(st.st_mode)
        except OSError as ex:
            return from_oserror(ex)
    mem.store_bytes(buf & MASK32, abi.pack_fdstat(
        ft, e.fdflags, e.rights_base, e.rights_inheriting))
    return Errno.SUCCESS


@wasi_fn("fd_fdstat_set_flags", "ii")
def fd_fdstat_set_flags(env: WasiEnviron, mem, fd, flags):
    e = env.get_fd(fd, Rights.FD_FDSTAT_SET_FLAGS)
    flags &= MASK32
    if flags & ~(Fdflags.APPEND | Fdflags.NONBLOCK | Fdflags.DSYNC
                 | Fdflags.RSYNC | Fdflags.SYNC):
        return Errno.INVAL
    e.fdflags = flags
    want_blocking = not (flags & Fdflags.NONBLOCK)
    try:
        if e.kind == "socket":
            e.sock.setblocking(want_blocking)
        elif e.kind == "file":
            if os.get_blocking(e.os_fd) != want_blocking:
                os.set_blocking(e.os_fd, want_blocking)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("fd_fdstat_set_rights", "iII")
def fd_fdstat_set_rights(env: WasiEnviron, mem, fd, base, inheriting):
    e = env.get_fd(fd)
    base &= (1 << 64) - 1
    inheriting &= (1 << 64) - 1
    # Rights may only shrink (capability monotonicity).
    if base & ~e.rights_base or inheriting & ~e.rights_inheriting:
        return Errno.NOTCAPABLE
    e.rights_base = base
    e.rights_inheriting = inheriting
    return Errno.SUCCESS


@wasi_fn("fd_filestat_get", "ii")
def fd_filestat_get(env: WasiEnviron, mem, fd, buf):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.FD_FILESTAT_GET)
    try:
        st = os.fstat(e.os_fd)
    except OSError as ex:
        return from_oserror(ex)
    mem.store_bytes(buf & MASK32, abi.pack_filestat(*env.filestat_tuple(st)))
    return Errno.SUCCESS


@wasi_fn("fd_filestat_set_size", "iI")
def fd_filestat_set_size(env: WasiEnviron, mem, fd, size):
    e = env.get_fd(fd, Rights.FD_FILESTAT_SET_SIZE)
    try:
        os.ftruncate(e.os_fd, size)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


def _resolve_times(atim, mtim, fstflags, now_ns):
    a = m = None
    if fstflags & abi.Fstflags.ATIM:
        a = atim
    elif fstflags & abi.Fstflags.ATIM_NOW:
        a = now_ns
    if fstflags & abi.Fstflags.MTIM:
        m = mtim
    elif fstflags & abi.Fstflags.MTIM_NOW:
        m = now_ns
    return a, m


@wasi_fn("fd_filestat_set_times", "iIIi")
def fd_filestat_set_times(env: WasiEnviron, mem, fd, atim, mtim, fstflags):
    import time as _t

    e = env.get_fd(fd, Rights.FD_FILESTAT_SET_TIMES)
    a, m = _resolve_times(atim, mtim, fstflags & MASK32, _t.time_ns())
    try:
        st = os.fstat(e.os_fd)
        os.utime(e.os_fd, ns=(a if a is not None else st.st_atime_ns,
                              m if m is not None else st.st_mtime_ns))
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


def _do_read(env, mem, fd, iovs, iovs_len, nread_ptr, offset=None):
    mem = _mem_required(mem)
    need = Rights.FD_READ if offset is None \
        else (Rights.FD_READ | Rights.FD_SEEK)
    e = env.get_fd(fd, need)
    vecs = _read_iovs(mem, iovs & MASK32, iovs_len & MASK32)
    # Validate targets before any syscall.
    for buf, ln in vecs:
        mem.check_bounds(buf, ln)
    total = 0
    try:
        for buf, ln in vecs:
            if ln == 0:
                continue
            if e.kind == "socket":
                data = e.sock.recv(ln)
            elif offset is None:
                data = os.read(e.os_fd, ln)
            else:
                data = os.pread(e.os_fd, ln, offset + total)
            mem.store_bytes(buf, data)
            total += len(data)
            if len(data) < ln:
                break
    except OSError as ex:
        return from_oserror(ex)
    mem.store(nread_ptr & MASK32, 4, total)
    return Errno.SUCCESS


@wasi_fn("fd_read", "iiii")
def fd_read(env: WasiEnviron, mem, fd, iovs, iovs_len, nread_ptr):
    return _do_read(env, mem, fd, iovs, iovs_len, nread_ptr)


@wasi_fn("fd_pread", "iiiIi")
def fd_pread(env: WasiEnviron, mem, fd, iovs, iovs_len, offset, nread_ptr):
    return _do_read(env, mem, fd, iovs, iovs_len, nread_ptr, offset=offset)


def _do_write(env, mem, fd, iovs, iovs_len, nw_ptr, offset=None):
    mem = _mem_required(mem)
    need = Rights.FD_WRITE if offset is None \
        else (Rights.FD_WRITE | Rights.FD_SEEK)
    e = env.get_fd(fd, need)
    vecs = _read_iovs(mem, iovs & MASK32, iovs_len & MASK32)
    total = 0
    try:
        for buf, ln in vecs:
            data = mem.load_bytes(buf, ln)
            if not data:
                continue
            if e.kind == "socket":
                n = e.sock.send(data)
            elif offset is None:
                n = os.write(e.os_fd, data)
            else:
                n = os.pwrite(e.os_fd, data, offset + total)
            total += n
            if n < len(data):
                break
    except OSError as ex:
        return from_oserror(ex)
    mem.store(nw_ptr & MASK32, 4, total)
    return Errno.SUCCESS


@wasi_fn("fd_write", "iiii")
def fd_write(env: WasiEnviron, mem, fd, iovs, iovs_len, nw_ptr):
    return _do_write(env, mem, fd, iovs, iovs_len, nw_ptr)


@wasi_fn("fd_pwrite", "iiiIi")
def fd_pwrite(env: WasiEnviron, mem, fd, iovs, iovs_len, offset, nw_ptr):
    return _do_write(env, mem, fd, iovs, iovs_len, nw_ptr, offset=offset)


@wasi_fn("fd_prestat_get", "ii")
def fd_prestat_get(env: WasiEnviron, mem, fd, buf):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.kind != "prestat-dir":
        return Errno.BADF
    mem.store_bytes(buf & MASK32,
                    abi.pack_prestat_dir(len(e.preopen_name.encode())))
    return Errno.SUCCESS


@wasi_fn("fd_prestat_dir_name", "iii")
def fd_prestat_dir_name(env: WasiEnviron, mem, fd, path_ptr, path_len):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.kind != "prestat-dir":
        return Errno.BADF
    raw = e.preopen_name.encode()
    if (path_len & MASK32) < len(raw):
        return Errno.NAMETOOLONG
    mem.store_bytes(path_ptr & MASK32, raw)
    return Errno.SUCCESS


@wasi_fn("fd_readdir", "iiiIi")
def fd_readdir(env: WasiEnviron, mem, fd, buf, buf_len, cookie, bufused_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.FD_READDIR)
    if e.host_path is None:
        return Errno.NOTDIR
    try:
        names = [".", ".."] + sorted(os.listdir(e.host_path))
    except OSError as ex:
        return from_oserror(ex)
    buf &= MASK32
    buf_len &= MASK32
    cookie &= (1 << 64) - 1  # marshaled signed; dirent cookies are u64
    used = 0
    for idx in range(min(cookie, len(names)), len(names)):
        name = names[idx]
        raw = name.encode()
        full = os.path.join(e.host_path, name)
        try:
            st = os.lstat(full)
            ino = st.st_ino
            from wasmedge_tpu.host.wasi.environ import _filetype_of_mode

            dt = _filetype_of_mode(st.st_mode)
        except OSError:
            ino, dt = 0, Filetype.UNKNOWN
        ent = abi.pack_dirent(idx + 1, ino, len(raw), dt) + raw
        take = min(len(ent), buf_len - used)
        if take <= 0:
            break
        mem.store_bytes(buf + used, ent[:take])
        used += take
        if take < len(ent):
            break
    mem.store(bufused_ptr & MASK32, 4, used)
    return Errno.SUCCESS


@wasi_fn("fd_renumber", "ii")
def fd_renumber(env: WasiEnviron, mem, fd, to):
    e = env.get_fd(fd)
    env.get_fd(to)
    if fd == to:
        return Errno.SUCCESS
    env.close_fd(to)
    env.fds[to] = e
    del env.fds[fd]
    return Errno.SUCCESS


@wasi_fn("fd_seek", "iIii", "i")
def fd_seek(env: WasiEnviron, mem, fd, offset, whence, newoff_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.FD_SEEK)
    if whence not in (Whence.SET, Whence.CUR, Whence.END):
        return Errno.INVAL
    try:
        pos = os.lseek(e.os_fd, offset,
                       {Whence.SET: os.SEEK_SET, Whence.CUR: os.SEEK_CUR,
                        Whence.END: os.SEEK_END}[whence])
    except OSError as ex:
        return from_oserror(ex)
    mem.store(newoff_ptr & MASK32, 8, pos)
    return Errno.SUCCESS


@wasi_fn("fd_sync", "i")
def fd_sync(env: WasiEnviron, mem, fd):
    e = env.get_fd(fd, Rights.FD_SYNC)
    try:
        os.fsync(e.os_fd)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("fd_tell", "ii")
def fd_tell(env: WasiEnviron, mem, fd, off_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.FD_TELL)
    try:
        pos = os.lseek(e.os_fd, 0, os.SEEK_CUR)
    except OSError as ex:
        return from_oserror(ex)
    mem.store(off_ptr & MASK32, 8, pos)
    return Errno.SUCCESS


# ---------------------------------------------------------------------------
# path family
# ---------------------------------------------------------------------------
@wasi_fn("path_create_directory", "iii")
def path_create_directory(env: WasiEnviron, mem, fd, path, path_len):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_CREATE_DIRECTORY)
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len))
        os.mkdir(host)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_filestat_get", "iiiii")
def path_filestat_get(env: WasiEnviron, mem, fd, flags, path, path_len, buf):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_FILESTAT_GET)
    follow = bool(flags & Lookupflags.SYMLINK_FOLLOW)
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=follow)
        st = os.stat(host) if follow else os.lstat(host)
    except OSError as ex:
        return from_oserror(ex)
    mem.store_bytes(buf & MASK32, abi.pack_filestat(*env.filestat_tuple(st)))
    return Errno.SUCCESS


@wasi_fn("path_filestat_set_times", "iiiiIIi")
def path_filestat_set_times(env: WasiEnviron, mem, fd, flags, path, path_len,
                            atim, mtim, fstflags):
    import time as _t

    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_FILESTAT_SET_TIMES)
    follow = bool(flags & Lookupflags.SYMLINK_FOLLOW)
    a, m = _resolve_times(atim, mtim, fstflags & MASK32, _t.time_ns())
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=follow)
        st = os.stat(host) if follow else os.lstat(host)
        os.utime(host, ns=(a if a is not None else st.st_atime_ns,
                           m if m is not None else st.st_mtime_ns),
                 follow_symlinks=follow)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_link", "iiiiiii")
def path_link(env: WasiEnviron, mem, old_fd, old_flags, old_path,
              old_path_len, new_fd, new_path, new_path_len):
    mem = _mem_required(mem)
    eo = env.get_fd(old_fd, Rights.PATH_LINK_SOURCE)
    en = env.get_fd(new_fd, Rights.PATH_LINK_TARGET)
    follow = bool(old_flags & Lookupflags.SYMLINK_FOLLOW)
    try:
        src = env.resolve_path(eo, _load_str(mem, old_path, old_path_len),
                               follow_final=follow)
        dst = env.resolve_path(en, _load_str(mem, new_path, new_path_len),
                               follow_final=False)
        os.link(src, dst, follow_symlinks=follow)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_open", "iiiiiIIii")
def path_open(env: WasiEnviron, mem, dirfd, dirflags, path, path_len, oflags,
              rights_base, rights_inheriting, fdflags, opened_fd_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(dirfd, Rights.PATH_OPEN)
    rights_base &= (1 << 64) - 1
    rights_inheriting &= (1 << 64) - 1
    # Requested rights must be within what the directory can grant.
    if rights_base & ~e.rights_inheriting \
            or rights_inheriting & ~e.rights_inheriting:
        return Errno.NOTCAPABLE
    oflags &= MASK32
    fdflags &= MASK32
    follow = bool(dirflags & Lookupflags.SYMLINK_FOLLOW)
    read = bool(rights_base & (Rights.FD_READ | Rights.FD_READDIR))
    write = bool(rights_base & (Rights.FD_WRITE | Rights.FD_ALLOCATE
                                | Rights.FD_FILESTAT_SET_SIZE))
    if oflags & Oflags.DIRECTORY:
        flags = os.O_RDONLY  # directories only open read-only on POSIX
    else:
        flags = os.O_RDWR if (read and write) else (
            os.O_WRONLY if write else os.O_RDONLY)
    if oflags & Oflags.CREAT:
        if not (e.rights_base & Rights.PATH_CREATE_FILE):
            return Errno.NOTCAPABLE
        flags |= os.O_CREAT
    if oflags & Oflags.EXCL:
        flags |= os.O_EXCL
    if oflags & Oflags.TRUNC:
        if not write:
            return Errno.INVAL
        flags |= os.O_TRUNC
    if oflags & Oflags.DIRECTORY:
        flags |= os.O_DIRECTORY
    if fdflags & Fdflags.APPEND:
        flags |= os.O_APPEND
    if fdflags & Fdflags.NONBLOCK:
        flags |= os.O_NONBLOCK
    if fdflags & (Fdflags.SYNC | Fdflags.RSYNC):
        flags |= os.O_SYNC
    if fdflags & Fdflags.DSYNC:
        flags |= getattr(os, "O_DSYNC", os.O_SYNC)
    if not follow:
        flags |= os.O_NOFOLLOW
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=follow)
        os_fd = os.open(host, flags, 0o666)
        st = os.fstat(os_fd)
    except OSError as ex:
        return from_oserror(ex)
    is_dir = os.path.isdir(host)
    entry = FdEntry(
        "dir" if is_dir else "file", os_fd=os_fd,
        rights_base=rights_base, rights_inheriting=rights_inheriting,
        fdflags=fdflags, host_path=host if is_dir else None)
    newfd = env.insert_entry(entry)
    mem.store(opened_fd_ptr & MASK32, 4, newfd)
    return Errno.SUCCESS


@wasi_fn("path_readlink", "iiiiii")
def path_readlink(env: WasiEnviron, mem, fd, path, path_len, buf, buf_len,
                  bufused_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_READLINK)
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=False)
        target = os.readlink(host).encode()
    except OSError as ex:
        return from_oserror(ex)
    n = min(len(target), buf_len & MASK32)
    mem.store_bytes(buf & MASK32, target[:n])
    mem.store(bufused_ptr & MASK32, 4, n)
    return Errno.SUCCESS


@wasi_fn("path_remove_directory", "iii")
def path_remove_directory(env: WasiEnviron, mem, fd, path, path_len):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_REMOVE_DIRECTORY)
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=False)
        os.rmdir(host)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_rename", "iiiiii")
def path_rename(env: WasiEnviron, mem, fd, old_path, old_path_len, new_fd,
                new_path, new_path_len):
    mem = _mem_required(mem)
    eo = env.get_fd(fd, Rights.PATH_RENAME_SOURCE)
    en = env.get_fd(new_fd, Rights.PATH_RENAME_TARGET)
    try:
        src = env.resolve_path(eo, _load_str(mem, old_path, old_path_len),
                               follow_final=False)
        dst = env.resolve_path(en, _load_str(mem, new_path, new_path_len),
                               follow_final=False)
        os.rename(src, dst)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_symlink", "iiiii")
def path_symlink(env: WasiEnviron, mem, old_path, old_path_len, fd, new_path,
                 new_path_len):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_SYMLINK)
    try:
        target = _load_str(mem, old_path, old_path_len)
        dst = env.resolve_path(e, _load_str(mem, new_path, new_path_len),
                               follow_final=False)
        os.symlink(target, dst)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("path_unlink_file", "iii")
def path_unlink_file(env: WasiEnviron, mem, fd, path, path_len):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.PATH_UNLINK_FILE)
    try:
        host = env.resolve_path(e, _load_str(mem, path, path_len),
                                follow_final=False)
        os.unlink(host)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


# ---------------------------------------------------------------------------
# poll / proc
# ---------------------------------------------------------------------------
@wasi_fn("poll_oneoff", "iiii")
def poll_oneoff(env: WasiEnviron, mem, in_ptr, out_ptr, nsubs, nevents_ptr):
    mem = _mem_required(mem)
    in_ptr &= MASK32
    out_ptr &= MASK32
    nsubs &= MASK32
    if nsubs == 0:
        return Errno.INVAL
    subs = []
    for k in range(nsubs):
        base = in_ptr + k * abi.SUBSCRIPTION_SIZE
        userdata = mem.load(base, 8, False)
        tag = mem.load(base + 8, 1, False)
        if tag == abi.Eventtype.CLOCK:
            clock_id = mem.load(base + 16, 4, False)
            timeout = mem.load(base + 24, 8, False)
            flags = mem.load(base + 40, 2, False)
            subs.append(("clock", userdata, clock_id, timeout, flags))
        elif tag in (abi.Eventtype.FD_READ, abi.Eventtype.FD_WRITE):
            fd = mem.load(base + 16, 4, False)
            subs.append(("fd", userdata, tag, fd))
        else:
            subs.append(("bad", userdata))

    # Shortest clock deadline bounds the wait.
    import time as _t

    now_mono = _t.monotonic_ns()
    deadline = None
    immediate = []  # events for invalid subscriptions, delivered without waiting
    for s in subs:
        if s[0] != "clock":
            continue
        _, userdata, clock_id, timeout, flags = s
        # A bad clock id fails only this subscription (per-event errno),
        # not the whole call. Relative waits are computed in the
        # subscription's own clock domain (ABSTIME: deadline minus that
        # clock's current reading).
        try:
            if flags & abi.Subclockflags.ABSTIME:
                base_now = env.clock_time(clock_id)
                rel = max(0, timeout - base_now)
            else:
                env.clock_time(clock_id)  # validate the clock id
                rel = timeout
        except WasiError as werr:
            immediate.append(abi.pack_event(userdata, werr.errno,
                                            abi.Eventtype.CLOCK))
            continue
        deadline = rel if deadline is None else min(deadline, rel)

    rlist, wlist = [], []
    fd_map = {}
    for s in subs:
        if s[0] != "fd":
            continue
        _, userdata, tag, fd = s
        try:
            e = env.get_fd(fd, Rights.POLL_FD_READWRITE)
        except WasiError as werr:
            immediate.append(abi.pack_event(userdata, werr.errno, tag))
            continue
        osfd = e.sock.fileno() if e.sock is not None else e.os_fd
        fd_map[osfd] = (userdata, tag, e)
        (rlist if tag == abi.Eventtype.FD_READ else wlist).append(osfd)

    if immediate:
        # A bad subscription resolves the poll immediately (spec: event
        # carries the errno; do not sleep on the other subscriptions).
        for i, ev in enumerate(immediate):
            mem.store_bytes(out_ptr + i * abi.EVENT_SIZE, ev)
        mem.store(nevents_ptr & MASK32, 4, len(immediate))
        return Errno.SUCCESS

    timeout_s = None if deadline is None else deadline / 1e9
    if rlist or wlist:
        rr, ww, _ = select.select(rlist, wlist, [], timeout_s)
    else:
        if timeout_s:
            _t.sleep(timeout_s)
        rr, ww = [], []

    events = []
    for osfd in rr:
        userdata, tag, _ = fd_map[osfd]
        events.append(abi.pack_event(userdata, Errno.SUCCESS, tag, 1, 0))
    for osfd in ww:
        userdata, tag, _ = fd_map[osfd]
        events.append(abi.pack_event(userdata, Errno.SUCCESS, tag, 1, 0))
    if not events:
        for s in subs:
            if s[0] == "clock":
                events.append(abi.pack_event(s[1], Errno.SUCCESS,
                                             abi.Eventtype.CLOCK))
                break
        else:
            for s in subs:
                if s[0] == "bad":
                    events.append(abi.pack_event(s[1], Errno.INVAL, 0))
    for i, ev in enumerate(events):
        mem.store_bytes(out_ptr + i * abi.EVENT_SIZE, ev)
    mem.store(nevents_ptr & MASK32, 4, len(events))
    return Errno.SUCCESS


@wasi_fn("proc_exit", "i", "")
def proc_exit(env: WasiEnviron, mem, code):
    env.exit_code = code & MASK32
    env.exited = True
    raise WasiExit(env.exit_code)


@wasi_fn("proc_raise", "i")
def proc_raise(env: WasiEnviron, mem, sig):
    return Errno.NOSYS


# ---------------------------------------------------------------------------
# sockets (wasmedge extension; reference: wasifunc.cpp:1599+)
# ---------------------------------------------------------------------------
_AF = {0: socket.AF_INET, 1: socket.AF_INET6}
_SOCKTYPE = {0: socket.SOCK_DGRAM, 1: socket.SOCK_STREAM}

_SOCK_RIGHTS = (Rights.FD_READ | Rights.FD_WRITE | Rights.POLL_FD_READWRITE
                | Rights.SOCK_SHUTDOWN | Rights.SOCK_OPEN | Rights.SOCK_CLOSE
                | Rights.SOCK_RECV | Rights.SOCK_SEND | Rights.SOCK_BIND)


def _read_wasi_address(mem, address_ptr) -> bytes:
    """__wasi_address_t {buf: ptr, buf_len: u32} -> raw address bytes."""
    buf = mem.load(address_ptr & MASK32, 4, False)
    ln = mem.load((address_ptr & MASK32) + 4, 4, False)
    return mem.load_bytes(buf, ln)


def _write_wasi_address(mem, address_ptr, raw: bytes):
    buf = mem.load(address_ptr & MASK32, 4, False)
    ln = mem.load((address_ptr & MASK32) + 4, 4, False)
    mem.store_bytes(buf, raw[:ln])


def _addr_str(raw: bytes) -> str:
    """Family comes from the buffer length (4 = v4, 16 = v6), never from
    the payload bytes — '::' is all zeros yet must stay IPv6."""
    if len(raw) >= 16:
        return socket.inet_ntop(socket.AF_INET6, raw[:16])
    return socket.inet_ntop(socket.AF_INET, raw[:4])


@wasi_fn("sock_open", "iii")
def sock_open(env: WasiEnviron, mem, af, socktype, ro_fd_ptr):
    mem = _mem_required(mem)
    if (af & MASK32) not in _AF or (socktype & MASK32) not in _SOCKTYPE:
        return Errno.INVAL
    try:
        s = socket.socket(_AF[af & MASK32], _SOCKTYPE[socktype & MASK32])
    except OSError as ex:
        return from_oserror(ex)
    fd = env.insert_entry(FdEntry("socket", sock=s, rights_base=_SOCK_RIGHTS,
                                  rights_inheriting=_SOCK_RIGHTS))
    mem.store(ro_fd_ptr & MASK32, 4, fd)
    return Errno.SUCCESS


@wasi_fn("sock_bind", "iii")
def sock_bind(env: WasiEnviron, mem, fd, address_ptr, port):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.SOCK_BIND)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        raw = _read_wasi_address(mem, address_ptr)
        e.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        e.sock.bind((_addr_str(raw), port & 0xFFFF))
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("sock_connect", "iii")
def sock_connect(env: WasiEnviron, mem, fd, address_ptr, port):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        raw = _read_wasi_address(mem, address_ptr)
        e.sock.connect((_addr_str(raw), port & 0xFFFF))
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("sock_listen", "ii")
def sock_listen(env: WasiEnviron, mem, fd, backlog):
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        e.sock.listen(backlog & MASK32)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


@wasi_fn("sock_accept", "ii")
def sock_accept(env: WasiEnviron, mem, fd, ro_fd_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        conn, _ = e.sock.accept()
    except OSError as ex:
        return from_oserror(ex)
    nfd = env.insert_entry(FdEntry("socket", sock=conn,
                                   rights_base=_SOCK_RIGHTS,
                                   rights_inheriting=_SOCK_RIGHTS))
    mem.store(ro_fd_ptr & MASK32, 4, nfd)
    return Errno.SUCCESS


@wasi_fn("sock_recv", "iiiiii")
def sock_recv(env: WasiEnviron, mem, fd, ri_data, ri_data_len, ri_flags,
              ro_datalen_ptr, ro_flags_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.SOCK_RECV)
    if e.sock is None:
        return Errno.NOTSOCK
    vecs = _read_iovs(mem, ri_data & MASK32, ri_data_len & MASK32)
    # Validate every target iovec before any recv: the guest-controlled
    # length otherwise sizes a host allocation (mirrors _do_read).
    for buf, ln in vecs:
        mem.check_bounds(buf, ln)
    total = 0
    try:
        for buf, ln in vecs:
            if ln == 0:
                continue
            data = e.sock.recv(ln)
            mem.store_bytes(buf, data)
            total += len(data)
            if len(data) < ln:
                break
    except OSError as ex:
        return from_oserror(ex)
    mem.store(ro_datalen_ptr & MASK32, 4, total)
    mem.store(ro_flags_ptr & MASK32, 2, 0)
    return Errno.SUCCESS


@wasi_fn("sock_recv_from", "iiiiiii")
def sock_recv_from(env: WasiEnviron, mem, fd, ri_data, ri_data_len,
                   address_ptr, ri_flags, ro_datalen_ptr, ro_flags_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.SOCK_RECV)
    if e.sock is None:
        return Errno.NOTSOCK
    vecs = _read_iovs(mem, ri_data & MASK32, ri_data_len & MASK32)
    for buf, ln in vecs:
        mem.check_bounds(buf, ln)
    total = 0
    addr = None
    try:
        for buf, ln in vecs:
            if ln == 0:
                continue
            data, addr = e.sock.recvfrom(ln)
            mem.store_bytes(buf, data)
            total += len(data)
            break  # datagram: one message
    except OSError as ex:
        return from_oserror(ex)
    if addr is not None:
        try:
            host = addr[0].split("%", 1)[0]  # strip ipv6 zone id
            fam = socket.AF_INET6 if ":" in host else socket.AF_INET
            _write_wasi_address(mem, address_ptr, socket.inet_pton(fam, host))
        except OSError:
            pass  # unparseable peer address: deliver data without it
    mem.store(ro_datalen_ptr & MASK32, 4, total)
    mem.store(ro_flags_ptr & MASK32, 2, 0)
    return Errno.SUCCESS


@wasi_fn("sock_send", "iiiii")
def sock_send(env: WasiEnviron, mem, fd, si_data, si_data_len, si_flags,
              so_datalen_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.SOCK_SEND)
    if e.sock is None:
        return Errno.NOTSOCK
    vecs = _read_iovs(mem, si_data & MASK32, si_data_len & MASK32)
    total = 0
    try:
        for buf, ln in vecs:
            data = mem.load_bytes(buf, ln)
            if data:
                total += e.sock.send(data)
    except OSError as ex:
        return from_oserror(ex)
    mem.store(so_datalen_ptr & MASK32, 4, total)
    return Errno.SUCCESS


@wasi_fn("sock_send_to", "iiiiiii")
def sock_send_to(env: WasiEnviron, mem, fd, si_data, si_data_len, address_ptr,
                 port, si_flags, so_datalen_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd, Rights.SOCK_SEND)
    if e.sock is None:
        return Errno.NOTSOCK
    vecs = _read_iovs(mem, si_data & MASK32, si_data_len & MASK32)
    total = 0
    try:
        raw = _read_wasi_address(mem, address_ptr)
        dest = (_addr_str(raw), port & 0xFFFF)
        for buf, ln in vecs:
            data = mem.load_bytes(buf, ln)
            if data:
                total += e.sock.sendto(data, dest)
    except OSError as ex:
        return from_oserror(ex)
    mem.store(so_datalen_ptr & MASK32, 4, total)
    return Errno.SUCCESS


@wasi_fn("sock_shutdown", "ii")
def sock_shutdown(env: WasiEnviron, mem, fd, how):
    e = env.get_fd(fd, Rights.SOCK_SHUTDOWN)
    if e.sock is None:
        return Errno.NOTSOCK
    how &= MASK32
    if how == abi.Sdflags.RD:
        flag = socket.SHUT_RD
    elif how == abi.Sdflags.WR:
        flag = socket.SHUT_WR
    elif how == (abi.Sdflags.RD | abi.Sdflags.WR):
        flag = socket.SHUT_RDWR
    else:
        return Errno.INVAL
    try:
        e.sock.shutdown(flag)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


_SOL = {0: socket.SOL_SOCKET}
_SO = {1: socket.SO_REUSEADDR, 2: socket.SO_TYPE, 3: socket.SO_ERROR}


@wasi_fn("sock_getsockopt", "iiiii")
def sock_getsockopt(env: WasiEnviron, mem, fd, level, name, flag_ptr,
                    flag_size_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    if (level & MASK32) not in _SOL or (name & MASK32) not in _SO:
        return Errno.NOPROTOOPT
    try:
        v = e.sock.getsockopt(_SOL[level & MASK32], _SO[name & MASK32])
    except OSError as ex:
        return from_oserror(ex)
    mem.store(flag_ptr & MASK32, 4, v & MASK32)
    mem.store(flag_size_ptr & MASK32, 4, 4)
    return Errno.SUCCESS


@wasi_fn("sock_setsockopt", "iiiii")
def sock_setsockopt(env: WasiEnviron, mem, fd, level, name, flag_ptr,
                    flag_size_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    if (level & MASK32) not in _SOL or (name & MASK32) not in _SO:
        return Errno.NOPROTOOPT
    try:
        v = mem.load(flag_ptr & MASK32, 4, False)
        e.sock.setsockopt(_SOL[level & MASK32], _SO[name & MASK32], v)
    except OSError as ex:
        return from_oserror(ex)
    return Errno.SUCCESS


def _write_sockaddr(env, mem, address_ptr, addr_type_ptr, port_ptr, addr):
    host, port = addr[0], addr[1]
    if ":" in host:
        raw, at = socket.inet_pton(socket.AF_INET6, host), 1
    else:
        raw, at = socket.inet_pton(socket.AF_INET, host), 0
    _write_wasi_address(mem, address_ptr, raw)
    mem.store(addr_type_ptr & MASK32, 4, at)
    mem.store(port_ptr & MASK32, 4, port)
    return Errno.SUCCESS


@wasi_fn("sock_getlocaladdr", "iiii")
def sock_getlocaladdr(env: WasiEnviron, mem, fd, address_ptr, addr_type_ptr,
                      port_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        return _write_sockaddr(env, mem, address_ptr, addr_type_ptr, port_ptr,
                               e.sock.getsockname())
    except OSError as ex:
        return from_oserror(ex)


@wasi_fn("sock_getpeeraddr", "iiii")
def sock_getpeeraddr(env: WasiEnviron, mem, fd, address_ptr, addr_type_ptr,
                     port_ptr):
    mem = _mem_required(mem)
    e = env.get_fd(fd)
    if e.sock is None:
        return Errno.NOTSOCK
    try:
        return _write_sockaddr(env, mem, address_ptr, addr_type_ptr, port_ptr,
                               e.sock.getpeername())
    except OSError as ex:
        return from_oserror(ex)


@wasi_fn("sock_getaddrinfo", "iiiiiiii")
def sock_getaddrinfo(env: WasiEnviron, mem, node_ptr, node_len, service_ptr,
                     service_len, hints_ptr, res_ptr, max_res_len,
                     res_len_ptr):
    # Resolution without the full __wasi_addrinfo_t graph: the reference
    # packs linked records; we expose count only (callers in the
    # wasi-socket tests use the count + first record). Marked minimal.
    mem = _mem_required(mem)
    try:
        node = _load_str(mem, node_ptr, node_len) or None
        service = _load_str(mem, service_ptr, service_len) or None
        infos = socket.getaddrinfo(node, service)
    except (OSError, socket.gaierror):
        return Errno.NOENT
    mem.store(res_len_ptr & MASK32, 4, min(len(infos), max_res_len & MASK32))
    return Errno.SUCCESS
