"""Lane-memory virtualization: the scheduler as a hypervisor (r14).

The serving layer's capacity was hard-capped at the physical lane
count: every admitted request owned a full device-resident linear
memory + stack plane for its whole lifetime, even while parked behind
a long neighbour.  Following "Towards a Linear-Algebraic Hypervisor"
(PAPERS.md), this package decouples *virtual* lanes (requests with
live guest state) from *physical* device lanes: cold lanes swap their
memory/stack/globals/t0 plane columns to a host-side content-addressed
`SwapStore` at launch boundaries, and swap back onto ANY free physical
lane through the same jitted column-install seam the lane recycler
uses — a parked lane is a suspended continuation whose state needs no
HBM ("Continuing WebAssembly with Effect Handlers", PAPERS.md).

  swapstore.py   content-addressed host store (crash-atomic writes,
                 refcounted blobs, corruption detection) + the per-lane
                 plane column serializer (batch/checkpoint.py's plane
                 discipline, one lane wide)
  policy.py      deterministic LRU eviction policy (last-progress
                 step, deadline-distance bias, never mid-hostcall-
                 drain, never the sole runnable lane) and the
                 resident-bytes budget math (seeded from
                 DeviceImage.analysis footprint bounds when available)
  manager.py     LaneVirtualizer: the BatchServer-side orchestrator —
                 virtual admission, boundary rebalance (swap-out /
                 swap-in), per-tenant resident caps, checkpoint
                 journal, fault seams (swap_out / swap_in /
                 swap_store_write)
"""

from wasmedge_tpu.hv.manager import LaneVirtualizer, VirtualLane  # noqa: F401
from wasmedge_tpu.hv.policy import (  # noqa: F401
    EvictionCandidate,
    effective_lane_bytes,
    pick_victims,
    resident_lane_cap,
)
from wasmedge_tpu.hv.swapstore import (  # noqa: F401
    SwapCorrupt,
    SwapStore,
    deserialize_lane,
    serialize_lanes,
)
