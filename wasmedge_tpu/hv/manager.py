"""LaneVirtualizer: the BatchServer-side lane-virtualization manager.

One instance rides one BatchServer (serve/server.py) and runs entirely
under the server's lock at launch boundaries.  It owns:

  - the VIRTUAL lane table: admitted requests currently off-device,
    either `fresh` (never installed — their state is reproducible from
    func+args through the recycler's template seam, so nothing is
    serialized) or `swapped` (their live plane columns parked in the
    SwapStore under a content key)
  - the boundary REBALANCE: fill free physical lanes with waiting
    virtual lanes first; once the device is full (or the resident-
    bytes budget is), evict policy-chosen victims (hv/policy.py) and
    install waiters into the freed columns — round-robin rotation
    under ties, so every virtual lane keeps making progress
  - per-tenant resident caps: a tenant's `resident_budget_bytes`
    (gateway/tenants.py) divided by the effective per-lane footprint
    caps how many physical lanes its requests may hold at once; over-
    cap requests wait as virtual lanes instead of being rejected
  - the fault seams (`swap_out` / `swap_in` / `swap_store_write`,
    testing/faults.py): a faulted swap-out leaves the lane resident
    and retries at the next boundary; a faulted swap-in re-queues the
    virtual lane without losing it; a corrupt store entry rejects that
    one request machine-readably and the server keeps serving

Results stay bit-identical to a never-swapped run for lane-placement-
independent guests: a swap round-trips the exact plane columns, and
the per-lane interpreter carries no cross-lane state (the same scoping
as the r9 recycler guarantee — tier-0 random_get keys its stream on
the physical lane index, so placement-dependent guests are out of
scope there and here alike).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from wasmedge_tpu.hv.policy import (
    EvictionCandidate,
    effective_lane_bytes,
    pick_victims,
    resident_lane_cap,
)
from wasmedge_tpu.hv.swapstore import (
    SwapCorrupt,
    SwapStore,
    deserialize_lane,
    serialize_lanes,
)


class VirtualLane:
    """One admitted request currently off-device."""

    __slots__ = ("req", "key", "stdout_pos", "admitted_round", "swaps")

    def __init__(self, req, key: Optional[str] = None,
                 stdout_pos: int = 0, admitted_round: int = 0):
        self.req = req
        self.key = key                # None = fresh (never installed)
        self.stdout_pos = int(stdout_pos)
        self.admitted_round = int(admitted_round)
        self.swaps = 0

    @property
    def fresh(self) -> bool:
        return self.key is None

    def journal(self) -> dict:
        """JSON-serializable checkpoint entry (deadlines are monotonic
        stamps and never journaled — same rule as ServeRequest)."""
        return {"id": self.req.id, "func": self.req.func_name,
                "args": [int(a) for a in self.req.args],
                "tenant": self.req.tenant,
                "key": self.key, "stdout_pos": self.stdout_pos}


class LaneVirtualizer:
    """Virtual-lane table + boundary rebalance for one BatchServer.
    Not thread-safe on its own: every entry point runs under the
    owning server's lock."""

    def __init__(self, engine, recycler, knobs, obs, faults=None,
                 record=None, tenant_budgets: Optional[Dict[str, int]] = None):
        self.engine = engine
        self.recycler = recycler
        self.k = knobs
        self.obs = obs
        self.faults = faults
        self._record = record or (lambda fault_class, exc: None)
        self.lanes = int(engine.lanes)
        self.store = SwapStore(dir=knobs.swap_dir, faults=faults)
        # bytes one resident lane charges against the budget: the
        # analyzer's proven footprint bound when available, else the
        # allocated geometry (hv/policy.py)
        self.lane_bytes = effective_lane_bytes(engine)
        self.resident_cap = resident_lane_cap(
            self.lanes, knobs.resident_budget_bytes, self.lane_bytes)
        mv = knobs.max_virtual_lanes
        self.virtual_cap = max(int(mv), 1) if mv is not None else self.lanes
        # kept verbatim so a live reshard (resize) can re-derive the
        # per-tenant caps at the new lane width
        self._tenant_budgets: Dict[str, int] = {
            t: int(b) for t, b in (tenant_budgets or {}).items()
            if b is not None}
        self.tenant_caps: Dict[str, int] = {
            t: resident_lane_cap(self.lanes, b, self.lane_bytes)
            for t, b in self._tenant_budgets.items()}
        self.waiting: "OrderedDict[int, VirtualLane]" = OrderedDict()
        # per-resident-lane tracking (host side)
        self._last_progress: Dict[int, int] = {}
        self._resident_since: Dict[int, int] = {}
        self._last_retired = np.zeros(self.lanes, np.int64)
        self._last_trap = np.zeros(self.lanes, np.int64)
        self._install_jit = [None]
        # server-side install hook (counters/obs the server owns:
        # recycled_lanes, admission latency) — called as
        # install_cb(lane, req, first_install)
        self.install_cb = None
        # server-side loss hook: called with the request just BEFORE a
        # corrupt-entry rejection resolves its future, so the server's
        # outcome counters stay reconcilable (submitted == completed +
        # trapped + expired + killed + rejected)
        self.lost_cb = None
        self.counters = {
            "swaps_in": 0, "swaps_out": 0, "swap_out_faults": 0,
            "swap_in_faults": 0, "swap_corrupt": 0,
            "swap_bytes_out": 0, "swap_bytes_in": 0,
        }
        self.peak_admitted = 0
        self.peak_resident_by_tenant: Dict[str, int] = {}

    # -- geometry ----------------------------------------------------------
    def resize(self, lanes: int):
        """Adopt a grown lane pool after a live reshard (r21,
        serve/server.py reshard): lanes only ever grow, and global
        lane indices are preserved, so resident tracking keeps its
        entries verbatim and the per-lane mirrors pad with zeros (the
        new tail lanes are idle — no progress, trap TRAP_DONE lands
        with the next note_progress).  Budgets re-derive at the new
        width; waiting virtual lanes are keyed by request id and ride
        through untouched."""
        lanes = int(lanes)
        if lanes < self.lanes:
            raise ValueError(
                f"hv resize cannot shrink ({self.lanes} -> {lanes})")
        if lanes == self.lanes:
            return
        grow = lanes - self.lanes
        self.lanes = lanes
        self.resident_cap = resident_lane_cap(
            self.lanes, self.k.resident_budget_bytes, self.lane_bytes)
        mv = self.k.max_virtual_lanes
        self.virtual_cap = max(int(mv), 1) if mv is not None \
            else self.lanes
        self.tenant_caps = {
            t: resident_lane_cap(self.lanes, b, self.lane_bytes)
            for t, b in self._tenant_budgets.items()}
        self._last_retired = np.concatenate(
            [self._last_retired, np.zeros(grow, np.int64)])
        self._last_trap = np.concatenate(
            [self._last_trap, np.zeros(grow, np.int64)])
        self._install_jit = [None]  # retrace at the new state shapes

    # -- admission ---------------------------------------------------------
    def admitted(self, bindings) -> int:
        return len(bindings) + len(self.waiting)

    def headroom(self, bindings) -> int:
        """Virtual-lane slots still open: the oversubscription budget
        the admission phase may pop from the queue this round."""
        return max(self.virtual_cap - self.admitted(bindings), 0)

    def admit(self, req, rnd: int) -> VirtualLane:
        """Register one popped request as a fresh virtual lane (it
        installs onto a physical lane at this or a later boundary's
        rebalance, budget permitting)."""
        v = VirtualLane(req, admitted_round=rnd)
        self.waiting[req.id] = v
        return v

    def note_admitted_peak(self, bindings):
        n = self.admitted(bindings)
        if n > self.peak_admitted:
            self.peak_admitted = n

    def expire(self, now: float) -> List[object]:
        """Pop + return waiting virtual lanes whose deadline passed
        (their blobs are released; the server rejects the futures and
        counts them as in-flight kills — a virtual lane IS admitted).
        Virtual lanes whose future already resolved elsewhere (a
        gateway withdraw after a failed journal write, a crash-restore
        replay) are reaped silently — installing one would burn a
        physical lane on work its caller already disowned."""
        out = []
        for rid in [rid for rid, v in self.waiting.items()
                    if v.req.future.done
                    or (v.req.deadline is not None
                        and now >= v.req.deadline)]:
            v = self.waiting.pop(rid)
            if v.key is not None:
                self.store.release(v.key)
            if not v.req.future.done:
                out.append(v.req)
        return out

    # -- progress tracking -------------------------------------------------
    def note_progress(self, trap: np.ndarray, retired: np.ndarray,
                      total: int):
        """Called after each launch slice with the round's host mirrors:
        lanes whose retired count advanced are 'recently used' for the
        LRU key; the trap mirror backs the mid-drain exclusion."""
        retired = np.asarray(retired, np.int64)
        moved = np.nonzero(retired != self._last_retired)[0]
        for lane in moved:
            if int(lane) in self._resident_since:
                self._last_progress[int(lane)] = int(total)
        self._last_retired[:] = retired
        self._last_trap[:] = np.asarray(trap, np.int64)

    def on_install(self, lane: int, rnd: int, total: int):
        self._resident_since[lane] = rnd
        self._last_progress[lane] = total
        self._last_trap[lane] = 0   # install clears the trap plane

    def on_free(self, lane: int):
        self._resident_since.pop(lane, None)
        self._last_progress.pop(lane, None)

    def reset_residency(self, lanes, rnd: int, total: int):
        """Re-anchor the per-lane tracking after a restore/adoption:
        exactly the restored binding set is resident, everything else
        is free, and LRU history restarts at the restored cursor."""
        self._resident_since.clear()
        self._last_progress.clear()
        self._last_retired[:] = 0
        self._last_trap[:] = 0
        for lane in lanes:
            self.on_install(int(lane), rnd, total)

    # -- boundary rebalance ------------------------------------------------
    def _fits(self, tenant: str, res_by_tenant: Dict[str, int]) -> bool:
        cap = self.tenant_caps.get(tenant)
        return cap is None or res_by_tenant.get(tenant, 0) < cap

    def _next_waiter(self, res_by_tenant, skip) -> Optional[VirtualLane]:
        for rid, v in self.waiting.items():
            if rid in skip:
                continue
            if self._fits(v.req.tenant, res_by_tenant):
                return v
        return None

    def rebalance(self, state, bindings: Dict[int, object],
                  free: List[int], now: float, total: int, rnd: int):
        """The launch-boundary scheduling pass (under the server lock).

        PLAN first (pure host data: which waiters install into which
        free lanes, which victims rotate out for which waiters — all
        respecting the global resident cap and per-tenant resident
        caps), then EXECUTE: fire the swap_out seams, batch-serialize
        every victim with one device gather per plane, park them in
        one column set, and install the planned waiters (fresh ones
        grouped per function through the recycler's batched install,
        swapped ones through the jitted per-lane column restore).
        Mutates `bindings` and the `free` heap in place; returns the
        updated state."""
        import heapq

        if not self.waiting:
            self.note_admitted_peak(bindings)
            return state
        res: Dict[str, int] = {}
        for req in bindings.values():
            res[req.tenant] = res.get(req.tenant, 0) + 1
        skip = set()          # waiter ids already planned this round
        plan: List[tuple] = []   # (lane, VirtualLane) to install
        # -- phase 1 plan: free lanes, resident budget permitting
        planned_resident = len(bindings)
        while free and planned_resident < self.resident_cap:
            v = self._next_waiter(res, skip)
            if v is None:
                break
            lane = heapq.heappop(free)
            plan.append((lane, v))
            skip.add(v.req.id)
            res[v.req.tenant] = res.get(v.req.tenant, 0) + 1
            planned_resident += 1
        # -- phase 2 plan: rotate victims out for remaining waiters
        budget = self.k.max_swaps_per_round
        budget = int(budget) if budget is not None else self.lanes
        pairs: List[tuple] = []   # (victim_lane, victim_req, waiter)
        planned_victims = set()   # rotating out this round
        no_fit = set()            # eviction would seat no waiter
        while budget > 0:
            cands = [
                EvictionCandidate(
                    lane=lane,
                    last_progress_step=self._last_progress.get(lane, 0),
                    resident_since_round=self._resident_since.get(
                        lane, rnd),
                    deadline=req.deadline,
                    trap=int(self._last_trap[lane]))
                for lane, req in bindings.items()
                if lane not in planned_victims and lane not in no_fit]
            # the sole-runnable guard credits lanes outside `cands`
            # that still keep the device busy: installs planned this
            # boundary, rotation pairs (each removes one runnable but
            # seats another), and no_fit lanes (excluded from the pick
            # yet still resident and runnable)
            victims = pick_victims(
                cands, 1, now, rnd,
                min_resident_rounds=int(self.k.min_resident_rounds),
                incoming_runnable=len(plan) + len(pairs)
                + len(no_fit))
            if not victims:
                break
            victim = victims[0]
            vreq = bindings[victim]
            # the eviction must buy an installable waiter: account the
            # victim's slot as freed when checking tenant caps (an
            # own-tenant rotation always fits).  When THIS victim's
            # eviction seats nobody (a capped tenant's waiter needs its
            # OWN lane back, not another tenant's), move on to the next
            # victim in policy order instead of abandoning rotation —
            # otherwise a capped tenant's virtual lane starves behind a
            # colder lane it can never use.
            after = dict(res)
            after[vreq.tenant] = max(after.get(vreq.tenant, 1) - 1, 0)
            v = self._next_waiter(after, skip)
            if v is None:
                no_fit.add(victim)
                continue
            pairs.append((victim, vreq, v))
            planned_victims.add(victim)
            skip.add(v.req.id)
            res = after
            res[v.req.tenant] = res.get(v.req.tenant, 0) + 1
            budget -= 1
        # -- execute: swap victims out (seams -> batched serialize ->
        # one park), collecting the lanes that actually freed
        state, freed_pairs = self._swap_out_batch(state, pairs,
                                                  bindings, rnd)
        installs = plan + freed_pairs
        # -- execute: install planned waiters.  Fresh lanes group per
        # function (one recycler column-set pass each, exactly like
        # plain admission); swapped lanes restore per-lane.
        state = self._install_batch(state, installs, bindings, free,
                                    total, rnd)
        self.note_admitted_peak(bindings)
        return state

    # -- swap-out ----------------------------------------------------------
    def _swap_out_batch(self, state, pairs, bindings, rnd: int):
        """Swap a planned victim set out: per-victim `swap_out` seam,
        ONE batched device gather per plane for the survivors, per-
        victim store put (its own `swap_store_write` seam), one park.
        A fault at any victim's seam/put leaves THAT lane resident and
        its paired waiter waiting (retried next boundary); the rest of
        the batch proceeds.  Returns (state, [(freed_lane, waiter)])."""
        if not pairs:
            return state, []
        t0 = self.obs.now()
        live = []
        for victim, vreq, waiter in pairs:
            try:
                if self.faults is not None:
                    self.faults.fire("swap_out", lane=int(victim),
                                     id=vreq.id)
                live.append((victim, vreq, waiter))
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.counters["swap_out_faults"] += 1
                self._record("swap", e)
        if not live:
            return state, []
        cur = getattr(self.engine, "_stdout_cursor", None)
        lanes_idx = [victim for victim, _, _ in live]
        spos = [int(cur[0][lane]) if cur is not None else 0
                for lane in lanes_idx]
        try:
            payloads = serialize_lanes(state, lanes_idx, self.lanes,
                                       stdout_pos=spos)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # a whole-batch serialization failure leaves every victim
            # resident — the boundary retries
            self.counters["swap_out_faults"] += len(live)
            self._record("swap", e)
            return state, []
        parked = []
        freed_pairs = []
        for (victim, vreq, waiter), payload, sp in zip(live, payloads,
                                                       spos):
            try:
                key = self.store.put(payload)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.counters["swap_out_faults"] += 1
                self._record("swap", e)
                continue
            v = VirtualLane(vreq, key=key, stdout_pos=sp,
                            admitted_round=rnd)
            v.swaps = 1
            self.waiting[vreq.id] = v      # FIFO tail: behind waiters
            bindings.pop(victim, None)
            self.on_free(victim)
            parked.append(victim)
            freed_pairs.append((victim, waiter))
            self.counters["swaps_out"] += 1
            self.counters["swap_bytes_out"] += len(payload)
            self.obs.instant("swap_out", cat="hv", track="hv",
                             lane=int(victim), id=vreq.id,
                             nbytes=len(payload), tenant=vreq.tenant)
        if parked:
            state = self.recycler.park(state, parked)
            self.obs.observe_swap("out", self.obs.now() - t0)
        return state, freed_pairs

    # -- swap-in / install -------------------------------------------------
    def _install_batch(self, state, installs, bindings, free,
                       total: int, rnd: int):
        """Install planned (lane, VirtualLane) pairs: fresh lanes batch
        per function through the recycler template seam; swapped lanes
        batch through one jitted column-set pass (_swap_in_batch).  A
        failed install pushes its lane back onto the free heap."""
        fresh: Dict[int, List[tuple]] = {}
        swapped: List[tuple] = []
        for lane, v in installs:
            if v.fresh:
                fidx = self.recycler.func_idx(v.req.func_name)
                fresh.setdefault(fidx, []).append((lane, v))
            else:
                swapped.append((lane, v))
        for fidx, group in fresh.items():
            lanes_list = [lane for lane, _ in group]
            nargs = max((len(v.req.args) for _, v in group), default=0)
            args_rows = [[(v.req.args[i] if i < len(v.req.args) else 0)
                          for _, v in group] for i in range(nargs)]
            state = self.recycler.install(state, lanes_list, fidx,
                                          args_rows)
            for lane, v in group:
                self._finish_install(lane, v, bindings, total, rnd)
        return self._swap_in_batch(state, swapped, bindings, free,
                                   total, rnd)

    def _swap_in_batch(self, state, pairs, bindings, free,
                       total: int, rnd: int):
        """Restore swapped virtual lanes: per-lane `swap_in` seam +
        fetch + verify, then ONE jitted column-set pass over the whole
        surviving set (the mirror of _swap_out_batch's batched gather
        — a per-lane jit dispatch would pay the overhead once per
        victim per boundary).  A faulted swap-in re-queues its virtual
        lane without losing it (the lane stays free); a corrupt store
        entry rejects that one request machine-readably."""
        import heapq

        if not pairs:
            return state
        t0 = self.obs.now()
        ready = []   # (lane, v, cols, spos, nbytes)
        for lane, v in pairs:
            req = v.req
            try:
                if self.faults is not None:
                    self.faults.fire("swap_in", lane=int(lane),
                                     id=req.id)
                payload = self.store.get(v.key)
                cols, spos = deserialize_lane(payload)
            except (KeyboardInterrupt, SystemExit):
                raise
            except SwapCorrupt as e:
                # the state is unrecoverable: machine-readable failure
                # for THIS request; everyone else keeps serving
                from wasmedge_tpu.serve.queue import ServeRejected

                self.counters["swap_corrupt"] += 1
                self._record("swap", e)
                self.waiting.pop(req.id, None)
                self.store.release(v.key)
                if self.lost_cb is not None and not req.future.done:
                    self.lost_cb(req)
                req.future._reject(ServeRejected(
                    f"request {req.id} lost: swapped lane state "
                    f"corrupt ({e.reason})"))
                heapq.heappush(free, lane)
                continue
            except Exception as e:
                self.counters["swap_in_faults"] += 1
                self._record("swap", e)
                heapq.heappush(free, lane)
                continue
            ready.append((lane, v, cols, spos, len(payload)))
        if not ready:
            return state
        try:
            state = self._install_columns(
                state, [r[0] for r in ready], [r[2] for r in ready])
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # whole-batch install failure: every lane stays free and
            # every virtual lane keeps waiting — retried next boundary
            self.counters["swap_in_faults"] += len(ready)
            self._record("swap", e)
            for lane, *_ in ready:
                heapq.heappush(free, lane)
            return state
        cur = getattr(self.engine, "_stdout_cursor", None)
        for lane, v, cols, spos, nbytes in ready:
            req = v.req
            if cur is not None:
                # continue the REQUEST's logical output stream on the
                # new physical lane: pos picks up where the request
                # left off, and the written high-water collapses to it
                # (the target lane's history belongs to other requests)
                cur[0][lane] = spos
                cur[1][lane] = spos
            self.store.release(v.key)
            self.counters["swaps_in"] += 1
            self.counters["swap_bytes_in"] += nbytes
            self.obs.instant("swap_in", cat="hv", track="hv",
                             lane=int(lane), id=req.id,
                             tenant=req.tenant)
            self._finish_install(lane, v, bindings, total, rnd)
        self.obs.observe_swap("in", self.obs.now() - t0)
        return state

    def _finish_install(self, lane: int, v: VirtualLane, bindings,
                        total: int, rnd: int):
        req = v.req
        self.waiting.pop(req.id, None)
        bindings[lane] = req
        if self.install_cb is not None:
            self.install_cb(lane, req, v.fresh)
        self.on_install(lane, rnd, total)
        n = sum(1 for r in bindings.values() if r.tenant == req.tenant)
        if n > self.peak_resident_by_tenant.get(req.tenant, 0):
            self.peak_resident_by_tenant[req.tenant] = n

    def _install_columns(self, state, lanes_list, cols_list):
        return install_lane_columns(state, self.lanes, lanes_list,
                                    cols_list, self._install_jit)

    # -- checkpoint / restore ----------------------------------------------
    def journal_entries(self) -> List[dict]:
        return [v.journal() for v in self.waiting.values()]

    def snapshot_payload(self) -> List[tuple]:
        """In-memory lineage payload: (req, key, stdout_pos) triples —
        request OBJECTS so an in-process restore resolves the futures
        callers already hold."""
        return [(v.req, v.key, v.stdout_pos)
                for v in self.waiting.values()]

    def blob_arrays(self, record=None) -> Dict[str, np.ndarray]:
        """Swapped blobs as npz-ready uint8 arrays, read from the store
        WITHOUT faulting any lane in — the checkpoint embeds them so a
        restore never depends on store retention.  Corrupt entries are
        recorded and skipped (the restore path re-queues those ids)."""
        out = {}
        for v in self.waiting.values():
            if v.key is None:
                continue
            try:
                payload = self.store.get(v.key)
            except SwapCorrupt as e:
                (record or self._record)("swap", e)
                continue
            out[f"hvblob_{v.key}"] = np.frombuffer(payload, np.uint8)
        return out

    def restore(self, triples, blobs: Dict[str, bytes],
                covered_ids) -> List[object]:
        """Reset the virtual table to a snapshot's view.  `triples` are
        (req, key, stdout_pos); `blobs` maps key -> payload bytes (the
        snapshot-embedded copies); ids in `covered_ids` (the snapshot's
        RESIDENT bindings) are skipped — a request must never be both
        resident and virtual.  Returns requests whose swapped state
        could not be restored (corrupt/missing blob) for the caller to
        re-queue or reject."""
        for v in self.waiting.values():
            if v.key is not None:
                self.store.release(v.key)
        self.waiting.clear()
        lost = []
        for req, key, spos in triples:
            if req.id in covered_ids or req.future.done:
                continue
            if key is not None:
                payload = blobs.get(key)
                try:
                    if payload is None:
                        raise SwapCorrupt(key, "blob missing from "
                                               "snapshot")
                    self.store.adopt(key, bytes(payload))
                except SwapCorrupt as e:
                    self.counters["swap_corrupt"] += 1
                    self._record("swap", e)
                    lost.append(req)
                    continue
            self.waiting[req.id] = VirtualLane(req, key=key,
                                               stdout_pos=spos)
        return lost

    def drop_all(self) -> List[object]:
        """Shutdown/terminal-failure sweep: release every blob and
        return the virtual requests so the server can reject their
        futures."""
        out = []
        for v in self.waiting.values():
            if v.key is not None:
                self.store.release(v.key)
            out.append(v.req)
        self.waiting.clear()
        return out

    # -- introspection -----------------------------------------------------
    def stats(self, bindings) -> dict:
        swapped = sum(1 for v in self.waiting.values()
                      if v.key is not None)
        return {
            "resident": len(bindings),
            "virtual": len(self.waiting),
            "virtual_swapped": swapped,
            "virtual_fresh": len(self.waiting) - swapped,
            "max_virtual_lanes": self.virtual_cap,
            "resident_cap": self.resident_cap,
            "lane_bytes": self.lane_bytes,
            "tenant_resident_caps": dict(self.tenant_caps),
            "peak_admitted": self.peak_admitted,
            "peak_resident_by_tenant":
                dict(self.peak_resident_by_tenant),
            "store_entries": len(self.store),
            "store_bytes": self.store.bytes_held,
            **self.counters,
        }


# ---------------------------------------------------------------------------
# shared column-install pass (hv swap-in + effects/ session unpark)
# ---------------------------------------------------------------------------
def install_lane_columns(state, total_lanes: int, lanes_list, cols_list,
                         jit_cache):
    """One jitted column-set pass restoring every serialized plane at
    the given lanes (the swap-in half of the recycler's install seam —
    same donation discipline and power-of-two index padding, so at most
    log2(lanes)+1 variants compile per engine).  Pads repeat lane 0
    with lane 0's columns: duplicate index writes carry identical
    values, so the pads are idempotent.

    `jit_cache` is a single-slot list holding the compiled setter; the
    owner clears it (sets [None]) when the state geometry changes
    (reshard) so the pass retraces at the new shapes.  Shared with the
    effects/ runtime: a parked session's unpark install is the exact
    code path of an hv swap-in."""
    import jax
    import jax.numpy as jnp

    if jit_cache[0] is None:
        def install(state, idx, cols):
            updates = {}
            for name, col in cols.items():
                plane = getattr(state, name)
                if plane.ndim == 1:
                    updates[name] = plane.at[idx].set(col)
                else:
                    updates[name] = plane.at[:, idx].set(col)
            return state._replace(**updates)

        donate = (0,)
        if jax.default_backend() == "cpu" and \
                getattr(jax.config, "jax_compilation_cache_dir", None):
            donate = ()
        jit_cache[0] = jax.jit(install, donate_argnums=donate)
    n = len(lanes_list)
    w = min(total_lanes, 1 << (n - 1).bit_length())
    idx = np.full(w, lanes_list[0], np.int64)
    idx[:n] = lanes_list
    stacked = {}
    for name in cols_list[0]:
        cols = [np.asarray(c[name]) for c in cols_list]
        cols = cols + [cols[0]] * (w - n)
        # branch on the PLANE's rank, not the column's: serialized
        # columns of 1-D planes arrive as shape (1,) (numpy's
        # ascontiguousarray promotes 0-d scalars), which is
        # indistinguishable from a depth-1 2-D plane's column
        if getattr(state, name).ndim == 1:
            stacked[name] = np.asarray(
                [c.reshape(()) for c in cols])          # (w,)
        else:
            stacked[name] = np.stack(cols, axis=-1)     # (D, w)
    return jit_cache[0](state, jnp.asarray(idx),
                        {k: jnp.asarray(a) for k, a in stacked.items()})
