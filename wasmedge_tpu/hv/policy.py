"""Eviction policy + resident-budget math for lane virtualization.

Everything here is a pure, deterministic function over plain host data
— the manager feeds it per-lane tracking dicts and it returns ordered
victim lists — so the policy is unit-testable without a device and two
runs over the same inputs always evict the same lanes (the bit-
identical oversubscription guarantee depends on it).

Eviction order (most-evictable first):
  1. LRU over last-retired step: lanes whose retired count has not
     advanced for the longest (parked/blocked lanes have the stalest
     progress, so the "biased toward parked/blocked" clause falls out
     of the same key)
  2. deadline distance: among equally-cold lanes, no-deadline lanes
     first, then the most deadline-DISTANT (evicting a lane about to
     meet its deadline would convert a near-win into a 504)
  3. longest-resident first (round-robin rotation under ties — every
     virtual lane gets device time, so no future starves)
  4. lane index (total order: determinism under full ties)

Hard exclusions (never victims):
  - a lane mid-hostcall-drain (trap == TRAP_HOSTCALL): its host-side
    outcall is in flight and the drain writes back into the column
  - the sole runnable resident lane: evicting it would idle the device
  - a lane resident for fewer than `min_resident_rounds` (anti-thrash:
    every install earns at least one launch slice)
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class EvictionCandidate:
    """Host-side view of one resident lane at a launch boundary."""

    lane: int
    last_progress_step: int     # server total at last retired advance
    resident_since_round: int   # round the lane was (re)installed
    deadline: Optional[float]   # monotonic stamp, None = none
    trap: int = 0               # 0 running / TRAP_HOSTCALL mid-drain


def pick_victims(candidates: Sequence[EvictionCandidate], need: int,
                 now: float, current_round: int,
                 min_resident_rounds: int = 1,
                 incoming_runnable: int = 0) -> List[int]:
    """Up to `need` victim lane indices, most-evictable first.  Pure +
    deterministic (see module docstring for the order).

    Only RUNNABLE lanes (trap == 0) are eligible: that excludes every
    parked/trapped lane and, in particular, a mid-hostcall-drain lane
    (the TRAP_HOSTCALL sentinel is nonzero) — its host-side outcall
    writes back into the column.

    `incoming_runnable` counts runnable lanes the caller has already
    planned to install this same boundary (they are not in
    `candidates` yet) — the sole-runnable guard must credit them, or a
    server that frees a lane every round would never rotate."""
    if need <= 0:
        return []
    eligible = [c for c in candidates
                if c.trap == 0
                and current_round - c.resident_since_round
                >= min_resident_rounds]
    runnable = sum(1 for c in candidates if c.trap == 0) \
        + max(int(incoming_runnable), 0)
    # never the sole runnable lane: at least one runnable resident must
    # survive every eviction pass or the device idles (and a 1-lane
    # server would stall outright)
    max_evict = max(runnable - 1, 0)

    def key(c: EvictionCandidate):
        return (
            c.last_progress_step,
            0 if c.deadline is None else 1,
            -(c.deadline - now) if c.deadline is not None else 0.0,
            c.resident_since_round,
            c.lane,
        )

    picks = sorted(eligible, key=key)[:min(need, max_evict)]
    return [c.lane for c in picks]


# ---------------------------------------------------------------------------
# resident-bytes budget
# ---------------------------------------------------------------------------
def effective_lane_bytes(engine) -> int:
    """Bytes of device state the budget charges per resident lane.

    Seeded from `DeviceImage.analysis` static footprint bounds when the
    analyzer proved them (analysis/analyzer.py: mem_pages_bound /
    value_stack_bound / call_depth_bound) — a module proven to touch
    one page and 40 stack slots should not be charged for the full
    configured plane allocation, since that is exactly the headroom a
    kernel-tier block-packed layout reclaims.  Each term clamps to the
    engine's actual allocation (image page ceiling, configured stack/
    frame depths), so the bound never exceeds what the planes hold.
    Falls back to the allocated geometry for unbounded or unanalyzed
    modules, so the budget is never optimistic without proof."""
    analysis = getattr(getattr(engine, "img", None), "analysis", None)
    if analysis is None:
        return _geometry_lane_bytes(engine)
    pages = getattr(analysis, "mem_pages_bound", None)
    stack = getattr(analysis, "value_stack_bound", None)
    depth = getattr(analysis, "call_depth_bound", None)
    if pages is None or stack is None or depth is None:
        return _geometry_lane_bytes(engine)
    # absint page-touch bound (r19): when the abstract interpreter
    # proved every access site's reach, the pages a lane can DIRTY are
    # tighter than what the module declares — the swap/budget cost a
    # content-addressed store actually pays tracks dirtied pages, so
    # the budget charges the proven touch, never more than declared
    touched = getattr(analysis, "mem_pages_touch_bound", None)
    if touched is not None:
        pages = min(int(pages), int(touched))
    cfg = engine.cfg
    mem_b = min(int(pages), int(engine.img.mem_pages_max)) * 65536
    # per-slot cost matches the allocated plane set: lo/hi int32 pairs,
    # plus e2/e3 only when the image carries the v128 extension planes
    slot_b = 4 * (4 if getattr(engine.img, "has_simd", False) else 2)
    stack_b = min(int(stack), int(cfg.value_stack_depth)) * slot_b
    frame_b = min(int(depth), int(cfg.call_stack_depth)) * 12
    bound = max(mem_b + stack_b + frame_b + 256, 1)
    # the proven bound can never charge MORE than the allocation holds
    return min(bound, _geometry_lane_bytes(engine))


def _geometry_lane_bytes(engine) -> int:
    """Static per-lane byte estimate from the engine geometry alone
    (no state built yet): memory plane + value stack (lo/hi[/e2/e3]) +
    frame planes + globals + fixed scalars."""
    cfg = engine.cfg
    img = engine.img
    mem_b = max(int(img.mem_pages_max), 0) * 65536 \
        if getattr(img, "has_memory", True) else 0
    simd = 4 if getattr(img, "has_simd", False) else 2
    stack_b = int(cfg.value_stack_depth) * 4 * simd
    frame_b = int(cfg.call_stack_depth) * 12
    glob_b = len(getattr(img, "globals_lo", ())) * 8
    return max(mem_b + stack_b + frame_b + glob_b + 256, 1)


def resident_lane_cap(lanes: int, budget_bytes: Optional[int],
                      lane_bytes: int) -> int:
    """Physical lanes the resident-bytes budget admits concurrently:
    floor(budget / bytes-per-lane), clamped to [1, lanes] — at least
    one lane must stay installable or the server deadlocks with work
    admitted."""
    if budget_bytes is None:
        return int(lanes)
    return max(1, min(int(lanes), int(budget_bytes) // max(lane_bytes, 1)))
