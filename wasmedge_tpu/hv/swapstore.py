"""Host-side swap store for cold lane state + the lane column serializer.

A swapped-out virtual lane is one column of every lane-axis BatchState
plane (pc/stacks/frames/globals/memory/t0 — exactly the planes
batch/checkpoint.py snapshots, one lane wide) packed into a compressed
npz payload.  The `SwapStore` keys payloads by content (sha256), keeps
them in memory, and — when given a directory — mirrors them to disk
through `utils/fsio.atomic_write_bytes`, so a crash mid-swap can never
leave a truncated blob where a later swap-in would trip over it.

Integrity is end-to-end: `get()` re-hashes the payload against its key
and raises `SwapCorrupt` on any mismatch (bit rot, torn write, a
crafted file) — the caller decides whether that is a skip-and-record
(checkpoint adoption) or a rejected request (live swap-in).

Blobs are refcounted, not garbage-collected by scan: the manager
releases a key when the owning request resolves (or when a re-swap
supersedes it); serve checkpoints embed the payload bytes directly in
the snapshot npz, so a restore never depends on the store's retention.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from wasmedge_tpu.utils.fsio import atomic_write_bytes


class SwapCorrupt(RuntimeError):
    """A swap-store payload failed its content-hash check (or is
    missing entirely): the lane state it held is unrecoverable.  Live
    swap-ins surface this as a machine-readable request failure;
    lineage adoption records and skips the entry."""

    def __init__(self, key: str, reason: str):
        super().__init__(f"swap entry {key[:12]}… corrupt: {reason}")
        self.key = key
        self.reason = reason


class SwapStore:
    """Content-addressed, refcounted host store for swapped lane state.

    `faults` is an optional testing.faults.FaultInjector: `put()` fires
    the `swap_store_write` seam before any bytes move, so an injected
    store failure leaves neither a memory entry nor a disk file — the
    swap-out that drove it keeps its lane resident and retries at the
    next boundary."""

    def __init__(self, dir: Optional[str] = None, faults=None):
        self.dir = os.fspath(dir) if dir else None
        self.faults = faults
        self._mem: Dict[str, bytes] = {}
        self._refs: Dict[str, int] = {}
        self.puts = 0
        self.gets = 0
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)

    def __len__(self) -> int:
        return len(self._mem)

    @property
    def bytes_held(self) -> int:
        return sum(len(b) for b in self._mem.values())

    @staticmethod
    def key_of(payload: bytes) -> str:
        return hashlib.sha256(payload).hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.lane")

    def put(self, payload: bytes) -> str:
        """Store one serialized lane; returns the content key.  An
        identical payload (same content) shares the entry — the
        refcount tracks owners."""
        key = self.key_of(payload)
        if self.faults is not None:
            self.faults.fire("swap_store_write", key=key,
                             nbytes=len(payload))
        if key not in self._mem:
            self._mem[key] = bytes(payload)
            if self.dir:
                atomic_write_bytes(self._path(key), payload)
        self._refs[key] = self._refs.get(key, 0) + 1
        self.puts += 1
        # r24 at-rest rot seam: an armed BitFlip corrupts the STORED
        # copy (memory and disk mirror both) after the key is issued —
        # get() detects on read; the integrity scrubber detects before
        # a wake needs it and repairs from a mirror or a fleet peer
        if self.faults is not None and hasattr(self.faults, "flip"):
            rotted = self.faults.flip("corrupt_swap", self._mem[key],
                                      key=key, nbytes=len(payload))
            if rotted is not self._mem[key]:
                self._mem[key] = rotted
                if self.dir:
                    atomic_write_bytes(self._path(key), rotted)
        return key

    def adopt(self, key: str, payload: bytes):
        """Re-seed an entry from a checkpoint-embedded blob (restore
        path).  The payload is verified against the key FIRST — a
        corrupt snapshot blob must never become a trusted entry."""
        if self.key_of(payload) != key:
            raise SwapCorrupt(key, "adopted payload hash mismatch")
        if key not in self._mem:
            self._mem[key] = bytes(payload)
            if self.dir:
                atomic_write_bytes(self._path(key), payload)
        self._refs[key] = self._refs.get(key, 0) + 1

    def get(self, key: str) -> bytes:
        """Fetch + verify one payload; raises SwapCorrupt on hash
        mismatch or a missing entry."""
        self.gets += 1
        payload = self._mem.get(key)
        if payload is None and self.dir:
            try:
                with open(self._path(key), "rb") as f:
                    payload = f.read()
            except OSError as e:
                raise SwapCorrupt(key, f"unreadable: {e}") from e
        if payload is None:
            raise SwapCorrupt(key, "missing entry")
        if self.key_of(payload) != key:
            raise SwapCorrupt(key, "content hash mismatch")
        return payload

    # -- at-rest scrubbing (wasmedge_tpu/integrity/scrub.py, r24) ----------
    def scrub_keys(self):
        """Every key the store currently claims to hold (memory plus
        any disk mirrors) — the scrubber's walk set."""
        keys = set(self._mem)
        if self.dir:
            try:
                for fn in os.listdir(self.dir):
                    if fn.endswith(".lane"):
                        keys.add(fn[:-len(".lane")])
            except OSError:
                pass
        return sorted(keys)

    def scrub_verify(self, key: str):
        """Verify both copies of one entry, healing a bad mirror from a
        good one.  Returns (status, payload): "ok" both copies verify
        (or the only copy does), "healed" one copy was corrupt and was
        rewritten from the other, "corrupt" no copy verifies (payload
        None — the caller repairs from a peer replica or gives up)."""
        mem = self._mem.get(key)
        mem_ok = mem is not None and self.key_of(mem) == key
        disk = None
        disk_ok = False
        if self.dir:
            try:
                with open(self._path(key), "rb") as f:
                    disk = f.read()
                disk_ok = self.key_of(disk) == key
            except OSError:
                disk = None
        good = mem if mem_ok else (disk if disk_ok else None)
        if good is None:
            return "corrupt", None
        healed = False
        if mem is not None and not mem_ok:
            self._mem[key] = bytes(good)
            healed = True
        if self.dir and disk is not None and not disk_ok:
            atomic_write_bytes(self._path(key), good)
            healed = True
        return ("healed" if healed else "ok"), good

    def scrub_restore(self, key: str, payload: bytes) -> bool:
        """Reinstall a repaired payload (e.g. fetched from a fleet
        peer).  Verified against the key first; refcounts untouched —
        the entry's owners never noticed the rot."""
        if self.key_of(payload) != key:
            return False
        if key in self._mem:
            self._mem[key] = bytes(payload)
        if self.dir:
            atomic_write_bytes(self._path(key), payload)
        return True

    def scrub_evict(self, key: str):
        """Drop an unrepairable entry's copies (refcounts kept so a
        later release stays a no-op): the next reader takes the
        missing-entry path instead of trusting rot."""
        self._mem.pop(key, None)
        if self.dir:
            try:
                os.unlink(self._path(key))
            except OSError:
                pass

    def peek(self, key: str) -> Optional[bytes]:
        """Best-effort VERIFIED read for fleet replica serving: returns
        the payload only when a local copy matches the key (corruption
        must never propagate to a repairing peer), else None.  Does not
        count as a get."""
        for payload in (self._mem.get(key),):
            if payload is not None and self.key_of(payload) == key:
                return payload
        if self.dir:
            try:
                with open(self._path(key), "rb") as f:
                    payload = f.read()
            except OSError:
                return None
            if self.key_of(payload) == key:
                return payload
        return None

    def release(self, key: str):
        """Drop one reference; the entry (and its disk mirror) goes
        away with the last one.  Unknown keys are a no-op — a restore
        may release entries an older process owned."""
        n = self._refs.get(key)
        if n is None:
            return
        if n > 1:
            self._refs[key] = n - 1
            return
        self._refs.pop(key, None)
        self._mem.pop(key, None)
        if self.dir:
            try:
                os.unlink(self._path(key))
            except OSError:
                pass


# ---------------------------------------------------------------------------
# lane column serialization
# ---------------------------------------------------------------------------
def lane_plane_names(state, lanes: int) -> Tuple[str, ...]:
    """The BatchState fields that carry a per-lane column (last axis ==
    lanes) — the same detection rule the LaneRecycler's template
    capture uses, so the two seams can never disagree about what
    constitutes 'lane state'."""
    out = []
    for name in state._fields:
        plane = getattr(state, name)
        if plane is None:
            continue
        arr = np.asarray(plane)
        if arr.ndim == 0 or arr.shape[-1] != lanes:
            continue  # no lane axis (e.g. the op_hist histogram)
        out.append(name)
    return tuple(out)


def serialize_lanes(state, lane_idx, lanes: int,
                    stdout_pos=None) -> list:
    """Several lanes' plane columns -> one compressed npz payload per
    lane.  Batched on purpose: ONE device->host gather per plane for
    the whole victim set (a per-lane loop would pay the dispatch
    overhead `planes x victims` times per boundary).

    `stdout_pos[k]` is lane k's logical stdout stream position
    (batch/hostcall.py cursor) — it rides the payload so a swap-in onto
    a DIFFERENT physical lane continues the request's output stream
    instead of inheriting the target lane's history."""
    idx = np.asarray(lane_idx, np.int64)
    names = lane_plane_names(state, lanes)
    mirrors = {}
    for name in names:
        plane = getattr(state, name)
        # jnp fancy-index gathers only the victim columns; np.asarray
        # then moves exactly those bytes host-side
        mirrors[name] = np.asarray(plane[..., idx])
    out = []
    for k in range(idx.size):
        arrays = {f"p_{name}": np.ascontiguousarray(m[..., k])
                  for name, m in mirrors.items()}
        meta = {"planes": list(names),
                "stdout_pos": int(stdout_pos[k])
                if stdout_pos is not None else 0}
        buf = io.BytesIO()
        np.savez_compressed(buf, meta=json.dumps(meta), **arrays)
        out.append(buf.getvalue())
    return out




def deserialize_lane(payload: bytes) -> Tuple[Dict[str, np.ndarray], int]:
    """Payload bytes -> ({plane_name: column}, stdout_pos)."""
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        cols = {name: z[f"p_{name}"] for name in meta["planes"]}
    return cols, int(meta.get("stdout_pos", 0))


def serialize_columns(cols: Dict[str, np.ndarray],
                      meta: Optional[dict] = None) -> bytes:
    """Generic named-column payload (same npz envelope as lane
    serialization, arbitrary names + JSON side-meta).  The imagestore
    snapshot path stores a module's post-init plane columns this way so
    they content-address and integrity-check through the same SwapStore
    machinery as swapped lanes."""
    arrays = {f"p_{name}": np.ascontiguousarray(arr)
              for name, arr in cols.items()}
    m = dict(meta or {})
    m["planes"] = sorted(cols)
    buf = io.BytesIO()
    np.savez_compressed(buf, meta=json.dumps(m), **arrays)
    return buf.getvalue()


def deserialize_columns(payload: bytes
                        ) -> Tuple[Dict[str, np.ndarray], dict]:
    """Payload bytes -> ({name: array}, meta dict)."""
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        cols = {name: z[f"p_{name}"] for name in meta["planes"]}
    return cols, meta
