"""Segmented device images, the persistent compile cache, and
pre-initialized lane snapshots (r22) — the cold-start subsystem.

Three coupled pieces, all knob-gated through `Configure.imagestore`
(every default OFF reproduces the r21 path bit-identically):

- `segments.SegmentCache` memoizes per-module rebased image segments so
  registering module N+1 rebases exactly one segment and a generation
  swap is an indirection-table update, not an O(modules) rebuild.
- `compilecache.CompileCache` is ONE sha256-keyed lowering cache: the
  r12 in-memory probe stash is its hot tier, the aot image payload its
  persistent tier — gateway restarts and fleet siblings never re-lower.
- `snapshot` captures a module's post-`_start` plane columns once at
  registration (content-addressed through hv/swapstore) and admits new
  requests by installing the snapshot through the recycler's jitted
  column-set pass instead of replaying init per lane.
"""

from wasmedge_tpu.imagestore.compilecache import CompileCache
from wasmedge_tpu.imagestore.segments import SegmentCache
from wasmedge_tpu.imagestore.snapshot import (
    SnapshotEntry,
    capture_snapshot,
    decode_overlay,
    init_export_of,
)

__all__ = [
    "CompileCache",
    "SegmentCache",
    "SnapshotEntry",
    "capture_snapshot",
    "decode_overlay",
    "init_export_of",
]
