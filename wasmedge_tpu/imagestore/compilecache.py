"""ONE sha256-keyed lowering cache for the serving gateway (r22).

Unifies the two places a finished lowering used to hide:

- the r12 registry probe cache — instantiated `RegisteredModule`s whose
  registration was rolled back after the expensive lowering succeeded —
  is now the cache's HOT tier (`stash_probe`/`pop_probe`, unchanged
  adopt-on-re-POST semantics, so the `lowered_count` pins hold);
- the aot image payload (`aot.serialize_image`, the exact bytes a
  `.twasm` embeds) is the PERSISTENT tier: content-addressed by the
  module's wasm sha256, mirrored to disk when a directory is enabled,
  consulted by the validator's precompiled fast path on the next
  registration — across gateway restarts and, via
  `entry_bytes`/`adopt_entry`, across fleet siblings (the r16 peer
  protocol replicates entries alongside module blobs).

Entry file format (`<dir>/<sha>.img`): magic ``WTIC`` + u32 version +
raw sha256(payload) + payload.  Integrity is end-to-end — `load()`
re-hashes the payload against the stored digest and treats any mismatch
as a miss (a corrupt entry falls back to a fresh lower, never serves
wrong code).  The `cache_read` fault seam (testing/faults.py) injects
exactly that failure."""

from __future__ import annotations

import hashlib
import os
import struct
import threading
from collections import OrderedDict
from typing import Dict, Optional

_MAGIC = b"WTIC"
_VERSION = 1
_HEADER = struct.Struct("<4sI32s")

# probe-tier depth (unchanged from the r12 registry stash): each entry
# pins an instantiated module + two sink fds, so keep it small
PROBE_DEPTH = 4


def _new_counts() -> Dict[str, int]:
    return {"probe_hits": 0, "disk_hits": 0, "misses": 0, "stores": 0,
            "corrupt": 0, "read_faults": 0}


class CompileCache:
    """Two-tier content-addressed lowering cache.

    Constructed unconditionally by the ModuleRegistry (the probe tier
    IS the r12 behavior); the persistent tier stays inert until
    `enable()` — so a gateway without the knob is bit-identical r21."""

    def __init__(self, faults=None):
        self.faults = faults
        self._lock = threading.Lock()
        self._probe: "OrderedDict[str, object]" = OrderedDict()
        self.dir: Optional[str] = None
        self._payloads: Optional[Dict[str, bytes]] = None
        self.counts = _new_counts()

    # -- persistent tier ---------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._payloads is not None or self.dir is not None

    def enable(self, dir: Optional[str] = None) -> None:
        """Turn the persistent tier on.  With a directory entries
        mirror to disk (restart + fleet survival); without one they
        stay in-memory for the process lifetime (still unifies the
        probe/aot paths and still serves fleet replication)."""
        if dir:
            self.dir = os.fspath(dir)
            os.makedirs(self.dir, exist_ok=True)
        if self._payloads is None:
            self._payloads = {}

    def _path(self, sha: str) -> str:
        return os.path.join(self.dir, f"{sha}.img")

    @staticmethod
    def _encode(payload: bytes) -> bytes:
        return _HEADER.pack(_MAGIC, _VERSION,
                            hashlib.sha256(payload).digest()) + payload

    @staticmethod
    def _decode(raw: bytes) -> Optional[bytes]:
        """Entry bytes -> verified payload, or None for anything torn,
        truncated, version-skewed, or bit-rotted."""
        if len(raw) < _HEADER.size:
            return None
        magic, version, digest = _HEADER.unpack_from(raw)
        if magic != _MAGIC or version != _VERSION:
            return None
        payload = raw[_HEADER.size:]
        if hashlib.sha256(payload).digest() != digest:
            return None
        return payload

    def load(self, sha: str) -> Optional[bytes]:
        """Verified aot image payload for a wasm sha, or None (miss).
        Every failure mode — injected read fault, missing entry,
        integrity mismatch — is a miss: the caller lowers fresh."""
        if not self.enabled:
            return None
        if self.faults is not None:
            from wasmedge_tpu.testing.faults import InjectedFault

            try:
                self.faults.fire("cache_read", sha=sha)
            except InjectedFault:
                self.counts["read_faults"] += 1
                return None
        raw = None
        with self._lock:
            if self._payloads is not None and sha in self._payloads:
                raw = self._payloads[sha]
        if raw is None and self.dir:
            try:
                with open(self._path(sha), "rb") as f:
                    raw = f.read()
            except OSError:
                raw = None
        if raw is None:
            self.counts["misses"] += 1
            return None
        payload = self._decode(raw)
        if payload is None:
            self.counts["corrupt"] += 1
            return None
        self.counts["disk_hits"] += 1
        return payload

    def store(self, sha: str, payload: bytes) -> None:
        """Record a fresh lowering's image payload.  Write failures are
        swallowed — the cache is an accelerator, never a correctness
        dependency."""
        if not self.enabled:
            return
        raw = self._encode(bytes(payload))
        with self._lock:
            if self._payloads is not None:
                self._payloads[sha] = raw
        if self.dir:
            try:
                from wasmedge_tpu.utils.fsio import atomic_write_bytes

                atomic_write_bytes(self._path(sha), raw)
            except OSError:
                pass
        self.counts["stores"] += 1
        # r24 at-rest rot seam: an armed BitFlip corrupts the STORED
        # envelope (memory and disk mirror both) — load() detects via
        # the embedded digest (miss, fresh lower); the scrubber detects
        # early and repairs from a peer replica or evicts
        if self.faults is not None and hasattr(self.faults, "flip"):
            rotted = self.faults.flip("corrupt_cache", raw, sha=sha)
            if rotted is not raw:
                with self._lock:
                    if self._payloads is not None:
                        self._payloads[sha] = rotted
                if self.dir:
                    try:
                        from wasmedge_tpu.utils.fsio import \
                            atomic_write_bytes

                        atomic_write_bytes(self._path(sha), rotted)
                    except OSError:
                        pass

    # -- fleet replication (r16 peer protocol) -----------------------------
    def entry_bytes(self, sha: str) -> bytes:
        """Raw entry (header + payload) for peer replication; raises
        KeyError when absent."""
        with self._lock:
            if self._payloads is not None and sha in self._payloads:
                return self._payloads[sha]
        if self.dir:
            try:
                with open(self._path(sha), "rb") as f:
                    return f.read()
            except OSError:
                pass
        raise KeyError(sha)

    def adopt_entry(self, sha: str, raw: bytes) -> bool:
        """Install a peer-replicated entry after verifying its payload
        digest; a corrupt entry is dropped (the local lower path covers
        it).  Returns True when adopted."""
        if not self.enabled:
            return False
        raw = bytes(raw)
        if self._decode(raw) is None:
            self.counts["corrupt"] += 1
            return False
        with self._lock:
            if self._payloads is not None:
                self._payloads[sha] = raw
        if self.dir:
            try:
                from wasmedge_tpu.utils.fsio import atomic_write_bytes

                atomic_write_bytes(self._path(sha), raw)
            except OSError:
                pass
        return True

    # -- at-rest scrubbing (wasmedge_tpu/integrity/scrub.py, r24) ----------
    def verify_entry(self, sha: str) -> bool:
        """True when a resident entry's envelope decodes and its
        payload digest verifies (missing entries are vacuously absent,
        not corrupt — the scrubber walks known_shas first)."""
        try:
            raw = self.entry_bytes(sha)
        except KeyError:
            return True
        return self._decode(raw) is not None

    def drop_entry(self, sha: str) -> None:
        """Evict an unrepairable entry (memory + disk): the next load
        is a clean miss and the registration lowers fresh — rot is
        never served."""
        with self._lock:
            if self._payloads is not None:
                self._payloads.pop(sha, None)
        if self.dir:
            try:
                os.unlink(self._path(sha))
            except OSError:
                pass

    def known_shas(self) -> list:
        """Shas with a resident persistent-tier entry (fleet gossip)."""
        out = set()
        with self._lock:
            if self._payloads is not None:
                out.update(self._payloads)
        if self.dir:
            try:
                out.update(fn[:-4] for fn in os.listdir(self.dir)
                           if fn.endswith(".img"))
            except OSError:
                pass
        return sorted(out)

    # -- probe tier (the r12 rejected-registration stash) ------------------
    def pop_probe(self, sha: str):
        """Adopt-and-remove a stashed RegisteredModule for these exact
        bytes (None = no probe)."""
        with self._lock:
            rm = self._probe.pop(sha, None)
        if rm is not None:
            self.counts["probe_hits"] += 1
        return rm

    def stash_probe(self, sha: str, rm) -> None:
        """Park a rolled-back module's lowered engine for a re-POST of
        the same bytes; displaced/evicted entries close (their sink fds
        must not leak)."""
        with self._lock:
            displaced = self._probe.pop(sha, None)
            self._probe[sha] = rm
            evicted = []
            while len(self._probe) > PROBE_DEPTH:
                evicted.append(self._probe.popitem(last=False))
        if displaced is not None:
            displaced.close()
        for _, old in evicted:
            old.close()

    def close(self) -> None:
        with self._lock:
            probes = list(self._probe.values())
            self._probe.clear()
        for rm in probes:
            rm.close()

    def stats(self) -> dict:
        with self._lock:
            probe_depth = len(self._probe)
        return dict(self.counts, enabled=self.enabled,
                    probe_entries=probe_depth,
                    dir=self.dir or "")
