"""Per-module segment memoization for concatenated device images.

`batch/multitenant.concat_images` builds one `Segment` per tenant — a
pure function of (tenant DeviceImage, index-space offsets, merged
fuse-pattern prefix).  This cache keys on exactly those inputs, so a
generation rebuild after registering module N+1 replays modules 1..N's
segments verbatim and rebases only the newcomer: registration work is
O(1) in the registered-module count, and the swap reduces to updating
the indirection table (the `bases` list) plus one concatenation.

Keying uses the image's content fingerprint (`image_fingerprint`,
batch/image.py) so two generations that happen to hold equal-content
images at the same offsets share segments, while any re-lowered or
re-planned image (fingerprint covers the fuse/tier planes) misses and
rebuilds.  Entries also pin the image object itself: a hit additionally
requires identity, which keeps a cached segment's arrays alive exactly
as long as the engine that produced them and makes hits O(1) without
re-hashing (the fingerprint memoizes on the image)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from wasmedge_tpu.batch.image import image_fingerprint

# enough for every registered module of a deep gateway plus a couple of
# in-flight rebuild generations; LRU beyond that (a miss just rebuilds)
_DEFAULT_DEPTH = 64

_OFF_KEYS = ("pc", "func", "glob", "type", "brt", "table", "v128",
             "eseg", "eflat", "dseg", "dbyte", "tier_slot")


class SegmentCache:
    """LRU of rebased image segments keyed by (image content, offsets,
    pattern prefix)."""

    def __init__(self, depth: int = _DEFAULT_DEPTH):
        self.depth = int(depth)
        self._entries: OrderedDict = OrderedDict()
        self.hits = 0
        self.builds = 0

    @staticmethod
    def _key(img, off: dict, pat_state: tuple):
        return (image_fingerprint(img),
                tuple(off[k] for k in _OFF_KEYS),
                pat_state)

    def lookup(self, img, off: dict, pat_state: tuple):
        key = self._key(img, off, pat_state)
        ent = self._entries.get(key)
        if ent is None:
            return None
        cached_img, seg = ent
        if cached_img is not img:
            # same content at the same offsets but a different live
            # image object: the segment arrays are still valid (they
            # are pure functions of content + offsets) — refresh the
            # pin so the arrays outlive the older engine
            self._entries[key] = (img, seg)
        self._entries.move_to_end(key)
        self.hits += 1
        return seg

    def store(self, img, off: dict, pat_state: tuple, seg) -> None:
        key = self._key(img, off, pat_state)
        self._entries[key] = (img, seg)
        self._entries.move_to_end(key)
        self.builds += 1
        while len(self._entries) > self.depth:
            self._entries.popitem(last=False)

    def stats(self) -> dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "builds": self.builds}
