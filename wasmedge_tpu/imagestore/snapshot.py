"""Pre-initialized lane snapshots: run a module's init once, admit
requests from the captured columns (r22).

At registration the gateway runs the module's exported `_initialize`
(reactor) or `_start` (command) ONCE on the module's solo lanes=1
engine, captures the post-init per-lane plane columns — memory sized by
r19's proven `mem_pages_touch_bound` when the analyzer proved one —
and stores them as a content-addressed SwapStore payload.  Generation
builds decode the entry into an `init_overlay` for the concatenated
serving engine: every admitted lane then starts from the post-init
image through the recycler's existing jitted column-set pass, instead
of replaying init per lane (or relying on guest-side lazy init).

Capture is strictly best-effort and conservative: no init export, a
trapping init, an init that reaches a host outcall (its effects would
span the WASI environ, which the overlay cannot carry), or an injected
fault all mean "no snapshot" — the module admits through plain
template init exactly as r21 did.  Install verifies content end-to-end
(SwapStore re-hashes; the `snapshot_install` fault seam injects the
failure) and falls back the same way: wrong state is never served."""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from wasmedge_tpu.batch.image import TRAP_DONE

_PAGE_WORDS = 65536 // 4

# WASI preopens both spellings; reactor-style _initialize first — a
# command _start that also runs main() is still a valid snapshot point
# (the captured state is simply post-main, which is what a re-POSTed
# run would observe too)
_INIT_EXPORTS = ("_initialize", "_start")


@dataclasses.dataclass
class SnapshotEntry:
    """One captured post-init state: the SwapStore content key plus the
    scalar side-meta the overlay needs (page count, table size)."""

    key: str
    meta: dict


def init_export_of(rm) -> Optional[str]:
    """The module's nullary init export name, or None."""
    for name in _INIT_EXPORTS:
        ex = rm.inst.exports.get(name)
        if ex is None or ex[0] != 0:
            continue
        ft = rm.inst.funcs[ex[1]].functype
        if not tuple(ft.params) and not tuple(ft.results):
            return name
    return None


def capture_snapshot(rm, store, counts: dict,
                     max_steps: int = 2_000_000) -> Optional[SnapshotEntry]:
    """Run `rm`'s init once on its registration-time solo engine and
    store the post-init columns; returns the entry or None (skipped).

    Pure with respect to the engine: `initial_state` is functional, so
    the registration engine's image is untouched either way."""
    from wasmedge_tpu.batch.engine import check_batch_entry
    from wasmedge_tpu.hv.swapstore import serialize_columns

    name = init_export_of(rm)
    if name is None:
        return None
    eng = rm.engine  # lanes=1 BatchEngine kept from registration
    try:
        local = check_batch_entry(rm.inst, name)
        state = eng.initial_state(local, [])
        state, _total = eng.run_from_state(state, 0, max_steps)
    except Exception:
        counts["skipped"] = counts.get("skipped", 0) + 1
        return None
    trap = int(np.asarray(state.trap)[0])
    if trap != TRAP_DONE:
        # still running (fuel), trapped, or parked on a host outcall —
        # the overlay cannot represent any of those; admit via template
        counts["skipped"] = counts.get("skipped", 0) + 1
        return None
    img = eng.img
    cols = {}
    meta = {"module": rm.name, "sha": rm.sha256}
    if img.has_memory:
        pages = int(np.asarray(state.mem_pages)[0])
        meta["mem_pages"] = pages
        mem = np.asarray(state.mem)
        rows = pages * _PAGE_WORDS
        # r19's proven page-touch bound: init can only have written
        # inside it, and rows beyond the capture keep the template's
        # init content at install time (overlay writes [0, rows) only)
        ana = getattr(img, "analysis", None)
        bound = getattr(ana, "mem_pages_touch_bound", None)
        if bound is not None:
            rows = min(rows, max(int(bound) * _PAGE_WORDS,
                                 img.mem_init.shape[0]))
        rows = min(rows, mem.shape[0])
        cols["mem"] = mem[:rows, 0]
    cols["glob_lo"] = np.asarray(state.glob_lo)[:, 0]
    cols["glob_hi"] = np.asarray(state.glob_hi)[:, 0]
    if getattr(state, "tab", None) is not None:
        cols["tab"] = np.asarray(state.tab)[:, 0]
        meta["tsize"] = int(np.asarray(state.tsize)[0])
    if getattr(state, "edrop", None) is not None:
        cols["edrop"] = np.asarray(state.edrop)[:, 0]
    if getattr(state, "ddrop", None) is not None:
        cols["ddrop"] = np.asarray(state.ddrop)[:, 0]
    key = store.put(serialize_columns(cols, meta))
    counts["captured"] = counts.get("captured", 0) + 1
    return SnapshotEntry(key=key, meta=meta)


def decode_overlay(rm, store, faults=None,
                   counts: Optional[dict] = None) -> Optional[dict]:
    """SnapshotEntry -> init_overlay dict for the serving engine, or
    None (template fallback) on any integrity or injected failure."""
    from wasmedge_tpu.hv.swapstore import SwapCorrupt, deserialize_columns

    entry = getattr(rm, "snapshot", None)
    if entry is None:
        return None
    counts = counts if counts is not None else {}
    if faults is not None:
        from wasmedge_tpu.testing.faults import InjectedFault

        try:
            faults.fire("snapshot_install", module=rm.name,
                        key=entry.key)
        except InjectedFault:
            counts["install_faults"] = counts.get("install_faults", 0) + 1
            return None
    try:
        payload = store.get(entry.key)
    except SwapCorrupt:
        counts["corrupt"] = counts.get("corrupt", 0) + 1
        return None
    cols, meta = deserialize_columns(payload)
    overlay = {k: cols.get(k) for k in ("mem", "glob_lo", "glob_hi",
                                        "tab", "edrop", "ddrop")}
    overlay["mem_pages"] = meta.get("mem_pages")
    overlay["tsize"] = meta.get("tsize")
    return overlay
