"""End-to-end integrity against silent data corruption (r24).

Three legs:
  - audit.py: shadow-audit lanes — at seeded launch boundaries a small
    lane subset's pre-slice planes are exported, the identical slice is
    re-executed through a reference re-trace of the same step program,
    and the post-slice planes are compared bit-exact.  A divergence is
    an SDC incident: FailureRecord("integrity"), rollback to the newest
    good checkpoint, per-device attribution.
  - quarantine.py: the divergence->eject ladder — repeated divergences
    attributed to one device eject it through the r21 reshard path.
  - scrub.py: the at-rest scrubber — a cadence-driven walk re-verifying
    sha256 over SwapStore entries (parked r23 sessions included),
    checkpoint lineage members, and r22 WTIC compile-cache entries
    before a wake/restore needs them, repairing from mirrors or fleet
    peer replicas, else evicting with a fresh-lower/init-replay
    fallback.

Integrity off (the default IntegrityConfigure) installs no hook and
starts no thread: the serving stack runs the exact r23 path,
bit-identical by construction.
"""

from wasmedge_tpu.integrity.audit import (AuditSampler, IntegrityDivergence,
                                          ShadowAuditor)
from wasmedge_tpu.integrity.quarantine import DeviceQuarantine
from wasmedge_tpu.integrity.scrub import Scrubber

__all__ = [
    "AuditSampler",
    "DeviceQuarantine",
    "IntegrityDivergence",
    "Scrubber",
    "ShadowAuditor",
]
