"""Shadow-audit lanes: launch-boundary re-execution of a sampled lane
subset, compared bit-exact against the device's answer.

At each launch boundary a deterministic seeded sampler decides whether
to audit and which lanes; the auditor exports those lanes' pre-slice
plane columns (before the launch donates the state), lets the launch
run, re-executes the identical slice through a reference program, and
compares the post-slice columns bit-exact.  The reference is NOT a
different engine: it is the SAME `_make_step(img, cfg, k)` program
re-traced at the sampled width `k` and driven for exactly the same
number of loop iterations with the same per-launch time base — the
construction lane compaction's narrowing rung already proved width-
invariant (batch/engine.py _build_narrow_chunk).  Re-execution through
the identical program means a transient device fault (an SDC bit flip
in flight or at rest in HBM) cannot reproduce on the replay, so any
bitwise mismatch is a divergence.

A divergence raises `IntegrityDivergence` (point "integrity"): the
supervisor/server recovery tier records a FailureRecord with fault
class "integrity", rolls back to the newest good checkpoint, and
re-executes — masking the corruption.  Every diverged lane is also
attributed to the mesh device holding its shard, feeding the
`DeviceQuarantine` ladder (quarantine.py).

One caveat gates comparison: the tier-0 in-kernel RNG keys its stream
by ABSOLUTE lane position (t0_rng_seq_hash over lane_iota), so a
sampled lane replayed at a shifted position would legitimately draw
different numbers.  When the sampled index set is not positional
(idx[j] != j somewhere) AND any sampled lane consumed RNG during the
slice, the audit records verdict "skipped_rng" instead of comparing —
never a false divergence.  Full-width audits (the bench campaign) are
always positional and never skip.
"""

from __future__ import annotations

import hashlib

import numpy as np

from wasmedge_tpu.obs.recorder import NULL_RECORDER

# t0_ctr row indices (batch/tier0.py): clock / rng / fd_write / sys
_T0_RNG_ROW = 1


class IntegrityDivergence(RuntimeError):
    """An audited lane's replayed planes differ bit-wise from the
    device's — a silent-data-corruption incident.  `point` routes the
    recovery tier to fault class "integrity"; `lanes` is EMPTY on
    purpose (divergence is a device problem, not a poison input — the
    whole batch retries from the newest good checkpoint), with the
    diverged lane set carried separately for attribution/reporting."""

    point = "integrity"
    lanes = ()

    def __init__(self, boundary: int, diverged_lanes, devices, planes,
                 message: str = ""):
        self.boundary = int(boundary)
        self.diverged_lanes = tuple(int(x) for x in diverged_lanes)
        self.devices = tuple(int(x) for x in devices)
        self.planes = tuple(planes)
        super().__init__(
            message or "shadow audit divergence at boundary "
            f"{self.boundary}: lanes={list(self.diverged_lanes)} "
            f"planes={list(self.planes)} devices={list(self.devices)}")


class AuditSampler:
    """Deterministic boundary/lane sampler: hashing seed+boundary makes
    the audited boundary set stable (not periodic — a periodic audit
    would miss any corruption phase-locked to it) and the lane choice
    reproducible.  Same seed, same schedule."""

    def __init__(self, seed: int = 0, every: int = 16,
                 lanes_per_audit: int = 2):
        self.seed = int(seed)
        self.every = max(int(every), 1)
        self.lanes_per_audit = max(int(lanes_per_audit), 1)

    def _hash(self, boundary: int) -> int:
        h = hashlib.sha256(
            f"audit|{self.seed}|{int(boundary)}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    def pick(self, boundary: int, lanes: int):
        """Sorted sampled lane indices for this boundary, or None when
        the boundary is not audited."""
        if lanes <= 0:
            return None
        h = self._hash(boundary)
        if h % self.every != 0:
            return None
        k = min(self.lanes_per_audit, lanes)
        rng = np.random.RandomState((h >> 16) & 0x7FFFFFFF)
        idx = rng.choice(lanes, size=k, replace=False)
        return np.sort(idx).astype(np.int64)


class ShadowAuditor:
    """Engine hook (`BatchEngine._audit_hook`): `pre` snapshots sampled
    lane columns at a launch boundary, `post` replays and compares.
    Reference chunk programs are cached per sampled width."""

    def __init__(self, knobs, obs=None, faults=None, quarantine=None):
        self.knobs = knobs
        self.sampler = AuditSampler(knobs.audit_seed, knobs.audit_every,
                                    knobs.audit_lanes)
        self.quarantine = quarantine if quarantine is not None \
            else _make_quarantine(knobs)
        self.obs = obs if obs is not None else NULL_RECORDER
        self.faults = faults
        self.stats = {
            "boundaries": 0,
            "audits": 0,
            "match": 0,
            "divergence": 0,
            "skipped_rng": 0,
            "error": 0,
        }
        self._boundary = 0
        self._ref_chunks = {}
        self._gather_fn = None

    def _gather(self, state, names, jidx):
        """Sampled lane columns for `names`, as host arrays — ONE jitted
        dispatch and ONE device_get, not a transfer per plane (the
        per-plane form costs several launch-times per audit and is what
        the within-10%-of-audit-off bar is lost to)."""
        import jax

        if self._gather_fn is None:
            def g(planes, idx):
                return {n: p[..., idx] for n, p in planes.items()}

            self._gather_fn = jax.jit(g)
        planes = {n: getattr(state, n) for n in names}
        return jax.device_get(self._gather_fn(planes, jidx))

    # -- engine seam -------------------------------------------------------
    def pre(self, engine, state, tt):
        """Called after the boundary rebalance, before the launch
        donates `state`.  Returns an opaque token for `post`, or None
        when this boundary is not audited."""
        b = self._boundary
        self._boundary += 1
        self.stats["boundaries"] += 1
        idx = self.sampler.pick(b, engine.lanes)
        if idx is None:
            return None
        import jax.numpy as jnp

        jidx = jnp.asarray(idx)
        names = [name for name in state._fields
                 if getattr(state, name) is not None
                 and getattr(getattr(state, name), "ndim", 0)
                 and getattr(state, name).shape[-1] == engine.lanes]
        pre = self._gather(state, names, jidx)
        return {"boundary": b, "idx": idx, "pre": pre,
                "tt": np.asarray(tt)}

    def post(self, engine, tok, state, done_steps: int):
        """Called after the launch lands (and after any corrupt_plane
        flip seam — the flip must be visible to the audit).  Raises
        IntegrityDivergence on a bitwise mismatch."""
        import jax.numpy as jnp

        idx = tok["idx"]
        if self.faults is not None:
            try:
                self.faults.fire("audit_compare", boundary=tok["boundary"],
                                 lanes=len(idx))
            except Exception:
                # the audit INFRA failed, not the device: void this
                # audit, keep serving
                self.stats["error"] += 1
                return
        jidx = jnp.asarray(idx)
        names = [name for name in tok["pre"]
                 if getattr(state, name) is not None]
        post = self._gather(state, names, jidx)
        # tier-0 RNG keys by absolute lane position: a non-positional
        # sample that consumed RNG this slice cannot be replayed
        # faithfully — skip, never false-positive
        positional = bool(np.array_equal(idx, np.arange(len(idx))))
        if not positional and "t0_ctr" in post:
            drew = post["t0_ctr"][_T0_RNG_ROW] \
                - tok["pre"]["t0_ctr"][_T0_RNG_ROW]
            if np.any(drew != 0):
                self.stats["skipped_rng"] += 1
                return
        self.stats["audits"] += 1
        t0 = self.obs.now()
        ref = self._replay(engine, tok, state, int(done_steps))
        import jax

        ref_host = jax.device_get(
            {name: getattr(ref, name) for name in post})
        bad_planes = []
        bad_lanes = set()
        for name, dev in post.items():
            r = ref_host[name]
            neq = dev != r
            if not np.any(neq):
                continue
            bad_planes.append(name)
            lane_bad = np.any(
                neq, axis=tuple(range(neq.ndim - 1))) if neq.ndim > 1 \
                else neq
            bad_lanes.update(int(idx[j]) for j in np.nonzero(lane_bad)[0])
        if not bad_planes:
            self.stats["match"] += 1
            if self.obs.enabled:
                self.obs.span("integrity_audit", t0, cat="integrity",
                              lanes=len(idx), verdict="match")
            return
        self.stats["divergence"] += 1
        n_dev = engine.mesh.devices.size if engine.mesh is not None else 1
        devices = sorted({lane * n_dev // engine.lanes
                          for lane in bad_lanes})
        for d in devices:
            self.quarantine.note(d)
        self.obs.instant("integrity_divergence",
                         boundary=tok["boundary"],
                         lanes=sorted(bad_lanes), planes=bad_planes,
                         devices=devices)
        raise IntegrityDivergence(tok["boundary"], sorted(bad_lanes),
                                  devices, bad_planes)

    # -- reference replay --------------------------------------------------
    def _ref_chunk(self, engine, width: int):
        fn = self._ref_chunks.get(width)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax

        from wasmedge_tpu.batch.engine import _make_step

        step = _make_step(engine.img, engine.cfg, width,
                          t0kinds=getattr(engine, "_t0kinds", None))

        def run_ref(state, t0_time, nsteps):
            # the iteration budget is the DEVICE's done_steps, not
            # cfg.steps_per_launch: autotune may retune the chunk
            # length between launches, and an early all-trapped exit
            # must replay to the same iteration count
            def cond(carry):
                i, s = carry
                return (i < nsteps) & jnp.any(s.trap == 0)

            def body(carry):
                i, s = carry
                return i + 1, step(s, t0_time)

            i, state = lax.while_loop(cond, body, (jnp.int32(0), state))
            return i, state

        fn = jax.jit(run_ref)
        self._ref_chunks[width] = fn
        return fn

    def _replay(self, engine, tok, state, done_steps: int):
        import jax.numpy as jnp

        width = len(tok["idx"])
        fn = self._ref_chunk(engine, width)
        fields = {}
        for name in state._fields:
            p = getattr(state, name)
            if p is None:
                fields[name] = None
            elif name in tok["pre"]:
                fields[name] = jnp.asarray(tok["pre"][name])
            else:
                # laneless obs counter planes (op_hist/fu_ctr/tu_ctr):
                # pure accumulators, never read by the step — zeros
                # keep the replay's arithmetic identical and its
                # counts discarded
                fields[name] = jnp.zeros_like(p)
        sub = type(state)(**fields)
        _, ref = fn(sub, jnp.asarray(tok["tt"]), jnp.int32(done_steps))
        return ref


def _make_quarantine(knobs):
    from wasmedge_tpu.integrity.quarantine import DeviceQuarantine

    return DeviceQuarantine(getattr(knobs, "quarantine_threshold", 3))
