"""Device quarantine ladder: divergence attribution -> eject.

Each audit divergence attributes the lanes that diverged to the mesh
devices holding their shards (`note`).  Once one device accumulates
`threshold` attributions it becomes an eject candidate; the serving
layer's recovery path (serve/server.py _recover) drains candidates
through `pending_ejects` and removes them from the mesh via the r21
`reshard(devices=...)` path — the same machinery a planned scale-down
uses, so every resident lane survives the eject.  Single-device
engines have nowhere to eject to; candidates are counted but stay
(`pending_ejects` filters them out when ejecting would empty the
mesh — the caller passes the population)."""

from __future__ import annotations

import threading


class DeviceQuarantine:
    """Thread-safe divergence counter per device index."""

    def __init__(self, threshold: int = 3):
        self.threshold = max(int(threshold), 1)
        self.counts = {}
        self.ejected = set()
        self._lock = threading.Lock()

    def note(self, device: int) -> bool:
        """Record one divergence attributed to `device`; True when the
        ladder's threshold is now crossed."""
        with self._lock:
            d = int(device)
            self.counts[d] = self.counts.get(d, 0) + 1
            return self.counts[d] >= self.threshold and d not in self.ejected

    def pending_ejects(self):
        """Devices over threshold and not yet ejected."""
        with self._lock:
            return sorted(d for d, c in self.counts.items()
                          if c >= self.threshold and d not in self.ejected)

    def mark_ejected(self, device: int):
        with self._lock:
            self.ejected.add(int(device))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "threshold": self.threshold,
                "counts": dict(self.counts),
                "ejected": sorted(self.ejected),
            }
