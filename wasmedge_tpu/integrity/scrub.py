"""At-rest integrity scrubber: verify sha256 over every durable byte
BEFORE a wake/restore needs it.

Three walk targets, each already content-addressed or digest-framed by
construction, so the scrubber adds no new format — it just reads what
the write paths committed to:

  - SwapStore entries (hv swap blobs, parked r23 session blobs,
    imagestore snapshots): key == sha256(payload).  A corrupt copy
    heals from its healthy mirror (memory vs disk) when one survives,
    else repairs from a fleet peer replica (GET /v1/fleet/blob/<key>),
    else — where a clean fallback exists (snapshot store: init-replay)
    — evicts.  hv/effects blobs without a replica are left counted as
    unrepairable: get() still refuses to serve them, and serve
    checkpoints embed their payloads for restore.
  - Checkpoint lineage members: checkpoint.save writes a `<path>.sha256`
    sidecar; a mismatch quarantines the member (renamed `<path>.corrupt`)
    so the recovery walk falls back to the next-older member instead of
    tripping over rot mid-incident.  Members predating the sidecar are
    backfilled on first scrub.
  - WTIC compile-cache entries: the envelope's embedded digest is
    re-verified; a corrupt entry repairs from a peer
    (GET /v1/fleet/cache/<sha>) or is evicted — the next registration
    lowers fresh, wrong code is never served.

The `scrub_read` fault seam (testing/faults.py) models an unreadable
local copy: an injected fault routes that entry down the same repair
path a hash mismatch takes.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

from wasmedge_tpu.obs.recorder import NULL_RECORDER


def sidecar_path(path) -> str:
    return os.fspath(path) + ".sha256"


def file_sha256(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _new_stats() -> dict:
    return {
        "scans": 0,
        "entries": 0,
        "corrupt": 0,
        "repaired": 0,
        "evicted": 0,
        "unrepairable": 0,
        "read_faults": 0,
        "quarantined_members": 0,
        "last_seconds": 0.0,
    }


class Scrubber:
    """Cadence-driven at-rest verification walk.

    Providers are callables resolved at scrub time (the gateway's
    serving generation — and with it every store — can be swapped
    between passes):
      - `swap_stores() -> [(kind, store, evict_on_fail), ...]`
      - `checkpoints() -> [member_path, ...]`
      - `compile_cache() -> CompileCache | None`
      - `fetch_blob(key) -> bytes | None` (fleet peer replica)
      - `fetch_cache_entry(sha) -> bytes | None` (raw WTIC envelope)
    """

    def __init__(self, knobs, obs=None, faults=None, swap_stores=None,
                 checkpoints=None, compile_cache=None, fetch_blob=None,
                 fetch_cache_entry=None):
        self.knobs = knobs
        self.obs = obs if obs is not None else NULL_RECORDER
        self.faults = faults
        self.swap_stores = swap_stores or (lambda: ())
        self.checkpoints = checkpoints or (lambda: ())
        self.compile_cache = compile_cache or (lambda: None)
        self.fetch_blob = fetch_blob
        self.fetch_cache_entry = fetch_cache_entry
        self.stats = _new_stats()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Arm the background cadence when scrub_interval_s > 0 (0 =
        manual scrub_once() only — tests and the bench drive it)."""
        interval = float(getattr(self.knobs, "scrub_interval_s", 0.0))
        if interval <= 0 or self._thread is not None:
            return

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scrub_once()
                except Exception:
                    # the scrubber is a defense layer, never a crash
                    # source; a failed pass retries next cadence
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="integrity-scrubber")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- one pass ----------------------------------------------------------
    def scrub_once(self) -> dict:
        """Walk every target once; returns the pass's delta counts."""
        with self._lock:
            t0 = time.monotonic()
            delta = _new_stats()
            for kind, store, evict_on_fail in self.swap_stores() or ():
                self._scrub_store(kind, store, evict_on_fail, delta)
            for path in self.checkpoints() or ():
                self._scrub_checkpoint(path, delta)
            cc = self.compile_cache()
            if cc is not None and getattr(cc, "enabled", False):
                self._scrub_cache(cc, delta)
            delta["last_seconds"] = time.monotonic() - t0
            delta["scans"] = 1
            for k, v in delta.items():
                if k == "last_seconds":
                    self.stats[k] = v
                else:
                    self.stats[k] += v
            if self.obs.enabled:
                self.obs.instant(
                    "scrub_pass", cat="integrity",
                    entries=delta["entries"], corrupt=delta["corrupt"],
                    repaired=delta["repaired"], evicted=delta["evicted"],
                    seconds=round(delta["last_seconds"], 6))
            return delta

    def _read_seam(self, kind: str, key, delta) -> bool:
        """Fire scrub_read; False = injected unreadable local copy
        (take the repair path)."""
        if self.faults is None:
            return True
        from wasmedge_tpu.testing.faults import InjectedFault

        try:
            self.faults.fire("scrub_read", kind=kind, key=str(key))
        except InjectedFault:
            delta["read_faults"] += 1
            return False
        return True

    def _scrub_store(self, kind, store, evict_on_fail, delta):
        repair = bool(getattr(self.knobs, "scrub_repair", True))
        for key in store.scrub_keys():
            delta["entries"] += 1
            readable = self._read_seam(kind, key, delta)
            status, _ = store.scrub_verify(key) if readable \
                else ("corrupt", None)
            if status == "ok":
                continue
            delta["corrupt"] += 1
            if status == "healed":
                delta["repaired"] += 1
                continue
            data = self.fetch_blob(key) \
                if (repair and self.fetch_blob is not None) else None
            if data is not None and store.scrub_restore(key, data):
                delta["repaired"] += 1
            elif evict_on_fail:
                store.scrub_evict(key)
                delta["evicted"] += 1
            else:
                delta["unrepairable"] += 1

    def _scrub_checkpoint(self, path, delta):
        path = os.fspath(path)
        side = sidecar_path(path)
        if not os.path.exists(path):
            # orphaned sidecar after a lineage prune
            if os.path.exists(side):
                try:
                    os.unlink(side)
                except OSError:
                    pass
            return
        delta["entries"] += 1
        if not self._read_seam("checkpoint", path, delta):
            digest = None
        else:
            try:
                digest = file_sha256(path)
            except OSError:
                digest = None
        if not os.path.exists(side):
            if digest is not None:
                # pre-r24 member: adopt its current content as the
                # baseline (rot before the first scrub is out of scope
                # — checkpoint.load's archive validation still covers)
                try:
                    with open(side, "w") as f:
                        f.write(digest)
                except OSError:
                    pass
            return
        try:
            with open(side) as f:
                want = f.read().strip()
        except OSError:
            return
        if digest == want:
            return
        delta["corrupt"] += 1
        delta["quarantined_members"] += 1
        # quarantine the member: the recovery walk (lineage.walk_newest)
        # falls back to the next-older member instead of loading rot
        try:
            os.replace(path, path + ".corrupt")
            os.unlink(side)
        except OSError:
            pass
        self.obs.instant("scrub_checkpoint_quarantined", cat="integrity",
                         path=os.path.basename(path))

    def _scrub_cache(self, cc, delta):
        repair = bool(getattr(self.knobs, "scrub_repair", True))
        for sha in cc.known_shas():
            delta["entries"] += 1
            readable = self._read_seam("cache", sha, delta)
            if readable and cc.verify_entry(sha):
                continue
            delta["corrupt"] += 1
            raw = self.fetch_cache_entry(sha) \
                if (repair and self.fetch_cache_entry is not None) else None
            if raw is not None and cc.adopt_entry(sha, raw):
                delta["repaired"] += 1
            else:
                cc.drop_entry(sha)
                delta["evicted"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)
