from wasmedge_tpu.loader.loader import Loader

__all__ = ["Loader"]
