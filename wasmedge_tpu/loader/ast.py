"""AST node definitions.

Mirrors the reference AST (/root/reference/include/ast/): a Module with the
13 section kinds, and — the critical design point SURVEY.md §2.2 calls out —
a *flat post-decode instruction* list per function body: `block`/`loop`/`if`
carry relative jump distances precomputed at decode time (reference:
lib/loader/ast/instruction.cpp:38-96), so no later stage ever re-scans for
`end`.

Instruction is a small record: dense opcode id + immediate fields. The
validator lowers these further into SoA arrays (see validator/lowering).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from wasmedge_tpu.common.types import ValType


@dataclasses.dataclass
class FunctionType:
    params: Tuple[ValType, ...]
    results: Tuple[ValType, ...]

    def __eq__(self, other):
        return (
            isinstance(other, FunctionType)
            and self.params == other.params
            and self.results == other.results
        )

    def __hash__(self):
        return hash((self.params, self.results))


@dataclasses.dataclass
class Limit:
    min: int
    max: Optional[int] = None
    shared: bool = False


@dataclasses.dataclass
class TableType:
    ref_type: ValType
    limit: Limit


@dataclasses.dataclass
class MemoryType:
    limit: Limit


@dataclasses.dataclass
class GlobalType:
    val_type: ValType
    mutable: bool


@dataclasses.dataclass
class Instruction:
    """Flat decoded instruction (reference: include/ast/instruction.h:27-274).

    Immediate fields by kind:
      block/loop/if : block_type (int typeidx | ValType | None), jump_end,
                      jump_else (if only) — relative distances, set at decode
      br/br_if      : target_idx (label depth); jump descriptor filled by
                      the validator
      br_table      : targets list + default, descriptors by validator
      call          : target_idx = funcidx
      call_indirect : target_idx = typeidx, source_idx = tableidx
      local/global/table ops: target_idx (+ source_idx for table.copy/init)
      memory ops    : mem_align, mem_offset, target_idx/source_idx mem/data idx
      const         : imm = raw bit pattern (int)
      ref.null      : ref_type
      select_t      : val_types list
    """

    op: int  # dense opcode id (common.opcodes)
    offset: int = 0  # byte offset in the original binary (error reporting)
    block_type: object = None
    jump_end: int = 0
    jump_else: int = 0
    target_idx: int = 0
    source_idx: int = 0
    mem_align: int = 0
    mem_offset: int = 0
    imm: int = 0
    targets: Optional[List[int]] = None
    ref_type: Optional[ValType] = None
    val_types: Optional[List[ValType]] = None


@dataclasses.dataclass
class ImportDesc:
    module: str
    name: str
    kind: int  # 0 func, 1 table, 2 mem, 3 global
    type_idx: int = 0  # for funcs
    table_type: Optional[TableType] = None
    memory_type: Optional[MemoryType] = None
    global_type: Optional[GlobalType] = None


@dataclasses.dataclass
class ExportDesc:
    name: str
    kind: int  # 0 func, 1 table, 2 mem, 3 global
    index: int


@dataclasses.dataclass
class GlobalSegment:
    type: GlobalType
    init: List[Instruction]


@dataclasses.dataclass
class ElementSegment:
    mode: int  # 0 active, 1 passive, 2 declarative
    table_idx: int
    offset: Optional[List[Instruction]]  # const expr for active
    ref_type: ValType
    init_exprs: List[List[Instruction]]  # one const expr per element


@dataclasses.dataclass
class DataSegment:
    mode: int  # 0 active, 1 passive
    memory_idx: int
    offset: Optional[List[Instruction]]
    data: bytes


@dataclasses.dataclass
class CodeSegment:
    locals: List[Tuple[int, ValType]]  # (count, type) runs
    body: List[Instruction]
    size: int = 0


@dataclasses.dataclass
class CustomSection:
    name: str
    data: bytes
    start: int = -1  # byte offset of the section header in the binary
    #                  (lets the AOT layer hash the bytes that precede it)


@dataclasses.dataclass
class Module:
    types: List[FunctionType] = dataclasses.field(default_factory=list)
    imports: List[ImportDesc] = dataclasses.field(default_factory=list)
    functions: List[int] = dataclasses.field(default_factory=list)  # typeidx
    tables: List[TableType] = dataclasses.field(default_factory=list)
    memories: List[MemoryType] = dataclasses.field(default_factory=list)
    globals: List[GlobalSegment] = dataclasses.field(default_factory=list)
    exports: List[ExportDesc] = dataclasses.field(default_factory=list)
    start: Optional[int] = None
    elements: List[ElementSegment] = dataclasses.field(default_factory=list)
    codes: List[CodeSegment] = dataclasses.field(default_factory=list)
    datas: List[DataSegment] = dataclasses.field(default_factory=list)
    data_count: Optional[int] = None
    customs: List[CustomSection] = dataclasses.field(default_factory=list)
    validated: bool = False
    lowered: object = None  # LoweredModule attached by the validator
    source_bytes: bytes = b""  # original binary (AOT-section hash check)

    # -- import accessors (reference: include/ast/module.h import counting) --
    # Imports are immutable after loading, so the kind-filtered views are
    # cached (validation calls func_type_of per call-site).
    _imported_funcs_cache: object = None

    def imported_funcs(self) -> List[ImportDesc]:
        if self._imported_funcs_cache is None:
            self._imported_funcs_cache = [im for im in self.imports if im.kind == 0]
        return self._imported_funcs_cache

    def imported_tables(self) -> List[ImportDesc]:
        return [im for im in self.imports if im.kind == 1]

    def imported_memories(self) -> List[ImportDesc]:
        return [im for im in self.imports if im.kind == 2]

    def imported_globals(self) -> List[ImportDesc]:
        return [im for im in self.imports if im.kind == 3]

    @property
    def num_imported_funcs(self) -> int:
        return len(self.imported_funcs())

    def func_type_of(self, func_idx: int) -> FunctionType:
        """FunctionType for a function index (imports first, then local)."""
        nimp = self.num_imported_funcs
        if func_idx < nimp:
            return self.types[self.imported_funcs()[func_idx].type_idx]
        return self.types[self.functions[func_idx - nimp]]

    @property
    def total_funcs(self) -> int:
        return self.num_imported_funcs + len(self.functions)

    def all_table_types(self) -> List[TableType]:
        return [im.table_type for im in self.imported_tables()] + self.tables

    def all_memory_types(self) -> List[MemoryType]:
        return [im.memory_type for im in self.imported_memories()] + self.memories

    def all_global_types(self) -> List[GlobalType]:
        return [im.global_type for im in self.imported_globals()] + [
            g.type for g in self.globals
        ]

    @property
    def num_imported_globals(self) -> int:
        return len(self.imported_globals())
