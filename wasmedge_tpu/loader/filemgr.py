"""Byte/LEB128 reader over an in-memory buffer.

Mirrors the reference FileMgr (/root/reference/include/loader/filemgr.h:31-60,
lib/loader/filemgr.cpp): offset-tracked reads with strict LEB128 validation
(IntegerTooLong for over-length encodings, IntegerTooLarge for unused-bit
violations, UnexpectedEnd on truncation) so malformed-module spec tests get
the same error classes.
"""

from __future__ import annotations

import struct

from wasmedge_tpu.common.errors import ErrCode, LoadError


class FileMgr:
    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, start: int = 0, end: int | None = None):
        self.data = data
        self.pos = start
        self.end = len(data) if end is None else end

    def remaining(self) -> int:
        return self.end - self.pos

    def at_end(self) -> bool:
        return self.pos >= self.end

    def _need(self, n: int):
        if self.pos + n > self.end:
            raise LoadError(ErrCode.UnexpectedEnd, offset=self.pos)

    def read_byte(self) -> int:
        self._need(1)
        b = self.data[self.pos]
        self.pos += 1
        return b

    def peek_byte(self) -> int:
        self._need(1)
        return self.data[self.pos]

    def read_bytes(self, n: int) -> bytes:
        if n < 0:
            raise LoadError(ErrCode.LengthOutOfBounds, offset=self.pos)
        self._need(n)
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_u32_raw(self) -> int:
        self._need(4)
        (v,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        return v

    def _read_uleb(self, max_bits: int) -> int:
        result = 0
        shift = 0
        max_bytes = (max_bits + 6) // 7
        for i in range(max_bytes):
            b = self.read_byte()
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                # Unused bits in the final byte must be zero.
                if i == max_bytes - 1:
                    unused = 7 - (max_bits - 7 * (max_bytes - 1))
                    if unused > 0 and (b & 0x7F) >> (7 - unused):
                        raise LoadError(ErrCode.IntegerTooLarge, offset=self.pos - 1)
                return result
            shift += 7
        raise LoadError(ErrCode.IntegerTooLong, offset=self.pos - 1)

    def _read_sleb(self, max_bits: int) -> int:
        result = 0
        shift = 0
        max_bytes = (max_bits + 6) // 7
        for i in range(max_bytes):
            b = self.read_byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                if i == max_bytes - 1:
                    # Final byte: sign bits beyond max_bits must agree.
                    used = max_bits - 7 * (max_bytes - 1)
                    payload = b & 0x7F
                    sign_bit = (payload >> (used - 1)) & 1
                    mask = (0x7F >> used) << used
                    high = payload & mask
                    if sign_bit and high != mask:
                        raise LoadError(ErrCode.IntegerTooLarge, offset=self.pos - 1)
                    if not sign_bit and high != 0:
                        raise LoadError(ErrCode.IntegerTooLarge, offset=self.pos - 1)
                if b & 0x40:
                    result |= -(1 << shift)
                return result
        raise LoadError(ErrCode.IntegerTooLong, offset=self.pos - 1)

    def read_u32(self) -> int:
        return self._read_uleb(32)

    def read_u64(self) -> int:
        return self._read_uleb(64)

    def read_s32(self) -> int:
        return self._read_sleb(32)

    def read_s33(self) -> int:
        return self._read_sleb(33)

    def read_s64(self) -> int:
        return self._read_sleb(64)

    def read_f32_bits(self) -> int:
        self._need(4)
        (v,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        return v

    def read_f64_bits(self) -> int:
        self._need(8)
        (v,) = struct.unpack_from("<Q", self.data, self.pos)
        self.pos += 8
        return v

    def read_name(self) -> str:
        n = self.read_u32()
        raw = self.read_bytes(n)
        try:
            return raw.decode("utf-8", errors="strict")
        except UnicodeDecodeError:
            raise LoadError(ErrCode.MalformedUTF8, offset=self.pos)
