"""Binary .wasm -> AST loader.

Mirrors the reference Loader pipeline (/root/reference/lib/loader/
loader.cpp:64-135 header dispatch; lib/loader/ast/*.cpp section loaders).
Decodes all 13 section kinds, validates section ordering and size
cross-checks, applies proposal gating per opcode/type at load time
(reference: loader.cpp:167-216), and precomputes block jump distances via a
block stack during instruction decode (lib/loader/ast/instruction.cpp:38-96).
"""

from __future__ import annotations

from typing import List, Optional

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, LoadError
from wasmedge_tpu.common.opcodes import OPCODES, WIRE_TO_ID, Op
from wasmedge_tpu.common.types import ValType
from wasmedge_tpu.loader import ast
from wasmedge_tpu.loader.filemgr import FileMgr

MAGIC = b"\x00asm"
VERSION = b"\x01\x00\x00\x00"

# section names for ErrInfo context records (errinfo.h InfoAST analog)
_SECTION_NAMES = {0: "Custom", 1: "Type", 2: "Import", 3: "Function",
                  4: "Table", 5: "Memory", 6: "Global", 7: "Export",
                  8: "Start", 9: "Element", 10: "Code", 11: "Data",
                  12: "DataCount"}

_NUM_TYPES = {0x7F: ValType.I32, 0x7E: ValType.I64, 0x7D: ValType.F32, 0x7C: ValType.F64}
_REF_TYPES = {0x70: ValType.FuncRef, 0x6F: ValType.ExternRef}

# Section ids in required order (custom sections may appear anywhere).
_SECTION_ORDER = [1, 2, 3, 4, 5, 6, 7, 8, 9, 12, 10, 11]


class Loader:
    def __init__(self, conf: Optional[Configure] = None):
        self.conf = conf or Configure()
        self.gates = self.conf.proposal_gates()

    # -- public entry ------------------------------------------------------
    def parse_module(self, data: bytes) -> ast.Module:
        fm = FileMgr(data)
        if fm.read_bytes(4) != MAGIC:
            raise LoadError(ErrCode.MalformedMagic, offset=0)
        if fm.read_bytes(4) != VERSION:
            raise LoadError(ErrCode.MalformedVersion, offset=4)
        mod = ast.Module()
        last_order = -1  # section ordering cursor
        code_count_seen = 0
        while not fm.at_end():
            sec_start = fm.pos
            sec_id = fm.read_byte()
            sec_size = fm.read_u32()
            if sec_size > fm.remaining():
                raise LoadError(ErrCode.LengthOutOfBounds, offset=fm.pos)
            sec_end = fm.pos + sec_size
            sub = FileMgr(fm.data, fm.pos, sec_end)
            if sec_id == 0:
                name = sub.read_name()
                mod.customs.append(ast.CustomSection(
                    name, sub.data[sub.pos : sec_end], start=sec_start))
            else:
                if sec_id not in _SECTION_ORDER:
                    raise LoadError(ErrCode.MalformedSection, offset=fm.pos)
                order = _SECTION_ORDER.index(sec_id)
                if order <= last_order:
                    raise LoadError(ErrCode.JunkSection, offset=fm.pos)
                last_order = order
                try:
                    self._load_section(sec_id, sub, mod)
                    if sub.pos != sec_end:
                        raise LoadError(ErrCode.SectionSizeMismatch,
                                        offset=sub.pos)
                except LoadError as e:
                    from wasmedge_tpu.common.errinfo import InfoAST

                    raise e.with_info(InfoAST(
                        f"section {_SECTION_NAMES.get(sec_id, sec_id)}"))
                if sec_id == 10:
                    code_count_seen = len(mod.codes)
            fm.pos = sec_end
        if len(mod.functions) != code_count_seen:
            raise LoadError(ErrCode.IncompatibleFuncCode, offset=fm.pos)
        if mod.data_count is not None and mod.data_count != len(mod.datas):
            raise LoadError(ErrCode.IncompatibleDataCount, offset=fm.pos)
        mod.source_bytes = data
        return mod

    def parse_file(self, path: str) -> ast.Module:
        from wasmedge_tpu.common.errinfo import InfoFile

        with open(path, "rb") as f:
            data = f.read()
        try:
            return self.parse_module(data)
        except LoadError as e:
            raise e.with_info(InfoFile(path))

    # -- sections ----------------------------------------------------------
    def _load_section(self, sec_id: int, fm: FileMgr, mod: ast.Module):
        if sec_id == 1:
            mod.types = [self._load_functype(fm) for _ in range(fm.read_u32())]
        elif sec_id == 2:
            mod.imports = [self._load_import(fm) for _ in range(fm.read_u32())]
        elif sec_id == 3:
            mod.functions = [fm.read_u32() for _ in range(fm.read_u32())]
        elif sec_id == 4:
            mod.tables = [self._load_tabletype(fm) for _ in range(fm.read_u32())]
        elif sec_id == 5:
            mod.memories = [ast.MemoryType(self._load_limit(fm)) for _ in range(fm.read_u32())]
        elif sec_id == 6:
            mod.globals = [
                ast.GlobalSegment(self._load_globaltype(fm), self._load_expr(fm))
                for _ in range(fm.read_u32())
            ]
        elif sec_id == 7:
            mod.exports = [
                self._load_export(fm) for _ in range(fm.read_u32())
            ]
        elif sec_id == 8:
            mod.start = fm.read_u32()
        elif sec_id == 9:
            mod.elements = [self._load_elem(fm) for _ in range(fm.read_u32())]
        elif sec_id == 10:
            mod.codes = [self._load_code(fm) for _ in range(fm.read_u32())]
        elif sec_id == 11:
            mod.datas = [self._load_data(fm) for _ in range(fm.read_u32())]
        elif sec_id == 12:
            if "bulk-memory" not in self.gates and "reference-types" not in self.gates:
                raise LoadError(ErrCode.MalformedSection, offset=fm.pos)
            mod.data_count = fm.read_u32()

    def _load_valtype(self, fm: FileMgr) -> ValType:
        b = fm.read_byte()
        if b in _NUM_TYPES:
            return _NUM_TYPES[b]
        if b == 0x7B:
            if "simd" not in self.gates:
                raise LoadError(ErrCode.MalformedValType, offset=fm.pos)
            return ValType.V128
        if b in _REF_TYPES:
            if b == 0x6F and "reference-types" not in self.gates:
                raise LoadError(ErrCode.MalformedValType, offset=fm.pos)
            return _REF_TYPES[b]
        raise LoadError(ErrCode.MalformedValType, offset=fm.pos)

    def _load_reftype(self, fm: FileMgr) -> ValType:
        b = fm.read_byte()
        if b not in _REF_TYPES:
            raise LoadError(ErrCode.MalformedRefType, offset=fm.pos)
        if b == 0x6F and "reference-types" not in self.gates:
            raise LoadError(ErrCode.MalformedRefType, offset=fm.pos)
        return _REF_TYPES[b]

    def _load_functype(self, fm: FileMgr) -> ast.FunctionType:
        if fm.read_byte() != 0x60:
            raise LoadError(ErrCode.IllegalGrammar, offset=fm.pos)
        params = tuple(self._load_valtype(fm) for _ in range(fm.read_u32()))
        results = tuple(self._load_valtype(fm) for _ in range(fm.read_u32()))
        if len(results) > 1 and "multi-value" not in self.gates:
            raise LoadError(ErrCode.InvalidResultArity, offset=fm.pos)
        return ast.FunctionType(params, results)

    def _load_limit(self, fm: FileMgr) -> ast.Limit:
        flag = fm.read_byte()
        if flag not in (0x00, 0x01):
            raise LoadError(ErrCode.IntegerTooLarge, offset=fm.pos)
        mn = fm.read_u32()
        mx = fm.read_u32() if flag == 0x01 else None
        if mx is not None and mx < mn:
            raise LoadError(ErrCode.InvalidLimit, offset=fm.pos)
        return ast.Limit(mn, mx)

    def _load_tabletype(self, fm: FileMgr) -> ast.TableType:
        rt = self._load_reftype(fm)
        return ast.TableType(rt, self._load_limit(fm))

    def _load_globaltype(self, fm: FileMgr) -> ast.GlobalType:
        vt = self._load_valtype(fm)
        mut = fm.read_byte()
        if mut not in (0, 1):
            raise LoadError(ErrCode.InvalidMut, offset=fm.pos)
        return ast.GlobalType(vt, bool(mut))

    def _load_import(self, fm: FileMgr) -> ast.ImportDesc:
        module = fm.read_name()
        name = fm.read_name()
        kind = fm.read_byte()
        im = ast.ImportDesc(module, name, kind)
        if kind == 0:
            im.type_idx = fm.read_u32()
        elif kind == 1:
            im.table_type = self._load_tabletype(fm)
        elif kind == 2:
            im.memory_type = ast.MemoryType(self._load_limit(fm))
        elif kind == 3:
            im.global_type = self._load_globaltype(fm)
        else:
            raise LoadError(ErrCode.MalformedImportKind, offset=fm.pos)
        return im

    def _load_export(self, fm: FileMgr) -> ast.ExportDesc:
        name = fm.read_name()
        kind = fm.read_byte()
        if kind > 3:
            raise LoadError(ErrCode.MalformedExportKind, offset=fm.pos)
        return ast.ExportDesc(name, kind, fm.read_u32())

    def _load_elem(self, fm: FileMgr) -> ast.ElementSegment:
        flags = fm.read_u32()
        if flags > 7:
            raise LoadError(ErrCode.IllegalGrammar, offset=fm.pos)
        if flags != 0 and "bulk-memory" not in self.gates and "reference-types" not in self.gates:
            raise LoadError(ErrCode.IllegalGrammar, offset=fm.pos)
        mode = 0 if flags in (0, 2, 4, 6) else (2 if flags in (3, 7) else 1)
        table_idx = fm.read_u32() if flags in (2, 6) else 0
        offset = self._load_expr(fm) if mode == 0 else None
        ref_type = ValType.FuncRef
        init_exprs: List[List[ast.Instruction]] = []
        if flags in (0, 1, 2, 3):
            if flags != 0:
                ek = fm.read_byte()  # elemkind, must be 0x00 (funcref)
                if ek != 0x00:
                    raise LoadError(ErrCode.MalformedElemType, offset=fm.pos)
            for _ in range(fm.read_u32()):
                fi = fm.read_u32()
                init_exprs.append(
                    [
                        ast.Instruction(Op.ref_func, target_idx=fi),
                        ast.Instruction(Op.end),
                    ]
                )
        else:  # 4..7: element expressions
            if flags != 4:
                ref_type = self._load_reftype(fm)
            for _ in range(fm.read_u32()):
                init_exprs.append(self._load_expr(fm))
        return ast.ElementSegment(mode, table_idx, offset, ref_type, init_exprs)

    def _load_data(self, fm: FileMgr) -> ast.DataSegment:
        flags = fm.read_u32()
        if flags > 2:
            raise LoadError(ErrCode.IllegalGrammar, offset=fm.pos)
        if flags > 0 and "bulk-memory" not in self.gates:
            # reference gates any nonzero check byte (segment.cpp:309-314)
            raise LoadError(ErrCode.ExpectedZeroByte, offset=fm.pos)
        mode = 1 if flags == 1 else 0
        mem_idx = fm.read_u32() if flags == 2 else 0
        offset = self._load_expr(fm) if mode == 0 else None
        data = fm.read_bytes(fm.read_u32())
        return ast.DataSegment(mode, mem_idx, offset, data)

    def _load_code(self, fm: FileMgr) -> ast.CodeSegment:
        size = fm.read_u32()
        body_end = fm.pos + size
        if body_end > fm.end:
            raise LoadError(ErrCode.LengthOutOfBounds, offset=fm.pos)
        sub = FileMgr(fm.data, fm.pos, body_end)
        locals_: List = []
        total = 0
        for _ in range(sub.read_u32()):
            count = sub.read_u32()
            vt = self._load_valtype(sub)
            total += count
            if total > 0x07FFFFFF:
                raise LoadError(ErrCode.TooManyLocals, offset=sub.pos)
            locals_.append((count, vt))
        body = self._load_instr_seq(sub)
        if sub.pos != body_end:
            raise LoadError(ErrCode.SectionSizeMismatch, offset=sub.pos)
        fm.pos = body_end
        return ast.CodeSegment(locals_, body, size)

    # -- expressions / instructions ---------------------------------------
    def _load_expr(self, fm: FileMgr) -> List[ast.Instruction]:
        return self._load_instr_seq(fm)

    def _read_opcode(self, fm: FileMgr) -> int:
        off = fm.pos
        b = fm.read_byte()
        if b in (0xFC, 0xFD):
            sub = fm.read_u32()
            key = (b, sub)
        else:
            key = (0, b)
        op_id = WIRE_TO_ID.get(key)
        if op_id is None:
            raise LoadError(ErrCode.IllegalOpCode, offset=off)
        info = OPCODES[op_id]
        if info.proposal is not None and info.proposal not in self.gates:
            raise LoadError(ErrCode.IllegalOpCode, offset=off)
        return op_id

    def _load_instr_seq(self, fm: FileMgr) -> List[ast.Instruction]:
        """Decode until the matching final `end`, precomputing jump_end /
        jump_else for block/loop/if via a block stack (reference:
        lib/loader/ast/instruction.cpp:38-96)."""
        instrs: List[ast.Instruction] = []
        block_stack: List[int] = []  # indices of open block/loop/if
        while True:
            off = fm.pos
            op_id = self._read_opcode(fm)
            instr = self._decode_immediates(op_id, fm, off)
            idx = len(instrs)
            instrs.append(instr)
            name = OPCODES[op_id].name
            if name in ("block", "loop", "if"):
                block_stack.append(idx)
            elif name == "else":
                if not block_stack:
                    raise LoadError(ErrCode.IllegalGrammar, offset=off)
                opener = instrs[block_stack[-1]]
                if OPCODES[opener.op].name != "if" or opener.jump_else:
                    raise LoadError(ErrCode.IllegalGrammar, offset=off)
                opener.jump_else = idx - block_stack[-1]
            elif name == "end":
                if not block_stack:
                    return instrs  # function/expr-terminating end
                opener_idx = block_stack.pop()
                instrs[opener_idx].jump_end = idx - opener_idx

    def _decode_immediates(self, op_id: int, fm: FileMgr, off: int) -> ast.Instruction:
        info = OPCODES[op_id]
        ins = ast.Instruction(op_id, offset=off)
        imm = info.imm
        if imm == "none":
            pass
        elif imm == "blocktype":
            b = fm.peek_byte()
            if b == 0x40:
                fm.read_byte()
                ins.block_type = None  # empty
            elif b in _NUM_TYPES or b in _REF_TYPES or b == 0x7B:
                ins.block_type = self._load_valtype(fm)
            else:
                v = fm.read_s33()
                if v < 0:
                    raise LoadError(ErrCode.MalformedValType, offset=fm.pos)
                ins.block_type = int(v)  # type index
        elif imm in ("labelidx", "funcidx", "localidx", "globalidx", "tableidx",
                     "dataidx", "elemidx"):
            ins.target_idx = fm.read_u32()
        elif imm == "brtable":
            n = fm.read_u32()
            ins.targets = [fm.read_u32() for _ in range(n)]
            ins.target_idx = fm.read_u32()  # default label
        elif imm == "typeidx_tableidx":
            ins.target_idx = fm.read_u32()
            if "reference-types" in self.gates:
                ins.source_idx = fm.read_u32()
            else:
                b = fm.read_byte()
                if b != 0x00:
                    raise LoadError(ErrCode.ExpectedZeroByte, offset=fm.pos)
                ins.source_idx = 0
        elif imm == "tableidx2":  # table.copy: dst, src
            ins.target_idx = fm.read_u32()
            ins.source_idx = fm.read_u32()
        elif imm == "elemidx_tableidx":  # table.init: elem, table
            ins.target_idx = fm.read_u32()
            ins.source_idx = fm.read_u32()
        elif imm == "dataidx_memidx":  # memory.init
            ins.target_idx = fm.read_u32()
            b = fm.read_byte()
            if b != 0x00:
                raise LoadError(ErrCode.ExpectedZeroByte, offset=fm.pos)
        elif imm == "memidx":
            b = fm.read_byte()
            if b != 0x00:
                raise LoadError(ErrCode.ExpectedZeroByte, offset=fm.pos)
        elif imm == "memidx2":
            for _ in range(2):
                if fm.read_byte() != 0x00:
                    raise LoadError(ErrCode.ExpectedZeroByte, offset=fm.pos)
        elif imm == "memarg":
            ins.mem_align = fm.read_u32()
            ins.mem_offset = fm.read_u32()
        elif imm == "memarg_lane":
            ins.mem_align = fm.read_u32()
            ins.mem_offset = fm.read_u32()
            ins.target_idx = fm.read_byte()  # lane index
        elif imm == "lane":
            ins.target_idx = fm.read_byte()
        elif imm == "v128const":
            ins.imm = int.from_bytes(fm.read_bytes(16), "little")
        elif imm == "shuffle":
            ins.imm = int.from_bytes(fm.read_bytes(16), "little")
        elif imm == "i32":
            ins.imm = fm.read_s32() & 0xFFFFFFFF
        elif imm == "i64":
            ins.imm = fm.read_s64() & 0xFFFFFFFFFFFFFFFF
        elif imm == "f32":
            ins.imm = fm.read_f32_bits()
        elif imm == "f64":
            ins.imm = fm.read_f64_bits()
        elif imm == "refnull":
            ins.ref_type = self._load_reftype(fm)
        elif imm == "select_t":
            n = fm.read_u32()
            ins.val_types = [self._load_valtype(fm) for _ in range(n)]
        else:
            raise LoadError(ErrCode.IllegalGrammar, offset=off)
        return ins
