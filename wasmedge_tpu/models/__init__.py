"""Example wasm workload corpus (the reference ships fibonacci/factorial wat
examples, /root/reference/tools/wasmedge/examples/). Built programmatically
via utils.builder since the image has no wat2wasm and copying reference
bytes is off-limits. These are the benchmark workloads from BASELINE.md:
fib (config 1), a CoreMark-style integer/memory kernel (config 2 analog),
plus small modules exercising each subsystem.
"""

from wasmedge_tpu.models.programs import (
    build_call_counted_loop,
    build_coremark_kernel,
    build_counted_loop,
    build_fac,
    build_fib,
    build_loop_sum,
    build_memfuse_workload,
    build_memory_workload,
    build_simd_memfuse_workload,
)

__all__ = [
    "build_fib",
    "build_fac",
    "build_loop_sum",
    "build_counted_loop",
    "build_call_counted_loop",
    "build_memory_workload",
    "build_memfuse_workload",
    "build_simd_memfuse_workload",
    "build_coremark_kernel",
]
