"""Benchmark/workload module builders."""

from __future__ import annotations

from wasmedge_tpu.utils.builder import ModuleBuilder


def build_fib() -> bytes:
    """Recursive fib(n) — BASELINE config 1: i32 numeric + call/br only."""
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 2), "i32.lt_s",
        ("if", "i32"),
        ("local.get", 0),
        "else",
        ("local.get", 0), ("i32.const", 1), "i32.sub", ("call", 0),
        ("local.get", 0), ("i32.const", 2), "i32.sub", ("call", 0),
        "i32.add",
        "end",
    ], export="fib")
    return b.build()


def build_fac() -> bytes:
    """Recursive factorial over i64 (reference example: fac(12))."""
    b = ModuleBuilder()
    b.add_function(["i64"], ["i64"], [], [
        ("local.get", 0), ("i64.const", 1), "i64.le_s",
        ("if", "i64"),
        ("i64.const", 1),
        "else",
        ("local.get", 0),
        ("local.get", 0), ("i64.const", 1), "i64.sub", ("call", 0),
        "i64.mul",
        "end",
    ], export="fac")
    return b.build()


def build_loop_sum() -> bytes:
    """sum(0..n) via a loop — pure-branch workload, no calls."""
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], ["i32", "i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        ("local.get", 2), ("local.get", 1), "i32.add", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0),
        "end",
        "end",
        ("local.get", 2),
    ], export="loop_sum")
    return b.build()


def build_memory_workload(passes: int = 1) -> bytes:
    """Write-then-checksum over linear memory (config 2 memory traffic).

    `passes` repeats the whole write+checksum cycle (same load/store mix,
    more work per invocation) so benchmarks can amortize fixed host-link
    round trips over enough device work to measure the engine rather
    than the link."""
    b = ModuleBuilder()
    b.add_memory(1, 16)
    # locals: 0=n (param), 1=i, 2=acc, 3=pass counter
    b.add_function(["i32"], ["i32"], ["i32", "i32", "i32"], [
        ("i32.const", passes), ("local.set", 3),
        ("block", None),
        ("loop", None),
        # store n words of i*2654435761
        ("i32.const", 0), ("local.set", 1),
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        ("local.get", 1), ("i32.const", 4), "i32.mul",
        ("local.get", 1), ("i32.const", 0x9E3779B1 - 2**32), "i32.mul",
        ("local.get", 3), ("i32.const", 1), "i32.sub", "i32.xor",
        ("i32.store", 2, 0),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0),
        "end",
        "end",
        # xor-reduce them back
        ("i32.const", 0), ("local.set", 1),
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 4), "i32.mul", ("i32.load", 2, 0),
        "i32.xor", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0),
        "end",
        "end",
        ("local.get", 3), ("i32.const", 1), "i32.sub", ("local.tee", 3),
        "i32.eqz", ("br_if", 1),
        ("br", 0),
        "end",
        "end",
        ("local.get", 2),
    ], export="mem_checksum")
    return b.build()


def build_counted_loop(n: int = 64) -> bytes:
    """Latch-tested counted loop with a CONSTANT limit — the canonical
    shape the absint trip analysis (analysis/absint.py) bounds
    EXACTLY: body runs `n` times, cost_bound == measured retired.
    Before r19 this verdict was "unbounded" (any loop was); the
    admission-precision fixture for `require_bounded` policies."""
    b = ModuleBuilder()
    # locals: 0=arg (ignored: limits must be static), 1=i, 2=acc
    b.add_function(["i32"], ["i32"], ["i32", "i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 2), ("local.get", 1), "i32.add", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("local.get", 1), ("i32.const", n), "i32.lt_u", ("br_if", 0),
        "end", "end",
        ("local.get", 2),
    ], export="count")
    return b.build()


def build_call_counted_loop(n: int = 64, calls: int = 24) -> bytes:
    """A non-promotable driver calling a promotable counted-loop leaf
    `calls` times — the r20 tier-up cadence fixture.  The driver has
    CALL ops so the compiled-function verdict refuses it; the leaf is
    the build_counted_loop shape (constant latch, exact absint trip
    bound) so it promotes.  With the compiled tier on, each call
    retires through ONE compiled-body dispatch plus the driver's
    per-op glue — enough launches either way that supervised runs
    cross checkpoint boundaries mid-stream (tests/test_tierup.py).

    Result: arg + calls * (n*(n-1)/2)."""
    b = ModuleBuilder()
    # func 0 (driver): locals 0=arg, 1=j, 2=acc
    b.add_function(["i32"], ["i32"], ["i32", "i32"], [
        ("local.get", 0), ("local.set", 2),
        ("block", None),
        ("loop", None),
        ("local.get", 2), ("local.get", 1), ("call", 1), "i32.add",
        ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("local.get", 1), ("i32.const", calls), "i32.lt_u", ("br_if", 0),
        "end", "end",
        ("local.get", 2),
    ], export="call_count")
    # func 1 (leaf): the counted-loop body — locals 0=arg, 1=i, 2=acc
    b.add_function(["i32"], ["i32"], ["i32", "i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 2), ("local.get", 1), "i32.add", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("local.get", 1), ("i32.const", n), "i32.lt_u", ("br_if", 0),
        "end", "end",
        ("local.get", 2),
    ])
    return b.build()


def build_memfuse_workload(n_words: int = 1024, passes: int = 1,
                           byte_offset: int = 0,
                           store_width: int = 4) -> bytes:
    """Write-then-xor-checksum with STATIC bounds — the r19 memory-run
    fusion workload.  Unlike build_memory_workload (whose limits are
    params, so nothing licenses), every loop here is counted against a
    constant, so absint proves each store/load in-bounds and aligned
    and batch/fuse.py compiles the whole loop bodies into fused
    gather/scatter runs.

    `byte_offset`/`store_width` build the ADVERSARIAL variants: a
    byte_offset of 2 with store_width 4 makes every access misaligned
    (license refused -> per-op path), and an n_words pushing
    n_words*4 + byte_offset past the 64 KiB page makes the tail access
    OOB (license refused; the trap must land identically on the
    per-op path whether fusion is on or off)."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    store_op = {1: "i32.store8", 2: "i32.store16", 4: "i32.store"}[
        store_width]
    # locals: 0=arg (ignored), 1=i, 2=acc, 3=pass counter
    b.add_function(["i32"], ["i32"], ["i32", "i32", "i32"], [
        ("i32.const", passes), ("local.set", 3),
        ("block", None), ("loop", None),
        # store n_words words of i*0x9E3779B1 ^ (pass-1)
        ("i32.const", 0), ("local.set", 1),
        ("block", None), ("loop", None),
        ("local.get", 1), ("i32.const", 4), "i32.mul",
        ("i32.const", byte_offset), "i32.add",
        ("local.get", 1), ("i32.const", 0x9E3779B1 - 2 ** 32),
        "i32.mul",
        ("local.get", 3), ("i32.const", 1), "i32.sub", "i32.xor",
        (store_op, 0, 0),
        ("local.get", 1), ("i32.const", 1), "i32.add",
        ("local.set", 1),
        ("local.get", 1), ("i32.const", n_words), "i32.lt_u",
        ("br_if", 0),
        "end", "end",
        # xor-reduce them back
        ("i32.const", 0), ("local.set", 1),
        ("block", None), ("loop", None),
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 4), "i32.mul",
        ("i32.const", byte_offset), "i32.add",
        ("i32.load", 2, 0),
        "i32.xor", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add",
        ("local.set", 1),
        ("local.get", 1), ("i32.const", n_words), "i32.lt_u",
        ("br_if", 0),
        "end", "end",
        # next pass (counted down to zero: `ne 0` trip shape)
        ("local.get", 3), ("i32.const", 1), "i32.sub",
        ("local.tee", 3), ("br_if", 0),
        "end", "end",
        ("local.get", 2),
    ], export="memfuse")
    return b.build()


def build_simd_memfuse_workload(n_vecs: int = 64,
                                passes: int = 1) -> bytes:
    """v128 analog of build_memfuse_workload: fill `n_vecs` 16-byte
    vectors with splatted counters, then xor-reduce a lane back out
    through v128 loads.  Every access sits at i*16 against CONSTANT
    loop bounds, so absint proves each v128 site in-bounds and
    word-aligned (16-byte stride => 4-aligned) and licenses it — the
    r20 satellite that lets batch/fuse.py compile the SIMD loop bodies
    into fused four-word gather/scatter runs.  The splat/extract cells
    stay per-op (not fusion-eligible), so each loop body realizes one
    fused run holding the licensed v128 access."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    # locals: 0=arg (ignored: limits must be static), 1=i, 2=acc, 3=pass
    b.add_function(["i32"], ["i32"], ["i32", "i32", "i32"], [
        ("i32.const", passes), ("local.set", 3),
        ("block", None), ("loop", None),
        # store n_vecs splatted vectors of i + pass
        ("i32.const", 0), ("local.set", 1),
        ("block", None), ("loop", None),
        ("local.get", 1), ("i32.const", 16), "i32.mul",
        ("local.get", 1), ("local.get", 3), "i32.add", "i32x4.splat",
        ("v128.store", 0, 0),
        ("local.get", 1), ("i32.const", 1), "i32.add",
        ("local.set", 1),
        ("local.get", 1), ("i32.const", n_vecs), "i32.lt_u",
        ("br_if", 0),
        "end", "end",
        # xor-reduce one lane of each back
        ("i32.const", 0), ("local.set", 1),
        ("block", None), ("loop", None),
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 16), "i32.mul",
        ("v128.load", 0, 0),
        ("i32x4.extract_lane", 1),
        "i32.xor", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add",
        ("local.set", 1),
        ("local.get", 1), ("i32.const", n_vecs), "i32.lt_u",
        ("br_if", 0),
        "end", "end",
        # next pass (counted down to zero: `ne 0` trip shape)
        ("local.get", 3), ("i32.const", 1), "i32.sub",
        ("local.tee", 3), ("br_if", 0),
        "end", "end",
        ("local.get", 2),
    ], export="simd_memfuse")
    return b.build()


def build_coremark_kernel() -> bytes:
    """CoreMark-flavored kernel: list-free core mix of matrix-multiply-ish
    integer MACs, state-machine branches, and CRC over linear memory.
    Not the full CoreMark (no libc), but the same op mix — the config-2
    stand-in until a wasm32 CoreMark binary is available offline."""
    b = ModuleBuilder()
    b.add_memory(1, 16)

    # crc16 step: crc = (crc >> 1) ^ (0xA001 if (crc^bit)&1 else 0)
    crc8 = b.add_function(["i32", "i32"], ["i32"], ["i32"], [
        # for 8 bits
        ("block", None),
        ("loop", None),
        ("local.get", 2), ("i32.const", 8), "i32.ge_u", ("br_if", 1),
        ("local.get", 1), ("local.get", 0), "i32.xor", ("i32.const", 1), "i32.and",
        ("if", None),
        ("local.get", 1), ("i32.const", 1), "i32.shr_u",
        ("i32.const", 0xA001), "i32.xor", ("local.set", 1),
        "else",
        ("local.get", 1), ("i32.const", 1), "i32.shr_u", ("local.set", 1),
        "end",
        ("local.get", 0), ("i32.const", 1), "i32.shr_u", ("local.set", 0),
        ("local.get", 2), ("i32.const", 1), "i32.add", ("local.set", 2),
        ("br", 0),
        "end",
        "end",
        ("local.get", 1),
    ])

    # matrix-ish MAC over memory words + state machine + crc
    b.add_function(["i32"], ["i32"], ["i32", "i32", "i32", "i32"], [
        # locals: 0=n 1=i 2=acc 3=state 4=crc
        ("i32.const", 0xFFFF), ("local.set", 4),
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        # acc += (i*3) * (i+7)  (MAC)
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 3), "i32.mul",
        ("local.get", 1), ("i32.const", 7), "i32.add",
        "i32.mul", "i32.add", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        # state-machine dispatch on acc low bits: all arms continue the loop
        ("local.get", 2), ("i32.const", 7), "i32.and",
        ("br_table", [0, 0, 0], 0),
        "end",
        "end",
        # store acc, crc it
        ("i32.const", 0), ("local.get", 2), ("i32.store", 2, 0),
        ("local.get", 2), ("i32.const", 0xFF), "i32.and",
        ("local.get", 4), ("call", crc8), ("local.set", 4),
        ("local.get", 4), ("local.get", 2), "i32.xor",
    ], export="coremark")
    return b.build()
