"""Native scalar engine: ctypes bindings over the C++ dispatch loop.

This is the `EngineKind.NATIVE` implementation — a C++ interpreter over the
same lowered SoA image the Python oracle and the TPU engines execute
(engine.cpp here mirrors /root/reference/lib/executor/engine/
engine.cpp:68-1641 structurally).  It serves two roles:

1. the fast host-side engine behind `--engine native`, and
2. the *live-measured* single-core denominator for bench.py's vs_baseline
   (a real dispatch loop on this machine, not a recorded constant).

Build-on-demand: the shared library is compiled with g++ on first use and
cached by source hash under ~/.cache/wasmedge_tpu (no pip, no network).
The opcode-id header is generated from the Python opcode table so the two
sides cannot drift, and the supported-op set is parsed back out of
engine.cpp's `case` labels so eligibility is always exactly "what the C++
actually implements".

Eligibility (else the caller falls back to the Python engine — the same
graceful degradation the reference applies to mismatched AOT sections,
lib/loader/ast/module.cpp:279-326): single module, no imports/host
functions, no SIMD/table-mutation ops, at most one memory and one table
with locally-resolvable funcrefs.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
import sys
from typing import List, Optional

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.common.opcodes import NAME_TO_ID, OPCODES
from wasmedge_tpu.validator.image import LOP_BR, LOP_BRNZ, LOP_BRZ

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "engine.cpp")

# ErrCode values the C++ side traps with (names must exist in ErrCode)
_ERR_EXPORTS = {
    "E_Terminated": ErrCode.Terminated,
    "E_Unreachable": ErrCode.Unreachable,
    "E_MemoryOOB": ErrCode.MemoryOutOfBounds,
    "E_DivideByZero": ErrCode.DivideByZero,
    "E_IntegerOverflow": ErrCode.IntegerOverflow,
    "E_InvalidConvToInt": ErrCode.InvalidConvToInt,
    "E_UndefinedElement": ErrCode.UndefinedElement,
    "E_UninitializedElement": ErrCode.UninitializedElement,
    "E_IndirectCallTypeMismatch": ErrCode.IndirectCallTypeMismatch,
    "E_CallStackExhausted": ErrCode.CallStackExhausted,
    "E_StackOverflow": ErrCode.StackOverflow,
    "E_ExecutionFailed": ErrCode.ExecutionFailed,
    "E_TableOOB": ErrCode.TableOutOfBounds,
}


def _sanitize(name: str) -> str:
    return name.replace(".", "_")


def _gen_header() -> str:
    lines = ["// generated from wasmedge_tpu/common/opcodes.py — do not edit"]
    for op_id, info in enumerate(OPCODES):
        lines.append(f"#define OP_{_sanitize(info.name)} {op_id}")
    lines.append(f"#define LOP_BR_ID {LOP_BR}")
    lines.append(f"#define LOP_BRZ_ID {LOP_BRZ}")
    lines.append(f"#define LOP_BRNZ_ID {LOP_BRNZ}")
    for cname, code in _ERR_EXPORTS.items():
        lines.append(f"#define {cname} {int(code)}")
    lines.append("")
    return "\n".join(lines)


_lib = None
_supported_ids: Optional[frozenset] = None


def _build_lib():
    """Compile (or reuse cached) shared library; returns ctypes CDLL."""
    global _lib
    if _lib is not None:
        return _lib
    src = open(_SRC).read()
    header = _gen_header()
    key = hashlib.sha256((src + header + "v1").encode()).hexdigest()[:16]
    cache = os.path.join(os.path.expanduser("~"), ".cache", "wasmedge_tpu")
    os.makedirs(cache, exist_ok=True)
    so_path = os.path.join(cache, f"we_native_{key}.so")
    if not os.path.exists(so_path):
        gen_dir = os.path.join(cache, f"gen_{key}")
        os.makedirs(gen_dir, exist_ok=True)
        with open(os.path.join(gen_dir, "gen_opcodes.h"), "w") as f:
            f.write(header)
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               f"-I{gen_dir}", "-o", tmp, _SRC]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(f"native engine build failed:\n{e.stderr}")
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(so_path)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.we_native_invoke.restype = ctypes.c_int32
    lib.we_native_invoke.argtypes = [
        i32p, i32p, i32p, i32p, i64p, ctypes.c_int32,   # code planes
        i32p,                                           # br_table
        i32p, i32p, i32p, i32p, i32p, i32p, ctypes.c_int32,  # func metas
        i32p,                                           # typeid_of_type
        i32p, i32p, ctypes.c_int32,                     # table/size/cap
        i32p, i32p, i32p, ctypes.c_int32, u8p,          # elem segs + drop
        u8p, i32p, i32p, ctypes.c_int32, u8p,           # data segs + drop
        u64p,                                           # globals
        u8p, ctypes.c_int32, ctypes.c_int32,            # mem, cur/max pages
        ctypes.c_int32, u64p, ctypes.c_int32, u64p,     # func, args, results
        ctypes.c_int32, ctypes.c_int64,                 # depth/stack limits
        i32p,                                           # stop flag
        i64p, i32p,                                     # retired, out_pages
    ]
    lib.we_native_selfbench.restype = ctypes.c_double
    lib.we_native_selfbench.argtypes = [
        i32p, i32p, i32p, i32p, i64p, ctypes.c_int32, i32p,
        i32p, i32p, i32p, i32p, i32p, i32p, ctypes.c_int32, i32p,
        i32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
    ]
    _lib = lib
    return lib


def supported_op_ids() -> frozenset:
    """Lowered-op ids the C++ engine implements, parsed from its source's
    `case` labels — eligibility can never drift from the implementation."""
    global _supported_ids
    if _supported_ids is not None:
        return _supported_ids
    src = open(_SRC).read()
    ids = set()
    name_by_macro = {f"OP_{_sanitize(info.name)}": NAME_TO_ID[info.name]
                     for info in OPCODES}
    name_by_macro["LOP_BR_ID"] = LOP_BR
    name_by_macro["LOP_BRZ_ID"] = LOP_BRZ
    name_by_macro["LOP_BRNZ_ID"] = LOP_BRNZ
    for m in re.finditer(r"case\s+(\w+)\s*:", src):
        macro = m.group(1)
        if macro in name_by_macro:
            ids.add(name_by_macro[macro])
    _supported_ids = frozenset(ids)
    return _supported_ids


class NativeModule:
    """Per-module prepared image + eligibility for the native engine."""

    def __init__(self, inst, store=None):
        self.inst = inst
        self.store = store  # funcref handle resolution + write-back
        self.reason: Optional[str] = None
        self._membuf = None  # cached memory transfer buffer
        self._prep(inst, store)

    def _prep(self, inst, store):
        image = inst.lowered
        mod = inst.ast
        if mod is not None and getattr(mod, "imports", None):
            if len(mod.imports.descs) > 0:
                self.reason = "module has imports"
                return
        for fn in image.funcs:
            if fn.is_import:
                self.reason = "imported/host function"
                return
        supported = supported_op_ids()
        for pc2 in range(image.code_len):
            if image.op[pc2] not in supported:
                from wasmedge_tpu.validator.image import lop_name
                self.reason = f"unsupported op {lop_name(image.op[pc2])}"
                return
        # branch/return keep counts are copied through a fixed kept[16]
        # buffer in the C++ loop; wider multi-value stays on Python
        for fn in image.funcs:
            if fn.nresults > 16:
                self.reason = "multi-value arity > 16"
                return
        for pc2 in range(image.code_len):
            if image.op[pc2] in (LOP_BR, LOP_BRNZ) and image.b[pc2] > 16:
                self.reason = "multi-value branch arity > 16"
                return
        arrays0 = image.arrays
        if arrays0["br_table"].size and (arrays0["br_table"][:, 1] > 16).any():
            self.reason = "multi-value branch arity > 16"
            return
        if len(inst.memories) > 1 or len(inst.tables) > 1:
            self.reason = "multiple memories/tables"
            return
        for g in inst.globals:
            if g.value < 0 or g.value >= (1 << 64):
                self.reason = "non-64-bit global"
                return

        arrays = image.arrays
        self.ops = np.ascontiguousarray(arrays["op"], np.int32)
        self.aa = np.ascontiguousarray(arrays["a"], np.int32)
        self.bb = np.ascontiguousarray(arrays["b"], np.int32)
        self.cc = np.ascontiguousarray(arrays["c"], np.int32)
        self.imm = np.ascontiguousarray(arrays["imm"], np.int64)
        self.brt = np.ascontiguousarray(arrays["br_table"].reshape(-1),
                                        np.int32)
        nf = len(image.funcs)
        self.f_entry = np.zeros(nf, np.int32)
        self.f_nparams = np.zeros(nf, np.int32)
        self.f_nlocals = np.zeros(nf, np.int32)
        self.f_nresults = np.zeros(nf, np.int32)
        self.f_ftop = np.zeros(nf, np.int32)
        self.f_typeid = np.zeros(nf, np.int32)
        type_ids = {}

        def dense(ti):
            key = (mod.types[ti].params, mod.types[ti].results) \
                if mod is not None else ti
            return type_ids.setdefault(key, len(type_ids))

        for i, fn in enumerate(image.funcs):
            self.f_entry[i] = fn.entry_pc
            self.f_nparams[i] = fn.nparams
            self.f_nlocals[i] = fn.nlocals
            self.f_nresults[i] = fn.nresults
            self.f_ftop[i] = fn.max_height
            self.f_typeid[i] = dense(fn.type_idx)
        ntypes = len(mod.types) if mod is not None else 0
        self.typeid_of_type = np.asarray(
            [dense(t) for t in range(ntypes)] or [0], np.int32)

        # table snapshot: funcidx+1, 0 = null (device-image convention)
        if inst.tables:
            func_index = {id(f): i for i, f in enumerate(inst.funcs)}
            refs = []
            for h in inst.tables[0].refs:
                if h == 0:
                    refs.append(0)
                    continue
                fi = store.deref_func(h) if store is not None else None
                idx = func_index.get(id(fi)) if fi is not None else None
                if idx is None:
                    self.reason = "table entry references non-local function"
                    return
                refs.append(idx + 1)
            self.table = np.asarray(refs or [0], np.int32)
        else:
            self.table = np.zeros(1, np.int32)

    @property
    def eligible(self) -> bool:
        return self.reason is None

    def _img_args(self, lib):
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)

        def p32(a):
            return a.ctypes.data_as(i32p)

        return (p32(self.ops), p32(self.aa), p32(self.bb), p32(self.cc),
                self.imm.ctypes.data_as(i64p), len(self.ops),
                p32(self.brt), p32(self.f_entry), p32(self.f_nparams),
                p32(self.f_nlocals), p32(self.f_nresults), p32(self.f_ftop),
                p32(self.f_typeid), len(self.f_entry),
                p32(self.typeid_of_type))

    def invoke(self, func_idx: int, raw_args: List[int],
               max_call_depth: int = 2048,
               stop_cell: Optional[np.ndarray] = None):
        """Run one invocation; mutates instance globals/memory in place.
        Returns (results, retired). Raises TrapError on traps."""
        lib = _build_lib()
        inst = self.inst
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        glob = np.asarray([g.value for g in inst.globals] or [0], np.uint64)
        if inst.memories:
            m = inst.memories[0]
            cur_pages = m.pages
            max_pages = m.page_limit if m.max is None \
                else min(m.max, m.page_limit)
            # Reuse one max-pages transfer buffer across invokes (np.zeros
            # maps lazily via calloc, so the declared max costs only the
            # pages actually touched).  m.data stays authoritative between
            # calls: copy in before, copy back after.
            buf = self._membuf
            if buf is None or buf.shape[0] != max_pages * 65536:
                buf = np.zeros(max_pages * 65536, np.uint8)
                self._membuf = buf
            # copy (not frombuffer view): a live view would pin the
            # bytearray and make the post-run resize raise BufferError
            n = len(m.data)
            buf[:n] = np.frombuffer(bytes(m.data), np.uint8)
            buf[n:cur_pages * 65536] = 0
        else:
            cur_pages = 0
            max_pages = 0
            buf = np.zeros(8, np.uint8)
        meta = inst.lowered.funcs[func_idx]
        args = np.asarray([a & ((1 << 64) - 1) for a in raw_args] or [0],
                          np.uint64)
        results = np.zeros(max(meta.nresults, 1), np.uint64)
        retired = np.zeros(1, np.int64)
        out_pages = np.zeros(1, np.int32)
        if stop_cell is None:
            stop_cell = np.zeros(1, np.int32)

        # Mutable table + segment state, rebuilt per invoke from the
        # instance (the scalar engine persists mutations across invokes;
        # so must this one) and written back after.  Capacity: declared
        # max when present, else a 64k-headroom growth window (growth
        # beyond it returns -1, which the spec allows at any size).
        u8p_ = u8p
        func_index = {id(f): i for i, f in enumerate(inst.funcs)}

        def to_handle_plane(refs):
            out = np.zeros(max(len(refs), 1), np.int32)
            for i, h in enumerate(refs):
                if h == 0:
                    continue
                fi = store.deref_func(h) if store is not None else None
                idx = func_index.get(id(fi)) if fi is not None else None
                if idx is None:
                    raise RuntimeError("non-local funcref in table/elem")
            # second pass fills (first pass validated)
            for i, h in enumerate(refs):
                if h:
                    out[i] = func_index[id(store.deref_func(h))] + 1
            return out

        store = self.store
        if inst.tables:
            t0 = inst.tables[0]
            tsize0 = t0.size
            tcap = t0.max if t0.max is not None else tsize0 + 65536
            tcap = max(tcap, tsize0)
            tbl = np.zeros(max(tcap, 1), np.int32)
            tbl[:tsize0] = to_handle_plane(t0.refs)[:tsize0] \
                if tsize0 else tbl[:0]
        else:
            tsize0, tcap = 0, 0
            tbl = np.zeros(1, np.int32)
        tsize_io = np.asarray([tsize0], np.int32)
        esegs = inst.elems
        eoff = np.zeros(max(len(esegs), 1), np.int32)
        elen = np.zeros(max(len(esegs), 1), np.int32)
        eflat_parts = []
        acc = 0
        for i, seg in enumerate(esegs):
            eoff[i] = acc
            elen[i] = len(seg.refs)
            eflat_parts.append(to_handle_plane(seg.refs)[:len(seg.refs)])
            acc += len(seg.refs)
        eflat = np.concatenate(eflat_parts) if acc else np.zeros(1, np.int32)
        edrop = np.zeros(max(len(esegs), 1), np.uint8)
        for i, seg in enumerate(esegs):
            if not seg.refs:
                edrop[i] = 1  # dropped (or empty) segment: length 0
        dsegs = inst.datas
        doff = np.zeros(max(len(dsegs), 1), np.int32)
        dlen = np.zeros(max(len(dsegs), 1), np.int32)
        dacc = bytearray()
        for i, seg in enumerate(dsegs):
            doff[i] = len(dacc)
            dlen[i] = len(seg.data)
            dacc.extend(seg.data)
        dflat = np.frombuffer(bytes(dacc) or b"\0", np.uint8).copy()
        ddrop = np.zeros(max(len(dsegs), 1), np.uint8)

        rc = lib.we_native_invoke(
            *self._img_args(lib),
            tbl.ctypes.data_as(i32p), tsize_io.ctypes.data_as(i32p),
            int(tcap),
            eflat.ctypes.data_as(i32p), eoff.ctypes.data_as(i32p),
            elen.ctypes.data_as(i32p), len(esegs),
            edrop.ctypes.data_as(u8p_),
            dflat.ctypes.data_as(u8p_), doff.ctypes.data_as(i32p),
            dlen.ctypes.data_as(i32p), len(dsegs),
            ddrop.ctypes.data_as(u8p_),
            glob.ctypes.data_as(u64p),
            buf.ctypes.data_as(u8p), cur_pages, max_pages,
            func_idx, args.ctypes.data_as(u64p), len(raw_args),
            results.ctypes.data_as(u64p),
            max_call_depth, 1 << 20,
            stop_cell.ctypes.data_as(i32p),
            retired.ctypes.data_as(i64p),
            out_pages.ctypes.data_as(i32p))

        # write state back (even on trap: partial effects are observable,
        # matching the Python engine which mutates in place)
        for i, g in enumerate(inst.globals):
            g.value = int(glob[i])
        if inst.memories:
            m = inst.memories[0]
            nbytes = int(out_pages[0]) * 65536
            m.data[:] = buf[:nbytes].tobytes()
        if inst.tables:
            t0 = inst.tables[0]
            ns = int(tsize_io[0])
            new_refs = []
            for i in range(ns):
                h = int(tbl[i])
                new_refs.append(
                    0 if h == 0 else
                    (store.intern_ref(inst.funcs[h - 1])
                     if store is not None else h))
            t0.refs = new_refs
        for i, seg in enumerate(esegs):
            if edrop[i] and seg.refs:
                seg.clear()
        for i, seg in enumerate(dsegs):
            if ddrop[i] and seg.data:
                seg.clear()
        if rc != 0:
            raise TrapError(ErrCode(rc))
        return [int(results[i]) for i in range(meta.nresults)], int(retired[0])


def module_for(inst, store=None) -> NativeModule:
    return NativeModule(inst, store)


def scalar_fib_ops_per_sec(n: int) -> float:
    """Live single-core baseline: fib(n) on the C++ dispatch loop."""
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    nm = NativeModule(inst, store)
    if not nm.eligible:
        raise RuntimeError(f"fib not native-eligible: {nm.reason}")
    lib = _build_lib()
    func_idx = inst.exports["fib"][1]
    # best of three: the baseline is "one dedicated CPU core"; taking
    # the max keeps the denominator honest when the host is busy (a
    # slow contended run would otherwise inflate every vs_baseline)
    i32p = ctypes.POINTER(ctypes.c_int32)
    tbl = nm.table.ctypes.data_as(i32p)
    ops = max(lib.we_native_selfbench(*nm._img_args(lib), tbl,
                                      len(nm.table), func_idx, n)
              for _ in range(3))
    if ops <= 0:
        raise RuntimeError("native selfbench failed")
    return ops
