// Native scalar engine: the C++ dispatch loop over the lowered SoA image.
//
// Structural mirror of the reference interpreter's hot loop
// (/root/reference/lib/executor/engine/engine.cpp:68-1641): `while (true)`
// over a flat pre-lowered instruction array with a single switch dispatch,
// branch = stack-erase + pc assignment (helper.cpp:179-193), call = frame
// push with zero-filled locals (helper.cpp:153-176).  Executes the same
// LoweredModule image as the Python oracle and the TPU engines; semantics
// are bit-exact with executor/numeric.py (NaN canonicalization on float
// arithmetic, trapping truncation bounds, masked shifts, trunc division).
//
// Scope: the full scalar ISA (i32/i64/f32/f64 numerics + control +
// memory), the table/segment families (get/set/size/grow/fill/copy/init,
// elem.drop, memory.init/data.drop — reference tableInstr.cpp) and tail
// calls (frame replacement, stackmgr.h:80-98), for single-module,
// single-table, no-host-import execution.  SIMD, cross-module calls and
// host functions stay on the Python engine — the ctypes wrapper
// (native/__init__.py) gates eligibility from this file's own `case`
// labels and falls back, the same graceful degradation the reference
// applies to mismatched AOT sections (lib/loader/ast/module.cpp:279-326).
// Table/segment mutations write back to the instance, so invokes
// interleave with the other engines without state divergence.
//
// Opcode ids come from gen_opcodes.h, generated from the Python opcode
// table at build time so the two sides can never drift.

#include <cmath>
#include <cstdint>
#include <cstring>

#include "gen_opcodes.h"

typedef uint64_t cell;

static inline int32_t s32(cell v) { return (int32_t)(uint32_t)v; }
static inline int64_t s64(cell v) { return (int64_t)v; }
static inline cell u32c(uint32_t v) { return (cell)v; }

static inline float f32_of(cell v) {
  float f;
  uint32_t b = (uint32_t)v;
  std::memcpy(&f, &b, 4);
  return f;
}
static inline cell bits_f32(float f) {
  uint32_t b;
  std::memcpy(&b, &f, 4);
  return (cell)b;
}
static inline double f64_of(cell v) {
  double d;
  std::memcpy(&d, &v, 8);
  return d;
}
static inline cell bits_f64(double d) {
  cell b;
  std::memcpy(&b, &d, 8);
  return b;
}
static inline cell canon32(cell bits) {
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu))
    return 0x7FC00000u;
  return bits;
}
static inline cell canon64(cell bits) {
  if ((bits & 0x7FF0000000000000ull) == 0x7FF0000000000000ull &&
      (bits & 0x000FFFFFFFFFFFFFull))
    return 0x7FF8000000000000ull;
  return bits;
}

struct Frame {
  int32_t ret_pc;
  int64_t fp;
  int64_t opbase;
};

extern "C" int32_t we_native_invoke(
    // image (all read-only)
    const int32_t* ops, const int32_t* aa, const int32_t* bb,
    const int32_t* cc, const int64_t* imm, int32_t code_len,
    const int32_t* brt, const int32_t* f_entry, const int32_t* f_nparams,
    const int32_t* f_nlocals, const int32_t* f_nresults,
    const int32_t* f_ftop, const int32_t* f_typeid, int32_t nf,
    const int32_t* typeid_of_type,
    // table 0: mutable entries + size (funcidx+1 handles, 0 = null);
    // tcap bounds table.grow (declared max clamped by the wrapper)
    int32_t* table, int32_t* tsize_io, int32_t tcap,
    // passive segments for table.init / memory.init; drop flags are
    // written back so segment drops persist on the instance
    const int32_t* elem_flat, const int32_t* elem_off,
    const int32_t* elem_len, int32_t n_eseg, uint8_t* edrop,
    const uint8_t* data_flat, const int32_t* data_off,
    const int32_t* data_len, int32_t n_dseg, uint8_t* ddrop,
    // mutable instance state
    cell* globals, uint8_t* mem, int32_t cur_pages, int32_t max_pages,
    // invocation
    int32_t func_idx, const cell* args, int32_t nargs, cell* results,
    int32_t max_call_depth, int64_t max_value_stack,
    const volatile int32_t* stop_flag,
    // outputs
    int64_t* retired_out, int32_t* out_pages) {
  int32_t trapcode = 0;
  int64_t retired = 0;
  int32_t tsize = tsize_io ? *tsize_io : 0;
  cell* st = new cell[max_value_stack];
  Frame* frames = new Frame[max_call_depth + 2];
  int64_t sp = 0;  // next free slot
  int32_t depth = 0;

  const int32_t entry_nlocals = f_nlocals[func_idx];
  const int32_t entry_nres = f_nresults[func_idx];
  (void)entry_nres;
  if ((int64_t)entry_nlocals + f_ftop[func_idx] > max_value_stack) {
    delete[] st;
    delete[] frames;
    *retired_out = 0;
    *out_pages = cur_pages;
    return E_StackOverflow;
  }
  for (int32_t i = 0; i < nargs; i++) st[sp++] = args[i];
  for (int32_t i = nargs; i < entry_nlocals; i++) st[sp++] = 0;
  int64_t fp = 0;
  int64_t opbase = entry_nlocals;
  int32_t pc = f_entry[func_idx];

#define TRAP(code)     \
  do {                 \
    trapcode = (code); \
    goto done;         \
  } while (0)
#define CHECK_STOP() \
  if (stop_flag && *stop_flag) TRAP(E_Terminated)
#define PUSH(v) st[sp++] = (v)
#define POP() st[--sp]
#define TOP() st[sp - 1]
#define MEM_BYTES ((int64_t)cur_pages << 16)

  // typed memory access with bounds checks (software guard: SURVEY §5.2)
#define LOADN(n, dst)                                             \
  do {                                                            \
    uint64_t _ea = (uint64_t)(uint32_t)TOP() + (uint64_t)imm[pc]; \
    if (_ea + (n) > (uint64_t)MEM_BYTES) TRAP(E_MemoryOOB);       \
    uint64_t _lv = 0;                                             \
    std::memcpy(&_lv, mem + _ea, (n));                            \
    dst = _lv;                                                    \
  } while (0)
#define STOREN(n)                                                 \
  do {                                                            \
    cell _sv = POP();                                             \
    uint64_t _ea = (uint64_t)(uint32_t)POP() + (uint64_t)imm[pc]; \
    if (_ea + (n) > (uint64_t)MEM_BYTES) TRAP(E_MemoryOOB);       \
    std::memcpy(mem + _ea, &_sv, (n));                            \
  } while (0)

  // binary-op plumbing
#define BIN32(expr)                                    \
  do {                                                 \
    uint32_t b = (uint32_t)POP(), a = (uint32_t)TOP(); \
    (void)a;                                           \
    (void)b;                                           \
    TOP() = u32c((uint32_t)(expr));                    \
  } while (0)
#define BIN64(expr)                        \
  do {                                     \
    cell b = POP(), a = TOP();             \
    (void)a;                               \
    (void)b;                               \
    TOP() = (cell)((uint64_t)(expr));      \
  } while (0)
#define FBIN32(expr)                          \
  do {                                        \
    float b = f32_of(POP()), a = f32_of(TOP()); \
    TOP() = canon32(bits_f32((expr)));        \
  } while (0)
#define FBIN64(expr)                            \
  do {                                          \
    double b = f64_of(POP()), a = f64_of(TOP()); \
    TOP() = canon64(bits_f64((expr)));          \
  } while (0)
#define FCMP32(expr)                            \
  do {                                          \
    float b = f32_of(POP()), a = f32_of(TOP()); \
    TOP() = (expr) ? 1 : 0;                     \
  } while (0)
#define FCMP64(expr)                              \
  do {                                            \
    double b = f64_of(POP()), a = f64_of(TOP()); \
    TOP() = (expr) ? 1 : 0;                      \
  } while (0)
#define FUN32(expr)            \
  do {                         \
    float a = f32_of(TOP());   \
    TOP() = canon32(bits_f32((expr))); \
  } while (0)
#define FUN64(expr)            \
  do {                         \
    double a = f64_of(TOP());  \
    TOP() = canon64(bits_f64((expr))); \
  } while (0)

  while (true) {
    const int32_t op = ops[pc];
    retired++;
    switch (op) {
      // ---- locals / consts / parametric -----------------------------
      case OP_local_get:
        PUSH(st[fp + aa[pc]]);
        pc++;
        break;
      case OP_local_set:
        st[fp + aa[pc]] = POP();
        pc++;
        break;
      case OP_local_tee:
        st[fp + aa[pc]] = TOP();
        pc++;
        break;
      case OP_i32_const:
      case OP_i64_const:
      case OP_f32_const:
      case OP_f64_const:
        PUSH((cell)imm[pc]);
        pc++;
        break;
      case OP_drop:
        sp--;
        pc++;
        break;
      case OP_select: {
        cell c = POP();
        cell v2 = POP();
        if (c == 0) TOP() = v2;
        pc++;
        break;
      }
      case OP_global_get:
        PUSH(globals[aa[pc]]);
        pc++;
        break;
      case OP_global_set:
        globals[aa[pc]] = POP();
        pc++;
        break;
      case OP_nop:
        pc++;
        break;
      case OP_unreachable:
        TRAP(E_Unreachable);
      case OP_ref_null:
        PUSH(0);
        pc++;
        break;
      case OP_ref_is_null:
        TOP() = TOP() == 0 ? 1 : 0;
        pc++;
        break;

      // ---- control --------------------------------------------------
      case LOP_BR_ID: {
        CHECK_STOP();
        int32_t keep = bb[pc];
        cell kept[16];
        for (int32_t k = 0; k < keep; k++) kept[k] = st[sp - keep + k];
        sp = opbase + cc[pc];
        for (int32_t k = 0; k < keep; k++) st[sp++] = kept[k];
        pc = aa[pc];
        break;
      }
      case LOP_BRZ_ID:
        if (POP() == 0)
          pc = aa[pc];
        else
          pc++;
        break;
      case LOP_BRNZ_ID:
        if (POP() != 0) {
          CHECK_STOP();
          int32_t keep = bb[pc];
          cell kept[16];
          for (int32_t k = 0; k < keep; k++) kept[k] = st[sp - keep + k];
          sp = opbase + cc[pc];
          for (int32_t k = 0; k < keep; k++) st[sp++] = kept[k];
          pc = aa[pc];
        } else {
          pc++;
        }
        break;
      case OP_br_table: {
        CHECK_STOP();
        uint32_t i = (uint32_t)POP();
        uint32_t n = (uint32_t)bb[pc];
        int64_t entry = ((int64_t)aa[pc] + (i < n ? i : n)) * 3;
        int32_t keep = brt[entry + 1];
        cell kept[16];
        for (int32_t k = 0; k < keep; k++) kept[k] = st[sp - keep + k];
        sp = opbase + brt[entry + 2];
        for (int32_t k = 0; k < keep; k++) st[sp++] = kept[k];
        pc = brt[entry];
        break;
      }
      case OP_return: {
        int32_t n = bb[pc];
        cell kept[16];
        for (int32_t k = 0; k < n; k++) kept[k] = st[sp - n + k];
        sp = fp;
        for (int32_t k = 0; k < n; k++) st[sp++] = kept[k];
        if (depth == 0) {
          for (int32_t k = 0; k < n; k++) results[k] = st[sp - n + k];
          goto done;
        }
        depth--;
        pc = frames[depth].ret_pc;
        fp = frames[depth].fp;
        opbase = frames[depth].opbase;
        break;
      }
      case OP_call:
      case OP_call_indirect:
      case OP_return_call:
      case OP_return_call_indirect: {
        CHECK_STOP();
        bool tail = (op == OP_return_call || op == OP_return_call_indirect);
        int32_t callee;
        if (op == OP_call || op == OP_return_call) {
          callee = aa[pc];
        } else {
          uint32_t i = (uint32_t)POP();
          if (i >= (uint32_t)tsize) TRAP(E_UndefinedElement);
          int32_t h = table[i];
          if (h == 0) TRAP(E_UninitializedElement);
          callee = h - 1;
          if (f_typeid[callee] != typeid_of_type[aa[pc]])
            TRAP(E_IndirectCallTypeMismatch);
        }
        int32_t cn = f_nparams[callee];
        int32_t cl = f_nlocals[callee];
        if (tail) {
          // frame REPLACEMENT (reference StackManager tail-call path,
          // include/runtime/stackmgr.h:80-98): args slide onto the
          // caller's frame base, depth unchanged — O(1) frames for
          // arbitrarily deep tail recursion.  Ascending copy is
          // overlap-safe: src base sp-cn >= opbase >= fp.
          if (fp + cl + (int64_t)f_ftop[callee] > max_value_stack)
            TRAP(E_StackOverflow);
          for (int32_t k = 0; k < cn; k++) st[fp + k] = st[sp - cn + k];
          sp = fp + cn;
          for (int32_t k = cn; k < cl; k++) st[sp++] = 0;
          opbase = fp + cl;
          pc = f_entry[callee];
          break;
        }
        if (depth >= max_call_depth) TRAP(E_CallStackExhausted);
        frames[depth].ret_pc = pc + 1;
        frames[depth].fp = fp;
        frames[depth].opbase = opbase;
        depth++;
        fp = sp - cn;
        // per-function operand ceiling from the validator (f_frame_top),
        // the same bound the device engines check at call entry
        if (fp + cl + (int64_t)f_ftop[callee] > max_value_stack)
          TRAP(E_StackOverflow);
        for (int32_t k = cn; k < cl; k++) st[sp++] = 0;
        opbase = fp + cl;
        pc = f_entry[callee];
        break;
      }

      // ---- tables / segments (r05; reference tableInstr.cpp) --------
      case OP_ref_func:
        PUSH((cell)(uint32_t)(aa[pc] + 1));
        pc++;
        break;
      case OP_table_get: {
        uint32_t i = (uint32_t)POP();
        if (i >= (uint32_t)tsize) TRAP(E_TableOOB);
        PUSH((cell)(uint32_t)table[i]);
        pc++;
        break;
      }
      case OP_table_set: {
        cell v = POP();
        uint32_t i = (uint32_t)POP();
        if (i >= (uint32_t)tsize) TRAP(E_TableOOB);
        table[i] = (int32_t)(uint32_t)v;
        pc++;
        break;
      }
      case OP_table_size:
        PUSH((cell)(uint32_t)tsize);
        pc++;
        break;
      case OP_table_grow: {
        uint32_t delta = (uint32_t)POP();
        cell init = POP();
        uint64_t ns = (uint64_t)(uint32_t)tsize + delta;
        if (ns > (uint64_t)(uint32_t)tcap) {
          PUSH((cell)(uint32_t)(int32_t)-1);
        } else {
          for (uint32_t k = 0; k < delta; k++)
            table[tsize + (int32_t)k] = (int32_t)(uint32_t)init;
          PUSH((cell)(uint32_t)tsize);
          tsize = (int32_t)ns;
        }
        pc++;
        break;
      }
      case OP_table_fill: {
        uint32_t n = (uint32_t)POP();
        cell v = POP();
        uint32_t i = (uint32_t)POP();
        if ((uint64_t)i + n > (uint64_t)(uint32_t)tsize) TRAP(E_TableOOB);
        for (uint32_t k = 0; k < n; k++)
          table[i + k] = (int32_t)(uint32_t)v;
        pc++;
        break;
      }
      case OP_table_copy: {
        uint32_t n = (uint32_t)POP();
        uint32_t src = (uint32_t)POP();
        uint32_t dst = (uint32_t)POP();
        if ((uint64_t)src + n > (uint64_t)(uint32_t)tsize ||
            (uint64_t)dst + n > (uint64_t)(uint32_t)tsize)
          TRAP(E_TableOOB);
        std::memmove(table + dst, table + src, (size_t)n * 4);
        pc++;
        break;
      }
      case OP_table_init: {
        uint32_t n = (uint32_t)POP();
        uint32_t src = (uint32_t)POP();
        uint32_t dst = (uint32_t)POP();
        int32_t seg = aa[pc];
        uint32_t slen =
            (seg < n_eseg && !edrop[seg]) ? (uint32_t)elem_len[seg] : 0u;
        if ((uint64_t)src + n > slen ||
            (uint64_t)dst + n > (uint64_t)(uint32_t)tsize)
          TRAP(E_TableOOB);
        std::memcpy(table + dst, elem_flat + elem_off[seg] + src,
                    (size_t)n * 4);
        pc++;
        break;
      }
      case OP_elem_drop:
        if (aa[pc] < n_eseg) edrop[aa[pc]] = 1;
        pc++;
        break;
      case OP_memory_init: {
        uint32_t n = (uint32_t)POP();
        uint32_t src = (uint32_t)POP();
        uint32_t dst = (uint32_t)POP();
        int32_t seg = aa[pc];
        uint32_t slen =
            (seg < n_dseg && !ddrop[seg]) ? (uint32_t)data_len[seg] : 0u;
        if ((uint64_t)src + n > slen ||
            (uint64_t)dst + n > (uint64_t)MEM_BYTES)
          TRAP(E_MemoryOOB);
        std::memcpy(mem + dst, data_flat + data_off[seg] + src, n);
        pc++;
        break;
      }
      case OP_data_drop:
        if (aa[pc] < n_dseg) ddrop[aa[pc]] = 1;
        pc++;
        break;

      // ---- memory ---------------------------------------------------
      case OP_i32_load: {
        cell v;
        LOADN(4, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_f32_load: {
        cell v;
        LOADN(4, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i64_load:
      case OP_f64_load: {
        cell v;
        LOADN(8, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i32_load8_u: {
        cell v;
        LOADN(1, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i32_load8_s: {
        cell v;
        LOADN(1, v);
        TOP() = u32c((uint32_t)(int32_t)(int8_t)v);
        pc++;
        break;
      }
      case OP_i32_load16_u: {
        cell v;
        LOADN(2, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i32_load16_s: {
        cell v;
        LOADN(2, v);
        TOP() = u32c((uint32_t)(int32_t)(int16_t)v);
        pc++;
        break;
      }
      case OP_i64_load8_u: {
        cell v;
        LOADN(1, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i64_load8_s: {
        cell v;
        LOADN(1, v);
        TOP() = (cell)(int64_t)(int8_t)v;
        pc++;
        break;
      }
      case OP_i64_load16_u: {
        cell v;
        LOADN(2, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i64_load16_s: {
        cell v;
        LOADN(2, v);
        TOP() = (cell)(int64_t)(int16_t)v;
        pc++;
        break;
      }
      case OP_i64_load32_u: {
        cell v;
        LOADN(4, v);
        TOP() = v;
        pc++;
        break;
      }
      case OP_i64_load32_s: {
        cell v;
        LOADN(4, v);
        TOP() = (cell)(int64_t)(int32_t)v;
        pc++;
        break;
      }
      case OP_i32_store:
      case OP_f32_store:
        STOREN(4);
        pc++;
        break;
      case OP_i64_store:
      case OP_f64_store:
        STOREN(8);
        pc++;
        break;
      case OP_i32_store8:
      case OP_i64_store8:
        STOREN(1);
        pc++;
        break;
      case OP_i32_store16:
      case OP_i64_store16:
        STOREN(2);
        pc++;
        break;
      case OP_i64_store32:
        STOREN(4);
        pc++;
        break;
      case OP_memory_size:
        PUSH((cell)(uint32_t)cur_pages);
        pc++;
        break;
      case OP_memory_grow: {
        uint64_t delta = (uint32_t)POP();
        uint64_t nw = (uint64_t)(uint32_t)cur_pages + delta;  // no u32 wrap
        if (nw > (uint64_t)(uint32_t)max_pages || nw > 65536u) {
          PUSH(u32c((uint32_t)-1));
        } else {
          PUSH((cell)(uint32_t)cur_pages);
          std::memset(mem + ((int64_t)cur_pages << 16), 0,
                      (int64_t)delta << 16);
          cur_pages = (int32_t)nw;
        }
        pc++;
        break;
      }
      case OP_memory_copy: {
        uint64_t n = (uint32_t)POP();
        uint64_t src = (uint32_t)POP();
        uint64_t dst = (uint32_t)POP();
        if (src + n > (uint64_t)MEM_BYTES || dst + n > (uint64_t)MEM_BYTES)
          TRAP(E_MemoryOOB);
        std::memmove(mem + dst, mem + src, n);
        pc++;
        break;
      }
      case OP_memory_fill: {
        uint64_t n = (uint32_t)POP();
        uint8_t val = (uint8_t)POP();
        uint64_t dst = (uint32_t)POP();
        if (dst + n > (uint64_t)MEM_BYTES) TRAP(E_MemoryOOB);
        std::memset(mem + dst, val, n);
        pc++;
        break;
      }

      // ---- i32 numerics --------------------------------------------
      case OP_i32_add: BIN32(a + b); pc++; break;
      case OP_i32_sub: BIN32(a - b); pc++; break;
      case OP_i32_mul: BIN32(a * b); pc++; break;
      case OP_i32_and: BIN32(a & b); pc++; break;
      case OP_i32_or: BIN32(a | b); pc++; break;
      case OP_i32_xor: BIN32(a ^ b); pc++; break;
      case OP_i32_shl: BIN32(a << (b & 31)); pc++; break;
      case OP_i32_shr_u: BIN32(a >> (b & 31)); pc++; break;
      case OP_i32_shr_s: BIN32((uint32_t)((int32_t)a >> (b & 31))); pc++; break;
      case OP_i32_rotl: BIN32((b & 31) ? ((a << (b & 31)) | (a >> (32 - (b & 31)))) : a); pc++; break;
      case OP_i32_rotr: BIN32((b & 31) ? ((a >> (b & 31)) | (a << (32 - (b & 31)))) : a); pc++; break;
      case OP_i32_div_s: {
        uint32_t b = (uint32_t)POP(), a = (uint32_t)TOP();
        if (b == 0) TRAP(E_DivideByZero);
        if (a == 0x80000000u && b == 0xFFFFFFFFu) TRAP(E_IntegerOverflow);
        TOP() = u32c((uint32_t)((int32_t)a / (int32_t)b));
        pc++;
        break;
      }
      case OP_i32_div_u: {
        uint32_t b = (uint32_t)POP(), a = (uint32_t)TOP();
        if (b == 0) TRAP(E_DivideByZero);
        TOP() = u32c(a / b);
        pc++;
        break;
      }
      case OP_i32_rem_s: {
        uint32_t b = (uint32_t)POP(), a = (uint32_t)TOP();
        if (b == 0) TRAP(E_DivideByZero);
        if (a == 0x80000000u && b == 0xFFFFFFFFu)
          TOP() = 0;
        else
          TOP() = u32c((uint32_t)((int32_t)a % (int32_t)b));
        pc++;
        break;
      }
      case OP_i32_rem_u: {
        uint32_t b = (uint32_t)POP(), a = (uint32_t)TOP();
        if (b == 0) TRAP(E_DivideByZero);
        TOP() = u32c(a % b);
        pc++;
        break;
      }
      case OP_i32_eqz: TOP() = (uint32_t)TOP() == 0 ? 1 : 0; pc++; break;
      case OP_i32_eq: BIN32(a == b ? 1 : 0); pc++; break;
      case OP_i32_ne: BIN32(a != b ? 1 : 0); pc++; break;
      case OP_i32_lt_s: BIN32((int32_t)a < (int32_t)b ? 1 : 0); pc++; break;
      case OP_i32_lt_u: BIN32(a < b ? 1 : 0); pc++; break;
      case OP_i32_gt_s: BIN32((int32_t)a > (int32_t)b ? 1 : 0); pc++; break;
      case OP_i32_gt_u: BIN32(a > b ? 1 : 0); pc++; break;
      case OP_i32_le_s: BIN32((int32_t)a <= (int32_t)b ? 1 : 0); pc++; break;
      case OP_i32_le_u: BIN32(a <= b ? 1 : 0); pc++; break;
      case OP_i32_ge_s: BIN32((int32_t)a >= (int32_t)b ? 1 : 0); pc++; break;
      case OP_i32_ge_u: BIN32(a >= b ? 1 : 0); pc++; break;
      case OP_i32_clz: {
        uint32_t a = (uint32_t)TOP();
        TOP() = a ? __builtin_clz(a) : 32;
        pc++;
        break;
      }
      case OP_i32_ctz: {
        uint32_t a = (uint32_t)TOP();
        TOP() = a ? __builtin_ctz(a) : 32;
        pc++;
        break;
      }
      case OP_i32_popcnt:
        TOP() = __builtin_popcount((uint32_t)TOP());
        pc++;
        break;
      case OP_i32_extend8_s:
        TOP() = u32c((uint32_t)(int32_t)(int8_t)TOP());
        pc++;
        break;
      case OP_i32_extend16_s:
        TOP() = u32c((uint32_t)(int32_t)(int16_t)TOP());
        pc++;
        break;

      // ---- i64 numerics --------------------------------------------
      case OP_i64_add: BIN64(a + b); pc++; break;
      case OP_i64_sub: BIN64(a - b); pc++; break;
      case OP_i64_mul: BIN64(a * b); pc++; break;
      case OP_i64_and: BIN64(a & b); pc++; break;
      case OP_i64_or: BIN64(a | b); pc++; break;
      case OP_i64_xor: BIN64(a ^ b); pc++; break;
      case OP_i64_shl: BIN64(a << (b & 63)); pc++; break;
      case OP_i64_shr_u: BIN64(a >> (b & 63)); pc++; break;
      case OP_i64_shr_s: BIN64((uint64_t)((int64_t)a >> (b & 63))); pc++; break;
      case OP_i64_rotl: BIN64((b & 63) ? ((a << (b & 63)) | (a >> (64 - (b & 63)))) : a); pc++; break;
      case OP_i64_rotr: BIN64((b & 63) ? ((a >> (b & 63)) | (a << (64 - (b & 63)))) : a); pc++; break;
      case OP_i64_div_s: {
        cell b = POP(), a = TOP();
        if (b == 0) TRAP(E_DivideByZero);
        if (a == 0x8000000000000000ull && b == 0xFFFFFFFFFFFFFFFFull)
          TRAP(E_IntegerOverflow);
        TOP() = (cell)((int64_t)a / (int64_t)b);
        pc++;
        break;
      }
      case OP_i64_div_u: {
        cell b = POP(), a = TOP();
        if (b == 0) TRAP(E_DivideByZero);
        TOP() = a / b;
        pc++;
        break;
      }
      case OP_i64_rem_s: {
        cell b = POP(), a = TOP();
        if (b == 0) TRAP(E_DivideByZero);
        if (a == 0x8000000000000000ull && b == 0xFFFFFFFFFFFFFFFFull)
          TOP() = 0;
        else
          TOP() = (cell)((int64_t)a % (int64_t)b);
        pc++;
        break;
      }
      case OP_i64_rem_u: {
        cell b = POP(), a = TOP();
        if (b == 0) TRAP(E_DivideByZero);
        TOP() = a % b;
        pc++;
        break;
      }
      case OP_i64_eqz: TOP() = TOP() == 0 ? 1 : 0; pc++; break;
      case OP_i64_eq: BIN64(a == b ? 1 : 0); pc++; break;
      case OP_i64_ne: BIN64(a != b ? 1 : 0); pc++; break;
      case OP_i64_lt_s: BIN64((int64_t)a < (int64_t)b ? 1 : 0); pc++; break;
      case OP_i64_lt_u: BIN64(a < b ? 1 : 0); pc++; break;
      case OP_i64_gt_s: BIN64((int64_t)a > (int64_t)b ? 1 : 0); pc++; break;
      case OP_i64_gt_u: BIN64(a > b ? 1 : 0); pc++; break;
      case OP_i64_le_s: BIN64((int64_t)a <= (int64_t)b ? 1 : 0); pc++; break;
      case OP_i64_le_u: BIN64(a <= b ? 1 : 0); pc++; break;
      case OP_i64_ge_s: BIN64((int64_t)a >= (int64_t)b ? 1 : 0); pc++; break;
      case OP_i64_ge_u: BIN64(a >= b ? 1 : 0); pc++; break;
      case OP_i64_clz: {
        cell a = TOP();
        TOP() = a ? __builtin_clzll(a) : 64;
        pc++;
        break;
      }
      case OP_i64_ctz: {
        cell a = TOP();
        TOP() = a ? __builtin_ctzll(a) : 64;
        pc++;
        break;
      }
      case OP_i64_popcnt:
        TOP() = __builtin_popcountll(TOP());
        pc++;
        break;
      case OP_i64_extend8_s:
        TOP() = (cell)(int64_t)(int8_t)TOP();
        pc++;
        break;
      case OP_i64_extend16_s:
        TOP() = (cell)(int64_t)(int16_t)TOP();
        pc++;
        break;
      case OP_i64_extend32_s:
        TOP() = (cell)(int64_t)(int32_t)TOP();
        pc++;
        break;

      // ---- conversions ---------------------------------------------
      case OP_i32_wrap_i64: TOP() = (uint32_t)TOP(); pc++; break;
      case OP_i64_extend_i32_s: TOP() = (cell)(int64_t)s32(TOP()); pc++; break;
      case OP_i64_extend_i32_u: TOP() = (uint32_t)TOP(); pc++; break;
      case OP_i32_reinterpret_f32:
      case OP_f32_reinterpret_i32:
        pc++;
        break;  // raw cells already
      case OP_i64_reinterpret_f64:
      case OP_f64_reinterpret_i64:
        pc++;
        break;
      case OP_f32_convert_i32_s: TOP() = bits_f32((float)s32(TOP())); pc++; break;
      case OP_f32_convert_i32_u: TOP() = bits_f32((float)(uint32_t)TOP()); pc++; break;
      case OP_f32_convert_i64_s: TOP() = bits_f32((float)s64(TOP())); pc++; break;
      case OP_f32_convert_i64_u: TOP() = bits_f32((float)(uint64_t)TOP()); pc++; break;
      case OP_f64_convert_i32_s: TOP() = bits_f64((double)s32(TOP())); pc++; break;
      case OP_f64_convert_i32_u: TOP() = bits_f64((double)(uint32_t)TOP()); pc++; break;
      case OP_f64_convert_i64_s: TOP() = bits_f64((double)s64(TOP())); pc++; break;
      case OP_f64_convert_i64_u: TOP() = bits_f64((double)(uint64_t)TOP()); pc++; break;
      case OP_f32_demote_f64: TOP() = canon32(bits_f32((float)f64_of(TOP()))); pc++; break;
      case OP_f64_promote_f32: TOP() = canon64(bits_f64((double)f32_of(TOP()))); pc++; break;

#define TRUNC(fty_of, lo, hi, mask)                    \
  do {                                                 \
    double v = (double)fty_of(TOP());                  \
    if (std::isnan(v)) TRAP(E_InvalidConvToInt);       \
    double t = std::trunc(v);                          \
    if (!((lo) < t && t < (hi))) TRAP(E_IntegerOverflow); \
    TOP() = (cell)(((t) < 0 ? (uint64_t)(int64_t)t : (uint64_t)t)) & (mask); \
  } while (0)
#define TRUNC_SAT(fty_of, lo, hi, lo_res, hi_res, mask)  \
  do {                                                   \
    double v = (double)fty_of(TOP());                    \
    if (std::isnan(v)) {                                 \
      TOP() = 0;                                         \
    } else {                                             \
      double t = std::trunc(v);                          \
      if (t <= (lo))                                     \
        TOP() = (cell)(lo_res) & (mask);                 \
      else if (t >= (hi))                                \
        TOP() = (cell)(hi_res) & (mask);                 \
      else                                               \
        TOP() = (cell)(((t) < 0 ? (uint64_t)(int64_t)t : (uint64_t)t)) & (mask); \
    }                                                    \
  } while (0)

      case OP_i32_trunc_f32_s: TRUNC(f32_of, -2147483649.0, 2147483648.0, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_f32_u: TRUNC(f32_of, -1.0, 4294967296.0, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_f64_s: TRUNC(f64_of, -2147483649.0, 2147483648.0, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_f64_u: TRUNC(f64_of, -1.0, 4294967296.0, 0xFFFFFFFFull); pc++; break;
      case OP_i64_trunc_f32_s: TRUNC(f32_of, -9223372036854777856.0, 9223372036854775808.0, ~0ull); pc++; break;
      case OP_i64_trunc_f32_u: TRUNC(f32_of, -1.0, 18446744073709551616.0, ~0ull); pc++; break;
      case OP_i64_trunc_f64_s: TRUNC(f64_of, -9223372036854777856.0, 9223372036854775808.0, ~0ull); pc++; break;
      case OP_i64_trunc_f64_u: TRUNC(f64_of, -1.0, 18446744073709551616.0, ~0ull); pc++; break;
      case OP_i32_trunc_sat_f32_s: TRUNC_SAT(f32_of, -2147483649.0, 2147483648.0, (uint64_t)(uint32_t)INT32_MIN, (uint64_t)INT32_MAX, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_sat_f32_u: TRUNC_SAT(f32_of, -1.0, 4294967296.0, 0, 0xFFFFFFFFull, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_sat_f64_s: TRUNC_SAT(f64_of, -2147483649.0, 2147483648.0, (uint64_t)(uint32_t)INT32_MIN, (uint64_t)INT32_MAX, 0xFFFFFFFFull); pc++; break;
      case OP_i32_trunc_sat_f64_u: TRUNC_SAT(f64_of, -1.0, 4294967296.0, 0, 0xFFFFFFFFull, 0xFFFFFFFFull); pc++; break;
      case OP_i64_trunc_sat_f32_s: TRUNC_SAT(f32_of, -9223372036854777856.0, 9223372036854775808.0, (uint64_t)INT64_MIN, (uint64_t)INT64_MAX, ~0ull); pc++; break;
      case OP_i64_trunc_sat_f32_u: TRUNC_SAT(f32_of, -1.0, 18446744073709551616.0, 0, ~0ull, ~0ull); pc++; break;
      case OP_i64_trunc_sat_f64_s: TRUNC_SAT(f64_of, -9223372036854777856.0, 9223372036854775808.0, (uint64_t)INT64_MIN, (uint64_t)INT64_MAX, ~0ull); pc++; break;
      case OP_i64_trunc_sat_f64_u: TRUNC_SAT(f64_of, -1.0, 18446744073709551616.0, 0, ~0ull, ~0ull); pc++; break;

      // ---- f32 ------------------------------------------------------
      case OP_f32_add: FBIN32(a + b); pc++; break;
      case OP_f32_sub: FBIN32(a - b); pc++; break;
      case OP_f32_mul: FBIN32(a * b); pc++; break;
      case OP_f32_div: FBIN32(a / b); pc++; break;
      case OP_f32_eq: FCMP32(a == b); pc++; break;
      case OP_f32_ne: FCMP32(!(a == b)); pc++; break;
      case OP_f32_lt: FCMP32(a < b); pc++; break;
      case OP_f32_gt: FCMP32(a > b); pc++; break;
      case OP_f32_le: FCMP32(a <= b); pc++; break;
      case OP_f32_ge: FCMP32(a >= b); pc++; break;
      case OP_f32_abs: TOP() = TOP() & 0x7FFFFFFFull; pc++; break;
      case OP_f32_neg: TOP() = TOP() ^ 0x80000000ull; pc++; break;
      case OP_f32_copysign: {
        cell b = POP();
        TOP() = (TOP() & 0x7FFFFFFFull) | (b & 0x80000000ull);
        pc++;
        break;
      }
      case OP_f32_min:
      case OP_f32_max: {
        cell bbits = POP(), abits = TOP();
        float a = f32_of(abits), b = f32_of(bbits);
        if (std::isnan(a) || std::isnan(b)) {
          TOP() = 0x7FC00000ull;
        } else if (a == b) {
          bool sa = (abits >> 31) & 1;
          if (op == OP_f32_min)
            TOP() = sa ? abits : bbits;
          else
            TOP() = sa ? bbits : abits;
        } else {
          bool take_a = (a < b) == (op == OP_f32_min);
          TOP() = take_a ? abits : bbits;
        }
        pc++;
        break;
      }
      case OP_f32_ceil: FUN32(std::ceil(a)); pc++; break;
      case OP_f32_floor: FUN32(std::floor(a)); pc++; break;
      case OP_f32_trunc: FUN32(std::trunc(a)); pc++; break;
      case OP_f32_nearest: FUN32(std::nearbyint(a)); pc++; break;
      case OP_f32_sqrt: FUN32(std::sqrt(a)); pc++; break;

      // ---- f64 ------------------------------------------------------
      case OP_f64_add: FBIN64(a + b); pc++; break;
      case OP_f64_sub: FBIN64(a - b); pc++; break;
      case OP_f64_mul: FBIN64(a * b); pc++; break;
      case OP_f64_div: FBIN64(a / b); pc++; break;
      case OP_f64_eq: FCMP64(a == b); pc++; break;
      case OP_f64_ne: FCMP64(!(a == b)); pc++; break;
      case OP_f64_lt: FCMP64(a < b); pc++; break;
      case OP_f64_gt: FCMP64(a > b); pc++; break;
      case OP_f64_le: FCMP64(a <= b); pc++; break;
      case OP_f64_ge: FCMP64(a >= b); pc++; break;
      case OP_f64_abs: TOP() = TOP() & 0x7FFFFFFFFFFFFFFFull; pc++; break;
      case OP_f64_neg: TOP() = TOP() ^ 0x8000000000000000ull; pc++; break;
      case OP_f64_copysign: {
        cell b = POP();
        TOP() = (TOP() & 0x7FFFFFFFFFFFFFFFull) | (b & 0x8000000000000000ull);
        pc++;
        break;
      }
      case OP_f64_min:
      case OP_f64_max: {
        cell bbits = POP(), abits = TOP();
        double a = f64_of(abits), b = f64_of(bbits);
        if (std::isnan(a) || std::isnan(b)) {
          TOP() = 0x7FF8000000000000ull;
        } else if (a == b) {
          bool sa = (abits >> 63) & 1;
          if (op == OP_f64_min)
            TOP() = sa ? abits : bbits;
          else
            TOP() = sa ? bbits : abits;
        } else {
          bool take_a = (a < b) == (op == OP_f64_min);
          TOP() = take_a ? abits : bbits;
        }
        pc++;
        break;
      }
      case OP_f64_ceil: FUN64(std::ceil(a)); pc++; break;
      case OP_f64_floor: FUN64(std::floor(a)); pc++; break;
      case OP_f64_trunc: FUN64(std::trunc(a)); pc++; break;
      case OP_f64_nearest: FUN64(std::nearbyint(a)); pc++; break;
      case OP_f64_sqrt: FUN64(std::sqrt(a)); pc++; break;

      default:
        TRAP(E_ExecutionFailed);
    }
  }

done:
  *retired_out = retired;
  *out_pages = cur_pages;
  if (tsize_io) *tsize_io = tsize;
  delete[] st;
  delete[] frames;
  return trapcode;
}

// Quick self-contained throughput probe used by bench.py's denominator:
// returns retired instructions/second for a fib(n) run, measured on this
// same dispatch loop (the honest single-core baseline).
#include <chrono>

extern "C" double we_native_selfbench(
    const int32_t* ops, const int32_t* aa, const int32_t* bb,
    const int32_t* cc, const int64_t* imm, int32_t code_len,
    const int32_t* brt, const int32_t* f_entry, const int32_t* f_nparams,
    const int32_t* f_nlocals, const int32_t* f_nresults,
    const int32_t* f_ftop, const int32_t* f_typeid, int32_t nf,
    const int32_t* typeid_of_type, const int32_t* table, int32_t tsize,
    int32_t func_idx, int64_t arg) {
  cell args[1] = {(cell)arg};
  cell results[4];
  int64_t retired = 0;
  int32_t out_pages = 0;
  uint8_t dummy_mem[8] = {0};
  int32_t tbl_copy[64];
  int32_t nt = tsize < 64 ? tsize : 64;
  for (int32_t i = 0; i < nt; i++) tbl_copy[i] = table[i];
  int32_t ts_io = nt;
  uint8_t no_drop[1] = {0};
  auto t0 = std::chrono::steady_clock::now();
  int32_t rc = we_native_invoke(
      ops, aa, bb, cc, imm, code_len, brt, f_entry, f_nparams, f_nlocals,
      f_nresults, f_ftop, f_typeid, nf, typeid_of_type, tbl_copy, &ts_io,
      nt, nullptr, nullptr, nullptr, 0, no_drop, nullptr, nullptr, nullptr,
      0, no_drop,
      nullptr, dummy_mem, 0, 0, func_idx, args, 1, results, 8192, 1 << 20,
      nullptr, &retired, &out_pages);
  auto t1 = std::chrono::steady_clock::now();
  double dt = std::chrono::duration<double>(t1 - t0).count();
  if (rc != 0 || dt <= 0) return 0.0;
  return (double)retired / dt;
}
