"""Batch observability subsystem: flight recorder, trace + metrics export.

Three layers (ISSUE 3 / ROADMAP "attributable timings"):

  recorder.py  bounded-ring FlightRecorder + the NULL_RECORDER guard
               object all instrumentation seams hold when obs is off
  trace.py     Chrome trace_event JSON export (Perfetto-openable) +
               schema validator
  metrics.py   Prometheus text-format export + strict parser

Wiring: set `Configure.obs.enabled = True` (plus `opcode_histogram` for
the device-side hot-opcode plane) before building engines; every engine
/ scheduler / supervisor constructed from that Configure reports into
one shared FlightRecorder (`recorder_of(conf)`).  `VM.execute_batch`
takes `trace_out=` / `metrics_out=` paths (CLI: `--trace-out` /
`--metrics-out`) and exports after the run.
"""

from wasmedge_tpu.obs.recorder import (  # noqa: F401
    NULL_RECORDER,
    FlightRecorder,
    LatencyHistogram,
    NullRecorder,
    recorder_of,
)
from wasmedge_tpu.obs.trace import (  # noqa: F401
    chrome_trace,
    export_chrome_trace,
    validate_chrome_trace,
)
from wasmedge_tpu.obs.metrics import (  # noqa: F401
    export_prometheus,
    parse_prometheus,
    render_prometheus,
)
