"""Prometheus text-format export for batch observability.

One scrape-shaped snapshot aggregating everything a long-lived batch
server wants on a dashboard:

  - common/statistics.py counters (instructions, gas, wasm/host time)
  - per-kind hostcall drain latency histograms (flight recorder)
  - engine-tier residency seconds (supervisor ladder)
  - failure-taxonomy counts (FailureRecords by fault_class)
  - hostcall pipeline counters (tier-0/tier-1/serve rounds)
  - per-opcode retired counts when the device histogram plane was on

Rendering follows the Prometheus exposition format v0.0.4 (HELP/TYPE
comment lines, histogram `_bucket{le=...}` cumulative counts + `_sum` +
`_count`, escaped label values), so the output is scrapeable as-is by a
real Prometheus — and parseable by the test suite's strict parser.
"""

from __future__ import annotations

from typing import Optional


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


class _Writer:
    def __init__(self):
        self.lines = []
        self._typed = set()

    def head(self, name: str, typ: str, help_: str):
        if name in self._typed:
            return
        self._typed.add(name)
        self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {typ}")

    def sample(self, name: str, labels: Optional[dict], value):
        lab = ""
        if labels:
            inner = ",".join(f'{k}="{_esc(v)}"'
                             for k, v in sorted(labels.items()))
            lab = "{" + inner + "}"
        self.lines.append(f"{name}{lab} {_fmt(value)}")

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_prometheus(recorder=None, stats=None, hostcall_stats=None,
                      failures=None, http_requests=None,
                      analysis_counts=None, gateway_counts=None,
                      shed_counts=None, hv_stats=None,
                      fleet_stats=None, reshard_counts=None,
                      autoscale_actions=None,
                      compile_cache_counts=None,
                      snapshot_counts=None,
                      session_stats=None,
                      integrity_stats=None) -> str:
    """Render one metrics snapshot.  All sources optional: `recorder` a
    FlightRecorder, `stats` a common.statistics.Statistics, `hostcall_stats`
    an engine's pipeline counter dict, `failures` extra FailureRecords
    (e.g. statistics.recent_failures()) merged into the taxonomy counts,
    `http_requests` the gateway's {status_code: count} edge tally,
    `analysis_counts` the gateway's static-analysis admission summary
    ({"bounded": n, "unbounded": n, "policy_rejected": n}),
    `gateway_counts` the gateway's durability/robustness counters
    ({"restarts": n, "rollbacks": n}), `shed_counts` the per-tenant
    degraded-mode shed tally, `hv_stats` a BatchServer.hv_stats()
    lane-virtualization snapshot (wasmedge_tpu/hv/), `fleet_stats` a
    FleetController.stats() federation snapshot (wasmedge_tpu/fleet/),
    `reshard_counts` the gateway's {direction: count} live-reshard
    tally (emitted only when a reshard has happened), and
    `autoscale_actions` the AutoscaleController's {action: count}
    tally (emitted only when the controller is constructed) — both
    r21; a gateway without them renders bit-identically to r16.
    `compile_cache_counts` the registry compile cache's counter dict
    and `snapshot_counts` the imagestore snapshot tally — both r22,
    passed only when Configure.imagestore is active, so a gateway
    without the subsystem renders bit-identically to r21.
    `session_stats` an EffectsRuntime.stats() suspend/resume snapshot
    (wasmedge_tpu/effects/) — r23, passed only when Configure.effects
    is active, so a gateway without it renders bit-identically to
    r22.  `integrity_stats` a GatewayService.integrity_stats() block
    ({"audit": ShadowAuditor.stats, "quarantine":
    DeviceQuarantine.snapshot(), "scrub": Scrubber.snapshot()}, each
    key optional) — r24, passed only when Configure.integrity is
    active, so a gateway without it renders bit-identically to r23."""
    w = _Writer()

    if compile_cache_counts:
        w.head("wasmedge_compile_cache_hits_total", "counter",
               "Content-addressed compile-cache hits by tier: probe = "
               "in-process parked-engine adoption, disk = persistent "
               "cross-process image payload (imagestore/compilecache).")
        w.sample("wasmedge_compile_cache_hits_total", {"tier": "probe"},
                 int(compile_cache_counts.get("probe_hits", 0)))
        w.sample("wasmedge_compile_cache_hits_total", {"tier": "disk"},
                 int(compile_cache_counts.get("disk_hits", 0)))
        w.head("wasmedge_compile_cache_misses_total", "counter",
               "Registrations that lowered fresh: no cache entry, a "
               "corrupt/mismatched entry, or a faulted read (the last "
               "two also count in their own kinds below).")
        w.sample("wasmedge_compile_cache_misses_total", None,
                 int(compile_cache_counts.get("misses", 0)))
        if compile_cache_counts.get("corrupt") or \
                compile_cache_counts.get("read_faults"):
            w.head("wasmedge_compile_cache_errors_total", "counter",
                   "Cache entries rejected (corrupt = integrity/decode "
                   "failure, read_fault = injected/IO read fault); "
                   "every one fell back to a fresh lower.")
            for kind in ("corrupt", "read_faults"):
                if compile_cache_counts.get(kind):
                    w.sample("wasmedge_compile_cache_errors_total",
                             {"kind": kind},
                             int(compile_cache_counts[kind]))

    if snapshot_counts:
        w.head("wasmedge_snapshot_installs_total", "counter",
               "Lanes admitted through a pre-initialized snapshot "
               "overlay instead of init replay (imagestore/snapshot).")
        w.sample("wasmedge_snapshot_installs_total", None,
                 int(snapshot_counts.get("installs", 0)))
        w.head("wasmedge_snapshot_captures_total", "counter",
               "Registration-time snapshot captures by outcome "
               "(skipped = no init export / init parked or trapped).")
        for kind in ("captured", "skipped"):
            if snapshot_counts.get(kind):
                w.sample("wasmedge_snapshot_captures_total",
                         {"outcome": kind},
                         int(snapshot_counts[kind]))
        if snapshot_counts.get("install_faults") or \
                snapshot_counts.get("corrupt"):
            w.head("wasmedge_snapshot_errors_total", "counter",
                   "Snapshot overlays rejected at generation build "
                   "(faulted install, corrupt store entry); the "
                   "generation fell back to template init replay.")
            for kind in ("install_faults", "corrupt"):
                if snapshot_counts.get(kind):
                    w.sample("wasmedge_snapshot_errors_total",
                             {"kind": kind},
                             int(snapshot_counts[kind]))

    if fleet_stats:
        w.head("wasmedge_fleet_peers", "gauge",
               "Fleet peers by liveness state (wasmedge_tpu/fleet/: "
               "heartbeat-driven suspect->dead state machine).")
        peers = fleet_stats.get("peers", {})
        for state in ("alive", "suspect", "dead"):
            w.sample("wasmedge_fleet_peers", {"state": state},
                     int(peers.get(state, 0)))
        w.head("wasmedge_fleet_migrations_total", "counter",
               "Cross-host lane migrations (out = parked vlane "
               "shipped to a peer, in = adopted from one; SwapStore "
               "payloads hash-verified end to end).")
        w.sample("wasmedge_fleet_migrations_total", {"direction": "out"},
                 int(fleet_stats.get("migrations_out", 0)))
        w.sample("wasmedge_fleet_migrations_total", {"direction": "in"},
                 int(fleet_stats.get("migrations_in", 0)))
        w.head("wasmedge_fleet_adoptions_total", "counter",
               "Unresolved requests adopted from dead peers' "
               "replicated journals (re-queued at-least-once under "
               "their original ids).")
        w.sample("wasmedge_fleet_adoptions_total", None,
                 int(fleet_stats.get("adoptions", 0)))
        w.head("wasmedge_fleet_membership_epoch", "gauge",
               "Gossip membership view epoch (wasmedge_tpu/fleet/"
               "membership.py: bumps on join/leave origin events; a "
               "static fleet stays at 0).")
        w.sample("wasmedge_fleet_membership_epoch", None,
                 int(fleet_stats.get("membership_epoch", 0)))

    if reshard_counts:
        w.head("wasmedge_reshards_total", "counter",
               "Live reshards of the running generation by direction "
               "(serve/server.py reshard: device-set change at a "
               "launch boundary, resident lanes ride through).")
        for direction in sorted(reshard_counts):
            w.sample("wasmedge_reshards_total",
                     {"direction": str(direction)},
                     int(reshard_counts[direction]))

    if autoscale_actions is not None:
        w.head("wasmedge_autoscale_actions_total", "counter",
               "Autoscale controller actions by kind (gateway/"
               "autoscale.py: deterministic spike/calm ladder).")
        for action in sorted(autoscale_actions):
            w.sample("wasmedge_autoscale_actions_total",
                     {"action": str(action)},
                     int(autoscale_actions[action]))

    if hv_stats:
        w.head("wasmedge_hv_swaps_total", "counter",
               "Virtual-lane swaps by direction (wasmedge_tpu/hv/: "
               "out = lane state parked host-side, in = reinstalled "
               "onto a physical lane).")
        w.sample("wasmedge_hv_swaps_total", {"direction": "out"},
                 int(hv_stats.get("swaps_out", 0)))
        w.sample("wasmedge_hv_swaps_total", {"direction": "in"},
                 int(hv_stats.get("swaps_in", 0)))
        w.head("wasmedge_hv_resident_lanes", "gauge",
               "Physical lanes currently holding a request.")
        w.sample("wasmedge_hv_resident_lanes", None,
                 int(hv_stats.get("resident", 0)))
        w.head("wasmedge_hv_virtual_lanes", "gauge",
               "Admitted requests currently off-device (fresh + "
               "swapped virtual lanes).")
        w.sample("wasmedge_hv_virtual_lanes", None,
                 int(hv_stats.get("virtual", 0)))
        w.head("wasmedge_hv_resident_lane_cap", "gauge",
               "Physical lanes the resident-bytes budget admits.")
        w.sample("wasmedge_hv_resident_lane_cap", None,
                 int(hv_stats.get("resident_cap", 0)))
        w.head("wasmedge_hv_swap_store_bytes", "gauge",
               "Host bytes held by the swap store.")
        w.sample("wasmedge_hv_swap_store_bytes", None,
                 int(hv_stats.get("store_bytes", 0)))
        if hv_stats.get("swap_out_faults") or \
                hv_stats.get("swap_in_faults") or \
                hv_stats.get("swap_corrupt"):
            w.head("wasmedge_hv_swap_faults_total", "counter",
                   "Swap operations that failed (faulted swap-out/"
                   "swap-in retried; corrupt entries rejected).")
            for kind in ("swap_out_faults", "swap_in_faults",
                         "swap_corrupt"):
                if hv_stats.get(kind):
                    w.sample("wasmedge_hv_swap_faults_total",
                             {"kind": kind}, int(hv_stats[kind]))

    if session_stats:
        w.head("wasmedge_sessions_parked", "gauge",
               "Guest sessions suspended off-device awaiting an "
               "external wake or a timer (wasmedge_tpu/effects/: "
               "parked through the SwapStore, zero resident lanes).")
        w.sample("wasmedge_sessions_parked", None,
                 int(session_stats.get("parked", 0)))
        w.head("wasmedge_session_wakes_total", "counter",
               "Parked-session wakes by source (http = POST "
               "/v1/requests/<id>/wake payload delivery, timer = "
               "deterministic timer-wheel expiry).")
        w.sample("wasmedge_session_wakes_total", {"source": "http"},
                 int(session_stats.get("wakes_http", 0)))
        w.sample("wasmedge_session_wakes_total", {"source": "timer"},
                 int(session_stats.get("wakes_timer", 0)))
        w.head("wasmedge_session_parks_total", "counter",
               "Suspend transitions completed (lane serialized, "
               "journaled, and freed at a launch boundary).")
        w.sample("wasmedge_session_parks_total", None,
                 int(session_stats.get("parks", 0)))
        w.head("wasmedge_session_resumes_total", "counter",
               "Woken sessions reinstalled onto a physical lane.")
        w.sample("wasmedge_session_resumes_total", None,
                 int(session_stats.get("resumes", 0)))
        hist = session_stats.get("park_seconds")
        if hist is not None:
            w.head("wasmedge_session_park_seconds", "histogram",
                   "Wall seconds each completed park spent suspended "
                   "(park boundary to lane reinstall).")
            cum = 0
            for ub in sorted(hist.get("buckets", {}),
                             key=lambda k: float(k)):
                cum += int(hist["buckets"][ub])
                w.sample("wasmedge_session_park_seconds_bucket",
                         {"le": ub}, cum)
            w.sample("wasmedge_session_park_seconds_bucket",
                     {"le": "+Inf"}, int(hist.get("count", 0)))
            w.sample("wasmedge_session_park_seconds_sum", None,
                     float(hist.get("sum", 0.0)))
            w.sample("wasmedge_session_park_seconds_count", None,
                     int(hist.get("count", 0)))
        if session_stats.get("park_faults") or \
                session_stats.get("wake_faults") or \
                session_stats.get("corrupt"):
            w.head("wasmedge_session_faults_total", "counter",
                   "Suspend-path operations that failed (faulted park "
                   "left the lane resident and retried; faulted wake "
                   "re-queued; corrupt store entries rejected).")
            for kind in ("park_faults", "wake_faults", "corrupt"):
                if session_stats.get(kind):
                    w.sample("wasmedge_session_faults_total",
                             {"kind": kind},
                             int(session_stats[kind]))

    if integrity_stats:
        audit = integrity_stats.get("audit")
        if audit is not None:
            w.head("wasmedge_integrity_audits_total", "counter",
                   "Shadow-audit verdicts at launch boundaries "
                   "(wasmedge_tpu/integrity: a seeded lane subset "
                   "re-executed on the reference tier and compared "
                   "bit-exact; divergence = silent data corruption "
                   "detected, rolled back, and re-executed).")
            for verdict in ("match", "divergence", "skipped_rng",
                            "error"):
                w.sample("wasmedge_integrity_audits_total",
                         {"verdict": verdict},
                         int(audit.get(verdict, 0)))
        quar = integrity_stats.get("quarantine")
        if quar is not None:
            w.head("wasmedge_integrity_quarantined_devices", "gauge",
                   "Devices ejected from the serving mesh after "
                   "repeated audit-divergence attribution (integrity/"
                   "quarantine.py ladder, ejection via live reshard).")
            w.sample("wasmedge_integrity_quarantined_devices", None,
                     len(quar.get("ejected", ())))
        scrub = integrity_stats.get("scrub")
        if scrub is not None:
            w.head("wasmedge_integrity_scrub_entries_total", "counter",
                   "At-rest scrub outcomes over content-addressed "
                   "state (swap blobs, checkpoint members, compile-"
                   "cache entries): entries walked, corruption found, "
                   "repairs (mirror or fleet replica), evictions, "
                   "unrepairable counts (integrity/scrub.py).")
            for kind in ("entries", "corrupt", "repaired", "evicted",
                         "unrepairable", "read_faults",
                         "quarantined_members"):
                w.sample("wasmedge_integrity_scrub_entries_total",
                         {"kind": kind}, int(scrub.get(kind, 0)))
            w.head("wasmedge_integrity_scrub_passes_total", "counter",
                   "Completed at-rest scrub walks.")
            w.sample("wasmedge_integrity_scrub_passes_total", None,
                     int(scrub.get("scans", 0)))
            w.head("wasmedge_integrity_scrub_last_seconds", "gauge",
                   "Wall seconds the most recent scrub pass took.")
            w.sample("wasmedge_integrity_scrub_last_seconds", None,
                     float(scrub.get("last_seconds", 0.0)))

    if gateway_counts is not None:
        w.head("wasmedge_gateway_restarts_total", "counter",
               "Gateway crash/restart resumes over this state dir "
               "(durable count, gateway/durable.py manifest).")
        w.sample("wasmedge_gateway_restarts_total", None,
                 int(gateway_counts.get("restarts", 0)))
        w.head("wasmedge_generation_rollbacks_total", "counter",
               "Serving-generation builds/swaps that failed or timed "
               "out and rolled back atomically (gateway/service.py).")
        w.sample("wasmedge_generation_rollbacks_total", None,
                 int(gateway_counts.get("rollbacks", 0)))

    if shed_counts:
        w.head("wasmedge_gateway_shed_total", "counter",
               "Submissions shed at the edge while the gateway was "
               "degraded, by tenant (gateway/health.py ShedLoad).")
        for tenant in sorted(shed_counts):
            w.sample("wasmedge_gateway_shed_total",
                     {"tenant": str(tenant)},
                     int(shed_counts[tenant]))

    if analysis_counts and any(analysis_counts.values()):
        w.head("wasmedge_analysis_modules_total", "counter",
               "Modules vetted by the static analyzer at registration, "
               "by cost verdict (wasmedge_tpu/analysis/).")
        for verdict in ("bounded", "unbounded"):
            if analysis_counts.get(verdict):
                w.sample("wasmedge_analysis_modules_total",
                         {"verdict": verdict},
                         int(analysis_counts[verdict]))
        w.head("wasmedge_analysis_policy_rejections_total", "counter",
               "Registrations rejected by a static admission policy "
               "(analysis/policy.py AnalysisPolicy).")
        w.sample("wasmedge_analysis_policy_rejections_total", None,
                 int(analysis_counts.get("policy_rejected", 0)))

    if http_requests:
        w.head("wasmedge_gateway_http_requests_total", "counter",
               "Gateway HTTP responses by status code "
               "(wasmedge_tpu/gateway/).")
        for code in sorted(http_requests):
            w.sample("wasmedge_gateway_http_requests_total",
                     {"code": str(code)}, int(http_requests[code]))

    if stats is not None:
        w.head("wasmedge_instructions_total", "counter",
               "Instructions retired (Statistics.instr_count).")
        w.sample("wasmedge_instructions_total", None,
                 int(stats.instr_count))
        w.head("wasmedge_gas_cost_total", "counter",
               "Weighted gas cost consumed (Statistics.total_cost).")
        w.sample("wasmedge_gas_cost_total", None, int(stats.total_cost))
        w.head("wasmedge_exec_seconds_total", "counter",
               "Execution wall seconds split by where they were spent.")
        w.sample("wasmedge_exec_seconds_total", {"where": "wasm"},
                 stats.wasm_ns / 1e9)
        w.sample("wasmedge_exec_seconds_total", {"where": "host"},
                 stats.host_ns / 1e9)

    # Failure taxonomy: the SAME FailureRecord is mirrored into the
    # recorder, the run's Statistics, and the process-wide log, so
    # summing sources would double-count every incident.  Each source
    # individually counts the incidents it saw — merge by max per
    # class (covers classes only one source observed).
    counts = {}
    if recorder is not None:
        for fc, n in recorder.failure_counts.items():
            counts[fc] = max(counts.get(fc, 0), int(n))
    for src in ((stats.failures if stats is not None else []),
                (failures or [])):
        seen = {}
        for rec in src:
            fc = getattr(rec, "fault_class", "unknown")
            seen[fc] = seen.get(fc, 0) + 1
        for fc, n in seen.items():
            counts[fc] = max(counts.get(fc, 0), n)
    if counts:
        w.head("wasmedge_failures_total", "counter",
               "Supervised-execution incidents by fault class "
               "(FailureRecord taxonomy).")
        for fc in sorted(counts):
            w.sample("wasmedge_failures_total", {"fault_class": fc},
                     counts[fc])

    if recorder is not None:
        if recorder.hostcalls:
            name = "wasmedge_hostcall_drain_latency_seconds"
            w.head(name, "histogram",
                   "Tier-1 hostcall drain latency per WASI call kind "
                   "(one observation per drained group).")
            for kind in sorted(recorder.hostcalls):
                h = recorder.hostcalls[kind]
                for le, acc in h.cumulative():
                    w.sample(f"{name}_bucket",
                             {"kind": kind, "le": repr(float(le))}, acc)
                w.sample(f"{name}_bucket",
                         {"kind": kind, "le": "+Inf"}, h.count)
                w.sample(f"{name}_sum", {"kind": kind}, h.sum_s)
                w.sample(f"{name}_count", {"kind": kind}, h.count)
            w.head("wasmedge_hostcall_drained_lanes_total", "counter",
                   "Lanes served through the tier-1 drain per call kind.")
            for kind in sorted(recorder.hostcalls):
                w.sample("wasmedge_hostcall_drained_lanes_total",
                         {"kind": kind}, recorder.hostcalls[kind].lanes)
        hv_swaps = getattr(recorder, "hv_swaps", None)
        if hv_swaps:
            name = "wasmedge_hv_swap_latency_seconds"
            w.head(name, "histogram",
                   "Lane-virtualization swap latency by direction "
                   "(serialize+store for out, fetch+install for in).")
            for direction in sorted(hv_swaps):
                h = hv_swaps[direction]
                for le, acc in h.cumulative():
                    w.sample(f"{name}_bucket",
                             {"direction": direction,
                              "le": repr(float(le))}, acc)
                w.sample(f"{name}_bucket",
                         {"direction": direction, "le": "+Inf"},
                         h.count)
                w.sample(f"{name}_sum", {"direction": direction},
                         h.sum_s)
                w.sample(f"{name}_count", {"direction": direction},
                         h.count)
        admission = getattr(recorder, "admission", None)
        if admission is not None and admission.count:
            name = "wasmedge_serve_admission_latency_seconds"
            w.head(name, "histogram",
                   "Serving-layer admission latency: request submit() "
                   "to lane install (wasmedge_tpu/serve/).")
            for le, acc in admission.cumulative():
                w.sample(f"{name}_bucket", {"le": repr(float(le))}, acc)
            w.sample(f"{name}_bucket", {"le": "+Inf"}, admission.count)
            w.sample(f"{name}_sum", None, admission.sum_s)
            w.sample(f"{name}_count", None, admission.count)
        if recorder.tier_seconds:
            w.head("wasmedge_tier_residency_seconds", "counter",
                   "Wall seconds the batch spent on each engine tier "
                   "(supervisor degradation ladder).")
            for tier in sorted(recorder.tier_seconds):
                w.sample("wasmedge_tier_residency_seconds",
                         {"tier": tier}, recorder.tier_seconds[tier])
        conv = getattr(recorder, "convergence", None)
        if conv and conv.get("rounds"):
            w.head("wasmedge_convergence_unique_pcs", "gauge",
                   "Distinct active pcs among live lanes at the last "
                   "launch boundary (batch/compact.py divergence "
                   "estimate; 1 = fully convergent).")
            w.sample("wasmedge_convergence_unique_pcs", None,
                     int(conv.get("unique_pcs", 0)))
            w.head("wasmedge_convergence_largest_group_fraction",
                   "gauge",
                   "Largest convergent lane group as a fraction of "
                   "live lanes at the last launch boundary.")
            w.sample("wasmedge_convergence_largest_group_fraction",
                     None, round(float(conv.get("largest_group", 1.0)),
                                 6))
        n_compact = int(getattr(recorder, "compactions_total", 0))
        if n_compact:
            w.head("wasmedge_compactions_total", "counter",
                   "Lane compactions fired at launch boundaries "
                   "(PC-sorted regrouping, batch/compact.py).")
            w.sample("wasmedge_compactions_total", None, n_compact)
            h = recorder.compaction
            name = "wasmedge_compaction_latency_seconds"
            w.head(name, "histogram",
                   "Host-side latency of one fired lane compaction "
                   "(permutation build + dispatch).")
            for le, acc in h.cumulative():
                w.sample(f"{name}_bucket", {"le": repr(float(le))}, acc)
            w.sample(f"{name}_bucket", {"le": "+Inf"}, h.count)
            w.sample(f"{name}_sum", None, h.sum_s)
            w.sample(f"{name}_count", None, h.count)
        fused = getattr(recorder, "fused_counts", None)
        if fused and fused.get("retired_total"):
            w.head("wasmedge_fused_dispatches_total", "counter",
                   "Fused superinstruction dispatch cells executed on "
                   "the SIMT tier (each retires a whole straight-line "
                   "run in one dispatch, batch/fuse.py).")
            w.sample("wasmedge_fused_dispatches_total", None,
                     int(fused.get("dispatches", 0)))
            w.head("wasmedge_retired_by_path_total", "counter",
                   "Instructions retired by dispatch path: fused "
                   "superinstruction cells vs per-op dispatch.")
            rf = int(fused.get("retired_fused", 0))
            rt = int(fused.get("retired_total", 0))
            w.sample("wasmedge_retired_by_path_total",
                     {"path": "fused"}, rf)
            w.sample("wasmedge_retired_by_path_total",
                     {"path": "unfused"}, max(rt - rf, 0))
        tier = getattr(recorder, "tierup_counts", None)
        if tier and tier.get("retired_total"):
            w.head("wasmedge_tierup_dispatches_total", "counter",
                   "Compiled-function tier bodies dispatched (each "
                   "retires a whole function call in one dispatch, "
                   "batch/tierup.py).")
            w.sample("wasmedge_tierup_dispatches_total", None,
                     int(tier.get("dispatches", 0)))
            w.head("wasmedge_tierup_retired_total", "counter",
                   "Instructions retired by tier: compiled-function "
                   "bodies vs the interpreted SIMT path.")
            rc = int(tier.get("retired_comp", 0))
            rt = int(tier.get("retired_total", 0))
            w.sample("wasmedge_tierup_retired_total",
                     {"tier": "compiled"}, rc)
            w.sample("wasmedge_tierup_retired_total",
                     {"tier": "interpreted"}, max(rt - rc, 0))
        tus = getattr(recorder, "tierup_static", None)
        if tus:
            w.head("wasmedge_tierup_functions", "gauge",
                   "Whole functions promoted to the compiled tier "
                   "(batch/tierup.py plan_tierup) and counted loops "
                   "licensed as bounded device loops inside them.")
            w.sample("wasmedge_tierup_functions",
                     {"kind": "promoted"},
                     len(tus.get("promoted", ())))
            w.sample("wasmedge_tierup_functions",
                     {"kind": "device_loops"},
                     sum(int(p.get("device_loops", 0))
                         for p in tus.get("promoted", ())))
        mfs = getattr(recorder, "memfuse_static", None)
        if mfs:
            w.head("wasmedge_memfuse_runs", "gauge",
                   "Fused memory runs by license verdict: realized "
                   "(every load/store absint-licensed trap-free) vs "
                   "reverted load/store sites the license refused — "
                   "those stay on the per-op path (batch/fuse.py).")
            w.sample("wasmedge_memfuse_runs",
                     {"verdict": "licensed"},
                     int(mfs.get("mem_runs", 0)))
            w.sample("wasmedge_memfuse_runs",
                     {"verdict": "reverted_sites"},
                     int(mfs.get("unlicensed_sites", 0)))
        if recorder.opcode_counts is not None:
            from wasmedge_tpu.validator.image import lop_name

            w.head("wasmedge_opcode_retired_total", "counter",
                   "Instructions retired per opcode (device histogram "
                   "plane, Configure.obs.opcode_histogram).")
            for op_id, n in enumerate(recorder.opcode_counts):
                if n:
                    w.sample("wasmedge_opcode_retired_total",
                             {"op": lop_name(op_id)}, int(n))
        w.head("wasmedge_obs_events_total", "counter",
               "Flight-recorder events captured (ring occupancy).")
        w.sample("wasmedge_obs_events_total", None, len(recorder.events))
        w.head("wasmedge_obs_events_dropped_total", "counter",
               "Flight-recorder events dropped by the bounded ring.")
        w.sample("wasmedge_obs_events_dropped_total", None,
                 recorder.dropped)

    if hostcall_stats:
        w.head("wasmedge_hostcall_pipeline_total", "counter",
               "Three-tier hostcall pipeline counters "
               "(batch/engine.py new_hostcall_stats).")
        for key in sorted(hostcall_stats):
            w.sample("wasmedge_hostcall_pipeline_total",
                     {"counter": key}, int(hostcall_stats[key]))

    return w.render()


def export_prometheus(path, recorder=None, stats=None,
                      hostcall_stats=None, failures=None,
                      http_requests=None, analysis_counts=None,
                      gateway_counts=None, shed_counts=None,
                      hv_stats=None, fleet_stats=None,
                      reshard_counts=None,
                      autoscale_actions=None,
                      compile_cache_counts=None,
                      snapshot_counts=None,
                      session_stats=None,
                      integrity_stats=None) -> str:
    """Render and write a metrics snapshot to `path` (or file-like)."""
    text = render_prometheus(recorder=recorder, stats=stats,
                             hostcall_stats=hostcall_stats,
                             failures=failures,
                             http_requests=http_requests,
                             analysis_counts=analysis_counts,
                             gateway_counts=gateway_counts,
                             shed_counts=shed_counts,
                             hv_stats=hv_stats,
                             fleet_stats=fleet_stats,
                             reshard_counts=reshard_counts,
                             autoscale_actions=autoscale_actions,
                             compile_cache_counts=compile_cache_counts,
                             snapshot_counts=snapshot_counts,
                             session_stats=session_stats,
                             integrity_stats=integrity_stats)
    if hasattr(path, "write"):
        path.write(text)
    else:
        from wasmedge_tpu.utils.fsio import atomic_write_bytes

        atomic_write_bytes(path, text.encode())
    return text


def parse_prometheus(text: str) -> dict:
    """Strict-enough parser for the exposition format: returns
    {(name, frozenset(labels.items())): float}.  Used by the test suite
    to prove exports stay machine-readable, and handy for ad-hoc
    assertions on snapshots."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labpart, val = rest.rsplit("}", 1)
            labels = {}
            for item in _split_labels(labpart):
                k, v = item.split("=", 1)
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"unquoted label value: {line!r}")
                labels[k] = v[1:-1].replace('\\"', '"') \
                    .replace("\\n", "\n").replace("\\\\", "\\")
            out[(name, frozenset(labels.items()))] = float(val)
        else:
            name, val = line.rsplit(None, 1)
            out[(name, frozenset())] = float(val)
    return out


def _split_labels(s: str):
    """Split a label body on commas outside quotes."""
    items, cur, inq = [], "", False
    i = 0
    while i < len(s):
        ch = s[i]
        if ch == '"' and (i == 0 or s[i - 1] != "\\"):
            inq = not inq
        if ch == "," and not inq:
            if cur:
                items.append(cur)
            cur = ""
        else:
            cur += ch
        i += 1
    if cur:
        items.append(cur)
    return items
