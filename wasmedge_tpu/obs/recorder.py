"""Flight recorder: bounded ring of structured events for batch runs.

The batch engines execute thousands of lanes behind two or three layers
of scheduling (block scheduler -> kernel launches -> hostcall drains ->
supervisor retries); when a 4096-lane run misbehaves, aggregate G/s
numbers say nothing about *where* the time or the lanes went.  The
recorder is the single sink every layer reports into:

  span(name, t0)    a timed phase (kernel launch, hostcall drain,
                    checkpoint save, SIMT residue pass)
  instant(name)     a point incident (block split, quarantine, retry,
                    every FailureRecord)
  counter(name, v)  a sampled value series (live-lane occupancy,
                    hostcall queue depth)
  hostcall(kind, s) one tier-1 drain observation into the per-kind
                    latency histogram

Events land in a bounded deque (oldest dropped, drop count kept), so a
long-lived server can leave the recorder on without unbounded growth.
Exports: Chrome trace_event JSON (obs/trace.py — opens in Perfetto /
chrome://tracing) and Prometheus text format (obs/metrics.py).

Timing discipline: durations are differences of time.monotonic() (span
timing survives wall-clock steps); the wall clock is sampled ONCE at
recorder creation and event timestamps are reconstructed as
epoch + (mono - mono0), so the trace timeline is still wall-anchored.

Overhead discipline (guard-object pattern): when observability is off,
every instrumented component holds NULL_RECORDER, whose hooks are
no-ops and whose `enabled` is False — hot paths pay one attribute check
(`if obs.enabled:`) per *launch/serve round*, never per step, and the
disabled configuration allocates nothing.
"""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Optional

# Log-spaced latency bucket upper bounds (seconds) for the hostcall
# drain histograms; the +Inf bucket is implicit.  10us..30s covers
# in-process NumPy drains through tunneled-TPU round trips (~100ms).
LATENCY_BUCKETS = (
    1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2,
    1e-1, 3e-1, 1.0, 3.0, 10.0, 30.0,
)


class LatencyHistogram:
    """Fixed-bucket latency histogram (Prometheus-shaped: per-bucket
    counts + total observation count + sum of observed seconds), with a
    drained-lane tally on the side (one drain call serves many lanes)."""

    __slots__ = ("counts", "count", "sum_s", "lanes")

    def __init__(self):
        self.counts = [0] * len(LATENCY_BUCKETS)
        self.count = 0
        self.sum_s = 0.0
        self.lanes = 0

    def observe(self, dur_s: float, lanes: int = 1):
        i = bisect.bisect_left(LATENCY_BUCKETS, dur_s)
        if i < len(self.counts):
            self.counts[i] += 1
        self.count += 1
        self.sum_s += float(dur_s)
        self.lanes += int(lanes)

    def cumulative(self):
        """[(le_bound, cumulative_count)] for Prometheus rendering."""
        out, acc = [], 0
        for le, c in zip(LATENCY_BUCKETS, self.counts):
            acc += c
            out.append((le, acc))
        return out


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullRecorder:
    """Guard object for disabled observability: every hook is a no-op.

    Instrumented code never branches per event on "is obs on?" — it
    calls the recorder unconditionally at coarse seams (per launch /
    serve / split), and guards only the *extra data gathering* (device
    reads like occupancy) behind `if obs.enabled:`.  now() avoids even
    the clock syscall."""

    enabled = False

    def now(self) -> float:
        return 0.0

    def span(self, name, t0, cat="", track="main", **args):
        pass

    def timed(self, name, cat="", track="main", **args):
        return _NULL_SPAN

    def instant(self, name, cat="", track="main", **args):
        pass

    def counter(self, name, value, track="counters"):
        pass

    def hostcall(self, kind, dur_s, lanes=1, vectorized=True):
        pass

    def observe_admission(self, dur_s):
        pass

    def observe_swap(self, direction, dur_s):
        pass

    def observe_convergence(self, unique_pcs, largest_group):
        pass

    def observe_compaction(self, dur_s):
        pass

    def add_tier_seconds(self, tier, dur_s):
        pass

    def add_opcode_counts(self, counts):
        pass

    def add_fused_counts(self, dispatches, retired_fused, retired_total):
        pass

    def set_memfuse_static(self, section):
        pass

    def add_tierup_counts(self, dispatches, retired_comp, retired_total):
        pass

    def set_tierup_static(self, report):
        pass

    def failure(self, rec):
        pass


NULL_RECORDER = NullRecorder()


class _Span:
    """Context manager from FlightRecorder.timed()."""

    __slots__ = ("_rec", "_name", "_cat", "_track", "_args", "_t0")

    def __init__(self, rec, name, cat, track, args):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._track = track
        self._args = args

    def __enter__(self):
        self._t0 = self._rec.now()
        return self

    def __exit__(self, *exc):
        self._rec.span(self._name, self._t0, cat=self._cat,
                       track=self._track, **self._args)
        return False


class FlightRecorder:
    """Bounded-ring event recorder (see module docstring).

    Events are plain dicts {name, ph, cat, ts, dur, track, args}: ph is
    the Chrome trace_event phase ("X" complete span, "i" instant, "C"
    counter), ts/dur are SECONDS (the trace exporter scales to us),
    track is a logical lane mapped to a trace tid at export time."""

    enabled = True

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self.events = deque(maxlen=self.capacity)
        self.dropped = 0
        self._epoch = time.time()       # wall anchor, sampled once
        self._mono0 = time.monotonic()  # duration clock zero
        self.hostcalls = {}        # kind -> LatencyHistogram
        self.admission = LatencyHistogram()  # serve submit -> install
        self.hv_swaps = {}         # "in"/"out" -> LatencyHistogram
        # per-round convergence gauges (batch/engine.py run_from_state)
        # + lane-compaction counters (batch/compact.py): last-observed
        # values for the Prometheus gauges, counts for the totals
        self.convergence = {"rounds": 0, "unique_pcs": 0,
                            "largest_group": 1.0}
        self.compactions_total = 0
        self.compaction = LatencyHistogram()
        self.tier_seconds = {}     # tier -> accumulated seconds
        self.failure_counts = {}   # fault_class -> count
        self.opcode_counts = None  # np.int64 [NUM_OPCODES+3] when folded
        # superinstruction-fusion counters folded from the device
        # fu_ctr plane (batch/engine.py _fold_fuse_ctr)
        self.fused_counts = {"dispatches": 0, "retired_fused": 0,
                             "retired_total": 0}
        # memory-run fusion planning statics (r19): licensed vs
        # reverted (license-refused) load/store sites + realized runs,
        # set once per plan by BatchEngine._plan_fusion
        self.memfuse_static = None
        # compiled-function tier counters folded from the device
        # tu_ctr plane (batch/engine.py _fold_tierup_ctr) + the
        # promotion report set once per plan by _plan_tierup (r20)
        self.tierup_counts = {"dispatches": 0, "retired_comp": 0,
                              "retired_total": 0}
        self.tierup_static = None

    # The recorder is a shared sink, not configuration data: components
    # deepcopy their Configure (gas bridging, scalar reruns) and must
    # keep reporting into the SAME ring, not a silent private copy.
    def __deepcopy__(self, memo):
        return self

    def __copy__(self):
        return self

    # -- clock -------------------------------------------------------------
    def now(self) -> float:
        return time.monotonic()

    def _ts(self, mono: float) -> float:
        """Wall timestamp (seconds since epoch) for a monotonic stamp."""
        return self._epoch + (mono - self._mono0)

    # -- event hooks -------------------------------------------------------
    def _push(self, ev: dict):
        if len(self.events) == self.capacity:
            self.dropped += 1
        self.events.append(ev)

    def span(self, name, t0, cat="", track="main", **args):
        """Record a completed span begun at monotonic stamp `t0`."""
        t1 = time.monotonic()
        self._push({"name": name, "ph": "X", "cat": cat,
                    "ts": self._ts(t0), "dur": max(t1 - t0, 0.0),
                    "track": track, "args": args})

    def timed(self, name, cat="", track="main", **args):
        return _Span(self, name, cat, track, args)

    def instant(self, name, cat="", track="main", **args):
        self._push({"name": name, "ph": "i", "cat": cat,
                    "ts": self._ts(time.monotonic()), "dur": 0.0,
                    "track": track, "args": args})

    def counter(self, name, value, track="counters"):
        self._push({"name": name, "ph": "C", "cat": "counter",
                    "ts": self._ts(time.monotonic()), "dur": 0.0,
                    "track": track, "args": {name: value}})

    # -- aggregates --------------------------------------------------------
    def hostcall(self, kind, dur_s, lanes=1, vectorized=True):
        """One tier-1 drain observation: histogram + trace span on the
        hostcall track."""
        h = self.hostcalls.get(kind)
        if h is None:
            h = self.hostcalls[kind] = LatencyHistogram()
        h.observe(dur_s, lanes)
        t1 = time.monotonic()
        self._push({"name": f"drain/{kind}", "ph": "X", "cat": "hostcall",
                    "ts": self._ts(t1 - dur_s), "dur": dur_s,
                    "track": "hostcalls",
                    "args": {"lanes": int(lanes),
                             "vectorized": bool(vectorized)}})

    def observe_admission(self, dur_s):
        """One serving-layer admission observation: queue wait from
        submit() to lane install (wasmedge_tpu/serve/)."""
        self.admission.observe(dur_s)

    def observe_swap(self, direction, dur_s):
        """One lane-virtualization swap observation (wasmedge_tpu/hv/):
        serialize+store for "out", fetch+install for "in"."""
        h = self.hv_swaps.get(direction)
        if h is None:
            h = self.hv_swaps[direction] = LatencyHistogram()
        h.observe(dur_s)

    def observe_convergence(self, unique_pcs, largest_group):
        """One launch-round convergence observation: distinct active
        pcs + largest convergent group fraction among live lanes
        (batch/engine.py pulls the pc mirror once per launch when obs
        is on).  Last values back the Prometheus gauges; counter
        events land on the ring for the trace."""
        self.convergence["rounds"] += 1
        self.convergence["unique_pcs"] = int(unique_pcs)
        self.convergence["largest_group"] = float(largest_group)
        self.counter("convergence_unique_pcs", int(unique_pcs))
        self.counter("convergence_largest_group",
                     round(float(largest_group), 4))

    def observe_compaction(self, dur_s):
        """One fired lane compaction (batch/compact.py): latency
        histogram + total, rendered as wasmedge_compactions_total and
        wasmedge_compaction_latency_seconds."""
        self.compactions_total += 1
        self.compaction.observe(dur_s)

    def add_tier_seconds(self, tier, dur_s):
        self.tier_seconds[tier] = \
            self.tier_seconds.get(tier, 0.0) + float(dur_s)

    def add_fused_counts(self, dispatches, retired_fused, retired_total):
        """Fold the device fusion counters (fused dispatch cells
        executed / instructions retired through them / total retired
        while the plane was live — batch/engine.py _fold_fuse_ctr)."""
        self.fused_counts["dispatches"] += int(dispatches)
        self.fused_counts["retired_fused"] += int(retired_fused)
        self.fused_counts["retired_total"] += int(retired_total)

    def set_memfuse_static(self, section):
        """Record the memory-run fusion planning statics (the
        plan_fusion report's "memory" section: licensed vs reverted
        sites, realized runs/cells) for the Prometheus export."""
        self.memfuse_static = dict(section)

    def add_tierup_counts(self, dispatches, retired_comp, retired_total):
        """Fold the device tier-up counters (compiled-function bodies
        dispatched / instructions retired through them / total retired
        while the plane was live — batch/engine.py _fold_tierup_ctr)."""
        self.tierup_counts["dispatches"] += int(dispatches)
        self.tierup_counts["retired_comp"] += int(retired_comp)
        self.tierup_counts["retired_total"] += int(retired_total)

    def set_tierup_static(self, report):
        """Record the tier-up planning report (batch/tierup.py
        plan_tierup: promoted functions, refusal reasons, device-loop
        counts) for the Prometheus export."""
        self.tierup_static = dict(report)

    def add_opcode_counts(self, counts):
        """Fold a device-side opcode histogram (index = original opcode
        id, the Statistics cost_table domain) into the run aggregate."""
        import numpy as np

        counts = np.asarray(counts, np.int64)
        if self.opcode_counts is None:
            self.opcode_counts = counts.copy()
        else:
            n = max(len(self.opcode_counts), len(counts))
            if len(self.opcode_counts) < n:
                self.opcode_counts = np.pad(
                    self.opcode_counts, (0, n - len(self.opcode_counts)))
            self.opcode_counts[:len(counts)] += counts

    def failure(self, rec):
        """Mirror one FailureRecord as an instant event + taxonomy count."""
        fc = getattr(rec, "fault_class", "unknown")
        self.failure_counts[fc] = self.failure_counts.get(fc, 0) + 1
        self._push({"name": f"failure/{fc}", "ph": "i", "cat": "failure",
                    "ts": self._ts(time.monotonic()), "dur": 0.0,
                    "track": "supervisor", "args": rec.asdict()})

    # -- queries (tests / exporters) ---------------------------------------
    def event_names(self):
        return [e["name"] for e in self.events]


def recorder_of(conf) -> "FlightRecorder | NullRecorder":
    """The recorder for a Configure: NULL_RECORDER unless conf.obs is
    enabled, in which case one FlightRecorder is lazily created and
    shared by every component holding (a copy of) that Configure."""
    obs_conf = getattr(conf, "obs", None)
    if obs_conf is None or not getattr(obs_conf, "enabled", False):
        return NULL_RECORDER
    rec = getattr(obs_conf, "_recorder", None)
    if rec is None:
        rec = FlightRecorder(capacity=obs_conf.ring_capacity)
        obs_conf._recorder = rec
    return rec
