"""Chrome trace_event JSON export for the flight recorder.

Emits the "JSON Object Format" of the trace_event spec (the format
Perfetto and chrome://tracing open directly): a `traceEvents` array of
phase records plus a `metadata` object.  Recorder tracks become trace
threads of one process — one per block/tier/component — with
thread_name metadata events so the UI labels them; counters ("C"
events: live-lane occupancy, hostcall queue depth) render as counter
tracks above the span rows.

`validate_chrome_trace` is the schema check bench.py --trace-smoke and
the obs test suite run against every emitted artifact: it proves the
required keys and types per phase, not merely that json.loads
succeeds.
"""

from __future__ import annotations

import json
from typing import List, Optional

_US = 1e6  # trace_event timestamps/durations are microseconds


def chrome_trace(recorder, metadata: Optional[dict] = None) -> dict:
    """Build the trace_event JSON object from a FlightRecorder."""
    tids = {}
    events = []

    def tid_of(track: str) -> int:
        t = tids.get(track)
        if t is None:
            t = tids[track] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": t, "args": {"name": track}})
        return t

    events.append({"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                   "args": {"name": "wasmedge-tpu batch"}})
    for ev in recorder.events:
        rec = {
            "name": ev["name"],
            "cat": ev["cat"] or "batch",
            "ph": ev["ph"],
            "ts": ev["ts"] * _US,
            "pid": 1,
            "tid": tid_of(ev["track"]),
            "args": ev["args"],
        }
        if ev["ph"] == "X":
            rec["dur"] = ev["dur"] * _US
        elif ev["ph"] == "i":
            rec["s"] = "t"  # instant scope: thread
        events.append(rec)
    meta = {"recorder_capacity": recorder.capacity,
            "events_dropped": recorder.dropped}
    if recorder.tier_seconds:
        meta["tier_seconds"] = dict(recorder.tier_seconds)
    if recorder.failure_counts:
        meta["failure_counts"] = dict(recorder.failure_counts)
    if metadata:
        meta.update(metadata)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}


def export_chrome_trace(recorder, path, metadata: Optional[dict] = None):
    """Write the trace object to `path` (or a file-like object)."""
    obj = chrome_trace(recorder, metadata)
    if hasattr(path, "write"):
        json.dump(obj, path)
    else:
        from wasmedge_tpu.utils.fsio import atomic_write_bytes

        atomic_write_bytes(path, json.dumps(obj).encode())
    return obj


_REQUIRED = {"name", "ph", "pid", "tid"}
_KNOWN_PH = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s", "t",
             "f"}


def validate_chrome_trace(obj) -> List[str]:
    """Schema problems of a trace_event JSON object ([] = valid)."""
    probs = []
    if not isinstance(obj, dict):
        return ["top level is not an object"]
    evs = obj.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing traceEvents array"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            probs.append(f"event {i}: not an object")
            continue
        missing = _REQUIRED - set(ev)
        if missing:
            probs.append(f"event {i} ({ev.get('name')!r}): missing "
                         f"{sorted(missing)}")
            continue
        ph = ev["ph"]
        if ph not in _KNOWN_PH:
            probs.append(f"event {i}: unknown phase {ph!r}")
            continue
        if ph != "M" and not isinstance(ev.get("ts"), (int, float)):
            probs.append(f"event {i} ({ev['name']!r}): non-numeric ts")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            probs.append(f"event {i} ({ev['name']!r}): X without dur")
        if ph == "C" and not isinstance(ev.get("args"), dict):
            probs.append(f"event {i} ({ev['name']!r}): C without args")
    return probs
