from wasmedge_tpu.parallel.mesh import (
    MeshDriveError,
    lane_mesh,
    run_mesh,
    run_pallas_sharded,
    shard_batch_state,
    state_shardings,
)
from wasmedge_tpu.parallel.shard_drive import (
    ShardDrive,
    ShardDriveError,
    run_shard_drive,
)

__all__ = ["MeshDriveError", "ShardDrive", "ShardDriveError", "lane_mesh",
           "run_mesh", "run_pallas_sharded", "run_shard_drive",
           "shard_batch_state", "state_shardings"]
