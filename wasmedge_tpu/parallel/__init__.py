from wasmedge_tpu.parallel.mesh import (
    lane_mesh,
    shard_batch_state,
    state_shardings,
)

__all__ = ["lane_mesh", "shard_batch_state", "state_shardings"]
