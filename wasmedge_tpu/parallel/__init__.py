from wasmedge_tpu.parallel.mesh import (
    MeshDriveError,
    lane_mesh,
    run_pallas_sharded,
    shard_batch_state,
    state_shardings,
)

__all__ = ["MeshDriveError", "lane_mesh", "run_pallas_sharded",
           "shard_batch_state", "state_shardings"]
