"""Multi-chip scaling: shard the lane dimension over a device Mesh.

Wasm instances are share-nothing (SURVEY.md §2.10): the batch engine's lane
axis is embarrassingly parallel, so multi-chip execution is pure SPMD data
parallelism — state arrays sharded on their lane (last) dimension, code/
function tables replicated, zero collectives in steady state. ICI/DCN is
used only to scatter module images and gather results, replacing the
reference's (nonexistent) need for a NCCL-style collective backend.

Implementation is idiomatic pjit: NamedSharding annotations on the state
pytree + jit; XLA SPMD-partitions the step. Device-local work is identical
to the single-chip engine, so scaling is linear in chips.
"""

from __future__ import annotations

from typing import Optional


def lane_mesh(n_devices: Optional[int] = None, devices=None):
    """1-D mesh over the 'lanes' axis."""
    import jax
    from jax.sharding import Mesh

    import numpy as np

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("lanes",))


def state_shardings(mesh, state):
    """NamedSharding pytree for a BatchState: lane dim (last) sharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def spec_for(x):
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return NamedSharding(mesh, P())
        spec = [None] * (nd - 1) + ["lanes"]
        return NamedSharding(mesh, P(*spec))

    import jax
    return jax.tree_util.tree_map(spec_for, state)


def shard_batch_state(state, mesh):
    """Place a host-built BatchState onto the mesh, lane-sharded."""
    import jax

    return jax.device_put(state, state_shardings(mesh, state))
