"""Multi-chip scaling: shard the lane dimension over a device Mesh.

Wasm instances are share-nothing (SURVEY.md §2.10): the batch engine's lane
axis is embarrassingly parallel, so multi-chip execution is pure SPMD data
parallelism — state arrays sharded on their lane (last) dimension, code/
function tables replicated, zero collectives in steady state. ICI/DCN is
used only to scatter module images and gather results, replacing the
reference's (nonexistent) need for a NCCL-style collective backend.

Implementation is idiomatic pjit: NamedSharding annotations on the state
pytree + jit; XLA SPMD-partitions the step. Device-local work is identical
to the single-chip engine, so scaling is linear in chips.
"""

from __future__ import annotations

from typing import Optional


def normalize_devices(devices):
    """One rule for every `devices=` front door (VM.execute_batch,
    BatchServer, GatewayService): an int selects a prefix of
    jax.devices(), anything else is taken as an explicit device list;
    None means all devices."""
    import jax

    if devices is None:
        return jax.devices()
    if isinstance(devices, int):
        return jax.devices()[:devices]
    return list(devices)


def lane_mesh(n_devices: Optional[int] = None, devices=None):
    """1-D mesh over the 'lanes' axis."""
    import jax
    from jax.sharding import Mesh

    import numpy as np

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("lanes",))


def state_shardings(mesh, state):
    """NamedSharding pytree for a BatchState: lane dim (last) sharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lanes = int(state.pc.shape[0])

    def spec_for(x):
        nd = getattr(x, "ndim", 0)
        # replicate planes whose trailing dim is not the lane dim (e.g.
        # the [2, 2] tier-0 time base, batch/engine.py BatchState)
        if nd == 0 or int(x.shape[-1]) != lanes:
            return NamedSharding(mesh, P())
        spec = [None] * (nd - 1) + ["lanes"]
        return NamedSharding(mesh, P(*spec))

    import jax
    return jax.tree_util.tree_map(spec_for, state)


def shard_batch_state(state, mesh):
    """Place a host-built BatchState onto the mesh, lane-sharded."""
    import jax

    return jax.device_put(state, state_shardings(mesh, state))


class MeshDriveError(RuntimeError):
    """Aggregated per-device failures from a sharded drive.

    `failures` is [(device, exception)] for EVERY device that failed —
    surfacing only the first loses the (common) correlated-failure
    signature; the first failure stays chained as __cause__."""

    def __init__(self, failures):
        self.failures = list(failures)
        detail = "; ".join(f"{dev}: {e!r}" for dev, e in self.failures)
        super().__init__(
            f"sharded drive failed on {len(self.failures)} "
            f"device(s): {detail}")


def size_lane_args(args_lanes, lanes=None):
    """Normalize batch args for a mesh drive: int64 arrays, scalars
    broadcast to the lane count (taken from the first per-lane array
    when `lanes` is not given).  One rule shared by the unsupervised
    drive, the MeshSupervisor, and VM.execute_batch's devices path —
    the pinned bit-identical-across-device-counts guarantee depends on
    all three agreeing.  Returns (args, lanes)."""
    import numpy as np

    args = [np.asarray(a, np.int64) for a in (args_lanes or [])]
    if lanes is None:
        lanes = next((a.shape[0] for a in args if a.ndim), None)
        if lanes is None:
            raise ValueError(
                "the mesh drive needs `lanes` or at least one per-lane "
                "(non-scalar) argument array to size the batch")
    return ([a if a.ndim else np.full(lanes, a, np.int64) for a in args],
            int(lanes))


def split_lanes(lanes: int, n: int):
    """Contiguous near-equal lane ranges for n devices: uneven lane
    counts split unevenly (each device's engine is sized to its own
    slice, so no clone/pad lanes ever execute and host-visible WASI
    effects are never duplicated); devices left without lanes sit
    out."""
    import numpy as np

    return [p.astype(np.int64)
            for p in np.array_split(np.arange(lanes), n) if p.size]


def make_device_scheduler(inst, store, conf, func_name, dev_args,
                          max_steps, interpret, di):
    """One device's warp-interpreter drive: a PallasUniformEngine plus
    its BlockScheduler over `dev_args` (this device's lane slice).
    Shared by the unsupervised drive below and the MeshSupervisor's
    kernel tier (which rebuilds a fresh scheduler per retry)."""
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine
    from wasmedge_tpu.batch.scheduler import BlockScheduler

    eng = PallasUniformEngine(inst, store=store, conf=conf,
                              lanes=len(dev_args[0]) if dev_args else None,
                              interpret=interpret)
    if not eng.eligible:
        raise RuntimeError(f"pallas ineligible: {eng.ineligible_reason}")
    # per-device flight-recorder track (ROADMAP r8 open item): each
    # device's scheduler events — kernel rounds, splits, frees,
    # residue — land on their own trace track instead of interleaving
    # on one "pallas" lane, so a multi-chip run is attributable per
    # chip in Perfetto
    eng.obs_track = f"pallas/dev{di}"
    return BlockScheduler(eng, func_name, dev_args, max_steps)


def run_mesh(inst, store, conf, func_name, args_lanes, devices=None,
             max_steps: int = 10_000_000, interpret=None,
             drive: Optional[str] = None, supervised: bool = False,
             faults=None, stats=None, checkpoint_dir=None, resume=None,
             lanes=None):
    """Multi-device front door: pick a mesh drive and run.

    `drive` selects the rung:
      - None / "shard" (default): the single-program shard drive — ONE
        jitted program over the named mesh, lane planes sharded on the
        `lanes` axis, one driving host thread
        (parallel/shard_drive.py).  Unsupervised shard failures raise
        ShardDriveError; the fallback ladder lives in the supervisor.
      - "threaded": the per-device threaded drive (run_pallas_sharded)
        — N host threads, one Pallas/BlockScheduler engine per device —
        retained as the explicit degradation-ladder rung below the
        shard drive.

    `supervised=True` (or `resume`) routes through the MeshSupervisor,
    which attempts the shard drive first (unless `drive="threaded"`)
    and demotes to the threaded rungs on shard-drive failure, keeping
    device quarantine / lane migration / coordinated checkpointing."""
    if drive not in (None, "shard", "threaded"):
        raise ValueError(f"unknown mesh drive {drive!r} "
                         f"(expected 'shard' or 'threaded')")
    if supervised or resume:
        from wasmedge_tpu.parallel.supervisor import MeshSupervisor

        sup = MeshSupervisor(inst, store=store, conf=conf,
                             devices=devices, faults=faults, stats=stats,
                             checkpoint_dir=checkpoint_dir, resume=resume,
                             interpret=interpret, drive=drive)
        return sup.run(func_name, list(args_lanes), max_steps=max_steps,
                       lanes=lanes)
    if drive in (None, "shard"):
        from wasmedge_tpu.parallel.shard_drive import run_shard_drive

        return run_shard_drive(inst, store, conf, func_name,
                               list(args_lanes), devices=devices,
                               max_steps=max_steps, lanes=lanes,
                               faults=faults)
    return run_pallas_sharded(inst, store, conf, func_name, args_lanes,
                              devices=devices, max_steps=max_steps,
                              interpret=interpret, lanes=lanes)


def run_pallas_sharded(inst, store, conf, func_name, args_lanes,
                       devices=None, max_steps: int = 10_000_000,
                       interpret=None, threaded: bool = True,
                       supervised: bool = False, faults=None, stats=None,
                       checkpoint_dir=None, resume=None, lanes=None):
    """Run the Pallas warp-interpreter sharded across devices.

    Wasm instances are share-nothing, so multi-chip Pallas execution is
    block-level SPMD: the lane batch splits into one sub-batch per
    device, each device gets its OWN warp-interpreter engine (tables and
    state committed to that device) driven by its own block scheduler,
    and the host round-robins launch/process so all devices' kernels
    execute concurrently while divergence handling and outcall service
    interleave on the host — the same latency-hiding drive the
    multi-tenant engine uses across tenants, here across chips.
    Returns one merged BatchResult in original lane order.

    A lane count that does not divide the device count splits unevenly
    (contiguous `np.array_split` ranges; devices left without lanes sit
    out) — each device's engine is sized to its own slice, so no clone
    lanes execute and host-visible WASI effects are never duplicated.
    `supervised=True` (or `resume`) routes the
    drive through the MeshSupervisor (parallel/supervisor.py): device
    quarantine + retry with backoff, lane migration off ejected
    devices, coordinated mesh checkpointing, cooperative cancellation —
    `faults`/`stats`/`checkpoint_dir`/`resume` are its knobs.
    """
    import jax
    import numpy as np

    from wasmedge_tpu.batch.engine import BatchResult

    if supervised or resume:
        from wasmedge_tpu.parallel.supervisor import MeshSupervisor

        sup = MeshSupervisor(inst, store=store, conf=conf,
                             devices=devices, faults=faults, stats=stats,
                             checkpoint_dir=checkpoint_dir, resume=resume,
                             interpret=interpret)
        return sup.run(func_name, list(args_lanes), max_steps=max_steps,
                       lanes=lanes)

    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    args, lanes = size_lane_args(args_lanes, lanes)
    parts = split_lanes(lanes, n)

    scheds = []
    for di, part in enumerate(parts):
        dev = devices[di]
        with jax.default_device(dev):
            sl = slice(int(part[0]), int(part[-1]) + 1)
            scheds.append((dev, make_device_scheduler(
                inst, store, conf, func_name, [a[sl] for a in args],
                max_steps, interpret, di)))

    if threaded:
        # one host thread per device: device kernels already overlap
        # via async dispatch, threading additionally overlaps the
        # HOST-side work (ctrl mirrors, outcall serving, divergence
        # splitting) across devices — jax.default_device is
        # thread-local, so each thread pins its own device
        import threading

        errs = []

        def drive(dev, s):
            try:
                with jax.default_device(dev):
                    # one span per device thread bracketing its whole
                    # drive, on the device's own track — the thread's
                    # scheduler events nest under it in the trace
                    t0 = s.obs.now()
                    try:
                        s.run()   # includes the SIMT residue pass
                    finally:
                        s.obs.span("device_drive", t0, cat="mesh",
                                   track=s._track,
                                   device=str(dev), lanes=s.lanes)
            except Exception as e:  # noqa: BLE001
                errs.append((dev, e))

        ts = [threading.Thread(target=drive, args=(dev, s), daemon=True)
              for dev, s in scheds]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            # every device's failure, attributed — not just errs[0]
            raise MeshDriveError(errs) from errs[0][1]
    else:
        active = list(scheds)
        cur = None
        try:
            while active:
                for cur, s in active:
                    with jax.default_device(cur):
                        s.launch()
                done = []
                for cur, s in active:
                    with jax.default_device(cur):
                        if not s.process():
                            done.append((cur, s))
                for d in done:
                    active.remove(d)
            for cur, s in scheds:
                with jax.default_device(cur):
                    s._run_simt_residue()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # device-attributed wrapping for the serial drive too (its
            # exceptions used to escape raw, naming no device)
            raise MeshDriveError([(cur, e)]) from e

    results = [s.result() for _, s in scheds]
    nres = len(results[0].results)
    merged = BatchResult(
        results=[np.concatenate([r.results[k] for r in results])
                 for k in range(nres)],
        trap=np.concatenate([r.trap for r in results]),
        retired=np.concatenate([r.retired for r in results]),
        steps=max(r.steps for r in results))
    return merged
