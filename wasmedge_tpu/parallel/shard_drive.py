"""Single-program mesh drive: one jitted chunk program over a named
device mesh (shard_map semantics via NamedSharding + jit / GSPMD).

The threaded drive (parallel/mesh.py run_pallas_sharded) is N Python
threads coordinating N per-device engines under the GIL: per-round host
overhead grows with device count and pod scale is out of reach.  This
module recasts the whole fleet step as ONE array program, the
SNIPPETS.md [2] NamedSharding shape ("8-chip v4 to 6000-chip v5p
without changing application code") applied to the lane batch:

  - every BatchState plane becomes one GLOBAL lane-sharded array
    (`lanes` mesh axis on the trailing dim, parallel/mesh.py
    state_shardings — the replication rule for laneless planes is
    shared with the threaded drive's checkpoint slicing);
  - the existing jitted SIMT chunk body runs per-shard UNCHANGED —
    XLA's SPMD partitioner places one program on every device, zero
    collectives in steady state (wasm instances are share-nothing);
  - hostcall/trap/retired mirrors are gathered ONCE per launch
    boundary (np.asarray reassembles the per-device shards) and viewed
    per shard (`shard_mirrors` — the per-device mesh_round spans read
    the trap mirror through it), so the tier-1 WASI drain and the
    harvest logic see exactly the per-device views the threaded drive
    gave them — the drain itself serves the concatenation in global
    lane order, which restores single-device determinism (the threaded
    drive's cross-device flush interleaving was scheduler-dependent).

A lane count that does not divide the device count pads the GLOBAL
array up to the next multiple: pad lanes are born parked (trap ==
TRAP_DONE), so the step function's `active` mask excludes them — they
never retire an instruction, never park at a hostcall stub, and never
duplicate a WASI side effect; the harvest strips them before the merged
BatchResult is returned.

The drive is the default for devices > 1 (parallel/mesh.py run_mesh).
The threaded drive is retained as an explicit degradation-ladder rung:
the MeshSupervisor attempts this drive first and falls back to the
threaded per-device rungs on any shard-drive failure, preserving
quarantine / ejection / checkpoint semantics (parallel/supervisor.py).

Determinism note: tier-0 random_get keys its stream on the GLOBAL lane
index here, exactly like single-device execute_batch — the threaded
drive keys on the device-local index, so a random-drawing guest is
bit-identical between THIS drive and the single-device path, and
lane-placement-independent guests are bit-identical across all three.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class ShardDriveError(RuntimeError):
    """A single-program shard-drive failure — the MeshSupervisor's cue
    to demote to the threaded per-device rung (the failure stays
    chained as __cause__ for attribution)."""


def padded_lanes(lanes: int, n_devices: int) -> int:
    """Global lane count padded up to a multiple of the device count
    (NamedSharding splits the lane dim evenly across the mesh)."""
    n = max(int(n_devices), 1)
    return ((int(lanes) + n - 1) // n) * n


def shard_slices(padded: int, n_devices: int) -> List[slice]:
    """Contiguous per-device lane ranges of the padded global arrays —
    the per-shard view geometry (`lanes` axis shards are contiguous
    equal blocks, device order = mesh order)."""
    per = int(padded) // max(int(n_devices), 1)
    return [slice(d * per, (d + 1) * per) for d in range(int(n_devices))]


def shard_mirrors(mirror, slices):
    """Per-shard zero-copy views of one launch-boundary host mirror
    (trap / retired / so_off — any lane-trailing plane pulled to the
    host with np.asarray, which reassembles the per-device shards).
    The per-device mesh_round spans read the trap mirror through this,
    and the WASI drain / harvest see the same per-device views as the
    concatenation in global lane order."""
    return [mirror[sl] for sl in slices]


def regrow_state(state, old_lanes: int, idle_state, new_lanes: int):
    """Host-side state re-placement for a LIVE reshard (r21): every
    lane-trailing plane of `state` (the running generation, old_lanes
    wide) keeps its columns at their GLOBAL lane indices and extends
    with the matching columns of `idle_state` (a fresh all-idle state
    at the new geometry — its tail lanes are born parked TRAP_DONE,
    exactly like the pad lanes of an uneven split).  Laneless planes
    pass through from the running state untouched.

    Lanes only ever grow across a reshard (the server pads the lane
    pool up from its CURRENT width, never down — a device shrink keeps
    the width and just re-splits it), so every resident lane's column
    is preserved verbatim: results are bit-identical to the
    unresharded run by construction, not by remapping.

    Returns a host (numpy) pytree — the caller places it on the new
    mesh (parallel/mesh.py shard_batch_state) or hands it straight to
    the unsharded jit for a single-device target."""
    import jax

    if new_lanes < old_lanes:
        raise ValueError(
            f"reshard cannot shrink the lane pool "
            f"({old_lanes} -> {new_lanes}); device shrinks keep the "
            f"width and re-split it")

    def _combine(old_leaf, idle_leaf):
        o = np.asarray(old_leaf)
        if o.ndim and o.shape[-1] == old_lanes:
            if new_lanes == old_lanes:
                return o
            pad = np.asarray(idle_leaf)[..., old_lanes:new_lanes]
            return np.concatenate([o, pad.astype(o.dtype)], axis=-1)
        return o

    return jax.tree_util.tree_map(_combine, state, idle_state)


def _build_shard_chunk(run_chunk, mesh, probe_state, donate):
    """Jit the chunk body as ONE program over the named mesh.

    `run_chunk` is the engine's traced chunk loop (the SAME body the
    single-device path jits — batch/engine.py _build); this wrapper
    only pins the data placement: every lane-dim plane of the
    BatchState pytree sharded on the `lanes` mesh axis in and out, the
    per-launch time base replicated.  XLA's SPMD partitioner then
    compiles one per-shard executable and the host issues ONE dispatch
    per round regardless of device count.  `donate` is the caller's
    donation tuple — BatchEngine._build owns the CPU/persistent-cache
    carve-out, one copy for both branches.

    jit-purity lint target (tools/lint_jit_purity.py): everything
    nested here runs under trace.
    """
    import jax

    from wasmedge_tpu.parallel.mesh import state_shardings

    shardings = state_shardings(mesh, probe_state)
    return jax.jit(run_chunk, in_shardings=(shardings, None),
                   out_shardings=(None, shardings),
                   donate_argnums=donate)


class ShardDrive:
    """One module's batch driven as a single jitted program over a
    lane-sharded named device mesh.

    `run()` returns the same merged BatchResult the threaded drive
    does, bit-identical for lane-placement-independent guests (and
    bit-identical to single-device execute_batch unconditionally — the
    global lane index IS the single-device lane index).  `faults` arms
    the deterministic seams `shard_launch` / `shard_serve` (the
    engine's launch/serve seams re-labelled, so supervisor tests can
    target the shard rung without touching the threaded one).
    """

    def __init__(self, inst, store=None, conf=None, devices=None,
                 faults=None):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.parallel.mesh import (
            lane_mesh, normalize_devices)

        self.inst = inst
        self.store = store
        self.conf = conf if conf is not None else Configure()
        self.devices = normalize_devices(devices)
        if not self.devices:
            raise ValueError("shard drive needs at least one device")
        self.mesh = lane_mesh(devices=self.devices)
        self.faults = faults
        self.engine = None       # built per run (lane width is per-run)
        self._slices = []
        self._pad = 0
        self._lanes = 0

    # -- fault seam: engine launch/serve re-labelled shard_* -------------
    def _fault_hook(self, point, **ctx):
        if point in ("launch", "serve"):
            point = "shard_" + point
        self.faults.fire(point, drive="shard", **ctx)

    # -- per-round per-device spans (obs mesh_round satellite) -----------
    def _on_round(self, done_steps: int, trap_host, t_launch):
        from wasmedge_tpu.batch.image import TRAP_HOSTCALL

        obs = self.engine.obs
        if not obs.enabled:
            return
        for di, (sl, t) in enumerate(
                zip(self._slices, shard_mirrors(trap_host,
                                                self._slices))):
            pad = max(sl.stop - self._lanes, 0) if self._pad else 0
            obs.span("mesh_round", t_launch, cat="mesh",
                     track=f"mesh/dev{di}", device=str(self.devices[di]),
                     steps=int(done_steps), lanes=int(t.size),
                     live_lanes=int((t == 0).sum()),
                     parked_lanes=int((t == TRAP_HOSTCALL).sum()),
                     pad_lanes=int(min(pad, t.size)))

    def _build_engine(self, padded: int):
        from wasmedge_tpu.batch.engine import BatchEngine

        eng = BatchEngine(self.inst, store=self.store, conf=self.conf,
                          lanes=padded, mesh=self.mesh)
        # launch/serve spans of the single driving thread land on one
        # dedicated track; the per-device mesh_round spans above keep
        # per-chip attribution
        eng.obs_track = "mesh/shard"
        return eng

    def run(self, func_name: str, args_lanes, max_steps: int = 10_000_000,
            lanes: Optional[int] = None):
        from wasmedge_tpu.batch.engine import (
            BatchResult, new_hostcall_stats)
        from wasmedge_tpu.batch.hostcall import stdout_cursor_reset
        from wasmedge_tpu.batch.image import TRAP_DONE
        from wasmedge_tpu.parallel.mesh import (
            shard_batch_state, size_lane_args)

        args, lanes = size_lane_args(args_lanes, lanes)
        n = len(self.devices)
        padded = padded_lanes(lanes, n)
        self._lanes = lanes
        self._pad = padded - lanes
        self._slices = shard_slices(padded, n)
        if self._pad:
            args = [np.concatenate([a, np.zeros(self._pad, np.int64)])
                    for a in args]
        eng = self.engine
        if eng is None or eng.lanes != padded:
            eng = self.engine = self._build_engine(padded)
        func_idx = eng.export_func_idx(func_name)
        eng.hostcall_stats = new_hostcall_stats()
        stdout_cursor_reset(eng)   # fresh run = fresh output stream
        # lane compaction (batch/compact.py): per-shard permutations
        # only (the compactor derives the shard blocks from the mesh),
        # fresh mapping per run
        from wasmedge_tpu.batch.compact import arm

        arm(eng)
        state = eng.initial_state(func_idx, args)
        if self._pad:
            import jax.numpy as jnp

            # pad lanes are born parked: the step function's `active`
            # mask excludes them — zero retirements, zero WASI effects
            state = state._replace(
                trap=state.trap.at[lanes:].set(jnp.int32(TRAP_DONE)))
        state = shard_batch_state(state, self.mesh)
        if self.faults is not None:
            eng._fault_hook = self._fault_hook
        eng._round_hook = self._on_round
        try:
            state, total = eng.run_from_state(state, 0, max_steps)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            raise ShardDriveError(
                f"single-program shard drive failed over {n} device(s): "
                f"{e!r}") from e
        finally:
            eng._fault_hook = None
            eng._round_hook = None
        # harvest: same decode as BatchEngine.run, pads stripped.  A
        # compacted run's pads may have migrated within their shard, so
        # the restore order (physical position of each original lane)
        # replaces the plain prefix slice — sel[:lanes] covers exactly
        # the original lanes because pad src ids sort after them.
        nres = eng.func_nresults(func_idx)
        comp = getattr(eng, "compactor", None)
        order = None if comp is None else comp.restore_order()
        sel = slice(None, lanes) if order is None else order[:lanes]
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        results = []
        for r in range(nres):
            lo = stack_lo[r, sel].view(np.uint32).astype(np.uint64)
            hi = stack_hi[r, sel].view(np.uint32).astype(np.uint64)
            results.append((lo | (hi << np.uint64(32))).view(np.int64))
        return BatchResult(
            results=results,
            trap=np.asarray(state.trap)[sel].copy(),
            retired=np.asarray(state.retired)[sel].copy(),
            steps=total)


def run_shard_drive(inst, store, conf, func_name, args_lanes,
                    devices=None, max_steps: int = 10_000_000,
                    lanes: Optional[int] = None, faults=None):
    """Functional front door: one single-program shard-drive run.
    Raises ShardDriveError on any drive failure (callers wanting the
    threaded fallback ladder go through the MeshSupervisor —
    parallel/mesh.py run_mesh with supervised=True; failure accounting
    lives there too, on the supervisor's FailureRecord seam)."""
    return ShardDrive(inst, store=store, conf=conf, devices=devices,
                      faults=faults).run(
        func_name, args_lanes, max_steps=max_steps, lanes=lanes)
