"""MeshSupervisor: mesh-level fault tolerance for the sharded drive.

`run_pallas_sharded` (parallel/mesh.py) was the last unsupervised
multi-device path: one device exception killed the whole run and threw
away every surviving device's work.  This module extends r7's
single-device supervision (batch/supervisor.py) across the mesh.  Wasm
lanes are share-nothing, so every mechanism is per-device state surgery
— no collectives, no global barrier beyond the coordinator's round
boundary:

1. **Per-device failure detection and quarantine** — each device's
   drive runs in its own thread; an exception marks the device suspect.
   Suspects are retried from their newest mesh-checkpoint shard (else
   their initial sub-state) with the shared `backoff_seconds` formula;
   after `supervisor.max_device_retries` consecutive failures the
   device is ejected from the mesh.

2. **Lane migration (elastic shrink)** — an ejected device's unfinished
   lanes are exported at the last launch boundary (its restored
   BatchState — the same plane-level seam batch/checkpoint.py
   snapshots), column-sliced, and re-packed onto surviving devices,
   which run them to completion.  Results merge in original lane order
   either way.

3. **Coordinated mesh checkpointing** — a cadence (the shared
   `supervisor.checkpoint_every_steps/_s` knobs) snapshots EVERY
   device's state at a launch-boundary barrier into one atomic lineage
   member: a `mesh-<seq>/` directory of per-device shards plus a
   manifest and the partial merged results, renamed into place only
   when complete.  A whole-process crash resumes with `resume=True`
   exactly like the single-device supervisor, re-binding shards to the
   currently-available devices (the lineage machinery is the shared
   batch/lineage.py).

4. **Cooperative cancellation** — when a run is doomed (a device
   exhausts its retries with `eject_devices=False`, or no healthy
   device remains to migrate to), sibling device threads observe the
   cancel flag at their next launch boundary (BatchEngine._cancel_hook
   / BlockScheduler.cancel_check) instead of driving doomed work to
   completion.

Tier policy mirrors the single-device supervisor: the Pallas/
BlockScheduler kernel tier is attempted per device when eligible and
best-effort (a device that exhausts kernel-tier retries demotes to its
SIMT engine from the original arguments); checkpoint cadence, retry-
from-snapshot, and migration all operate on the SIMT tier, whose
BatchState the checkpoint layer understands.  A configured cadence (or
resume) therefore drives the SIMT tier directly — exporting a live
BlockScheduler's block-packed state remains a ROADMAP open item.

Side-effect caveat: a device retry that falls back to its initial
sub-state replays that device's lanes from scratch; tier-0 stdout
suppression is per-engine (batch/hostcall.py), so mesh-tier output is
at-least-once across device restores — pure-compute batches are
exactly-once by construction.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import List, Optional

import numpy as np

from wasmedge_tpu.batch.lineage import Lineage
from wasmedge_tpu.common.errors import EngineFailure
from wasmedge_tpu.common.statistics import FailureRecord, record_failure

MANIFEST_FORMAT = 1
_MEMBER_PATTERN = r"mesh-(\d+)"


def slice_state_lanes(state, cols):
    """Column-slice a BatchState on its lane (last) dim — the export
    seam lane migration rides.  Planes whose trailing dim is not the
    lane dim (the [2, 2] tier-0 time base) are shared, like
    state_shardings' replication rule."""
    import jax
    import jax.numpy as jnp

    lanes = int(state.pc.shape[0])
    idx = jnp.asarray(np.asarray(cols, np.int64))

    def take(x):
        nd = getattr(x, "ndim", 0)
        if nd == 0 or int(x.shape[-1]) != lanes:
            return x
        return jnp.take(x, idx, axis=nd - 1)

    return jax.tree_util.tree_map(take, state)


class _Shard:
    """One device's slice of the batch: its lane ids, engine, in-flight
    state, and supervision counters."""

    __slots__ = ("di", "dev_index", "device", "lane_ids", "engine",
                 "state", "total", "consecutive", "alive", "done",
                 "fatal", "track", "migrate_state")

    def __init__(self, di: int, dev_index: int, device, lane_ids):
        self.di = di                  # shard id (monotonic)
        self.dev_index = dev_index    # position in the device list
        self.device = device
        self.lane_ids = np.asarray(lane_ids, np.int64)
        self.engine = None
        self.state = None
        self.total = 0
        self.consecutive = 0
        self.alive = True
        self.done = False
        self.fatal = None
        self.track = f"mesh/dev{dev_index}"
        self.migrate_state = None     # sliced state handed off by _eject


class MeshSupervisor:
    """Supervised multi-device drive of one module's batch.

    `run()` returns the same merged BatchResult `run_pallas_sharded`
    does.  `faults` is an optional testing.faults.FaultInjector armed on
    the mesh seams (`device_launch`/`device_serve` per device-engine
    chunk with `device=<index>` context, `mesh_checkpoint_save` /
    `checkpoint_load` around the coordinated lineage)."""

    def __init__(self, inst, store=None, conf=None, devices=None,
                 faults=None, stats=None,
                 checkpoint_dir: Optional[str] = None,
                 resume: Optional[bool] = None, interpret=None,
                 drive: Optional[str] = None):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.obs.recorder import recorder_of

        self.inst = inst
        self.store = store
        self.conf = conf if conf is not None else Configure()
        self.k = self.conf.supervisor
        self.faults = faults
        self.stats = stats
        self.obs = recorder_of(self.conf)
        self.interpret = interpret
        self.checkpoint_dir = checkpoint_dir or self.k.checkpoint_dir
        self.resume = self.k.resume if resume is None else bool(resume)
        # drive selection: None defers to the use_shard_drive knob,
        # "shard" forces the single-program attempt, "threaded" skips
        # straight to the per-device rungs
        if drive not in (None, "shard", "threaded"):
            raise ValueError(f"unknown mesh drive {drive!r} "
                             f"(expected 'shard' or 'threaded')")
        self.drive = drive
        import jax

        self.devices = list(devices) if devices is not None \
            else jax.devices()
        if not self.devices:
            raise ValueError("mesh supervision needs at least one device")
        self.failures: List[FailureRecord] = []
        self.retries = 0
        self.shards: List[_Shard] = []
        self._lineage = Lineage()
        self._cancel = threading.Event()
        self._bad_devices = set()     # dev_index of ejected devices
        self._next_di = 0
        self._seq = 0                 # mesh member sequence counter
        self.resumed = False

    # -- public ------------------------------------------------------------
    def run(self, func_name: str, args_lanes, max_steps: int = 10_000_000,
            lanes=None):
        from wasmedge_tpu.parallel.mesh import size_lane_args, split_lanes

        ex = self.inst.exports.get(func_name)
        if ex is None or ex[0] != 0:
            raise KeyError(f"no exported function {func_name}")
        self._func_name = func_name
        self._func_idx = ex[1]
        self._nres = int(self.inst.lowered.funcs[self._func_idx].nresults)
        self._max_steps = int(max_steps)
        args, lanes = size_lane_args(args_lanes, lanes)
        self.lanes = lanes
        self._args = args
        self._invocation = self._invocation_fingerprint()
        # fresh run state (a reused supervisor starts over; only an
        # explicit resume adopts disk state)
        self._lineage.reset()
        self._cancel.clear()
        self._bad_devices = set()
        self.shards = []
        self._next_di = 0
        self._seq = 0
        self._steps = 0
        self.resumed = self.resume and self._adopt_lineage()
        if not self.resumed:
            self._init_accumulators()
            for di, part in enumerate(split_lanes(lanes,
                                                  len(self.devices))):
                self.shards.append(self._new_shard(
                    di, self.devices[di], part))
        # top of the degradation ladder: the single-program shard drive
        # (parallel/shard_drive.py) — one jitted program over the named
        # mesh.  Attempted only for fresh cadence-free runs (the
        # coordinated-checkpoint tier needs per-device SIMT states);
        # any failure demotes to the threaded per-device rungs below,
        # preserving quarantine/ejection/migration semantics.
        if not self.resumed and not self._wants_cadence() \
                and self._shard_drive_on() and self._run_shard_tier():
            for s in self.shards:
                s.done = True
        if not all(s.done for s in self.shards) \
                and not self.resumed and self.k.use_kernel_tier \
                and not self._wants_cadence() and self._kernel_tier_on():
            self._run_kernel_tier()
        self._reset_cadence()
        self._run_simt_rounds()
        return self._merged_result()

    # -- setup -------------------------------------------------------------
    def _invocation_fingerprint(self) -> dict:
        import hashlib

        h = hashlib.sha256()
        for a in self._args:
            h.update(np.ascontiguousarray(a).tobytes())
        return {"func": self._func_name, "args_sha256": h.hexdigest(),
                "lanes": self.lanes}

    def _init_accumulators(self):
        self._res = np.zeros((max(self._nres, 1), self.lanes), np.int64)
        self._trap = np.zeros(self.lanes, np.int32)
        self._retired = np.zeros(self.lanes, np.int64)
        self._done_mask = np.zeros(self.lanes, bool)

    def _new_shard(self, dev_index: int, device, lane_ids) -> _Shard:
        s = _Shard(self._next_di, dev_index, device, lane_ids)
        self._next_di += 1
        return s

    def _wants_cadence(self) -> bool:
        return bool(self.k.checkpoint_every_steps
                    or self.k.checkpoint_every_s)

    def _kernel_tier_on(self) -> bool:
        from wasmedge_tpu.batch.pallas_engine import pallas_enabled

        return bool(self.interpret) or pallas_enabled(self.conf.batch)

    def _shard_drive_on(self) -> bool:
        if self.drive == "threaded":
            return False
        if self.drive == "shard":
            return True
        return bool(self.k.use_shard_drive)

    # -- single-program shard tier (top of the ladder) ---------------------
    def _run_shard_tier(self) -> bool:
        """One single-program shard-drive attempt over the whole mesh
        (parallel/shard_drive.py).  True = merged and done; False =
        recorded demotion, the threaded per-device rungs take over with
        their quarantine/ejection/migration semantics intact."""
        from wasmedge_tpu.parallel.shard_drive import ShardDrive

        t0 = self.obs.now()
        try:
            drv = ShardDrive(self.inst, store=self.store, conf=self.conf,
                             devices=self.devices, faults=self.faults)
            res = drv.run(self._func_name, self._args,
                          max_steps=self._max_steps, lanes=self.lanes)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            self.retries += 1
            self._record("shard_drive", e, tier="shard")
            self.obs.instant("shard_drive_demote", cat="mesh",
                             track="mesh", error=repr(e),
                             devices=len(self.devices))
            return False
        for r in range(self._nres):
            self._res[r] = np.asarray(res.results[r], np.int64)
        self._trap[:] = np.asarray(res.trap, np.int32)
        self._retired[:] = np.asarray(res.retired, np.int64)
        self._done_mask[:] = True
        self._steps = max(self._steps, int(res.steps))
        self.obs.span("shard_drive", t0, cat="mesh", track="mesh",
                      devices=len(self.devices), lanes=int(self.lanes),
                      steps=int(res.steps))
        return True

    # -- kernel tier (best-effort, mirrors the single supervisor) ----------
    def _run_kernel_tier(self):
        """Per-device BlockScheduler drive with retry; a device that
        exhausts its kernel-tier budget demotes to the SIMT rounds from
        its original arguments (recorded), it is NOT ejected — device
        health is judged on the checkpointable tier."""
        import jax

        from wasmedge_tpu.parallel.mesh import make_device_scheduler

        k = self.k

        def drive(shard: _Shard):
            attempt = 0
            while not self._cancel.is_set():
                try:
                    if self.faults is not None:
                        self.faults.fire("device_launch",
                                         device=shard.dev_index,
                                         tier="pallas", attempt=attempt)
                    with jax.default_device(shard.device):
                        sched = make_device_scheduler(
                            self.inst, self.store, self.conf,
                            self._func_name,
                            [a[shard.lane_ids] for a in self._args],
                            self._max_steps, self.interpret,
                            shard.dev_index)
                        sched.cancel_check = self._cancel.is_set
                        t0 = self.obs.now()
                        sched.run()
                        if self._cancel.is_set():
                            return
                        res = sched.result()
                        self.obs.span("device_drive", t0, cat="mesh",
                                      track=shard.track,
                                      device=str(shard.device),
                                      lanes=int(shard.lane_ids.size))
                    self._merge_kernel_result(shard, res)
                    shard.done = True
                    return
                except (KeyboardInterrupt, SystemExit) as e:
                    shard.fatal = e
                    self._cancel.set()
                    return
                except Exception as e:
                    attempt += 1
                    self.retries += 1
                    self._record("device_launch", e, shard=shard,
                                 tier="pallas")
                    self.obs.instant("device_suspect", cat="mesh",
                                     track=shard.track,
                                     device=str(shard.device),
                                     tier="pallas", attempt=attempt)
                    if attempt > k.max_device_retries:
                        # best-effort tier: demote, don't eject
                        self._record("demote", e, shard=shard,
                                     tier="pallas")
                        return
                    self._backoff(attempt)

        ts = [threading.Thread(target=drive, args=(s,), daemon=True)
              for s in self.shards]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for s in self.shards:
            if s.fatal is not None:
                raise s.fatal

    def _merge_kernel_result(self, shard: _Shard, res):
        ids = shard.lane_ids
        for r in range(self._nres):
            self._res[r, ids] = np.asarray(res.results[r], np.int64)
        self._trap[ids] = np.asarray(res.trap, np.int32)
        self._retired[ids] = np.asarray(res.retired, np.int64)
        self._done_mask[ids] = True
        self._steps = max(self._steps, int(res.steps))

    # -- SIMT rounds (supervised tier) -------------------------------------
    def _ensure_engine(self, shard: _Shard):
        import jax

        if shard.engine is None:
            self._ensure_engine_only(shard)
        if shard.state is None:
            with jax.default_device(shard.device):
                if shard.migrate_state is not None:
                    shard.state = jax.device_put(shard.migrate_state,
                                                 shard.device)
                    shard.migrate_state = None
                else:
                    shard.state = self._initial_shard_state(shard)

    def _initial_shard_state(self, shard: _Shard):
        return shard.engine.initial_state(
            self._func_idx, [a[shard.lane_ids] for a in self._args])

    def _device_hook(self, shard: _Shard):
        fire = self.faults.fire

        def hook(point, **ctx):
            if point in ("launch", "serve"):
                point = "device_" + point
            fire(point, device=shard.dev_index, **ctx)

        return hook

    def _run_simt_rounds(self):
        import jax

        while True:
            active = [s for s in self.shards if s.alive and not s.done]
            if not active:
                break
            if self._cancel.is_set():
                self._raise_cancelled()
            for s in active:
                self._ensure_engine(s)
            errs = {}
            crash: List[BaseException] = []

            def drive(shard: _Shard):
                try:
                    with jax.default_device(shard.device):
                        eng = shard.engine
                        if self.faults is not None:
                            eng._fault_hook = self._device_hook(shard)
                        t0 = self.obs.now()
                        target = self._slice_target(shard.total)
                        shard.state, shard.total = eng.run_from_state(
                            shard.state, shard.total, target)
                        self.obs.span("device_slice", t0, cat="mesh",
                                      track=shard.track,
                                      device=str(shard.device),
                                      steps=int(shard.total))
                except (KeyboardInterrupt, SystemExit) as e:
                    shard.fatal = e
                    crash.append(e)
                    self._cancel.set()
                except Exception as e:
                    errs[shard.di] = e
                    # fail-fast mode: siblings may stop mid-slice as
                    # soon as this shard's budget is known-exhausted
                    if not self.k.eject_devices and \
                            shard.consecutive + 1 > self.k.max_device_retries:
                        self._cancel.set()
                finally:
                    if shard.engine is not None:
                        shard.engine._fault_hook = None

            if len(active) == 1:
                drive(active[0])   # no thread hop for a lone shard
            else:
                ts = [threading.Thread(target=drive, args=(s,),
                                       daemon=True) for s in active]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            if crash:
                raise crash[0]
            for s in active:
                e = errs.get(s.di)
                if e is None:
                    s.consecutive = 0
                    if s.alive and not s.done and self._finished(s):
                        self._harvest_shard(s)
                else:
                    self._handle_failure(s, e)
            if self._cancel.is_set():
                self._raise_cancelled()
            self._maybe_checkpoint()

    def _slice_target(self, total: int) -> int:
        # slice the drive so checkpoint decisions land on chunk-aligned
        # launch boundaries (same formula as the single supervisor)
        step = None
        if self.k.checkpoint_every_steps:
            step = int(self.k.checkpoint_every_steps)
        if self.k.checkpoint_every_s:
            chunk = max(int(self.conf.batch.steps_per_launch), 1)
            step = chunk if step is None else min(step, chunk)
        if step is None:
            return self._max_steps
        return min(self._max_steps, total + step)

    def _finished(self, shard: _Shard) -> bool:
        trap = np.asarray(shard.state.trap)
        return not (trap == 0).any() or shard.total >= self._max_steps

    # -- failure handling --------------------------------------------------
    def _handle_failure(self, shard: _Shard, exc: BaseException):
        self.retries += 1
        shard.consecutive += 1
        point = getattr(exc, "point", None) or "device_launch"
        if point in ("launch", "serve"):
            point = "device_" + point
        if point not in ("device_launch", "device_serve"):
            point = "device_launch"
        self._record(point, exc, shard=shard)
        self.obs.instant("device_suspect", cat="mesh", track=shard.track,
                         device=str(shard.device),
                         consecutive=shard.consecutive, point=point)
        if shard.consecutive > self.k.max_device_retries:
            if not self.k.eject_devices:
                shard.alive = False
                shard.fatal = exc
                self._cancel.set()
                return
            self._eject(shard, exc)
            return
        # the failed slice may have consumed donated buffers: never
        # reuse the state, restore from the mesh lineage (else initial)
        shard.state, shard.total = self._restore_shard(shard)
        self._backoff(shard.consecutive)

    def _eject(self, shard: _Shard, exc: BaseException):
        """Quarantine the device and migrate its unfinished lanes onto
        the surviving devices (elastic shrink)."""
        shard.alive = False
        self._bad_devices.add(shard.dev_index)
        self._record("device_quarantine", exc, shard=shard,
                     error=f"device {shard.dev_index} ({shard.device}) "
                           f"ejected after {shard.consecutive - 1} "
                           f"retries: {exc!r}")
        self.obs.instant("device_quarantine", cat="mesh",
                         track=shard.track, device=str(shard.device),
                         lanes=int(shard.lane_ids.size))
        targets = [(i, d) for i, d in enumerate(self.devices)
                   if i not in self._bad_devices]
        if not targets:
            shard.fatal = exc
            self._cancel.set()
            return
        state, total = self._restore_shard(shard)
        from wasmedge_tpu.batch.image import TRAP_HOSTCALL

        trap = np.asarray(state.trap)
        finished = (trap != 0) & (trap != TRAP_HOSTCALL)
        if finished.any():
            self._harvest_state(state, shard.lane_ids, finished, total)
        live = np.nonzero(~finished)[0]
        if not live.size:
            shard.done = True
            return
        parts = np.array_split(live, min(len(targets), int(live.size)))
        for part, (tidx, dev) in zip(parts, targets):
            sub = self._new_shard(tidx, dev, shard.lane_ids[part])
            sub.total = total
            sub.migrate_state = slice_state_lanes(state, part)
            self.shards.append(sub)
            self._record("lane_migrate", None, shard=shard,
                         error=f"{int(part.size)} lanes "
                               f"{shard.device} -> {dev}")
            self.obs.instant("lane_migrate", cat="mesh", track=sub.track,
                             lanes=int(part.size), src=str(shard.device),
                             dst=str(dev))

    def _restore_shard(self, shard: _Shard):
        """Newest mesh-lineage shard covering this shard's exact lane
        set, else the initial sub-state.  A shard file that fails to
        load is recorded but the member is kept — its OTHER shards may
        still be the best snapshot for their devices (unlike the
        single-device lineage, one member covers many devices)."""
        from wasmedge_tpu.batch import checkpoint

        want = [int(x) for x in shard.lane_ids]
        for m in reversed(self._lineage.members):
            manifest = m.payload or {}
            entry = next((s for s in manifest.get("shards", [])
                          if s.get("lane_ids") == want), None)
            if entry is None:
                continue   # e.g. a post-migration shard older members predate
            path = os.path.join(m.path, entry["file"])
            try:
                if self.faults is not None:
                    self.faults.fire("checkpoint_load", path=path,
                                     device=shard.dev_index)
                t0 = self.obs.now()
                import jax

                with jax.default_device(shard.device):
                    state, total = checkpoint.load(path, shard.engine)
                self.obs.span("checkpoint_load", t0, cat="mesh",
                              track=shard.track, checkpoint=path,
                              steps=int(total))
                return state, total
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self._record("checkpoint", e, shard=shard,
                             checkpoint=path)
        import jax

        with jax.default_device(shard.device):
            return self._initial_shard_state(shard), 0

    def _raise_cancelled(self):
        fatal = [(s, s.fatal) for s in self.shards if s.fatal is not None]
        detail = "; ".join(
            f"device {s.dev_index} ({s.device}): {e!r}"
            for s, e in fatal) or "cancelled"
        raise EngineFailure(
            f"mesh run cancelled, siblings stopped at their launch "
            f"boundary: {detail}", self.failures)

    # -- coordinated checkpointing -----------------------------------------
    def _reset_cadence(self):
        totals = [s.total for s in self.shards if s.alive and not s.done]
        self._last_ckpt_total = min(totals) if totals else 0
        self._last_ckpt_wall = time.monotonic()

    def _maybe_checkpoint(self):
        if not self._wants_cadence():
            return
        active = [s for s in self.shards if s.alive and not s.done]
        if not active:
            return
        cur = min(s.total for s in active)
        k = self.k
        due = bool(k.checkpoint_every_steps
                   and cur - self._last_ckpt_total
                   >= k.checkpoint_every_steps)
        due = due or bool(k.checkpoint_every_s
                          and time.monotonic() - self._last_ckpt_wall
                          >= k.checkpoint_every_s)
        if not due:
            return
        self._save_checkpoint(active, cur)

    def _save_checkpoint(self, active: List[_Shard], cur: int):
        """One atomic lineage member: per-device shards + manifest +
        partial merged results, written to a temp directory and renamed
        into place (a crash mid-write leaves only an ignored *.tmp)."""
        from wasmedge_tpu.batch import checkpoint

        if self.checkpoint_dir is None:
            self.checkpoint_dir = tempfile.mkdtemp(prefix="wasmedge-mesh-")
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        self._seq += 1
        name = f"mesh-{self._seq:06d}"
        final = os.path.join(self.checkpoint_dir, name)
        tmp = final + ".tmp"
        t0 = self.obs.now()
        try:
            if self.faults is not None:
                self.faults.fire("mesh_checkpoint_save", member=name)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            shards_meta = []
            for i, s in enumerate(active):
                # a shard migrated THIS round still parks its state in
                # migrate_state (engines materialize at the next
                # round's _ensure_engine) — materialize it now so the
                # member covers every active lane
                if s.engine is None or s.state is None:
                    self._ensure_engine(s)
                fn = f"shard{i}.npz"
                checkpoint.save(os.path.join(tmp, fn), s.engine, s.state,
                                s.total, invocation=self._invocation)
                shards_meta.append({
                    "file": fn,
                    "lane_ids": [int(x) for x in s.lane_ids],
                    "total": int(s.total),
                })
            np.savez_compressed(
                os.path.join(tmp, "merged.npz"), res=self._res,
                trap=self._trap, retired=self._retired,
                done=self._done_mask, steps=np.int64(self._steps))
            manifest = {
                "format": MANIFEST_FORMAT,
                "invocation": self._invocation,
                "lanes": int(self.lanes),
                "shards": shards_meta,
            }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            # a stale same-seq member (prior run's leftovers, or a
            # corrupt newer member popped at adoption) blocks a
            # directory rename with ENOTEMPTY — it is never referenced
            # by THIS run's lineage, so replace it
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # a failed snapshot must never kill a healthy run
            self._record("mesh_checkpoint", e, checkpoint=final)
            shutil.rmtree(tmp, ignore_errors=True)
            return
        self.obs.span("mesh_checkpoint", t0, cat="mesh", track="mesh",
                      member=final, shards=len(active), steps=int(cur))
        for s in active:
            self.obs.instant("mesh_checkpoint", cat="mesh", track=s.track,
                             member=final, steps=int(s.total))
        self._lineage.add(final, self._seq, manifest)
        self._lineage.prune(self.k.keep_checkpoints, unlink=shutil.rmtree)
        self._last_ckpt_total = int(cur)
        self._last_ckpt_wall = time.monotonic()

    def _adopt_lineage(self) -> bool:
        """Cross-process resume: adopt the newest complete mesh member
        (shared newest-good walk, batch/lineage.py), rebuilding shards
        over the currently-available devices — the member's lane
        assignment, not its device identities, is authoritative."""
        from wasmedge_tpu.batch import checkpoint
        import jax

        lin = self._lineage
        lin.install(Lineage.scan(self.checkpoint_dir, _MEMBER_PATTERN))

        def load(m):
            with open(os.path.join(m.path, "manifest.json")) as f:
                manifest = json.load(f)
            if manifest.get("format") != MANIFEST_FORMAT:
                raise ValueError(
                    f"unsupported mesh manifest format "
                    f"{manifest.get('format')}")
            inv = manifest.get("invocation")
            if inv != self._invocation:
                raise ValueError(
                    f"mesh checkpoint invocation mismatch: snapshot is "
                    f"{inv}, this run is {self._invocation}")
            with np.load(os.path.join(m.path, "merged.npz"),
                         allow_pickle=False) as z:
                merged = {k2: np.asarray(z[k2])
                          for k2 in ("res", "trap", "retired", "done",
                                     "steps")}
            shards = []
            for si, entry in enumerate(manifest["shards"]):
                dev_index = si % len(self.devices)
                shard = self._new_shard(dev_index,
                                        self.devices[dev_index],
                                        np.asarray(entry["lane_ids"],
                                                   np.int64))
                self._ensure_engine_only(shard)
                path = os.path.join(m.path, entry["file"])
                if self.faults is not None:
                    self.faults.fire("checkpoint_load", path=path,
                                     device=dev_index)
                with jax.default_device(shard.device):
                    shard.state, shard.total = checkpoint.load(
                        path, shard.engine)
                shards.append(shard)
            return manifest, merged, shards

        got = lin.walk_newest(
            load, lambda e, m: self._record("mesh_checkpoint", e,
                                            checkpoint=m.path))
        if got is None:
            return False
        manifest, merged, shards = got
        newest = lin.newest()
        # older members keep their manifests as restore-walk payloads;
        # ones with an unreadable manifest are dropped from the lineage
        survivors = []
        for m in lin.members[:-1]:
            try:
                with open(os.path.join(m.path, "manifest.json")) as f:
                    m.payload = json.load(f)
                survivors.append(m)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self._record("mesh_checkpoint", e, checkpoint=m.path)
        newest.payload = manifest
        lin.members = survivors + [newest]
        # bound crash/resume cycles at keep_checkpoints like the serve
        # twin — without this, adopted members accumulate on disk until
        # the next cadence save (which a short resumed run may never
        # reach)
        lin.prune(self.k.keep_checkpoints, unlink=shutil.rmtree)
        self._seq = int(newest.steps)
        self._res = merged["res"]
        self._trap = merged["trap"]
        self._retired = merged["retired"]
        self._done_mask = merged["done"]
        self._steps = int(merged["steps"])
        self.shards = shards
        self.obs.instant("resume_adopted", cat="mesh", track="mesh",
                         member=newest.path, shards=len(shards),
                         lineage=len(lin))
        return True

    def _ensure_engine_only(self, shard: _Shard):
        import jax

        from wasmedge_tpu.batch.engine import BatchEngine

        with jax.default_device(shard.device):
            eng = BatchEngine(self.inst, store=self.store, conf=self.conf,
                              lanes=int(shard.lane_ids.size))
        eng._cancel_hook = self._cancel.is_set
        eng.obs_track = shard.track
        shard.engine = eng

    # -- harvest / merge ---------------------------------------------------
    def _harvest_shard(self, shard: _Shard):
        mask = np.ones(shard.lane_ids.size, bool)
        self._harvest_state(shard.state, shard.lane_ids, mask, shard.total)
        shard.done = True

    def _harvest_state(self, state, lane_ids, mask, total: int):
        cols = np.nonzero(np.asarray(mask))[0]
        ids = np.asarray(lane_ids, np.int64)[cols]
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        for r in range(self._nres):
            lo = stack_lo[r, cols].view(np.uint32).astype(np.uint64)
            hi = stack_hi[r, cols].view(np.uint32).astype(np.uint64)
            self._res[r, ids] = (lo | (hi << np.uint64(32))).view(np.int64)
        self._trap[ids] = np.asarray(state.trap)[cols]
        self._retired[ids] = np.asarray(state.retired,
                                        np.int64)[cols]
        self._done_mask[ids] = True
        self._steps = max(self._steps, int(total))

    def _merged_result(self):
        from wasmedge_tpu.batch.engine import BatchResult

        return BatchResult(
            results=[self._res[r].copy() for r in range(self._nres)],
            trap=self._trap.copy(),
            retired=self._retired.copy(),
            steps=int(self._steps))

    # -- bookkeeping -------------------------------------------------------
    def _backoff(self, attempt: int):
        from wasmedge_tpu.batch.supervisor import backoff_seconds

        nap = backoff_seconds(self.k, attempt)
        if nap > 0:
            time.sleep(nap)

    def _record(self, fault_class, exc, shard: Optional[_Shard] = None,
                tier: str = "mesh", checkpoint=None, error=None):
        if error is None:
            error = "" if exc is None else repr(exc)
        if shard is not None and not error.startswith("device "):
            error = (f"device {shard.dev_index} ({shard.device}): "
                     f"{error}")
        rec = FailureRecord(
            fault_class=fault_class, error=error,
            lanes=tuple(getattr(exc, "lanes", ()) or ()),
            retry=self.retries, checkpoint=checkpoint, tier=tier).stamp()
        self.failures.append(rec)
        self.obs.failure(rec)
        if self.stats is not None:
            self.stats.add_failure(rec)
        else:
            record_failure(rec)
