"""Host functions and import objects.

Mirrors the reference HostFunctionBase/HostFunction<T> CRTP marshaling
(/root/reference/include/runtime/hostfunc.h:25-160) and ImportObject
(include/runtime/importobj.h): a host function declares a wasm signature,
receives the caller's MemoryInstance plus typed arguments, and returns
typed results. Marshaling between raw 64-bit cells and typed Python values
happens here, so host bodies are written naturally.

The same objects serve the batch engine's outcall channel: lanes that hit a
host call trap out, the host drains the outcall buffer and runs these
bodies (SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.common.types import ValType, bits_to_typed, to_valtype, typed_to_bits
from wasmedge_tpu.loader.ast import FunctionType, GlobalType, MemoryType, TableType


class HostFunctionBase:
    """Subclass and implement body(mem, *args) -> tuple/scalar/None."""

    def __init__(self, params: Sequence[ValType], results: Sequence[ValType],
                 cost: int = 0, name: str = ""):
        self.functype = FunctionType(tuple(to_valtype(p) for p in params),
                                     tuple(to_valtype(r) for r in results))
        self.cost = cost
        self.name = name

    def body(self, mem, *args):
        raise NotImplementedError

    def run(self, mem, raw_args: List[int]) -> List[int]:
        ft = self.functype
        if len(raw_args) != len(ft.params):
            raise TrapError(ErrCode.FuncSigMismatch)
        typed = [bits_to_typed(t, v) for t, v in zip(ft.params, raw_args)]
        out = self.body(mem, *typed)
        if out is None:
            out = ()
        elif not isinstance(out, tuple):
            out = (out,)
        if len(out) != len(ft.results):
            raise TrapError(ErrCode.FuncSigMismatch)
        return [typed_to_bits(t, v) for t, v in zip(ft.results, out)]


class PyHostFunction(HostFunctionBase):
    """Host function from a plain Python callable fn(mem, *args)."""

    def __init__(self, fn: Callable, params, results, cost: int = 0, name: str = ""):
        super().__init__(params, results, cost, name or getattr(fn, "__name__", "host"))
        self._fn = fn

    def body(self, mem, *args):
        return self._fn(mem, *args)


class ImportObject:
    """Named host module: a bag of host funcs/tables/memories/globals
    registered under a module name (reference: include/runtime/importobj.h)."""

    def __init__(self, name: str):
        self.name = name
        self.funcs: Dict[str, HostFunctionBase] = {}
        self.memories: Dict[str, object] = {}
        self.tables: Dict[str, object] = {}
        self.globals: Dict[str, object] = {}

    def add_func(self, name: str, fn: HostFunctionBase) -> "ImportObject":
        fn.name = fn.name or name
        self.funcs[name] = fn
        return self

    def add_py_func(self, name: str, fn: Callable, params, results) -> "ImportObject":
        return self.add_func(name, PyHostFunction(fn, params, results, name=name))

    def add_memory(self, name: str, mem) -> "ImportObject":
        self.memories[name] = mem
        return self

    def add_table(self, name: str, table) -> "ImportObject":
        self.tables[name] = table
        return self

    def add_global(self, name: str, glob) -> "ImportObject":
        self.globals[name] = glob
        return self
