"""Runtime instances: module, function, memory, table, global, elem, data.

Mirrors the reference's header-only runtime instances
(/root/reference/include/runtime/instance/*.h). Differences driven by the
TPU design:

  - values are raw 64-bit cells (ints), never tagged at runtime
  - references are store-interned handles (0 = null), because device lanes
    can only hold numbers
  - MemoryInstance is a bytearray with software bounds checks (the
    reference's guard-page trick, lib/system/allocator.cpp:60-97, has no
    TPU analog — SURVEY.md §5.2), and exposes a numpy view so the batch
    engine can scatter/gather lane memories wholesale
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.common.types import PAGE_SIZE, ValType
from wasmedge_tpu.loader import ast


class MemoryInstance:
    """Linear memory (reference: include/runtime/instance/memory.h:34-332)."""

    def __init__(self, mtype: ast.MemoryType, page_limit: int = 65536):
        self.min = mtype.limit.min
        self.max = mtype.limit.max
        self.page_limit = page_limit
        self.data = bytearray(self.min * PAGE_SIZE)

    @property
    def pages(self) -> int:
        return len(self.data) // PAGE_SIZE

    def check_bounds(self, off: int, length: int):
        if off + length > len(self.data):
            raise TrapError(ErrCode.MemoryOutOfBounds)

    def grow(self, delta: int) -> int:
        old = self.pages
        new = old + delta
        limit = self.page_limit
        if self.max is not None:
            limit = min(limit, self.max)
        if delta < 0 or new > limit or new > 65536:
            return -1
        self.data.extend(bytes(delta * PAGE_SIZE))
        return old

    # -- typed access (little-endian) --------------------------------------
    def load(self, off: int, nbytes: int, signed: bool) -> int:
        self.check_bounds(off, nbytes)
        v = int.from_bytes(self.data[off : off + nbytes], "little", signed=signed)
        return v

    def store(self, off: int, nbytes: int, value: int):
        self.check_bounds(off, nbytes)
        self.data[off : off + nbytes] = (value & ((1 << (8 * nbytes)) - 1)).to_bytes(
            nbytes, "little"
        )

    def load_bytes(self, off: int, n: int) -> bytes:
        self.check_bounds(off, n)
        return bytes(self.data[off : off + n])

    def store_bytes(self, off: int, data: bytes):
        self.check_bounds(off, len(data))
        self.data[off : off + len(data)] = data

    def as_numpy(self) -> np.ndarray:
        return np.frombuffer(self.data, dtype=np.uint8)


class TableInstance:
    """Reference table (reference: include/runtime/instance/table.h)."""

    def __init__(self, ttype: ast.TableType):
        self.ref_type = ttype.ref_type
        self.min = ttype.limit.min
        self.max = ttype.limit.max
        self.refs: List[int] = [0] * self.min  # store-interned handles, 0=null

    @property
    def size(self) -> int:
        return len(self.refs)

    def get(self, idx: int) -> int:
        if idx >= len(self.refs):
            raise TrapError(ErrCode.TableOutOfBounds)
        return self.refs[idx]

    def set(self, idx: int, ref: int):
        if idx >= len(self.refs):
            raise TrapError(ErrCode.TableOutOfBounds)
        self.refs[idx] = ref

    def grow(self, delta: int, init_ref: int) -> int:
        old = len(self.refs)
        new = old + delta
        if delta < 0 or (self.max is not None and new > self.max) or new >= 2**32:
            return -1
        self.refs.extend([init_ref] * delta)
        return old


class GlobalInstance:
    def __init__(self, gtype: ast.GlobalType, value: int = 0):
        self.type = gtype
        self.value = value  # raw 64-bit cell


class ElementInstance:
    """Passive element segment storage; clear() on elem.drop."""

    def __init__(self, ref_type: ValType, refs: List[int]):
        self.ref_type = ref_type
        self.refs = refs

    def clear(self):
        self.refs = []


class DataInstance:
    def __init__(self, data: bytes):
        self.data = data

    def clear(self):
        self.data = b""


class FunctionInstance:
    """Function: wasm (lowered image + meta) or host.

    The reference's 3-way variant (interpreted/AOT/host, include/runtime/
    instance/function.h:110-140) becomes kind tags; the batch engine is an
    execution *strategy* over the same wasm kind rather than a new kind.
    """

    __slots__ = ("kind", "module", "func_idx", "host", "functype")

    def __init__(self, kind: str, functype: ast.FunctionType,
                 module: "ModuleInstance" = None, func_idx: int = -1, host=None):
        self.kind = kind  # "wasm" | "host"
        self.functype = functype
        self.module = module
        self.func_idx = func_idx
        self.host = host

    @property
    def meta(self):
        return self.module.lowered.funcs[self.func_idx]


class ModuleInstance:
    """Per-module runtime state (reference: include/runtime/instance/
    module.h:37-345)."""

    def __init__(self, name: str, mod: ast.Module):
        self.name = name
        self.ast = mod
        self.lowered = mod.lowered
        self.funcs: List[FunctionInstance] = []
        self.tables: List[TableInstance] = []
        self.memories: List[MemoryInstance] = []
        self.globals: List[GlobalInstance] = []
        self.elems: List[ElementInstance] = []
        self.datas: List[DataInstance] = []
        self.exports: Dict[str, tuple] = {}  # name -> (kind, index)
        self.start: Optional[int] = None

    def export_instance(self, name: str):
        if name not in self.exports:
            return None
        kind, idx = self.exports[name]
        pool = [self.funcs, self.tables, self.memories, self.globals][kind]
        return pool[idx]

    def find_func(self, name: str) -> Optional[FunctionInstance]:
        ex = self.exports.get(name)
        if ex and ex[0] == 0:
            return self.funcs[ex[1]]
        return None

    def func_names(self) -> List[str]:
        return [n for n, (k, _) in self.exports.items() if k == 0]
