"""StoreManager: owns all instances, named + anonymous modules, ref interning.

Mirrors the reference StoreManager (/root/reference/include/runtime/
storemgr.h:54-343): named-module map, active (anonymous) module = last
instantiated, reset semantics that keep registered modules. The TPU-driven
addition is the funcref intern table: device lanes hold numeric handles, so
every FunctionInstance that can flow through a table/ref gets a dense id
(0 = null), shared across modules.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from wasmedge_tpu.runtime.instance import FunctionInstance, ModuleInstance


class StoreManager:
    def __init__(self):
        self.named: Dict[str, ModuleInstance] = {}
        self.anonymous: List[ModuleInstance] = []
        self._ref_pool: List[object] = []  # handle-1 -> FunctionInstance/extern
        self._ref_ids: Dict[int, int] = {}  # id(obj) -> handle

    # -- modules -----------------------------------------------------------
    def register_named(self, inst: ModuleInstance):
        self.named[inst.name] = inst

    def push_anonymous(self, inst: ModuleInstance):
        self.anonymous.append(inst)

    def get_active_module(self) -> Optional[ModuleInstance]:
        return self.anonymous[-1] if self.anonymous else None

    def find_module(self, name: str) -> Optional[ModuleInstance]:
        return self.named.get(name)

    def module_names(self) -> List[str]:
        return list(self.named.keys())

    def reset(self, keep_registered: bool = True):
        self.anonymous.clear()
        if not keep_registered:
            self.named.clear()
            self._ref_pool.clear()
            self._ref_ids.clear()

    # -- reference interning ----------------------------------------------
    def intern_ref(self, obj) -> int:
        """Object -> numeric handle (>=1); 0 is the null reference."""
        if obj is None:
            return 0
        key = id(obj)
        h = self._ref_ids.get(key)
        if h is None:
            self._ref_pool.append(obj)
            h = len(self._ref_pool)
            self._ref_ids[key] = h
        return h

    def deref(self, handle: int):
        if handle == 0:
            return None
        return self._ref_pool[handle - 1]

    def deref_func(self, handle: int) -> Optional[FunctionInstance]:
        obj = self.deref(handle)
        return obj if isinstance(obj, FunctionInstance) else None
