"""Continuous-batching serving layer (r9).

`BatchServer` turns the drain-to-empty batch engines into a long-lived
service: a bounded request queue with per-tenant weighted-fair
admission, lane recycling at launch boundaries, deadline/backpressure
enforcement, checkpoint/restore supervision, and serve-track
observability.  See serve/server.py for the architecture notes.
"""

from wasmedge_tpu.serve.queue import (  # noqa: F401
    DeadlineExceeded,
    FairQueue,
    QueueSaturated,
    ServeFuture,
    ServeRequest,
)
from wasmedge_tpu.serve.recycle import LaneRecycler  # noqa: F401
from wasmedge_tpu.serve.server import BatchServer  # noqa: F401
