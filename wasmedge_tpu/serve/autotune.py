"""steps_per_launch auto-tuning from the hostcall drain-latency histograms.

ROADMAP r8 open item: the tier-1 drain histograms (obs/recorder.py,
fed by host/wasi/vectorized.py) record how expensive each serve round's
host-side WASI work actually is — exactly the signal needed to pick the
launch chunk size.  `steps_per_launch` trades hostcall service latency
(parked lanes wait out the rest of the chunk before the drain runs)
against launch amortization (each serve round costs at least one device
round trip):

  - drains EXPENSIVE relative to the device launch  -> grow the chunk
    (amortize the serve overhead over more device work)
  - drains CHEAP while lanes are parking            -> shrink the chunk
    (serve sooner; the round trip is the only cost and it's small)

The rule is a conservative multiplicative feedback (double / halve,
clamped to [autotune_min_chunk, autotune_max_chunk]) because changing
the chunk rebuilds the engine's jitted step loop — power-of-two
quantization bounds the number of distinct compilations.  Off by
default (`Configure.serve.autotune`); every adjustment lands on the
flight recorder as an "autotune" instant with the inputs that drove it.
"""

from __future__ import annotations

from typing import Optional

# hysteresis thresholds: drain seconds per launch second
GROW_RATIO = 0.5     # drains cost >= half a launch -> amortize more
SHRINK_RATIO = 0.05  # drains cost < 5% of a launch -> serve sooner


class ChunkAutotuner:
    """Per-server feedback loop; call observe() once per serving round."""

    def __init__(self, engine, serve_cfg, recorder):
        self.engine = engine
        self.k = serve_cfg
        self.obs = recorder
        self._prev_count = 0
        self._prev_sum = 0.0
        self.adjustments = 0

    def _drain_delta(self):
        """(new observations, new drain seconds) since the last call,
        summed over every hostcall kind's histogram."""
        hists = getattr(self.obs, "hostcalls", None) or {}
        count = sum(h.count for h in hists.values())
        sum_s = sum(h.sum_s for h in hists.values())
        d_count = count - self._prev_count
        d_sum = sum_s - self._prev_sum
        self._prev_count, self._prev_sum = count, sum_s
        return d_count, d_sum

    def observe(self, launch_s: float, parked_lanes: int) -> Optional[int]:
        """One serving round's feedback: `launch_s` is the round's wall
        time in the engine (launch + serves), `parked_lanes` how many
        lanes hit the outcall channel.  Returns the new chunk when an
        adjustment was applied, else None."""
        d_count, d_sum = self._drain_delta()
        cfg = self.engine.cfg
        chunk = int(cfg.steps_per_launch)
        new = chunk
        if d_count > 0 and launch_s > 0:
            ratio = d_sum / launch_s
            if ratio >= GROW_RATIO:
                new = min(chunk * 2, int(self.k.autotune_max_chunk))
            elif ratio < SHRINK_RATIO and parked_lanes > 0:
                new = max(chunk // 2, int(self.k.autotune_min_chunk))
        if new == chunk:
            return None
        cfg.steps_per_launch = new
        # the chunk is baked into the jitted step loop; force a rebuild
        self.engine._run_chunk = None
        self.engine._step = None
        self.adjustments += 1
        self.obs.instant(
            "autotune", cat="serve", track="serve", old_chunk=chunk,
            new_chunk=new, drain_s=round(d_sum, 6), drains=d_count,
            launch_s=round(launch_s, 6), parked=int(parked_lanes))
        return new
