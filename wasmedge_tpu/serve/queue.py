"""Request queue for the continuous-batching serving layer.

One `ServeRequest` is one device lane's worth of work: an exported
function plus one argument tuple, owned by a tenant, optionally carrying
a deadline.  Requests wait in a bounded `FairQueue` — per-tenant FIFO
lanes drained by weighted deficit round-robin, so a flooding tenant can
never starve a quota'd one (the per-tenant WASI isolation story of
batch/multitenant.py extended to *admission*) — until the admission
controller installs them into freed device lanes.

Backpressure is explicit: `push()` beyond `queue_capacity` raises
`QueueSaturated` (an ErrCode-carrying WasmError), never a silent drop;
expired deadlines reject with `DeadlineExceeded` before burning a lane.

`ServeFuture` is the caller's handle: a threading.Event the serving loop
resolves with either the request's result cells or an error.  Futures
are process-local; across a crash the *requests* survive via the
server's checkpoint journal and come back under fresh futures.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from wasmedge_tpu.common.errors import ErrCode, WasmError


class QueueSaturated(WasmError):
    """The bounded request queue is full — backpressure, try later.

    The ONE retryable rejection in the serving taxonomy: `retryable`
    is the machine-readable contract (common/errors.rejection_info)
    callers branch on instead of the exception type or message text,
    and `retry_after_s` is an optional hint for when capacity is
    expected (the gateway forwards it as HTTP Retry-After)."""

    retryable = True

    def __init__(self, msg: str = "serve queue saturated",
                 retry_after_s: Optional[float] = None):
        super().__init__(ErrCode.CostLimitExceeded, msg)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(WasmError):
    """The request's deadline passed before it completed.  Never
    retryable: the deadline belonged to THIS request; the caller must
    issue a new one if the work still matters."""

    def __init__(self, msg: str = "request deadline exceeded"):
        super().__init__(ErrCode.Terminated, msg)


class ServeRejected(WasmError):
    """The serving LIFECYCLE rejected an accepted request before (or
    instead of) running it — non-drain shutdown, or the stall sweep
    for a request that can never be admitted.  Distinct from a guest
    trap so result consumers (the gateway's status mapping) never
    present an infrastructure rejection as \"the guest ran and
    trapped\"."""

    def __init__(self, msg: str):
        super().__init__(ErrCode.Terminated, msg)


class ServeFuture:
    """Resolution handle for one submitted request.

    Exactly one of `result()` / raised error is the outcome:
      result()  -> list of raw 64-bit result cells (one int per result)
      raises    WasmError — the request's trap (TrapError-shaped code),
                DeadlineExceeded, or the server's terminal failure.
    """

    __slots__ = ("_ev", "_cells", "_error", "request_id", "t_done",
                 "_mirrors", "_mlock")

    def __init__(self, request_id: int):
        self._ev = threading.Event()
        self._cells: Optional[List[int]] = None
        self._error: Optional[BaseException] = None
        self.request_id = request_id
        self.t_done: Optional[float] = None   # monotonic resolution stamp
        self._mirrors: Optional[List["ServeFuture"]] = None
        self._mlock = threading.Lock()

    # -- serving-loop side (first outcome wins: a replayed lane after a
    # crash restore may re-complete an already-resolved request) -----------
    def _resolve(self, cells: List[int]):
        if self._ev.is_set():
            return
        self._cells = list(cells)
        self.t_done = time.monotonic()
        self._ev.set()
        self._fan_out()

    def _reject(self, error: BaseException):
        if self._ev.is_set():
            return
        self._error = error
        self.t_done = time.monotonic()
        self._ev.set()
        self._fan_out()

    def mirror(self, other: "ServeFuture"):
        """Propagate this future's outcome into `other` (the fleet's
        local-fallback seam: a re-queued request gets a FRESH server
        future, while the caller still waits on the one its 202 was
        issued against).  First-outcome-wins on the target, so a
        mirror can never overwrite an already-settled future.
        `_mlock` closes the register-vs-settle race: without it a
        concurrent _fan_out could swap _mirrors to None between this
        method's check and append, dropping the registration."""
        with self._mlock:
            if not self._ev.is_set():
                if self._mirrors is None:
                    self._mirrors = []
                self._mirrors.append(other)
                return
        self._propagate(other)   # already settled: deliver now

    def _fan_out(self):
        with self._mlock:
            mirrors, self._mirrors = self._mirrors, None
        for m in (mirrors or ()):
            self._propagate(m)

    def _propagate(self, other: "ServeFuture"):
        if self._error is not None:
            other._reject(self._error)
        else:
            other._resolve(self._cells or [])

    # -- caller side -------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._ev.wait(timeout):
            raise TimeoutError("request not resolved yet")
        if self._error is not None:
            raise self._error
        return list(self._cells)

    @property
    def error(self) -> Optional[BaseException]:
        return self._error if self._ev.is_set() else None


_req_ids = itertools.count(1)
_req_ids_lock = threading.Lock()   # draws and rebinds must serialize
_last_req_id = 0                   # highest id ever issued/adopted


def _next_request_id() -> int:
    global _last_req_id
    with _req_ids_lock:
        rid = next(_req_ids)
        _last_req_id = max(_last_req_id, rid)
        return rid


def peek_request_ids() -> int:
    """The highest request id issued (or adopted) so far, without
    consuming one — the gateway journals it as `max_id` so a resumed
    process can tell "this id existed and aged out" (pruned 404) from
    "never issued" for ids below the crash floor."""
    with _req_ids_lock:
        return _last_req_id


def advance_request_ids(past_id: int):
    """Move the process-global request-id counter past `past_id`.

    Cross-process resume adopts journaled requests that keep their
    original (higher) ids; without this, fresh submits in the adopting
    process would restart at 1 — inverting the id-ordered crash-recovery
    requeue and eventually duplicating an adopted id in a later
    checkpoint journal.  Locked against concurrent draws: a submit on
    another server mid-rebind could otherwise still allocate an id at
    or below `past_id`."""
    global _req_ids, _last_req_id
    with _req_ids_lock:
        nxt = next(_req_ids)
        _req_ids = itertools.count(max(nxt, int(past_id) + 1))
        _last_req_id = max(_last_req_id, int(past_id))


INT64_MIN = -(1 << 63)
INT64_MAX = (1 << 63) - 1


class ServeRequest:
    """One lane's worth of work (immutable once submitted)."""

    __slots__ = ("id", "func_name", "args", "tenant", "deadline",
                 "t_submit", "future")

    def __init__(self, func_name: str, args: Tuple[int, ...],
                 tenant: str = "default",
                 deadline: Optional[float] = None,
                 t_submit: float = 0.0,
                 request_id: Optional[int] = None):
        self.id = int(request_id) if request_id is not None \
            else _next_request_id()
        self.func_name = func_name
        args = tuple(int(a) for a in args)
        for a in args:
            # lane cells are 64-bit: an unrepresentable arg must be
            # rejected HERE, at submission — np.int64 conversion at
            # admission would raise OverflowError on the SERVING
            # thread and terminally fail the whole generation (every
            # tenant's in-flight work) for one bad request
            if not (INT64_MIN <= a <= INT64_MAX):
                raise ValueError(
                    f"arg {a} does not fit a 64-bit lane cell")
        self.args = args
        self.tenant = tenant
        self.deadline = deadline      # monotonic stamp, None = none
        self.t_submit = t_submit      # monotonic stamp (admission latency)
        self.future = ServeFuture(self.id)

    def asdict(self) -> dict:
        """JSON-serializable journal entry (checkpoint binding record).
        Deadlines are monotonic stamps and futures are process-local —
        neither survives a process, so neither is journaled."""
        return {"id": self.id, "func": self.func_name,
                "args": [int(a) for a in self.args],
                "tenant": self.tenant}

    @classmethod
    def from_journal(cls, rec: dict) -> "ServeRequest":
        return cls(rec["func"], tuple(rec["args"]),
                   tenant=rec.get("tenant", "default"),
                   request_id=rec["id"])


class FairQueue:
    """Bounded multi-tenant queue with weighted deficit round-robin pop.

    Each tenant owns a FIFO; `pop()` walks tenants in first-seen order,
    crediting `weight` units of deficit per visit and popping while the
    deficit covers a request — the classic DRR scheduler, deterministic
    for a fixed submission schedule (no clocks, no hashing).  Per-tenant
    `quota` bounds a tenant's *in-flight* lanes: a tenant at quota is
    skipped (its deficit stops accruing too, so it gets no windfall when
    lanes free up)."""

    def __init__(self, capacity: int,
                 weights: Optional[Dict[str, float]] = None,
                 quotas: Optional[Dict[str, int]] = None):
        self.capacity = int(capacity)
        self.weights = dict(weights or {})
        self.quotas = dict(quotas or {})
        self._q: Dict[str, deque] = {}
        self._order: List[str] = []   # tenants, first-seen order
        self._deficit: Dict[str, float] = {}
        self.size = 0
        # tenant -> queued requests carrying a deadline: expire() skips
        # whole tenants at 0, so a flood of no-deadline work is never
        # rescanned every round for one deadlined request elsewhere
        self._deadlined: Dict[str, int] = {}

    def __len__(self) -> int:
        return self.size

    def depth_of(self, tenant: str) -> int:
        q = self._q.get(tenant)
        return len(q) if q else 0

    def push(self, req: ServeRequest):
        if self.size >= self.capacity:
            raise QueueSaturated(
                f"serve queue saturated ({self.size}/{self.capacity})")
        q = self._q.get(req.tenant)
        if q is None:
            q = self._q[req.tenant] = deque()
            self._order.append(req.tenant)
            self._deficit[req.tenant] = 0.0
        q.append(req)
        self.size += 1
        if req.deadline is not None:
            self._deadlined[req.tenant] = \
                self._deadlined.get(req.tenant, 0) + 1

    def push_front(self, reqs: List[ServeRequest]):
        """Re-queue requests at the head of their tenants' FIFOs (crash
        recovery: in-flight work goes back first, original order kept).
        Capacity is deliberately not enforced — dropping recovered work
        to backpressure would turn a transient fault into data loss."""
        for req in reversed(reqs):
            q = self._q.get(req.tenant)
            if q is None:
                q = self._q[req.tenant] = deque()
                self._order.append(req.tenant)
                self._deficit[req.tenant] = 0.0
            q.appendleft(req)
            self.size += 1
            if req.deadline is not None:
                self._deadlined[req.tenant] = \
                    self._deadlined.get(req.tenant, 0) + 1

    def expire(self, now: float) -> List[ServeRequest]:
        """Remove and return queued requests whose deadline passed.
        O(tenants) when nothing queued carries a deadline, and only
        tenants that do carry one are rescanned — the serving loop
        calls this every round."""
        out = []
        for t in self._order:
            if not self._deadlined.get(t):
                continue
            q = self._q[t]
            keep = deque()
            while q:
                r = q.popleft()
                if r.deadline is not None and now >= r.deadline:
                    out.append(r)
                    self.size -= 1
                    self._deadlined[t] -= 1
                else:
                    keep.append(r)
            self._q[t] = keep
        return out

    def remove_by_id(self, request_id: int) -> Optional[ServeRequest]:
        """Remove one QUEUED (not yet admitted) request by id — the
        gateway's withdrawal path for an acceptance it could not make
        durable.  Returns the removed request, or None when the id is
        not queued (already admitted, completed, or never here)."""
        for tenant, q in self._q.items():
            for r in q:
                if r.id == request_id:
                    q.remove(r)
                    self.size -= 1
                    if r.deadline is not None:
                        self._deadlined[tenant] -= 1
                    return r
        return None

    def pop_all(self) -> List[ServeRequest]:
        """Empty the queue unconditionally (shutdown/terminal-failure
        rejection sweep) — quotas and weights do not apply; every queued
        request must get its rejection, not strand behind a quota."""
        out = []
        for t in self._order:
            q = self._q[t]
            out.extend(q)
            q.clear()
        self.size = 0
        self._deadlined.clear()
        return out

    def pop(self, n: int, in_flight: Dict[str, int]) -> List[ServeRequest]:
        """Pop up to `n` requests by weighted deficit round-robin.
        `in_flight` maps tenant -> currently-installed lanes (quota
        accounting; this method treats its own picks as in-flight)."""
        if n <= 0 or self.size == 0:
            return []
        flight = dict(in_flight)
        out: List[ServeRequest] = []
        empty_walks = 0
        while len(out) < n and self.size:
            popped = False
            eligible = False
            for t in self._order:
                if len(out) >= n or not self.size:
                    break
                q = self._q[t]
                if not q:
                    self._deficit[t] = 0.0  # idle tenants bank nothing
                    continue
                quota = self.quotas.get(t)
                if quota is not None and flight.get(t, 0) >= quota:
                    continue
                w = self.weights.get(t, 1.0)
                if w <= 0:
                    continue
                eligible = True
                self._deficit[t] += w
                while q and self._deficit[t] >= 1.0 and len(out) < n:
                    if quota is not None and flight.get(t, 0) >= quota:
                        break
                    r = q.popleft()
                    if r.deadline is not None:
                        self._deadlined[t] -= 1
                    out.append(r)
                    self.size -= 1
                    self._deficit[t] -= 1.0
                    flight[t] = flight.get(t, 0) + 1
                    popped = True
            if not eligible:
                break  # everything queued is quota-blocked (or weight 0)
            if not popped:
                empty_walks += 1
                if empty_walks > 8:
                    # tiny fractional weights would need ~1/w walks to
                    # bank one unit — instead of spinning (or worse,
                    # starving an eligible tenant), force one pop from
                    # the highest-deficit eligible tenant; its deficit
                    # goes negative, which is classic DRR catch-up (the
                    # long-run weight ratio is preserved, nothing with
                    # weight > 0 is ever denied forever)
                    best = max(
                        (t for t in self._order if self._q[t]
                         and self.weights.get(t, 1.0) > 0
                         and not (self.quotas.get(t) is not None
                                  and flight.get(t, 0)
                                  >= self.quotas[t])),
                        key=lambda t: self._deficit[t], default=None)
                    if best is None:
                        break
                    r = self._q[best].popleft()
                    if r.deadline is not None:
                        self._deadlined[best] -= 1
                    out.append(r)
                    self.size -= 1
                    self._deficit[best] -= 1.0
                    flight[best] = flight.get(best, 0) + 1
                    empty_walks = 0
            else:
                empty_walks = 0
        return out
